"""Time-accounting plane: sampling profiler + critpath + perfwatch.

Covers ISSUE 12's acceptance gates: profiler off = no thread and no
samples; on = samples attribute to the busy span; measured overhead at
the default rate; critpath buckets sum to the task wall on a synthetic
tree AND a real quick merge; the block rides the StatsReporter final
record, MSG_STATS providers and flightrec/watchdog dumps; perfwatch
ingests every historical BENCH artifact, passes on an identical point
and fails on an injected 30% slowdown; histogram summaries export
bucket boundaries+counts that recompute percentiles offline.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from scripts import perfwatch  # noqa: E402
from uda_tpu.merger import LocalFetchClient, MergeManager  # noqa: E402
from uda_tpu.mofserver import DataEngine, DirIndexResolver  # noqa: E402
from uda_tpu.utils import critpath  # noqa: E402
from uda_tpu.utils.config import Config  # noqa: E402
from uda_tpu.utils.metrics import (metrics,  # noqa: E402
                                   percentile_from_summary)
from uda_tpu.utils.profiler import (DEFAULT_HZ, SamplingProfiler,  # noqa: E402
                                    profile_hz_from_env, profiler)
from uda_tpu.utils.stats import StatsReporter, introspection_snapshot  # noqa: E402
from uda_tpu.utils.watchdog import StallWatchdog  # noqa: E402

from helpers import make_mof_tree, map_ids  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _burn(seconds: float, span: str | None = None) -> None:
    """A deterministically busy loop, optionally inside a span."""
    def work():
        t0 = time.perf_counter()
        x = np.arange(4096)
        while time.perf_counter() - t0 < seconds:
            (x * x).sum()
    if span is None:
        work()
    else:
        with metrics.span(span):
            work()


# -- profiler ----------------------------------------------------------------

def test_profiler_off_no_thread_no_samples():
    before = {t.name for t in threading.enumerate()}
    assert not profiler.armed
    assert "uda-profiler" not in before
    assert profiler.span_summary() == {}
    assert profiler.folded() == ""
    # the off-path per-call cost: spans do NOT touch the thread
    # registry while no profiler asked for it
    metrics.enable_spans()
    from uda_tpu.utils.metrics import _THREAD_SPANS
    with metrics.span("net.serve"):
        assert _THREAD_SPANS == {}
    assert metrics.get("profile.samples") == 0
    assert metrics.get("profile.ticks") == 0


def test_profile_hz_env_parsing(monkeypatch):
    monkeypatch.delenv("UDA_TPU_PROFILE", raising=False)
    assert profile_hz_from_env() == 0.0
    monkeypatch.setenv("UDA_TPU_PROFILE", "0")
    assert profile_hz_from_env() == 0.0
    monkeypatch.setenv("UDA_TPU_PROFILE", "1")
    assert profile_hz_from_env() == DEFAULT_HZ
    monkeypatch.setenv("UDA_TPU_PROFILE", "250")
    assert profile_hz_from_env() == 250.0
    monkeypatch.setenv("UDA_TPU_PROFILE", "wat")
    assert profile_hz_from_env() == DEFAULT_HZ  # asked -> armed, loudly


def test_profiler_attributes_busy_span():
    """A deliberately busy net.serve span must dominate its thread's
    samples — the span-attribution acceptance gate."""
    metrics.enable_spans()
    profiler.start(200)
    try:
        t = threading.Thread(target=_burn, args=(0.5, "net.serve"))
        t.start()
        t.join()
    finally:
        profiler.stop()
    summary = profiler.span_summary()
    assert "net.serve" in summary, summary
    serve = summary["net.serve"]
    assert serve["self"] > 0 and serve["total"] >= serve["self"]
    # the busy span owns more samples than any other ATTRIBUTED span
    others = [v["self"] for k, v in summary.items()
              if k not in ("net.serve", "(unattributed)")]
    assert serve["self"] >= max(others, default=0)
    # flamegraph text carries span-prefixed folded stacks
    assert any(line.startswith("net.serve;")
               for line in profiler.folded().splitlines())
    # the counters flowed into the metrics hub (the snapshot surface)
    assert metrics.get("profile.samples") > 0
    assert metrics.get("profile.samples", span="net.serve") > 0
    assert metrics.get("profile.ticks") > 0
    # last-N-seconds slice sees the same attribution
    recent = profiler.recent_summary(30.0)
    assert recent["spans"].get("net.serve", 0) > 0
    profiler.reset()


def test_profiler_start_stop_idempotent_and_registry_cleanup():
    profiler.start(100)
    profiler.start(300)  # second arm keeps the first sampler
    assert profiler.armed and profiler.hz == 100
    profiler.stop()
    profiler.stop()
    assert not profiler.armed
    from uda_tpu.utils.metrics import _THREAD_SPANS
    assert _THREAD_SPANS == {}  # registry disabled + cleared
    assert metrics.get_gauge("profile.hz") == 0.0


def test_profiler_overhead_at_default_hz():
    """The <=3% overhead gate, MEASURED: interleaved min-of-reps of a
    fixed CPU workload with the profiler off vs armed at the default
    rate. Skips (not fails) when the host is too noisy to resolve 3%
    — the gate is about the profiler's cost, not the host's mood."""
    reps = 5
    dur = 0.25

    def timed() -> float:
        t0 = time.perf_counter()
        _burn(dur)
        return time.perf_counter() - t0

    off, on = [], []
    _burn(0.05)  # warm the allocator/caches
    for _ in range(reps):
        off.append(timed())
        profiler.start(DEFAULT_HZ)
        try:
            on.append(timed())
        finally:
            profiler.stop()
    base = min(off)
    spread = (max(off) - base) / base
    if spread > 0.08:
        pytest.skip(f"host too noisy to resolve a 3% gate "
                    f"(baseline spread {spread:.1%})")
    overhead = min(on) / base - 1.0
    assert overhead <= 0.03, f"profiler overhead {overhead:.2%} > 3%"
    profiler.reset()


# -- critpath ----------------------------------------------------------------

def _span(name, ts, dur, sid, parent=None, trace=1):
    return {"name": name, "ts": ts, "dur": dur, "tid": 0,
            "trace": trace, "id": sid, "parent": parent}


def test_critpath_synthetic_tree_buckets_sum_to_wall():
    spans = [
        _span("reduce_task", 0.0, 10.0, 1),
        _span("fetch", 0.0, 6.0, 2, parent=1),
        _span("overlap_pack", 2.0, 2.0, 3, parent=2),
        _span("merge", 5.0, 5.0, 4, parent=1),
        _span("merge.wait", 0.0, 5.0, 5, parent=4),
    ]
    block = critpath.analyze(spans)
    assert block["root"] == "reduce_task"
    assert block["wall_s"] == pytest.approx(10.0)
    b = block["buckets"]
    # priority partition: merge owns [5,10]; decompress_pack beats
    # fetch on [2,4]; fetch keeps [0,2]+[4,5]; wait is fully shadowed
    assert b["merge"]["critical_s"] == pytest.approx(5.0)
    assert b["decompress_pack"]["critical_s"] == pytest.approx(2.0)
    assert b["fetch"]["critical_s"] == pytest.approx(3.0)
    assert b["wait"]["critical_s"] == pytest.approx(0.0)
    assert b["wait"]["busy_s"] == pytest.approx(5.0)
    total = sum(rec["critical_s"] for rec in b.values()) + block["idle_s"]
    assert total == pytest.approx(block["wall_s"], rel=0.05)
    # busy can exceed the wall (that IS the overlap)
    assert sum(rec["busy_s"] for rec in b.values()) > block["wall_s"]
    # longest dependency chain: root -> fetch (6s) -> overlap_pack (2s)
    names = [s["name"] for s in block["critical_path"]]
    assert names == ["reduce_task", "fetch", "overlap_pack"]
    # trio reconciliation (critical seconds)
    assert block["trio"]["total_fetch_time"] == pytest.approx(3.0)
    assert block["trio"]["total_merge_time"] == pytest.approx(7.0)


def test_critpath_idle_and_rootless():
    # gap between spans = idle
    spans = [_span("reduce_task", 0.0, 4.0, 1),
             _span("fetch", 0.0, 1.0, 2, parent=1),
             _span("merge", 3.0, 1.0, 3, parent=1)]
    block = critpath.analyze(spans)
    assert block["idle_s"] == pytest.approx(2.0)
    # no reduce_task root (a supplier-side process): whole-window scope
    spans = [_span("net.serve", 1.0, 2.0, 7)]
    block = critpath.analyze(spans)
    assert block["root"] is None
    assert block["wall_s"] == pytest.approx(2.0)
    assert block["buckets"]["serve"]["critical_s"] == pytest.approx(2.0)
    assert critpath.analyze([]) is None


def test_critpath_span_buckets_cover_known_names():
    """Registry lockstep: every SPAN_REGISTRY name and every timer
    name critpath buckets must stay known to the table (a renamed
    timer silently falling into 'other' would corrupt the
    accounting)."""
    from uda_tpu.utils.metrics import SPAN_REGISTRY
    for name in SPAN_REGISTRY:
        if name in ("reduce_task", "net.stats"):
            continue  # the root frames; stats polls are other
        assert name in critpath.SPAN_BUCKETS, name
    for bucket in critpath.SPAN_BUCKETS.values():
        assert bucket in critpath.BUCKET_PRIORITY


def _run_quick_merge(tmp_path, cfg_extra=None):
    root = str(tmp_path / "mof")
    job = "timeacct"
    expected = make_mof_tree(root, job, num_maps=4, num_reducers=1,
                             records_per_map=400, seed=3)
    cfg = Config(dict({"mapred.rdma.buf.size": 8}, **(cfg_extra or {})))
    engine = DataEngine(DirIndexResolver(root), cfg)
    blocks = []
    try:
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes",
                          cfg)
        mm.run(job, map_ids(job, 4), 0,
               lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    assert len(expected[0]) == 1600
    return b"".join(blocks)


def test_critpath_real_quick_merge_and_final_record(tmp_path):
    """On a real (quick) merge with spans on: buckets sum to the task
    wall within 5%, and the block lands in the StatsReporter final
    record plus the MSG_STATS introspection snapshot."""
    metrics.enable_stats()
    out = _run_quick_merge(tmp_path)
    assert out
    block = critpath.time_accounting_block()
    assert block is not None and block["root"] == "reduce_task"
    total = (sum(rec["critical_s"] for rec in block["buckets"].values())
             + block["idle_s"])
    assert total == pytest.approx(block["wall_s"], rel=0.05)
    assert metrics.get("critpath.analyses") > 0
    # the StatsReporter final record carries it
    rep = StatsReporter(metrics, interval_s=60, out=open(os.devnull, "w"))
    rec = rep.report_once(final=True)
    assert rec["counters"]["total_fetch_time"] >= 0
    assert rec["time_accounting"]["root"] == "reduce_task"
    # MSG_STATS scrape surface: MergeManager installed the provider
    snap = introspection_snapshot()
    ta = snap["providers"]["time_accounting"]
    assert ta.get("root") == "reduce_task" or ta.get("available") is False
    rep.stop(final=False)


def test_buckets_from_counters_fallback():
    block = critpath.buckets_from_counters(
        {"fetch_time": 2.0, "merge_time": 3.0, "wait_mem_time": 0.5,
         "overlap_pack_time": 1.0, "emit_time": 0.25})
    assert block["kind"] == "busy_seconds_from_counters"
    assert block["buckets"]["fetch"] == pytest.approx(2.0)
    assert block["buckets"]["serve"] == pytest.approx(0.25)
    assert block["trio"]["total_merge_time"] == pytest.approx(4.0)


# -- exports: span file lanes + standalone critpath --------------------------

def test_span_export_profile_records_and_tools(tmp_path):
    metrics.enable_stats()
    profiler.start(200)
    try:
        _burn(0.3, "net.serve")
    finally:
        profiler.stop()
    path = str(tmp_path / "spans.jsonl")
    n = metrics.export_spans_jsonl(path)
    assert n >= 1
    recs = [json.loads(ln) for ln in open(path)]
    profs = [r for r in recs if r.get("kind") == "profile"]
    assert any(r["span"] == "net.serve" and r["self"] > 0
               for r in profs)
    # trace_merge renders a profile lane next to the span lanes
    out = str(tmp_path / "trace.json")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/trace_merge.py"),
         path, "--out", out], capture_output=True, text=True,
        timeout=120)
    assert res.returncode == 0, res.stderr
    assert "1 profile lane(s)" in res.stdout
    trace = json.load(open(out))
    assert any(e["name"].startswith("profile:net.serve")
               for e in trace["traceEvents"])
    # standalone critpath over the same file
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/critpath.py"),
         path, "--json"], capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    block = json.loads(res.stdout)
    assert block["buckets"]["serve"]["busy_s"] > 0
    profiler.reset()


# -- histogram bucket export (satellite) -------------------------------------

def test_histogram_summary_buckets_recompute_percentiles():
    metrics.enable_stats()
    rng = np.random.default_rng(5)
    for v in rng.gamma(2.0, 40.0, size=500):
        metrics.observe("fetch.latency_ms", float(v))
    s = metrics.histogram_summaries()["fetch.latency_ms"]
    assert s["buckets"] and all(len(b) == 2 for b in s["buckets"])
    assert sum(c for _, c in s["buckets"]) == s["count"] == 500
    # offline recompute == the live estimator, at ARBITRARY p
    for p in (10, 25, 50, 75, 90, 95, 99, 99.9):
        live = metrics.percentile("fetch.latency_ms", p)
        off = percentile_from_summary(s, p)
        assert off == pytest.approx(live, rel=1e-9), p
    # json-safe (no inf edges) and pre-bucket summaries degrade to 0
    json.dumps(s)
    assert percentile_from_summary({"count": 3}, 50) == 0.0


# -- perfwatch ---------------------------------------------------------------

def test_perfwatch_ingests_all_historical_artifacts(tmp_path):
    out = str(tmp_path / "traj.json")
    assert perfwatch.ingest([], out) == 0
    doc = json.load(open(out))
    entries = doc["entries"]
    assert len(entries) > 100
    workloads = {e["workload"] for e in entries}
    assert {"pipeline", "net", "terasort_singlechip",
            "regression_small"} <= workloads
    # every entry normalized: required keys + sane directions
    for e in entries:
        assert e["direction"] in ("up", "down", "info")
        assert isinstance(e["value"], (int, float))
    # the committed trajectory is in lockstep with the extractors
    committed = json.load(open(os.path.join(REPO,
                                            "PERF_TRAJECTORY.json")))
    committed_keys = {(e["run"], e["workload"], e["metric"])
                      for e in committed["entries"]}
    fresh_keys = {(e["run"], e["workload"], e["metric"])
                  for e in entries}
    assert fresh_keys <= committed_keys, (
        "historical entries missing from the committed "
        "PERF_TRAJECTORY.json — re-run scripts/perfwatch.py ingest")


def test_perfwatch_check_green_on_identical_red_on_slowdown(tmp_path):
    traj = str(tmp_path / "traj.json")
    perfwatch.ingest([os.path.join(REPO, "BENCH_PIPELINE_r09.json")],
                     traj)
    point = os.path.join(REPO, "BENCH_PIPELINE_r09.json")
    assert perfwatch.check(point, traj, 0.25, append=False) == 0
    # inject a 30% slowdown -> demonstrably red at the default band
    data = json.load(open(point))
    for key in list(data):
        if key.endswith("_MBps"):
            data[key] = round(data[key] * 0.7, 1)
    slow = str(tmp_path / "slow.json")
    json.dump(data, open(slow, "w"))
    assert perfwatch.check(slow, traj, 0.25, append=False) == 1
    # correctness booleans gate at tol 0 regardless of the band
    data = json.load(open(point))
    data["identity"]["all_identical"] = False
    broken = str(tmp_path / "broken.json")
    json.dump(data, open(broken, "w"))
    assert perfwatch.check(broken, traj, 5.0, append=False) == 1
    # improvements and unknown metrics never fail
    data = json.load(open(point))
    data["sorted_pipelined_MBps"] *= 2
    fast = str(tmp_path / "fast.json")
    json.dump(data, open(fast, "w"))
    assert perfwatch.check(fast, traj, 0.25, append=False) == 0


def test_perfwatch_check_append_and_new_baseline(tmp_path):
    traj = str(tmp_path / "traj.json")
    perfwatch.ingest([os.path.join(REPO, "BENCH_NET_r07.json")], traj)
    # a point with no matching workload: everything 'new', still green,
    # --append makes it the next baseline
    point = str(tmp_path / "point.json")
    json.dump({"bench": "net_loopback", "quick": True,
               "single_stream": {"evloop": {"mb_per_s": 100.0}}},
              open(point, "w"))
    assert perfwatch.check(point, traj, 0.25, append=True) == 0
    doc = json.load(open(traj))
    assert any(e["workload"] == "net_quick" for e in doc["entries"])
    # now a regressed second quick point fails against it
    slow = str(tmp_path / "slow.json")
    json.dump({"bench": "net_loopback", "quick": True,
               "single_stream": {"evloop": {"mb_per_s": 60.0}}},
              open(slow, "w"))
    assert perfwatch.check(slow, traj, 0.25, append=False) == 1


def test_perfwatch_offline_hist_percentiles_from_telemetry():
    """perfwatch consumes the exported bucket boundaries+counts: p90
    (not in the inline trio) recomputed from a telemetry block alone
    matches the live estimator."""
    from uda_tpu.utils.stats import telemetry_block
    metrics.enable_stats()
    for v in (1.0, 2.0, 4.0, 8.0, 100.0, 250.0):
        metrics.observe("fetch.latency_ms", v)
    data = {"metric": "terasort_singlechip_shuffle_merge_gbps",
            "value": 1.0, "telemetry": telemetry_block()}
    entries = perfwatch.extract("BENCH_X", data)
    p90 = [e for e in entries
           if e["metric"] == "hist_fetch.latency_ms_p90"]
    assert p90 and p90[0]["direction"] == "info"
    assert p90[0]["value"] == pytest.approx(
        metrics.percentile("fetch.latency_ms", 90), rel=1e-9)


def test_perfwatch_cli_roundtrip(tmp_path):
    """The ci.sh surface: ingest + --check over the CLI."""
    traj = str(tmp_path / "traj.json")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/perfwatch.py"),
         "ingest", os.path.join(REPO, "BENCH_PIPELINE_r09.json"),
         "--out", traj], capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/perfwatch.py"),
         "--check", os.path.join(REPO, "BENCH_PIPELINE_r09.json"),
         "--trajectory", traj], capture_output=True, text=True,
        timeout=120)
    assert res.returncode == 0, res.stderr + res.stdout
    assert "0 regression(s)" in res.stdout


# -- forensics wiring (satellite) --------------------------------------------

@pytest.mark.faults
def test_stall_dump_carries_profile_and_time_accounting(tmp_path):
    """The forensics rung: a watchdog stall dump AND the flightrec
    post-mortem carry the span-attributed profile slice when the
    profiler is armed — and neither ever arms it themselves."""
    from uda_tpu.utils.flightrec import flightrec
    metrics.enable_stats()
    profiler.start(200)
    stop = threading.Event()

    def busy():
        with metrics.span("net.serve"):
            x = np.arange(2048)
            while not stop.is_set():
                (x * x).sum()

    t = threading.Thread(target=busy)
    t.start()
    wd = StallWatchdog(0.3, lambda: 42, name="wd-timeacct").start()
    try:
        deadline = time.monotonic() + 10
        while not wd.fired and time.monotonic() < deadline:
            time.sleep(0.02)
        assert wd.fired
        assert "sampling profile" in wd.last_dump
        assert "net.serve" in wd.last_dump
        # the stall also dumped the black box, with the profile block
        rep = flightrec.reports[-1]
        assert rep["cause"] == "stall"
        assert rep["profile"]["samples"] > 0
        assert "net.serve" in rep["profile"]["spans"]
    finally:
        stop.set()
        t.join()
        wd.stop()
        profiler.stop()
        profiler.reset()


def test_dump_without_profiler_omits_block_not_raises():
    """Disarmed profiler -> the dump simply has no profile section
    (omission, never an error inside an unwind)."""
    from uda_tpu.utils.flightrec import flightrec
    from uda_tpu.utils.watchdog import dump_diagnostics
    assert not profiler.armed
    text = dump_diagnostics("unit")
    assert "sampling profile" not in text
    flightrec.record("unit", x=1)
    flightrec.dump("unit-test")
    assert "profile" not in flightrec.reports[-1]
