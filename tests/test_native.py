"""Native codec/reader parity vs the pure-Python reference
(SURVEY §2 [native] rows; Python side is the semantic oracle)."""

import os

import numpy as np
import pytest

from uda_tpu import native
from uda_tpu.utils import ifile, vint
from uda_tpu.utils.errors import StorageError

pytestmark = pytest.mark.skipif(
    not (native.available() or native.build()),
    reason="native library not built and build failed")


def _records(n=200, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.bytes(int(rng.integers(0, 50))),
             rng.bytes(int(rng.integers(0, 300)))) for _ in range(n)]


def test_crack_parity_with_python():
    recs = _records()
    buf = ifile.write_records(recs)
    py = ifile.crack(buf)
    nat = native.crack_native(buf)
    assert nat.num_records == py.num_records
    for arr_py, arr_nat in [(py.key_off, nat.key_off), (py.key_len, nat.key_len),
                            (py.val_off, nat.val_off), (py.val_len, nat.val_len)]:
        assert arr_py.tolist() == arr_nat.tolist()
    assert list(nat.iter_records()) == recs


def test_crack_partial_parity():
    recs = _records(50, seed=1)
    buf = ifile.write_records(recs)
    for cut in [0, 1, 7, len(buf) // 2, len(buf) - 3, len(buf)]:
        py_b, py_c, py_e = ifile.crack_partial(buf[:cut])
        na_b, na_c, na_e = native.crack_partial_native(buf[:cut])
        assert (py_b.num_records, py_c, py_e) == (na_b.num_records, na_c, na_e), cut
        assert list(py_b.iter_records()) == list(na_b.iter_records())


def test_crack_native_errors():
    with pytest.raises(StorageError):
        native.crack_native(b"\xfe\xfe")  # klen=-2: corrupt
    with pytest.raises(StorageError):
        native.crack_native(ifile.write_records([(b"k", b"v")])[:-2])


def test_write_records_parity():
    recs = _records(120, seed=5)
    buf = ifile.write_records(recs)
    batch = ifile.crack(buf)
    assert native.write_records_native(batch) == buf
    # no-EOF variant reframes just the records
    assert native.write_records_native(batch, write_eof=False) \
        == buf[:-len(ifile.EOF_MARKER)]


def test_decode_vlongs_parity():
    vals = [0, 1, -1, 127, -112, 128, -113, 2**40, -(2**40), 2**63 - 1,
            -(2**63)]
    buf = b"".join(vint.encode_vlong(v) for v in vals)
    got = native.decode_vlongs_native(buf)
    assert got.tolist() == vals
    with pytest.raises(IndexError):
        native.decode_vlongs_native(buf[:-1], count=len(vals))


def test_value_ending_in_eof_marker_bytes():
    # the trap case: a record VALUE containing/ending with 0xFFFF must not
    # terminate the native scan
    recs = [(b"k1", b"data\xff\xff"), (b"k2", b"\xff\xff"), (b"k3", b"x")]
    buf = ifile.write_records(recs)
    nat = native.crack_native(buf)
    assert list(nat.iter_records()) == recs


def test_read_pool(tmp_path):
    data = np.random.default_rng(0).bytes(1 << 20)
    path = str(tmp_path / "blob")
    with open(path, "wb") as f:
        f.write(data)
    fd = os.open(path, os.O_RDONLY)
    try:
        with native.ReadPool(threads=3) as pool:
            tags = {}
            for i in range(16):
                off = i * (1 << 16)
                tags[pool.submit(fd, off, 1 << 16)] = off
            got = {}
            while len(got) < 16:
                for tag, buf in pool.poll(min_events=1, timeout=5.0):
                    got[tag] = buf
            for tag, off in tags.items():
                assert got[tag].tobytes() == data[off:off + (1 << 16)]
    finally:
        os.close(fd)


def test_read_pool_submit_batch(tmp_path):
    """The C15 batch-submission half: N jobs in ONE native call, tags
    in job order, completions via the same get_events surface — with
    per-tag isolation (an EOF-shortened read hurts only its own tag)."""
    data = np.random.default_rng(1).bytes(1 << 19)
    path = str(tmp_path / "blob")
    with open(path, "wb") as f:
        f.write(data)
    fd = os.open(path, os.O_RDONLY)
    try:
        with native.ReadPool(threads=2) as pool:
            assert pool.backend() in ("io_uring", "pool")
            jobs = [(fd, 0, 4096), (fd, 4096, 4096),
                    (fd, (1 << 19) - 100, 4096),  # EOF-clamped
                    (fd, 1 << 18, 8192)]
            tags = pool.submit_batch(jobs)
            assert len(tags) == len(jobs)
            got = {}
            while len(got) < len(jobs):
                for tag, buf in pool.poll(min_events=1, timeout=5.0):
                    got[tag] = buf
            assert bytes(got[tags[0]]) == data[:4096]
            assert bytes(got[tags[1]]) == data[4096:8192]
            assert bytes(got[tags[2]]) == data[-100:]
            assert bytes(got[tags[3]]) == data[1 << 18:(1 << 18) + 8192]
            assert pool.submit_batch([]) == []
    finally:
        os.close(fd)


def test_read_pool_backend_on_this_host():
    """The ladder's runtime half: a 4.4-class kernel must land on the
    worker pool even though the io_uring backend may be compiled in;
    a newer kernel may legitimately report io_uring — both are valid
    rungs of the same ABI."""
    with native.ReadPool(threads=1) as pool:
        b = pool.backend()
        assert b in ("io_uring", "pool")


def test_use_native_flag_gates_codec(tmp_path):
    # regression: uda.tpu.use.native=false must disable the native codec
    # dispatch in ifile, not only the DataEngine reader
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.utils import ifile
    from uda_tpu.utils.config import Config

    try:
        DataEngine(DirIndexResolver(str(tmp_path)),
                   Config({"uda.tpu.use.native": False})).stop()
        assert ifile._native_mod() is None
    finally:
        ifile.set_native_enabled(True)
    assert ifile._native_mod() is not None


def test_bridge_reduce_exit_stops_owned_engine(tmp_path):
    from tests.helpers import make_mof_tree, map_ids
    from uda_tpu.bridge import Cmd, UdaBridge, form_cmd
    from uda_tpu.mofserver import DirIndexResolver
    from uda_tpu.utils.errors import StorageError
    import threading

    make_mof_tree(str(tmp_path), "jobN", 1, 1, 5)

    class H:
        def __init__(self):
            self.done = threading.Event()
            self._r = DirIndexResolver(str(tmp_path))

        def data_from_uda(self, d, n): pass

        def fetch_over_message(self): self.done.set()

        def get_path_uda(self, j, m, r): return self._r.resolve(j, m, r)

        def get_conf_data(self, n, d): return ""

        def failure_in_uda(self, e): self.done.set()

    h = H()
    b = UdaBridge()
    b.start(True, [], h)
    b.do_command(form_cmd(Cmd.INIT, ["jobN", "0", "1", "uda.tpu.RawBytes"]))
    b.do_command(form_cmd(Cmd.FETCH, ["h", "jobN", map_ids("jobN", 1)[0], "0"]))
    b.do_command(form_cmd(Cmd.FINAL, []))
    assert h.done.wait(30)
    engine = b._owned_engine
    assert engine is not None
    b.reduce_exit()
    assert b._owned_engine is None
    with pytest.raises(StorageError):
        from uda_tpu.mofserver import ShuffleRequest
        engine.fetch(ShuffleRequest("jobN", "x", 0, 0, 10))


def test_read_pool_short_read_at_eof(tmp_path):
    path = str(tmp_path / "small")
    with open(path, "wb") as f:
        f.write(b"hello")
    fd = os.open(path, os.O_RDONLY)
    try:
        with native.ReadPool(threads=1) as pool:
            tag = pool.submit(fd, 0, 100)
            [(t, buf)] = pool.poll()
            assert t == tag and buf.tobytes() == b"hello"
    finally:
        os.close(fd)
