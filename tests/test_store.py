"""Elastic disaggregated MOF store (ISSUE 18): backend parity, the
spill ladder, degraded-backend failover, mid-job join/drain, and the
checkpoint-resume locator revalidation.

The invariants under test:

- byte parity: a partition reads byte-identical through every backend
  arrangement (local fd path, blob tier, shadow twins), for plain AND
  compressed jobs, while never-migrated local partitions keep the
  zero-copy FdSlice fast path;
- the spill ladder bounds local retention at the watermark and the
  spilled shuffle still merges byte-identically;
- a killed blob backend fails over to the surviving tier with zero
  fallback signals and typed, structured errors;
- a mid-job joiner widens in-flight segments and rescues a fetch whose
  primary keeps failing; a drained supplier's partitions remain
  fetchable (migrated, not reconstructed);
- a resumed checkpointed task revalidates spilled locators before
  trusting them.
"""

import os
import threading
import time

import numpy as np
import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.merger import (HostRoutingClient, LocalFetchClient,
                            MergeManager, Segment)
from uda_tpu.mofserver import (BackendHealth, BlobStore, DataEngine,
                               DirIndexResolver, LocalFdStore,
                               ShuffleRequest, StoreManager)
from uda_tpu.mofserver.store import spill_watermark_bytes
from uda_tpu.mofserver.writer import MOFWriter
from uda_tpu.net import RemoteFetchClient, ShuffleServer, wire
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import FallbackSignal, StorageError, StoreError
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.ifile import crack
from uda_tpu.utils.metrics import metrics

from uda_tpu.utils import comparators

KT = comparators.get_key_type("uda.tpu.RawBytes")


def _counter(name: str) -> float:
    return metrics.get(name) or 0.0


@pytest.fixture(autouse=True)
def _quiesce_ambient(request):
    """Under the chaos tier, these tests craft exact backend states and
    arm their own scoped failpoints — the rung's ambient schedule must
    neither fire inside them nor shift phase because of them (the
    test_checkpoint idiom: the in-process analogue of a subprocess
    scrubbing UDA_FAILPOINTS from its env)."""
    if request.node.get_closest_marker("faults"):
        with failpoints.quiesced():
            yield
    else:
        yield


def _fetch_records(engine, job, mids, reduce_id=0):
    got = []
    for mid in mids:
        offset, chunks = 0, []
        while True:
            res = engine.fetch(
                ShuffleRequest(job, mid, reduce_id, offset, 1 << 20))
            chunks.append(res.data)
            offset += len(res.data)
            if res.is_last:
                break
        got += list(crack(b"".join(chunks)).iter_records())
    return sorted(got)


def _manager(tmp_path, job, num_maps=3, num_reducers=2, **kw):
    local = os.path.join(str(tmp_path), "local")
    blob = os.path.join(str(tmp_path), "blob")
    expected = make_mof_tree(local, job, num_maps, num_reducers, 40,
                             seed=11)
    resolver = DirIndexResolver(local)
    engine = DataEngine(resolver)
    mgr = StoreManager(resolver, blob, **kw)
    engine.attach_store(mgr)
    return expected, engine, mgr


# -- backends ----------------------------------------------------------------

def test_local_store_reads_exact_ranges(tmp_path):
    p = str(tmp_path / "obj")
    payload = bytes(range(256)) * 64
    with open(p, "wb") as f:
        f.write(payload)
    store = LocalFdStore()
    assert store.read(p, 100, 1000) == payload[100:1100]
    got = store.read_ranges(p, [(0, 16), (4096, 256), (16000, 64)])
    assert got == [payload[0:16], payload[4096:4352], payload[16000:16064]]
    with pytest.raises(StoreError) as ei:
        store.read(p, len(payload) - 10, 100)
    assert ei.value.cause == "short_read" and ei.value.backend == "local"
    with pytest.raises(StoreError) as ei:
        store.read(str(tmp_path / "nope"), 0, 10)
    assert ei.value.cause == "missing"
    store.close()


def test_blob_store_vectored_parity_and_put_crc(tmp_path):
    blob = BlobStore(str(tmp_path / "blob"))
    src = str(tmp_path / "src")
    rng = np.random.default_rng(3)
    payload = rng.bytes(3 << 20)  # multi-chunk: exercises streamed copy
    with open(src, "wb") as f:
        f.write(payload)
    dst = os.path.join(blob.root, "j", "m", "file.out")
    nbytes, crc = blob.put_file(src, dst, key="j/m")
    assert nbytes == len(payload)
    assert blob.object_crc(dst) == crc
    # vectored read parity vs the scalar floor, including adjacent and
    # gapped ranges in one run
    ranges = [(0, 100), (100, 50), (8192, 1024), (1 << 20, 4096)]
    vec = blob.read_ranges(dst, ranges)
    assert vec == [payload[o:o + n] for o, n in ranges]
    assert _counter("store.blob.reads") > 0
    blob.close()


def test_spill_watermark_resolution():
    assert spill_watermark_bytes(
        Config({"uda.tpu.store.spill.watermark.mb": 8})) == 8 << 20
    assert spill_watermark_bytes(Config()) == 0  # ladder off by default

    class Budget:
        host_budget_bytes = 1000

    assert spill_watermark_bytes(
        Config({"uda.tpu.store.spill.frac": 0.5}), budget=Budget()) == 500


def test_from_config_disabled_without_blob_root(tmp_path):
    resolver = DirIndexResolver(str(tmp_path))
    assert StoreManager.from_config(resolver, Config()) is None
    mgr = StoreManager.from_config(
        resolver, Config({"uda.tpu.store.blob.root":
                          str(tmp_path / "blob"),
                          "uda.tpu.store.spill.watermark.mb": 4}))
    assert mgr is not None and mgr.watermark_bytes == 4 << 20
    mgr.close()


# -- migration parity --------------------------------------------------------

def test_migration_byte_parity_and_zero_copy_preserved(tmp_path):
    job = "jobP"
    expected, engine, mgr = _manager(tmp_path, job)
    mids = map_ids(job, 3)
    try:
        base = {r: _fetch_records(engine, job, mids, r) for r in range(2)}
        assert base == {r: sorted(expected[r]) for r in range(2)}
        # zero-copy stays engaged for local partitions (cache is warm
        # after the fetches above)
        req = ShuffleRequest(job, mids[2], 0, 0, 1 << 20)
        plan = engine.try_plan(req)
        assert plan is not None
        plan.release()
        mgr.migrate(job, mids[0], reason="spill", shadow=True)
        mgr.migrate(job, mids[1], reason="spill", shadow=False)
        for r in range(2):
            assert _fetch_records(engine, job, mids, r) == base[r]
        # the blob-managed partition can no longer plan a zero-copy
        # slice; the untouched local one still can
        engine.fetch(ShuffleRequest(job, mids[0], 0, 0, 1 << 20))
        assert engine.try_plan(
            ShuffleRequest(job, mids[0], 0, 0, 1 << 20)) is None
        plan = engine.try_plan(req)
        assert plan is not None
        plan.release()
        # the non-shadow migration removed the local bytes entirely
        assert not os.path.exists(mgr.migrations()[1]["src"])
        assert _counter("store.migrated.bytes") > 0
    finally:
        mgr.close()
        engine.stop()


def test_migration_byte_parity_compressed_end_to_end(tmp_path):
    """A compressed job merges byte-identically after its partitions
    migrate to the blob tier (the decompressor never learns which tier
    served the compressed bytes)."""
    from uda_tpu.compress import DecompressingClient, get_codec

    codec = get_codec("zlib")
    job = "jobC"
    local = os.path.join(str(tmp_path), "local")
    blob = os.path.join(str(tmp_path), "blob")
    rng = np.random.default_rng(29)
    writer = MOFWriter(local, job, codec=codec)
    for m in range(4):
        recs = sorted((rng.bytes(8), rng.bytes(64)) for _ in range(80))
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])

    def merge_once():
        resolver = DirIndexResolver(local)
        engine = DataEngine(resolver)
        mgr = StoreManager(resolver, blob)
        engine.attach_store(mgr)
        blocks = []
        mm = MergeManager(DecompressingClient(LocalFetchClient(engine),
                                              codec), KT, Config())
        try:
            mm.run(job, writer.map_ids, 0,
                   lambda b: blocks.append(bytes(b)))
        finally:
            engine.stop()
        return b"".join(blocks), mgr

    ref, mgr0 = merge_once()
    mgr0.close()
    # migrate everything, then the same merge must emit the same bytes
    resolver = DirIndexResolver(local)
    mgr = StoreManager(resolver, blob)
    for mid in writer.map_ids:
        mgr.migrate(job, mid, reason="spill", shadow=False)
    mgr.close()
    out, mgr1 = merge_once()
    mgr1.close()
    assert out == ref


def test_stripe_locators_survive_migration(tmp_path):
    """A coded (v2 UDIX) partition's stripe section is preserved
    byte-for-byte by the index rewrite at the blob root."""
    from uda_tpu.coding import parse_scheme
    from uda_tpu.mofserver import read_index_file

    job = "jobV2"
    local = os.path.join(str(tmp_path), "local")
    rng = np.random.default_rng(5)
    writer = MOFWriter(local, job, scheme=parse_scheme("rs:2:3"))
    recs = sorted((rng.bytes(8), rng.bytes(40)) for _ in range(60))
    writer.write(f"attempt_{job}_m_000000_0", [recs])
    mid = writer.map_ids[0]
    src_idx = os.path.join(local, job, mid, "file.out.index")
    before = read_index_file(src_idx, "x")
    resolver = DirIndexResolver(local)
    mgr = StoreManager(resolver, os.path.join(str(tmp_path), "blob"))
    entry = mgr.migrate(job, mid, reason="spill", shadow=False)
    after = read_index_file(entry["dst"] + ".index", entry["dst"])
    assert [(r.start_offset, r.raw_length, r.part_length)
            for r in after] == \
        [(r.start_offset, r.raw_length, r.part_length) for r in before]
    assert after[0].stripe is not None
    assert (after[0].stripe.k, after[0].stripe.n) == \
        (before[0].stripe.k, before[0].stripe.n)
    assert after[0].stripe.parity == before[0].stripe.parity
    mgr.close()


# -- the spill ladder --------------------------------------------------------

def test_spill_ladder_bounds_retention_and_keeps_parity(tmp_path):
    job = "jobL"
    local = os.path.join(str(tmp_path), "local")
    blob = os.path.join(str(tmp_path), "blob")
    resolver = DirIndexResolver(local)
    mgr = StoreManager(resolver, blob, watermark_bytes=16 << 10)
    writer = MOFWriter(local, job, store=mgr)
    rng = np.random.default_rng(7)
    expected = []
    peak = 0
    for m in range(12):
        recs = sorted((rng.bytes(8), rng.bytes(512)) for _ in range(16))
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])
        peak = max(peak, mgr.retained_bytes())
        expected += recs
    # retention never exceeded watermark + one partition (the write
    # that crosses the line spills synchronously before returning)
    assert mgr.retained_bytes() <= mgr.watermark_bytes
    assert peak <= mgr.watermark_bytes + (10 << 10)
    assert len(mgr.migrations()) > 0
    assert _counter("store.spilled.bytes") > 0
    engine = DataEngine(resolver)
    engine.attach_store(mgr)
    try:
        assert _fetch_records(engine, job, writer.map_ids) == \
            sorted(expected)
    finally:
        mgr.close()
        engine.stop()


def test_failed_spill_keeps_partition_servable(tmp_path):
    """A spill that dies mid-PUT is an optimization failure, never a
    data loss: the partition stays locally servable and the on-air
    migration gauge unwinds."""
    job = "jobFS"
    expected, engine, mgr = _manager(tmp_path, job, num_maps=1,
                                     num_reducers=1)
    mid = map_ids(job, 1)[0]
    try:
        with failpoints.scoped("store.put=error"):
            mgr.account_write(job, mid, 1 << 30)  # far over watermark?
            # no watermark set -> no spill; drive the ladder directly
            with pytest.raises(StorageError):
                mgr.migrate(job, mid, reason="spill")
        assert metrics.get_gauge("store.migrate.bytes.on_air") == 0
        assert _fetch_records(engine, job, [mid]) == sorted(expected[0])
    finally:
        mgr.close()
        engine.stop()


# -- degraded-backend failover ----------------------------------------------

@pytest.mark.faults
def test_blob_kill_fails_over_byte_identical(tmp_path):
    job = "jobFO"
    expected, engine, mgr = _manager(tmp_path, job)
    mids = map_ids(job, 3)
    try:
        base = {r: _fetch_records(engine, job, mids, r) for r in range(2)}
        for mid in mids:
            mgr.migrate(job, mid, reason="spill", shadow=True)
        f0 = _counter("store.failover")
        with failpoints.scoped("store.get=error::match:blob"):
            for r in range(2):
                assert _fetch_records(engine, job, mids, r) == base[r]
        assert _counter("store.failover") > f0
        assert _counter("store.errors") > 0
        # the typed error carries STRUCTURED cause/backend (UDA005)
        assert mgr.health.faults("blob") >= 0  # health saw the faults
    finally:
        mgr.close()
        engine.stop()


@pytest.mark.faults
def test_batch_plane_fails_over_per_request(tmp_path):
    job = "jobFB"
    expected, engine, mgr = _manager(tmp_path, job, num_reducers=1)
    mids = map_ids(job, 3)
    try:
        for mid in mids:
            mgr.migrate(job, mid, reason="spill", shadow=True)
        with failpoints.scoped("store.get=error::match:blob"):
            futs = engine.submit_batch(
                [ShuffleRequest(job, m, 0, 0, 1 << 20) for m in mids])
            datas = [f.result() for f in futs]
        got = sorted(sum((list(crack(d.data).iter_records())
                          for d in datas), []))
        assert got == sorted(expected[0])
        assert _counter("store.failover") > 0
    finally:
        mgr.close()
        engine.stop()


@pytest.mark.faults
def test_no_twin_surfaces_typed_store_error(tmp_path):
    job = "jobNT"
    _, engine, mgr = _manager(tmp_path, job, num_maps=1, num_reducers=1)
    mid = map_ids(job, 1)[0]
    try:
        mgr.migrate(job, mid, reason="spill", shadow=False)  # no twin
        with failpoints.scoped("store.get=error::match:blob"):
            with pytest.raises(StoreError) as ei:
                engine.fetch(ShuffleRequest(job, mid, 0, 0, 1 << 20))
        assert ei.value.cause == "get" and ei.value.backend == "blob"
    finally:
        mgr.close()
        engine.stop()


@pytest.mark.faults
def test_boxed_backend_reroutes_proactively(tmp_path):
    job = "jobRR"
    _, engine, mgr = _manager(tmp_path, job, num_maps=1, num_reducers=1,
                              health=BackendHealth(threshold=2,
                                                   penalty_s=30.0))
    mid = map_ids(job, 1)[0]
    try:
        mgr.migrate(job, mid, reason="spill", shadow=True)
        with failpoints.scoped("store.get=error::match:blob"):
            engine.fetch(ShuffleRequest(job, mid, 0, 0, 1 << 20))
            engine.fetch(ShuffleRequest(job, mid, 0, 0, 1 << 20))
        assert mgr.health.boxed("blob")
        r0 = _counter("store.rerouted")
        engine.fetch(ShuffleRequest(job, mid, 0, 0, 1 << 20))
        assert _counter("store.rerouted") > r0  # twin served FIRST,
        # without burning an attempt against the boxed tier
    finally:
        mgr.close()
        engine.stop()


def test_backend_health_box_and_parole():
    h = BackendHealth(threshold=2, penalty_s=0.05)
    assert not h.punish("blob")
    assert h.punish("blob")  # second fault boxes
    assert h.boxed("blob")
    time.sleep(0.08)
    assert not h.boxed("blob")   # penalty expired -> parole
    assert h.punish("blob")      # ONE more fault re-boxes
    h.forgive("blob")
    h.forgive("blob")
    assert not h.boxed("blob") and h.faults("blob") == 0


@pytest.mark.faults
def test_store_faults_feed_recovery_ledger(tmp_path):
    from uda_tpu.merger.merge_manager import PenaltyBox
    from uda_tpu.merger.recovery import RecoveryLedger

    ledger = RecoveryLedger(PenaltyBox())
    job = "jobRL"
    _, engine, mgr = _manager(tmp_path, job, num_maps=1, num_reducers=1,
                              recovery=ledger)
    mid = map_ids(job, 1)[0]
    try:
        mgr.migrate(job, mid, reason="spill", shadow=True)
        with failpoints.scoped("store.get=error::match:blob"):
            engine.fetch(ShuffleRequest(job, mid, 0, 0, 1 << 20))
        snap = ledger.snapshot()
        kinds = [e["kind"] for e in snap["events"]]
        assert "store" in kinds  # the storage rung of the ladder
    finally:
        mgr.close()
        engine.stop()


# -- checkpoint-resume locator revalidation ---------------------------------

def test_validate_spilled_detects_damage(tmp_path):
    job = "jobVS"
    _, engine, mgr = _manager(tmp_path, job, num_maps=2, num_reducers=1)
    mids = map_ids(job, 2)
    try:
        for mid in mids:
            mgr.migrate(job, mid, reason="spill", shadow=False)
        assert mgr.validate_spilled(job) == 2
        assert _counter("store.revalidated") >= 2
        # corrupt one spilled object: revalidation must raise TYPED
        dst = mgr.migrations()[0]["dst"]
        with open(dst, "r+b") as f:
            f.seek(0)
            f.write(b"\xff\xff\xff\xff")
        with pytest.raises(StoreError) as ei:
            mgr.validate_spilled(job)
        assert ei.value.cause == "crc" and ei.value.backend == "blob"
        os.unlink(dst)
        with pytest.raises(StoreError) as ei:
            mgr.validate_spilled(job)
        assert ei.value.cause == "missing"
    finally:
        mgr.close()
        engine.stop()


def test_checkpoint_resume_revalidates_spilled_locators(tmp_path):
    """The resume interaction: attempt 1 checkpoints and dies; the
    partitions then SPILL while the task is down; attempt 2 must
    revalidate the spilled objects' CRCs before trusting the manifest
    — intact objects resume byte-identically, a damaged one surfaces
    as a typed failure at resume, not a late segment CRC mismatch."""
    job = "jobCK"
    local = os.path.join(str(tmp_path), "mof")
    blob = os.path.join(str(tmp_path), "blob")
    make_mof_tree(local, job, 6, 1, 100, seed=5)
    ckdir = os.path.join(str(tmp_path), "ck")

    def run(fault=None, extra=None, with_store=True):
        cfg = Config(dict({"uda.tpu.online.streaming": True,
                           "uda.tpu.ckpt.dir": ckdir,
                           "uda.tpu.ckpt.interval.s": 0.0},
                          **(extra or {})))
        resolver = DirIndexResolver(local)
        engine = DataEngine(resolver, cfg)
        mgr = None
        if with_store:
            mgr = StoreManager(resolver, blob)
            engine.attach_store(mgr)
        mm = MergeManager(LocalFetchClient(engine), KT, cfg)
        blocks = []
        try:
            if fault:
                with failpoints.scoped(fault):
                    mm.run(job, map_ids(job, 6), 0,
                           lambda b: blocks.append(bytes(b)))
            else:
                mm.run(job, map_ids(job, 6), 0,
                       lambda b: blocks.append(bytes(b)))
            return b"".join(blocks), mgr, None
        except FallbackSignal as e:
            return b"".join(blocks), mgr, e
        finally:
            if mgr is not None:
                mgr.close()
            engine.stop()

    ref, _, err = run(with_store=False)
    assert err is None and ref
    import shutil
    shutil.rmtree(ckdir)
    # attempt 1 dies mid-fetch, leaving a manifest
    _, _, err1 = run(fault="segment.fetch=error:match:m_000005",
                     extra={"uda.tpu.fetch.retries": 0})
    assert isinstance(err1, FallbackSignal)
    # partitions spill while the task is down; the next attempt's
    # StoreManager must re-learn the migrations to revalidate them, so
    # keep ONE manager across the window (the supplier process's view)
    resolver = DirIndexResolver(local)
    spill_mgr = StoreManager(resolver, blob)
    for mid in map_ids(job, 3):
        spill_mgr.migrate(job, mid, reason="spill", shadow=False)
    r0 = _counter("store.revalidated")

    def run_resume(mgr):
        cfg = Config({"uda.tpu.online.streaming": True,
                      "uda.tpu.ckpt.dir": ckdir,
                      "uda.tpu.ckpt.interval.s": 0.0})
        engine = DataEngine(DirIndexResolver(local), cfg)
        engine.attach_store(mgr)
        # share the spill manager's resolver roots (blob appended)
        engine.resolver.roots = list(mgr.resolver.roots)
        mm = MergeManager(LocalFetchClient(engine), KT, cfg)
        blocks = []
        try:
            mm.run(job, map_ids(job, 6), 0,
                   lambda b: blocks.append(bytes(b)))
            return b"".join(blocks), None
        except FallbackSignal as e:
            return b"".join(blocks), e
        finally:
            engine.stop()

    out, err2 = run_resume(spill_mgr)
    assert err2 is None
    assert out == ref  # byte-identical through the spilled tier
    assert _counter("store.revalidated") > r0  # resume DID revalidate
    # damaged spilled object: the NEXT resume must fail typed at load
    import shutil as _sh
    _sh.rmtree(ckdir, ignore_errors=True)
    _, _, err3 = run(fault="segment.fetch=error:match:m_000004",
                     extra={"uda.tpu.fetch.retries": 0})
    assert isinstance(err3, FallbackSignal)
    dst = spill_mgr.migrations()[0]["dst"]
    with open(dst, "r+b") as f:
        f.write(b"\x00\x00\x00\x00\x00\x00\x00\x00")
    out4, err4 = run_resume(spill_mgr)
    assert err4 is not None
    assert isinstance(err4.cause, StoreError)
    assert err4.cause.cause == "crc"
    spill_mgr.close()


# -- elasticity: join + drain ------------------------------------------------

def test_hello_banner_advertises_elastic_and_draining(tmp_path):
    job = "jobEB"
    local = os.path.join(str(tmp_path), "local")
    make_mof_tree(local, job, 1, 1, 10)
    engine = DataEngine(DirIndexResolver(local))
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    mid = map_ids(job, 1)[0]
    try:
        c1 = RemoteFetchClient("127.0.0.1", server.port, Config())
        done = threading.Event()
        c1.start_fetch(ShuffleRequest(job, mid, 0, 0, 1 << 20),
                       lambda res: done.set())
        assert done.wait(10)
        assert c1.peer_caps() & wire.CAP_ELASTIC
        assert not c1.peer_draining()
        c1.stop()
        d0 = _counter("elastic.drains")
        server.announce_drain()
        server.announce_drain()  # idempotent
        assert _counter("elastic.drains") == d0 + 1
        c2 = RemoteFetchClient("127.0.0.1", server.port, Config())
        done2 = threading.Event()
        c2.start_fetch(ShuffleRequest(job, mid, 0, 0, 1 << 20),
                       lambda res: done2.set())
        assert done2.wait(10)
        assert c2.peer_caps() & wire.CAP_DRAINING
        assert c2.peer_draining()
        c2.stop()
    finally:
        server.stop()
        engine.stop()


def test_drained_supplier_partitions_stay_fetchable(tmp_path):
    """announce_drain migrates the supplier's retained MOFs to the
    blob tier; fetches AFTER the migration serve the moved bytes
    (migrated, not reconstructed) with consistent accounting."""
    job = "jobDR"
    local = os.path.join(str(tmp_path), "local")
    blob = os.path.join(str(tmp_path), "blob")
    resolver = DirIndexResolver(local)
    mgr = StoreManager(resolver, blob)
    writer = MOFWriter(local, job, store=mgr)
    rng = np.random.default_rng(13)
    expected = []
    for m in range(3):
        recs = sorted((rng.bytes(8), rng.bytes(32)) for _ in range(50))
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])
        expected += recs
    engine = DataEngine(resolver)
    engine.attach_store(mgr)
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    try:
        moved = server.announce_drain(store=mgr, job_id=job)
        assert len(moved) == 3
        assert all(e["reason"] == "drain" for e in moved)
        assert _counter("store.drained.partitions") >= 3
        assert mgr.retained_bytes() == 0
        # the local bytes are gone; the blob tier serves byte-identical
        for e in moved:
            assert not os.path.exists(e["src"])
            assert os.path.exists(e["dst"])
        assert _fetch_records(engine, job, writer.map_ids) == \
            sorted(expected)
        rec0 = _counter("recovery.reconstructions") \
            if metrics.get("recovery.reconstructions") else 0
        assert rec0 == 0  # migrated, NOT reconstructed
    finally:
        server.stop()
        mgr.close()
        engine.stop()


def test_host_routing_membership_and_refresh(tmp_path):
    class StubClient:
        def __init__(self):
            self.stopped = False

        def start_fetch(self, req, cb):
            cb(StorageError("stub"))

        def resume_ok(self, host=""):
            return True

        def generation(self, host=""):
            return None

        def stop(self):
            self.stopped = True

    made = []

    def connect(host):
        c = StubClient()
        made.append((host, c))
        return c

    router = HostRoutingClient(connect=connect)
    router._client_for("A")
    assert len(made) == 1
    j0 = _counter("elastic.joins")
    router.notify_join("B")
    router.notify_join("B")  # idempotent: counted once
    assert _counter("elastic.joins") == j0 + 1
    assert router.members() == ["B"]
    # refresh drops the cached transport so the next fetch re-dials
    router.refresh("A")
    assert made[0][1].stopped
    router._client_for("A")
    assert len(made) == 2  # A re-dialed; join only refreshes, it
    # never pre-dials the joiner
    router.notify_drain("B")
    assert router.members() == []
    assert router.is_draining("B")
    router.stop()


def test_segment_add_host_widens_candidates():
    seg = Segment(None, "j", "m1", 0, 1 << 20, host="A", hosts=["A"])
    assert seg.add_host("B")
    assert not seg.add_host("B")      # already known
    assert not seg.add_host("")       # no empty hosts
    assert seg.hosts == ["A", "B"]
    seg._done.set()
    assert not seg.add_host("C")      # done segments never widen


def test_mid_job_join_rescues_failing_fetch(tmp_path):
    """Integration: the primary supplier is missing one map's output;
    a supplier holding it JOINS mid-job and the retry ladder's re-rank
    elects the joiner — the fetch completes without fallback."""
    job = "jobJN"
    root_a = os.path.join(str(tmp_path), "A")
    root_b = os.path.join(str(tmp_path), "B")
    expected = make_mof_tree(root_a, job, 3, 1, 30, seed=17)
    # map 2's output lives ONLY on the joiner B: move it over
    import shutil
    mid_missing = map_ids(job, 3)[2]
    os.makedirs(os.path.join(root_b, job), exist_ok=True)
    shutil.move(os.path.join(root_a, job, mid_missing),
                os.path.join(root_b, job, mid_missing))
    engines = {"A": DataEngine(DirIndexResolver(root_a)),
               "B": DataEngine(DirIndexResolver(root_b))}
    router = HostRoutingClient(
        connect=lambda host: LocalFetchClient(engines[host]))
    cfg = Config({"uda.tpu.fetch.retries": 30,
                  "mapred.rdma.fetch.retry.backoff.ms": 40.0,
                  "mapred.rdma.fetch.retry.backoff.max.ms": 80.0})
    mm = MergeManager(router, KT, cfg)
    joiner = threading.Timer(0.3, lambda: mm.notify_join("B"))
    joiner.daemon = True
    joiner.start()
    try:
        entries = [("A", m) for m in map_ids(job, 3)]
        segs = mm.fetch_all(job, entries, 0)
        got = sorted(sum((list(b.iter_records())
                          for s in segs for b in s.batches), []))
        assert got == sorted(expected[0])
        rescued = [s for s in segs if s.map_id == mid_missing][0]
        assert rescued.host == "B"  # the joiner served it
        assert "B" in rescued.hosts
        assert _counter("elastic.joins") > 0
    finally:
        joiner.cancel()
        mm.stop()
        for e in engines.values():
            e.stop()


def test_writer_add_supplier_root_joins_placement():
    w = MOFWriter("/tmp/x", "j", supplier_roots=["/r/a", "/r/b"],
                  supplier_index=0)
    w.add_supplier_root("/r/c", domain="rack2")
    w.add_supplier_root("/r/c")  # idempotent
    assert w.supplier_roots == ["/r/a", "/r/b", "/r/c"]
    assert w.domains["/r/c"] == "rack2"
    w.add_supplier_root("/r/d", supplier_index=1)
    assert w.supplier_index == 1


def test_merge_manager_notify_drain_records_ledger(tmp_path):
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    router = HostRoutingClient(
        connect=lambda host: LocalFetchClient(engine))
    mm = MergeManager(router, KT, Config())
    try:
        mm.notify_drain("hostX")
        assert router.is_draining("hostX")
        snap = mm.ledger.snapshot()
        assert "drain" in [e["kind"] for e in snap["events"]]
    finally:
        mm.stop()
        engine.stop()
