"""Compression path (reference src/Merger/DecompressorWrapper.cc,
SnappyDecompressor.cc): codecs, block framing, decompressing client,
end-to-end compressed jobs."""

import collections
import re

import pytest

from uda_tpu import compress
from uda_tpu.utils.errors import CompressionError


def _codecs():
    out = [compress.get_codec("zlib")]
    try:
        out.append(compress.get_codec("snappy"))
    except CompressionError:
        pass
    return out


@pytest.mark.parametrize("codec", _codecs(), ids=lambda c: c.name)
def test_block_stream_round_trip(codec):
    data = (b"hello world " * 5000) + bytes(range(256)) * 100
    blob = compress.compress_block_stream(data, codec, block_size=4096)
    assert blob != data
    assert compress.decompress_block_stream(blob, codec) == data
    # empty stream
    assert compress.decompress_block_stream(
        compress.compress_block_stream(b"", codec), codec) == b""


def test_snappy_available_here():
    # this image ships libsnappy.so.1: the dlopen path must work
    codec = compress.get_codec("org.apache.hadoop.io.compress.SnappyCodec")
    assert codec.decompress(codec.compress(b"x" * 1000), 1000) == b"x" * 1000


def test_unknown_codec_raises():
    with pytest.raises(CompressionError):
        compress.get_codec("com.example.NoSuchCodec")


def test_truncated_block_stream():
    codec = compress.get_codec("zlib")
    blob = compress.compress_block_stream(b"data" * 1000, codec)
    with pytest.raises(CompressionError):
        compress.decompress_block_stream(blob[:-3], codec)


@pytest.mark.parametrize("codec_name", ["zlib", "snappy"])
def test_compressed_merge_end_to_end(tmp_path, codec_name):
    """Full engine path over compressed MOFs: writer compresses, the
    DecompressingClient feeds the merge, output matches the plain run."""
    import functools
    import io

    import numpy as np

    from uda_tpu.compress import DecompressingClient, get_codec
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.mofserver.writer import MOFWriter
    from uda_tpu.utils import comparators
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.ifile import IFileReader

    try:
        codec = get_codec(codec_name)
    except CompressionError:
        pytest.skip(f"{codec_name} not available")

    rng = np.random.default_rng(21)
    job = "jobC_" + codec_name
    writer = MOFWriter(str(tmp_path), job, codec=codec)
    expected = []
    for m in range(3):
        recs = sorted((rng.bytes(10), rng.bytes(60)) for _ in range(150))
        expected += recs
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])

    # small chunks force multi-fetch + partial-block carry
    cfg = Config({"mapred.rdma.buf.size": 1})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    try:
        client = DecompressingClient(LocalFetchClient(engine), codec)
        mm = MergeManager(client, "uda.tpu.RawBytes", cfg)
        mm.chunk_size = 777  # not aligned to block boundaries
        blocks = []
        mm.run(job, writer.map_ids, 0, lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = sorted(expected, key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want


def test_compressed_wordcount_via_config(tmp_path):
    from uda_tpu.models import wordcount
    from uda_tpu.utils.config import Config

    text = b"alpha beta alpha gamma beta alpha\n" * 50
    cfg = Config({"mapred.compress.map.output": True,
                  "mapred.map.output.compression.codec": "zlib"})
    got = wordcount.run_wordcount(text, num_maps=3, num_reducers=2,
                                  config=cfg, work_dir=str(tmp_path))
    want = collections.Counter(
        m.group(0).lower() for m in re.finditer(rb"[A-Za-z0-9]+", text))
    assert got == dict(want)


def test_zlib_rejects_wrong_length_header():
    # a corrupt uncompressed_len in a block header must fail AT the
    # block for every codec, zlib included
    import zlib as _zlib

    from uda_tpu.compress import get_codec
    from uda_tpu.utils.errors import CompressionError

    codec = get_codec("zlib")
    comp = _zlib.compress(b"x" * 100)
    assert codec.decompress(comp, 100) == b"x" * 100
    with pytest.raises(CompressionError):
        codec.decompress(comp, 99)
