"""Compression path (reference src/Merger/DecompressorWrapper.cc,
SnappyDecompressor.cc): codecs, block framing, decompressing client,
end-to-end compressed jobs."""

import collections
import re

import pytest

from uda_tpu import compress
from uda_tpu.utils.errors import CompressionError


def _codecs():
    out = [compress.get_codec("zlib"), compress.get_codec("lzo")]
    try:
        out.append(compress.get_codec("snappy"))
    except CompressionError:
        pass
    return out


@pytest.mark.parametrize("codec", _codecs(), ids=lambda c: c.name)
def test_block_stream_round_trip(codec):
    data = (b"hello world " * 5000) + bytes(range(256)) * 100
    blob = compress.compress_block_stream(data, codec, block_size=4096)
    assert blob != data
    assert compress.decompress_block_stream(blob, codec) == data
    # empty stream
    assert compress.decompress_block_stream(
        compress.compress_block_stream(b"", codec), codec) == b""


def test_snappy_available_here():
    # this image ships libsnappy.so.1: the dlopen path must work
    codec = compress.get_codec("org.apache.hadoop.io.compress.SnappyCodec")
    assert codec.decompress(codec.compress(b"x" * 1000), 1000) == b"x" * 1000


def test_unknown_codec_raises():
    with pytest.raises(CompressionError):
        compress.get_codec("com.example.NoSuchCodec")


def test_truncated_block_stream():
    codec = compress.get_codec("zlib")
    blob = compress.compress_block_stream(b"data" * 1000, codec)
    with pytest.raises(CompressionError):
        compress.decompress_block_stream(blob[:-3], codec)


@pytest.mark.parametrize("codec_name", ["zlib", "snappy", "lzo"])
def test_compressed_merge_end_to_end(tmp_path, codec_name):
    """Full engine path over compressed MOFs: writer compresses, the
    DecompressingClient feeds the merge, output matches the plain run."""
    import functools
    import io

    import numpy as np

    from uda_tpu.compress import DecompressingClient, get_codec
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.mofserver.writer import MOFWriter
    from uda_tpu.utils import comparators
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.ifile import IFileReader

    try:
        codec = get_codec(codec_name)
    except CompressionError:
        pytest.skip(f"{codec_name} not available")

    rng = np.random.default_rng(21)
    job = "jobC_" + codec_name
    writer = MOFWriter(str(tmp_path), job, codec=codec)
    expected = []
    for m in range(3):
        recs = sorted((rng.bytes(10), rng.bytes(60)) for _ in range(150))
        expected += recs
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])

    # small chunks force multi-fetch + partial-block carry
    cfg = Config({"mapred.rdma.buf.size": 1})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    try:
        client = DecompressingClient(LocalFetchClient(engine), codec)
        mm = MergeManager(client, "uda.tpu.RawBytes", cfg)
        mm.chunk_size = 777  # not aligned to block boundaries
        blocks = []
        mm.run(job, writer.map_ids, 0, lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = sorted(expected, key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want


def test_compressed_wordcount_via_config(tmp_path):
    from uda_tpu.models import wordcount
    from uda_tpu.utils.config import Config

    text = b"alpha beta alpha gamma beta alpha\n" * 50
    cfg = Config({"mapred.compress.map.output": True,
                  "mapred.map.output.compression.codec": "zlib"})
    got = wordcount.run_wordcount(text, num_maps=3, num_reducers=2,
                                  config=cfg, work_dir=str(tmp_path))
    want = collections.Counter(
        m.group(0).lower() for m in re.finditer(rb"[A-Za-z0-9]+", text))
    assert got == dict(want)


class TestLzo:
    """LZO1X codec (reference src/Merger/LzoDecompressor.cc): the
    pure-Python stream implementation, plus the dlopen'd liblzo2 path
    when the library is present."""

    @pytest.mark.parametrize("size", [0, 1, 2, 3, 4, 17, 18, 238, 239,
                                      240, 493, 4096, 100_003])
    def test_pure_python_round_trip(self, size):
        from uda_tpu.compress.lzo import (lzo1x_compress_py,
                                          lzo1x_decompress_py)

        rng = __import__("numpy").random.default_rng(size)
        data = rng.bytes(size)
        blob = lzo1x_compress_py(data)
        assert lzo1x_decompress_py(blob, size) == data

    def test_decodes_match_tokens_m2(self):
        # hand-built stream exercising an overlapping M2 match:
        # initial 1-literal run 'a', M2 copy 7 from distance 1 with one
        # trailing literal 'b' (state bits), end marker
        from uda_tpu.compress.lzo import lzo1x_decompress_py

        stream = bytes([18]) + b"a" + bytes([193, 0]) + b"b" + b"\x11\x00\x00"
        assert lzo1x_decompress_py(stream, 9) == b"aaaaaaaab"

    def test_decodes_match_tokens_m3(self):
        # M3 match: copy "cdef" from distance 6 after "abcdefgh"
        from uda_tpu.compress.lzo import lzo1x_decompress_py

        stream = bytes([25]) + b"abcdefgh" + bytes([34, 20, 0]) \
            + b"\x11\x00\x00"
        assert lzo1x_decompress_py(stream, 12) == b"abcdefghcdef"

    def test_malformed_streams_raise(self):
        from uda_tpu.compress.lzo import lzo1x_decompress_py

        with pytest.raises(CompressionError):
            lzo1x_decompress_py(b"\x12a\x11\x00\x00", 5)  # wrong length
        with pytest.raises(CompressionError):
            lzo1x_decompress_py(bytes([25]) + b"abc", 8)  # truncated
        with pytest.raises(CompressionError):
            # match reaching before the start of the output
            lzo1x_decompress_py(bytes([18]) + b"a" + bytes([193, 9])
                                + b"b\x11\x00\x00", 9)

    def test_native_cross_check(self):
        # gated: only runs where liblzo2.so is installed (the reference's
        # runtime dlopen dependency, LzoDecompressor.cc:83-127)
        from uda_tpu.compress.lzo import (_native_compress,
                                          _native_decompress,
                                          lzo1x_compress_py,
                                          lzo1x_decompress_py,
                                          native_lzo_available)

        if not native_lzo_available():
            pytest.skip("liblzo2.so not installed")
        data = (b"the quick brown fox " * 400) + bytes(range(256)) * 8
        native_blob = _native_compress(data)
        assert lzo1x_decompress_py(native_blob, len(data)) == data
        py_blob = lzo1x_compress_py(data)
        assert _native_decompress(py_blob, len(data)) == data


class TestBuiltinNativeLzo:
    """The in-tree C++ LZO1X codec (uda_tpu/native/lzo.cc) — the native
    execution path VERDICT r4 flagged as untestable without liblzo2
    (reference LzoDecompressor.cc:83-127 parity target)."""

    def _codec(self):
        from uda_tpu.compress.lzo import (_builtin_compress,
                                          _builtin_decompress,
                                          native_lzo_source)

        if native_lzo_source() == "":
            pytest.skip("native library not built")
        return _builtin_compress, _builtin_decompress

    def test_roundtrip_vs_python_decoder(self):
        import numpy as np

        from uda_tpu.compress.lzo import (lzo1x_compress_py,
                                          lzo1x_decompress_py)

        comp, decomp = self._codec()
        rng = np.random.default_rng(123)
        cases = [b"", b"a", b"abc" * 3, rng.bytes(50_000),
                 (b"repeat me " * 5000), bytes(1000),
                 bytes(rng.integers(0, 4, 20_000, dtype=np.uint8))]
        for d in cases:
            blob = comp(d)
            assert decomp(blob, len(d)) == d
            assert lzo1x_decompress_py(blob, len(d)) == d
            assert decomp(lzo1x_compress_py(d), len(d)) == d

    def test_corrupt_streams_error_not_crash(self):
        import numpy as np

        from uda_tpu.utils.errors import CompressionError

        comp, decomp = self._codec()
        data = b"the quick brown fox jumps " * 200
        blob = bytearray(comp(data))
        # truncations at every prefix must error cleanly
        for cut in range(0, len(blob), max(1, len(blob) // 50)):
            with pytest.raises(CompressionError):
                decomp(bytes(blob[:cut]), len(data))
        # wrong declared length
        with pytest.raises(CompressionError):
            decomp(bytes(blob), len(data) - 1)
        # single-byte corruptions: must either roundtrip-fail or error —
        # never crash or hang (the lzo1x_decompress_safe contract)
        rng = np.random.default_rng(7)
        for _ in range(200):
            i = int(rng.integers(0, len(blob)))
            mut = bytearray(blob)
            mut[i] ^= int(rng.integers(1, 256))
            try:
                out = decomp(bytes(mut), len(data))
                assert len(out) == len(data)
            except CompressionError:
                pass

    def test_codec_registry_uses_native(self):
        from uda_tpu.compress import get_codec
        from uda_tpu.compress.lzo import native_lzo_source

        if native_lzo_source() == "":
            pytest.skip("native library not built")
        codec = get_codec("lzo")
        data = b"block payload " * 1000
        assert codec.decompress(codec.compress(data), len(data)) == data


def test_zlib_rejects_wrong_length_header():
    # a corrupt uncompressed_len in a block header must fail AT the
    # block for every codec, zlib included
    import zlib as _zlib

    from uda_tpu.compress import get_codec
    from uda_tpu.utils.errors import CompressionError

    codec = get_codec("zlib")
    comp = _zlib.compress(b"x" * 100)
    assert codec.decompress(comp, 100) == b"x" * 100
    with pytest.raises(CompressionError):
        codec.decompress(comp, 99)
