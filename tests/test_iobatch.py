"""Batched host-I/O plane (ISSUE 13): coalescing planner units,
submit_batch byte identity + per-request error isolation, the wire
serve path's batch feeding, and the batch-partial-failure chaos
contract (faults-marked — scripts/run_chaos.sh's iobatch rung runs
these under a seeded data_engine.preadv schedule with the
ResourceLedger and lockdep armed)."""

import hashlib
import os
import tempfile
import threading

import pytest

from uda_tpu.mofserver.data_engine import (DataEngine, ShuffleRequest,
                                           plan_coalesced)
from uda_tpu.mofserver.index import IndexRecord
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import ConfigError, StorageError
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.metrics import metrics

JOB = "jobIoBatch"
MAP = "attempt_jobIoBatch_m_000000_0"


class SyntheticResolver:
    """Every (job, map, reduce) resolves to one pre-written file."""

    def __init__(self, path: str, nbytes: int, start: int = 0):
        self._rec = IndexRecord(start_offset=start, raw_length=nbytes,
                                part_length=nbytes, path=path)

    def resolve(self, job_id, map_id, reduce_id):
        return self._rec


def _write(tmp, name, nbytes, seed=7):
    import random

    rng = random.Random(seed)
    path = os.path.join(tmp, name)
    with open(path, "wb") as f:
        f.write(bytes(rng.getrandbits(8) for _ in range(nbytes)))
    return path


@pytest.fixture()
def quiet_sites():
    """Identity assertions below are about the REAL read plane; the
    ambient chaos-rung schedules (data_engine.pread/preadv) would
    inject the very faults these tests assert absent — pinned out,
    trigger state restored on exit (the PR 10 idiom)."""
    with failpoints.scoped(""):
        failpoints.disarm("data_engine.pread")
        failpoints.disarm("data_engine.preadv")
        yield


# -- coalescing planner (pure units) -----------------------------------------


def test_plan_coalesced_adjacent_and_gap_merge():
    items = [("a", 0, 100), ("b", 100, 50), ("c", 180, 20)]
    runs = plan_coalesced(items, gap_bytes=30, max_run_bytes=1 << 20)
    assert [[i[0] for i in run] for run in runs] == [["a", "b", "c"]]
    runs = plan_coalesced(items, gap_bytes=29, max_run_bytes=1 << 20)
    assert [[i[0] for i in run] for run in runs] == [["a", "b"], ["c"]]


def test_plan_coalesced_zero_gap_only_adjacent():
    items = [("a", 0, 10), ("b", 10, 10), ("c", 21, 10)]
    runs = plan_coalesced(items, gap_bytes=0, max_run_bytes=1 << 20)
    assert [[i[0] for i in run] for run in runs] == [["a", "b"], ["c"]]


def test_plan_coalesced_overlap_starts_fresh_run():
    # duplicate/overlapping ranges cannot share one scatter read
    items = [("a", 0, 100), ("dup", 0, 100), ("b", 50, 100)]
    runs = plan_coalesced(items, gap_bytes=1 << 20,
                          max_run_bytes=1 << 20)
    assert len(runs) == 3
    for run in runs:
        end = -1
        for _, off, length in run:
            assert off >= end
            end = off + length


def test_plan_coalesced_max_run_bound():
    items = [("x%d" % i, i * 100, 100) for i in range(10)]
    runs = plan_coalesced(items, gap_bytes=0, max_run_bytes=300)
    assert all(sum(r[2] for r in run) <= 300 for run in runs)
    assert [len(run) for run in runs] == [3, 3, 3, 1]


def test_plan_coalesced_iov_max_bound():
    """A run never exceeds the IOV_MAX-derived item cap: preadv
    rejects >1024 buffers per call, and an oversized batch_max must
    split runs rather than fail the whole burst's reads."""
    items = [("x%d" % i, i * 10, 10) for i in range(1200)]
    runs = plan_coalesced(items, gap_bytes=0, max_run_bytes=1 << 30)
    assert all(len(run) <= 511 for run in runs)
    assert sum(len(run) for run in runs) == 1200


def test_plan_coalesced_unsorted_input_sorted_runs():
    items = [("b", 500, 10), ("a", 0, 10), ("c", 505, 10)]
    runs = plan_coalesced(items, gap_bytes=0, max_run_bytes=1 << 20)
    flat = [i[0] for run in runs for i in run]
    assert flat == ["a", "b", "c"]  # "c" overlaps "b": separate runs
    assert len(runs) == 3


# -- submit_batch semantics ---------------------------------------------------


def test_submit_batch_byte_identity_vs_file(tmp_path, quiet_sites):
    data_len = 1 << 20
    path = _write(str(tmp_path), "f.mof", data_len)
    with open(path, "rb") as f:
        blob = f.read()
    # pin the preadv rung: the coalescer under test only exists there —
    # on a host with the native lib built, "auto" resolves to io_uring,
    # which correctly submits one SQE per request (the kernel batches)
    # and the reads < requests assertion below would test the host's
    # build state instead of the scatter logic
    engine = DataEngine(SyntheticResolver(path, data_len),
                        Config({"uda.tpu.read.backend": "preadv"}))
    try:
        # adjacent, gapped, duplicate and tail-clamped ranges in one
        # batch — every shape the coalescer must scatter correctly
        offs = [0, 65536, 131072, 131072, 400000, 400100,
                data_len - 100]
        reqs = [ShuffleRequest(JOB, MAP, 0, off, 65536) for off in offs]
        futs = engine.submit_batch(reqs)
        for req, fut in zip(reqs, futs):
            res = fut.result(timeout=10)
            want = blob[req.offset:req.offset + 65536]
            assert bytes(res.data) == want
            assert res.last == (req.offset + len(res.data) >= data_len)
            assert res.raw_length == data_len
        assert metrics.get("io.batch.requests") == len(reqs)
        assert metrics.get("io.batch.submits") == 1
        # adjacent trio coalesced: strictly fewer reads than requests
        assert metrics.get("io.batch.reads") < len(reqs)
    finally:
        engine.stop()


def test_submit_batch_matches_single_submit(tmp_path, quiet_sites):
    """The A/B twin contract: batch results byte-identical to the
    single-pread path over the same requests."""
    data_len = 512 * 1024
    path = _write(str(tmp_path), "f.mof", data_len, seed=11)
    engine = DataEngine(SyntheticResolver(path, data_len), Config())
    try:
        offs = [0, 1000, 64 * 1024, 300000, 500000]
        reqs = [ShuffleRequest(JOB, MAP, 0, off, 32768) for off in offs]
        single = [engine.submit(r).result(timeout=10) for r in reqs]
        batched = [f.result(timeout=10)
                   for f in engine.submit_batch(reqs)]
        for s, b in zip(single, batched):
            assert bytes(s.data) == bytes(b.data)
            assert (s.raw_length, s.part_length, s.offset, s.last) == \
                (b.raw_length, b.part_length, b.offset, b.last)
    finally:
        engine.stop()


def test_submit_batch_bad_offset_fails_only_that_request(tmp_path,
                                                         quiet_sites):
    data_len = 256 * 1024
    path = _write(str(tmp_path), "f.mof", data_len)
    engine = DataEngine(SyntheticResolver(path, data_len), Config())
    try:
        reqs = [ShuffleRequest(JOB, MAP, 0, 0, 4096),
                ShuffleRequest(JOB, MAP, 0, data_len + 5, 4096),
                ShuffleRequest(JOB, MAP, 0, 8192, 4096)]
        futs = engine.submit_batch(reqs)
        assert futs[0].result(timeout=10).data
        with pytest.raises(StorageError):
            futs[1].result(timeout=10)
        assert futs[2].result(timeout=10).data
    finally:
        engine.stop()


def test_submit_batch_admission_rejection_is_per_request(tmp_path,
                                                         quiet_sites):
    data_len = 4 << 20
    path = _write(str(tmp_path), "f.mof", data_len)
    engine = DataEngine(
        SyntheticResolver(path, data_len),
        Config({"uda.tpu.supplier.read.budget.mb": 1}))
    try:
        # 1 MB budget: the first (idle-engine escape) admits, the
        # second cannot fit on top of it, the third neither — each
        # rejection is ITS future's StorageError, the admitted one
        # serves
        reqs = [ShuffleRequest(JOB, MAP, 0, i << 20, 1 << 20)
                for i in range(3)]
        futs = engine.submit_batch(reqs)
        assert len(futs[0].result(timeout=10).data) == 1 << 20
        for f in futs[1:]:
            with pytest.raises(StorageError):
                f.result(timeout=10)
        assert metrics.get("supplier.admission.rejections") == 2
    finally:
        engine.stop()
    assert metrics.get_gauge("supplier.read.bytes.on_air") == 0


def test_submit_batch_never_raises_when_stopped(tmp_path):
    path = _write(str(tmp_path), "f.mof", 1024)
    engine = DataEngine(SyntheticResolver(path, 1024), Config())
    engine.stop()
    futs = engine.submit_batch([ShuffleRequest(JOB, MAP, 0, 0, 512)])
    with pytest.raises(StorageError):
        futs[0].result(timeout=5)


def test_submit_batch_crc_stamped_from_disk_bytes(tmp_path,
                                                  quiet_sites):
    import zlib

    data_len = 128 * 1024
    path = _write(str(tmp_path), "f.mof", data_len)
    with open(path, "rb") as f:
        blob = f.read()
    engine = DataEngine(SyntheticResolver(path, data_len),
                        Config({"uda.tpu.fetch.crc": True}))
    try:
        futs = engine.submit_batch(
            [ShuffleRequest(JOB, MAP, 0, 4096, 8192)])
        res = futs[0].result(timeout=10)
        assert res.crc == (zlib.crc32(blob[4096:4096 + 8192])
                           & 0xFFFFFFFF)
    finally:
        engine.stop()


def test_backend_ladder_and_io_backend_recorded(tmp_path):
    """This 4.4-class host exercises the preadv rung; the selection is
    recorded as the io.backend label AND the engine attribute (the
    stats-record contract of the once-per-process-warn satellite)."""
    path = _write(str(tmp_path), "f.mof", 1024)
    engine = DataEngine(SyntheticResolver(path, 1024), Config())
    try:
        assert engine.io_backend in ("io_uring", "preadv", "pread")
        if hasattr(os, "preadv"):
            assert engine.io_backend in ("io_uring", "preadv")
        assert metrics.get("io.backend",
                           backend=engine.io_backend) >= 1
    finally:
        engine.stop()
    # explicit rung requests walk DOWN the ladder, typos fail loudly
    e2 = DataEngine(SyntheticResolver(path, 1024),
                    Config({"uda.tpu.read.backend": "pread"}))
    assert e2.io_backend == "pread"
    e2.stop()
    with pytest.raises(ConfigError):
        DataEngine(SyntheticResolver(path, 1024),
                   Config({"uda.tpu.read.backend": "io_urng"}))


def test_native_unavailable_warns_once_counts_every_time(tmp_path,
                                                         monkeypatch):
    """data_engine.py's native-fallback log.warn fired per
    construction; a fleet of engines must not spam — once per process,
    counted every time (io.native.unavailable)."""
    import uda_tpu.mofserver.data_engine as de

    path = _write(str(tmp_path), "f.mof", 1024)
    warns = []
    monkeypatch.setattr(de, "_native_warned", False)
    monkeypatch.setattr(
        de.log, "warn",
        lambda msg, *a, **k: warns.append(str(msg)))

    class _Boom:
        def __getattr__(self, name):
            raise RuntimeError("no native today")

    real_native_reads = de._NativeReads
    monkeypatch.setattr(de, "_NativeReads",
                        lambda pool: (_ for _ in ()).throw(
                            RuntimeError("no native today")))
    try:
        for _ in range(3):
            DataEngine(SyntheticResolver(path, 1024),
                       Config({"uda.tpu.use.native": True})).stop()
    finally:
        de._NativeReads = real_native_reads
    native_warns = [w for w in warns if "native reader unavailable"
                    in w]
    assert len(native_warns) == 1
    assert metrics.get("io.native.unavailable") == 3


# -- the wire serve path ------------------------------------------------------


def _wire_burst(path, data_len, batch, n=64, chunk=16 * 1024,
                server_cfg=None):
    from uda_tpu.net import ShuffleServer
    from uda_tpu.net.client import RemoteFetchClient

    engine = DataEngine(SyntheticResolver(path, data_len),
                        Config({"uda.tpu.read.batch": batch}))
    scfg = dict(server_cfg or {"uda.tpu.net.zerocopy": False})
    server = ShuffleServer(engine, Config(scfg), host="127.0.0.1",
                           port=0).start()
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    results = [None] * n
    done = threading.Event()
    lock = threading.Lock()
    count = [0]

    def mk(i):
        def cb(res):
            results[i] = res
            with lock:
                count[0] += 1
                if count[0] == n:
                    done.set()
        return cb

    try:
        for i in range(n):
            client.start_fetch(
                ShuffleRequest(JOB, MAP, 0, (i * chunk) % data_len,
                               chunk), mk(i))
        assert done.wait(30.0), f"burst stalled {count[0]}/{n}"
    finally:
        client.stop()
        server.stop()
        engine.stop()
    return results


def test_wire_burst_batched_is_byte_identical(tmp_path, quiet_sites):
    data_len = 2 << 20
    path = _write(str(tmp_path), "f.mof", data_len, seed=3)
    with open(path, "rb") as f:
        blob = f.read()

    def digest(results):
        h = hashlib.sha256()
        for r in results:
            assert not isinstance(r, Exception), r
            h.update(bytes(r.data))
        return h.hexdigest()

    got_on = _wire_burst(path, data_len, "on")
    on_batched = metrics.get("io.batch.requests")
    assert on_batched > 0, "batch plane never engaged with batch=on"
    d_on = digest(got_on)
    metrics.reset()
    got_off = _wire_burst(path, data_len, "off")
    assert metrics.get("io.batch.requests") == 0, \
        "batch=off must reproduce today's single-pread path exactly"
    assert digest(got_off) == d_on
    for r, want_off in zip(got_on,
                           [(i * 16384) % data_len for i in range(64)]):
        assert bytes(r.data) == blob[want_off:want_off + 16384]


def test_wire_zero_copy_requests_stay_unbatched(tmp_path, quiet_sites):
    """Slice-eligible requests keep the zero-copy plane: batching must
    never steal the sendfile/mmap path (it would trade a splice for a
    heap copy)."""
    data_len = 1 << 20
    path = _write(str(tmp_path), "f.mof", data_len)
    results = _wire_burst(path, data_len, "on", n=16,
                          server_cfg={"uda.tpu.net.zerocopy": True})
    assert all(not isinstance(r, Exception) for r in results)
    assert metrics.get("io.batch.requests") == 0
    assert metrics.get("net.serve.fd") > 0


# -- failure injection (the chaos rung's tests) -------------------------------


@pytest.mark.faults
def test_iobatch_partial_failure_only_targets_request(tmp_path):
    """THE batch-partial-failure contract: an injected
    data_engine.preadv fault (keyed <fd>@<file offset>) fails exactly
    the targeted request of a coalesced batch; its batch-mates
    complete byte-correct and every obligation settles (the conftest
    teardown + the chaos rung's armed ledger enforce zero leaks)."""
    data_len = 1 << 20
    path = _write(str(tmp_path), "f.mof", data_len, seed=5)
    with open(path, "rb") as f:
        blob = f.read()
    engine = DataEngine(SyntheticResolver(path, data_len), Config())
    try:
        # four ADJACENT chunks -> one coalesced vectored read; the
        # match trigger keys on the victim's file offset
        offs = [0, 16384, 32768, 49152]
        with failpoints.scoped(
                "data_engine.preadv=error:match:@32768"):
            failpoints.disarm("data_engine.pread")
            futs = engine.submit_batch(
                [ShuffleRequest(JOB, MAP, 0, off, 16384)
                 for off in offs])
            for off, fut in zip(offs, futs):
                if off == 32768:
                    with pytest.raises(StorageError):
                        fut.result(timeout=10)
                else:
                    res = fut.result(timeout=10)
                    assert bytes(res.data) == blob[off:off + 16384]
        assert metrics.get("failpoint.data_engine.preadv") >= 1
    finally:
        engine.stop()
    assert metrics.get_gauge("io.batch.inflight") == 0
    assert metrics.get_gauge("supplier.read.bytes.on_air") == 0


@pytest.mark.faults
def test_iobatch_truncate_damages_one_request(tmp_path):
    """Data-bearing injection on the batch plane: a truncated chunk
    looks like wire damage on ONE request (CRC validates per chunk),
    batch-mates untouched."""
    import zlib

    data_len = 256 * 1024
    path = _write(str(tmp_path), "f.mof", data_len)
    with open(path, "rb") as f:
        blob = f.read()
    engine = DataEngine(SyntheticResolver(path, data_len),
                        Config({"uda.tpu.fetch.crc": True}))
    try:
        with failpoints.scoped(
                "data_engine.preadv=truncate:100:match:@8192"):
            failpoints.disarm("data_engine.pread")
            futs = engine.submit_batch(
                [ShuffleRequest(JOB, MAP, 0, 0, 8192),
                 ShuffleRequest(JOB, MAP, 0, 8192, 8192)])
            ok = futs[0].result(timeout=10)
            assert bytes(ok.data) == blob[:8192]
            assert ok.crc == zlib.crc32(blob[:8192]) & 0xFFFFFFFF
            hurt = futs[1].result(timeout=10)
            # truncated AFTER the CRC stamp (truncate:<n> drops n tail
            # bytes): the mismatch is detectable exactly like wire
            # damage (the Segment's re-fetch contract)
            assert len(hurt.data) == 8192 - 100
            assert hurt.crc == zlib.crc32(blob[8192:16384]) & 0xFFFFFFFF
            assert zlib.crc32(bytes(hurt.data)) & 0xFFFFFFFF != hurt.crc
    finally:
        engine.stop()


@pytest.mark.faults
def test_iobatch_wire_pread_injection_still_fires(tmp_path):
    """Chaos coverage survives batching: the historical
    data_engine.pread site fires per request on the batch plane too
    (same <map>/<reduce> key), so every existing schedule keeps
    testing the wire serve path."""
    data_len = 512 * 1024
    path = _write(str(tmp_path), "f.mof", data_len)
    with failpoints.scoped("data_engine.pread=error:every:3"):
        failpoints.disarm("data_engine.preadv")
        results = _wire_burst(path, data_len, "on", n=12)
    errors = [r for r in results if isinstance(r, Exception)]
    ok = [r for r in results if not isinstance(r, Exception)]
    assert errors, "every:3 schedule never fired through the batch path"
    assert ok, "injection must not take down the whole batch"
    assert metrics.get("io.batch.requests") > 0
    assert metrics.get_gauge("io.batch.inflight") == 0


@pytest.mark.faults
def test_iobatch_preadv_delay_keeps_books_balanced(tmp_path):
    """A delay storm on the batch plane (the chaos rung's other
    action) must finish with zero in-flight obligations."""
    data_len = 256 * 1024
    path = _write(str(tmp_path), "f.mof", data_len)
    engine = DataEngine(SyntheticResolver(path, data_len), Config())
    try:
        with failpoints.scoped("data_engine.preadv=delay:5:prob:0.5:"
                               "seed:7"):
            failpoints.disarm("data_engine.pread")
            futs = engine.submit_batch(
                [ShuffleRequest(JOB, MAP, 0, i * 8192, 8192)
                 for i in range(16)])
            for fut in futs:
                fut.result(timeout=30)
    finally:
        engine.stop()
    assert metrics.get_gauge("io.batch.inflight") == 0
    assert metrics.get_gauge("supplier.reads.on_air") == 0
