"""Benchmark-ladder workloads end-to-end (BASELINE configs 1, 3, 4)."""

import collections
import re

import numpy as np

from uda_tpu.models import grep, inverted_index, secondary_sort, wordcount
from uda_tpu.models.pipeline import MapReduceJob, grouped_reduce
from uda_tpu.utils.config import Config

TEXT = (b"the quick brown fox jumps over the lazy dog\n"
        b"the dog barks and the fox runs away over the hill\n"
        b"pack my box with five dozen liquor jugs\n") * 7


def test_wordcount_matches_direct_count(tmp_path):
    got = wordcount.run_wordcount(TEXT, num_maps=3, num_reducers=2,
                                  work_dir=str(tmp_path))
    want = collections.Counter(
        m.group(0).lower() for m in re.finditer(rb"[A-Za-z0-9]+", TEXT))
    assert got == dict(want)


def test_wordcount_single_map_single_reduce(tmp_path):
    got = wordcount.run_wordcount(b"a b a", num_maps=1, num_reducers=1,
                                  work_dir=str(tmp_path))
    assert got == {b"a": 2, b"b": 1}


def test_secondary_sort_grouping_and_order(tmp_path):
    outputs = secondary_sort.run_secondary_sort(
        num_groups=10, per_group=30, num_maps=3, num_reducers=2,
        work_dir=str(tmp_path))
    # run_secondary_sort asserts order+partitioning internally; verify
    # record conservation here
    total = sum(len(recs) for recs in outputs.values())
    assert total == 10 * 30


def test_inverted_index_zipf_skew(tmp_path):
    index = inverted_index.run_inverted_index(
        num_docs=20, words_per_doc=60, num_maps=4, num_reducers=4,
        seed=1, work_dir=str(tmp_path))
    # zipf: the hottest term dominates (skew actually present)
    sizes = sorted((len(v) for v in index.values()), reverse=True)
    assert sizes[0] > 5 * sizes[len(sizes) // 2]


def test_grep_counts_descending(tmp_path):
    result = grep.run_grep(TEXT, rb"[a-z]*o[a-z]*", num_maps=2,
                           work_dir=str(tmp_path))
    counts = [c for _, c in result]
    assert counts == sorted(counts, reverse=True)
    want = collections.Counter()
    for line in TEXT.splitlines():
        for m in re.finditer(rb"[a-z]*o[a-z]*", line):
            want[m.group(0)] += 1
    assert dict(result) == dict(want)


def test_sort_job_per_reducer_order_and_conservation(tmp_path):
    from uda_tpu.models.sort_job import run_sort
    from uda_tpu.utils.comparators import memcmp

    rng = np.random.default_rng(4)
    records = [(rng.bytes(int(rng.integers(1, 16))),
                rng.bytes(int(rng.integers(0, 32)))) for _ in range(400)]
    records[10] = records[300]  # duplicate (key, value) survives identity
    out = run_sort(records, num_maps=3, num_reducers=3,
                   work_dir=str(tmp_path))
    got = []
    for recs in out.values():
        keys = [k for k, _ in recs]
        assert all(memcmp(a, b) <= 0 for a, b in zip(keys, keys[1:]))
        got.extend(recs)
    assert sorted(got) == sorted(records)


def test_pi_conserves_points_and_converges(tmp_path):
    from uda_tpu.models.pi import run_pi

    res = run_pi(num_maps=3, points_per_map=3000, work_dir=str(tmp_path))
    assert res["inside"] + res["outside"] == res["points"]
    # Halton at 9000 points: well inside +-0.1 of pi
    assert abs(res["estimate"] - 3.14159) < 0.1, res


def test_dfsio_round_trip_and_throughput(tmp_path):
    from uda_tpu.models.dfsio import run_dfsio

    res = run_dfsio(num_files=2, bytes_per_file=1 << 17,
                    chunk_size=1 << 13, work_dir=str(tmp_path))
    assert res["files"] == 2
    assert res["chunks"] >= res["files"] * 2  # chunking actually engaged
    assert res["write_mb_s"] > 0 and res["read_mb_s"] > 0


def test_grouped_reduce_contract():
    records = [(b"a", b"1"), (b"a", b"2"), (b"b", b"3")]
    out = list(grouped_reduce(iter(records),
                              lambda k, vs: [(k, b"".join(vs))]))
    assert out == [(b"a", b"12"), (b"b", b"3")]
    assert list(grouped_reduce(iter([]), lambda k, vs: [(k, b"")])) == []


def test_pipeline_hybrid_mode(tmp_path):
    cfg = Config({"mapred.netmerger.merge.approach": 2,
                  "uda.tpu.spill.dirs": str(tmp_path / "spill")})
    got = wordcount.run_wordcount(TEXT, num_maps=5, num_reducers=2,
                                  config=cfg, work_dir=str(tmp_path / "w"))
    want = collections.Counter(
        m.group(0).lower() for m in re.finditer(rb"[A-Za-z0-9]+", TEXT))
    assert got == dict(want)
