"""udaflow (the CFG/dataflow analysis tier) + ResourceLedger coverage.

Four layers:

1. CFG unit tests: the edge shapes the dataflow verdicts depend on
   (try/finally routing, raise/except dispatch, loop back-edges, with
   headers) are pinned structurally;
2. per-rule fixtures: UDA101/UDA102/UDA103 each proven to FIRE on the
   known historical leak shapes (try_plan-style unguarded charge,
   helper-hop blocking-under-lock, AB/BA static lock nesting) and stay
   quiet on the guarded/balanced twins;
3. the static<->runtime inventory lockstep: the UDA101 pair registry
   (analysis/flow.DEFAULT_PAIRS) and the ResourceLedger's paired-gauge
   table (utils/resledger.PAIRED_GAUGES) must name the same
   disciplines, so a static finding and a runtime leak report agree;
4. ResourceLedger unit + integration tests, including the faults-marked
   mid-pipeline leak test: a fault aborts a real pipelined merger with
   ZERO leaked obligations, and a seeded stray lease is reported at the
   abort drain point exactly once, with its acquire stack.

Seeded-leak fixtures use PRIVATE ResourceLedger instances (the LockDep
pattern): the process-global ledger must report zero leaks on real
code, and a fixture leak must never pollute that invariant (or the
``resledger.leaks`` counter the chaos gate enforces).
"""

from __future__ import annotations

import ast
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.analysis.cfg import build_cfg
from uda_tpu.analysis.core import Engine, Finding
from uda_tpu.analysis.flow import (DEFAULT_PAIRS, ObligationPair,
                                   ResourceBalanceRule, StaticLockOrderRule,
                                   TransitiveBlockingRule)
from uda_tpu.analysis.rules import ALL_RULES
from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.merger import overlap as overlap_mod
from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.ops import merge as merge_ops
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import FallbackSignal
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.resledger import (PAIRED_GAUGES, ResourceLedger,
                                     resledger)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KT = "uda.tpu.RawBytes"


def _cfg_of(src: str):
    tree = ast.parse(textwrap.dedent(src))
    return build_cfg(tree.body[0])


def lint(src: str, rules, rel: str = "uda_tpu/x.py") -> list[Finding]:
    eng = Engine(rules)
    out = eng.lint_source(textwrap.dedent(src), rel)
    out.extend(eng.finish())
    return out


def lint_tree(files: dict, rules) -> list[Finding]:
    eng = Engine(rules)
    out: list[Finding] = []
    for rel, src in files.items():
        out.extend(eng.lint_source(textwrap.dedent(src), rel))
    out.extend(eng.finish())
    return out


def rule_ids(findings) -> list[str]:
    return [f.rule for f in findings]


# -- CFG edge shapes ---------------------------------------------------------


class TestCFG:
    def test_straight_line_reaches_exit(self):
        cfg = _cfg_of("def f():\n    x = 1\n    y = 2\n")
        entry = cfg.node(cfg.entry)
        assert entry.kind == "stmt"
        nxt = cfg.node(entry.norm_succs[0])
        assert nxt.norm_succs == [cfg.exit_id]

    def test_call_gets_exception_edge_to_raise(self):
        cfg = _cfg_of("def f():\n    risky()\n")
        assert cfg.node(cfg.entry).exc_succs == [cfg.raise_id]

    def test_no_raise_callees_get_no_exception_edge(self):
        # metrics/log calls are modeled infallible (DEFAULT_NO_RAISE) —
        # without this, every counter bump between acquire and release
        # would manufacture a leak path
        cfg = _cfg_of("def f():\n    metrics.gauge_add('x', 1)\n")
        assert cfg.node(cfg.entry).exc_succs == []

    def test_raise_stmt_edge_shape(self):
        cfg = _cfg_of("def f():\n    raise ValueError('x')\n")
        entry = cfg.node(cfg.entry)
        assert entry.kind == "raise_stmt"
        assert entry.norm_succs == [] and entry.exc_succs == [cfg.raise_id]

    def test_finally_copied_per_continuation(self):
        # the finally body is wired once per way out of the try: the
        # normal path ends at EXIT, the exceptional path re-raises at
        # RAISE — never merged (a shared block would manufacture
        # normal-completion -> exceptional-exit paths)
        cfg = _cfg_of(
            "def f():\n"
            "    try:\n"
            "        risky()\n"
            "    finally:\n"
            "        cleanup()\n")
        copies = [n for n in cfg.nodes if n.line == 5]
        assert len(copies) == 2
        assert {c.norm_succs[0] for c in copies} == {cfg.exit_id,
                                                     cfg.raise_id}

    def test_return_through_finally(self):
        cfg = _cfg_of(
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    finally:\n"
            "        cleanup()\n")
        ret = next(n for n in cfg.nodes if n.kind == "return")
        fin = cfg.node(ret.norm_succs[0])
        assert fin.line == 5  # the return routes through the finally
        assert fin.norm_succs == [cfg.exit_id]

    def test_narrow_except_keeps_propagate_edge(self):
        cfg = _cfg_of(
            "def f():\n"
            "    try:\n"
            "        risky()\n"
            "    except ValueError:\n"
            "        handle()\n")
        disp = next(n for n in cfg.nodes if n.kind == "except_dispatch")
        assert cfg.raise_id in disp.exc_succs  # may not match -> onward

    def test_broad_except_drops_propagate_edge(self):
        cfg = _cfg_of(
            "def f():\n"
            "    try:\n"
            "        risky()\n"
            "    except Exception:\n"
            "        handle()\n")
        disp = next(n for n in cfg.nodes if n.kind == "except_dispatch")
        assert disp.exc_succs == []

    def test_loop_break_and_back_edge(self):
        cfg = _cfg_of(
            "def f(xs):\n"
            "    for x in xs:\n"
            "        if x:\n"
            "            break\n"
            "        use(x)\n"
            "    tail()\n")
        loop = next(n for n in cfg.nodes if n.kind == "loop")
        brk = next(n for n in cfg.nodes if n.kind == "break")
        tail = next(n for n in cfg.nodes
                    if n.kind == "stmt" and n.line == 6)
        assert brk.norm_succs == [tail.index]
        use = next(n for n in cfg.nodes
                   if n.kind == "stmt" and n.line == 5)
        assert use.norm_succs == [loop.index]  # back edge

    def test_with_header_can_raise(self):
        cfg = _cfg_of("def f(lk):\n    with lk:\n        body()\n")
        w = next(n for n in cfg.nodes if n.kind == "with")
        assert cfg.raise_id in w.exc_succs  # __enter__ may raise


# -- UDA101: resource balance ------------------------------------------------


PAIRS = (
    ObligationPair("engine.admit", acquire=("_admit_bytes",),
                   release=("_unadmit",)),
    ObligationPair("pool.lease", acquire=("lease",), release=("release",),
                   recv=r".*(pool|bufs).*"),
    ObligationPair("gauge.fetch.on_air", kind="gauge",
                   gauge="fetch.on_air"),
    ObligationPair("ctx.failpoints.scoped", kind="context",
                   acquire=("scoped",), recv=r".*failpoints.*",
                   transfer=("enter_context",)),
)


class TestResourceBalanceRule:
    def rules(self):
        return [ResourceBalanceRule(pairs=PAIRS)]

    def test_tryplan_shape_unguarded_charge_fires(self):
        # PR 6's historical leak: charge, then a fallible call whose
        # exception path exits without the paired release
        src = """
        def plan(self, req):
            self._admit_bytes(8)
            out = self._build(req)
            self._unadmit(8)
            return out
        """
        out = lint(src, self.rules())
        assert rule_ids(out) == ["UDA101"]
        assert out[0].line == 3  # anchored on the acquire
        assert "exception path" in out[0].message

    def test_finally_guard_passes(self):
        src = """
        def plan(self, req):
            self._admit_bytes(8)
            try:
                return self._build(req)
            finally:
                self._unadmit(8)
        """
        assert lint(src, self.rules()) == []

    def test_exception_path_release_passes(self):
        # the overlap.py review-hardening shape: release on the
        # exception path, obligation rides the return value otherwise
        src = """
        def stage(self, n):
            buf = self._pool.lease(n, 4)
            try:
                fill(buf)
                return buf
            except BaseException:
                self._pool.release(buf)
                raise
        """
        assert lint(src, self.rules()) == []

    def test_early_constant_return_leaks_normal_path(self):
        src = """
        def serve(self):
            self._admit_bytes(8)
            if self.closed:
                return None
            self._unadmit(8)
        """
        out = lint(src, self.rules())
        assert rule_ids(out) == ["UDA101"]
        assert "normal path" in out[0].message

    def test_value_return_is_a_transfer(self):
        # the FdSlice idiom: the obligation rides the returned handle,
        # whoever holds it owes the release (the runtime ledger agrees)
        src = """
        def grab(self, n):
            buf = self._pool.lease(n, 4)
            return buf
        """
        assert lint(src, self.rules()) == []

    def test_receiver_filter_scopes_generic_names(self):
        src = """
        def f(self):
            self.sem.lease(4, 4)
        """
        assert lint(src, self.rules()) == []  # not a pool/bufs receiver

    def test_gauge_pair_unguarded_fires(self):
        src = """
        def f(self):
            metrics.gauge_add("fetch.on_air", 1)
            self._issue()
            metrics.gauge_add("fetch.on_air", -1)
        """
        out = lint(src, self.rules())
        assert rule_ids(out) == ["UDA101"]
        assert out[0].data == {"pair": "gauge.fetch.on_air"}

    def test_gauge_pair_finally_guard_passes(self):
        src = """
        def f(self):
            metrics.gauge_add("fetch.on_air", 1)
            try:
                self._issue()
            finally:
                metrics.gauge_add("fetch.on_air", -1)
        """
        assert lint(src, self.rules()) == []

    def test_context_pair_must_be_entered(self):
        out = lint("def f():\n    s = failpoints.scoped('a=error')\n"
                   "    use(s)\n", self.rules())
        assert rule_ids(out) == ["UDA101"]
        assert "not entered" in out[0].message

    def test_context_pair_with_guard_passes(self):
        src = """
        def f():
            with failpoints.scoped('a=error'):
                go()
        """
        assert lint(src, self.rules()) == []

    def test_context_pair_enter_context_passes(self):
        src = """
        def f(stack):
            stack.enter_context(failpoints.scoped('a=error'))
            go()
        """
        assert lint(src, self.rules()) == []

    def test_loop_reacquire_balanced_passes(self):
        src = """
        def f(self, xs):
            for x in xs:
                self._admit_bytes(8)
                try:
                    use(x)
                finally:
                    self._unadmit(8)
        """
        assert lint(src, self.rules()) == []

    def test_nested_def_analyzed_on_its_own_cfg(self):
        src = """
        def f(self):
            def later():
                self._admit_bytes(8)
            return later
        """
        out = lint(src, self.rules())
        # the ENCLOSING function does not inherit the nested acquire
        # (deferred code runs on its own CFG) — but the nested def's
        # own unreleased charge IS a finding, at its own line
        assert rule_ids(out) == ["UDA101"]
        assert out[0].line == 4

    def test_pair_impl_bodies_exempt(self):
        # the function NAMED like the pair's acquire IS its
        # implementation — charging its body would double count
        src = """
        def _admit_bytes(self, want):
            self._check(want)
            self.total += want
        """
        assert lint(src, self.rules()) == []

    def test_suppression_silences(self):
        src = """
        def f(self):
            self._admit_bytes(8)  # udalint: disable=UDA101
            self._build()
            self._unadmit(8)
        """
        assert lint(src, self.rules()) == []


# -- UDA102: transitive blocking ---------------------------------------------


class TestTransitiveBlockingRule:
    def rules(self):
        return [TransitiveBlockingRule()]

    def test_helper_hop_under_lock_fires(self):
        # the hop that defeats UDA007: the blocking call lives one
        # helper away from the `with lock:`
        src = """
        class C:
            def _settle(self):
                self._done.wait()
            def run(self):
                with self._lock:
                    self._settle()
        """
        out = lint(src, self.rules())
        assert rule_ids(out) == ["UDA102"]
        assert "_settle" in out[0].message and ".wait()" in out[0].message

    def test_two_hop_chain_fires_with_witness(self):
        src = """
        class C:
            def _inner(self):
                self._fut.result()
            def _outer(self):
                self._inner()
            def run(self):
                with self._mu:
                    self._outer()
        """
        out = lint(src, self.rules())
        assert rule_ids(out) == ["UDA102"]
        assert "_outer -> _inner -> Future.result()" in out[0].message

    def test_bounded_helper_passes(self):
        src = """
        class C:
            def _settle(self):
                self._done.wait(timeout=2.0)
            def run(self):
                with self._lock:
                    self._settle()
        """
        assert lint(src, self.rules()) == []

    def test_one_benign_homonym_acquits(self):
        # name-keyed resolution convicts a name only when EVERY def of
        # it blocks — a blocking twin in an unrelated module must not
        # poison callers of the benign one
        files = {
            "uda_tpu/a.py": """
            def flush(self):
                self._q.get()
            """,
            "uda_tpu/b.py": """
            def flush(self):
                self.buf.clear()
            def run(self):
                with self._lock:
                    self.flush()
            """,
        }
        assert lint_tree(files, self.rules()) == []

    def test_loop_callback_helper_hop_fires_in_net(self):
        src = """
        def _pump(self):
            self._fut.result()

        @loop_callback
        def on_readable(self, mask):
            self._pump()
        """
        out = lint(src, self.rules(), rel="uda_tpu/net/x.py")
        assert rule_ids(out) == ["UDA102"]
        assert "@loop_callback" in out[0].message

    def test_loop_callback_outside_net_ignored(self):
        src = """
        def _pump(self):
            self._fut.result()

        @loop_callback
        def on_readable(self, mask):
            self._pump()
        """
        assert lint(src, self.rules(), rel="uda_tpu/merger/x.py") == []

    def test_direct_blocking_left_to_uda007(self):
        src = """
        class C:
            def run(self):
                with self._lock:
                    self._done.wait()
        """
        assert lint(src, self.rules()) == []  # UDA007's finding, not ours

    def test_suppression_silences(self):
        src = """
        class C:
            def _settle(self):
                self._done.wait()
            def run(self):
                with self._lock:
                    self._settle()  # udalint: disable=UDA102
        """
        assert lint(src, self.rules()) == []


# -- UDA103: static lock order -----------------------------------------------


class TestStaticLockOrderRule:
    def rules(self):
        return [StaticLockOrderRule()]

    def test_ab_ba_nesting_fires(self):
        src = """
        class C:
            def __init__(self):
                self._alk = TrackedLock("alpha")
                self._blk = TrackedLock("beta")
            def one(self):
                with self._alk:
                    with self._blk:
                        pass
            def two(self):
                with self._blk:
                    with self._alk:
                        pass
        """
        out = lint(src, self.rules())
        assert rule_ids(out) == ["UDA103"]
        assert "alpha" in out[0].message and "beta" in out[0].message

    def test_consistent_order_passes(self):
        src = """
        class C:
            def __init__(self):
                self._alk = TrackedLock("alpha")
                self._blk = TrackedLock("beta")
            def one(self):
                with self._alk:
                    with self._blk:
                        pass
            def two(self):
                with self._alk:
                    with self._blk:
                        pass
        """
        assert lint(src, self.rules()) == []

    def test_cross_file_inversion_fires(self):
        # the whole point of the tree-wide sweep: the two halves of the
        # inversion live in different modules and no test interleaves
        # them — lexical nesting alone convicts
        files = {
            "uda_tpu/p.py": """
            class P:
                def __init__(self):
                    self._alk = TrackedLock("alpha")
                    self._blk = TrackedLock("beta")
                def go(self):
                    with self._alk:
                        with self._blk:
                            pass
            """,
            "uda_tpu/q.py": """
            class Q:
                def __init__(self):
                    self._xl = TrackedLock("beta")
                    self._yl = TrackedLock("alpha")
                def go(self):
                    with self._xl:
                        with self._yl:
                            pass
            """,
        }
        out = lint_tree(files, self.rules())
        assert rule_ids(out) == ["UDA103"]

    def test_condition_wraps_lock_class(self):
        src = """
        class C:
            def __init__(self):
                self._cv = TrackedCondition(TrackedLock("alpha"))
                self._blk = TrackedLock("beta")
            def one(self):
                with self._cv:
                    with self._blk:
                        pass
            def two(self):
                with self._blk:
                    with self._cv:
                        pass
        """
        out = lint(src, self.rules())
        assert rule_ids(out) == ["UDA103"]

    def test_same_class_nesting_is_not_an_edge(self):
        # lockdep's rule: class-level self-edges false-positive on
        # instance hierarchies
        src = """
        class C:
            def __init__(self):
                self._alk = TrackedLock("alpha")
                self._blk = TrackedLock("alpha")
            def go(self):
                with self._alk:
                    with self._blk:
                        pass
        """
        assert lint(src, self.rules()) == []

    def test_enclosing_def_boundary_stops_the_chain(self):
        # a `with` in an ENCLOSING def is not held when the nested def
        # runs later — no edge
        src = """
        class C:
            def __init__(self):
                self._alk = TrackedLock("alpha")
                self._blk = TrackedLock("beta")
            def one(self):
                with self._blk:
                    def later(self):
                        with self._alk:
                            pass
                    return later
            def two(self):
                with self._alk:
                    with self._blk:
                        pass
        """
        assert lint(src, self.rules()) == []


# -- static <-> runtime inventory lockstep -----------------------------------


def test_static_and_runtime_inventories_agree():
    """A UDA101 finding and a runtime leak report must name the same
    discipline: the static registry's gauge pairs ARE the ledger's
    paired-gauge table, id for id."""
    static_gauges = {p.gauge: p.pair_id for p in DEFAULT_PAIRS
                     if p.kind == "gauge"}
    assert static_gauges == PAIRED_GAUGES


def test_udaflow_rules_registered_in_engine():
    ids = {cls.rule_id for cls in ALL_RULES}
    assert {"UDA101", "UDA102", "UDA103"} <= ids


def test_udalint_json_output_is_machine_readable():
    """The --json contract the CI/chaos gates consume: one object,
    files + rules + findings[] with file/line/col/rule fields."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "udalint.py"),
         "--json", "uda_tpu/analysis"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["findings"] == [] and doc["files"] >= 4
    assert "UDA101" in doc["rules"]


# -- ResourceLedger (runtime half) -------------------------------------------


class TestResourceLedger:
    def test_disabled_is_inert(self):
        led = ResourceLedger(enabled=False)
        led.acquire("pool.lease", key=1)
        assert led.outstanding() == []
        assert led.drain("x") == []

    def test_unit_acquire_settle(self):
        led = ResourceLedger(enabled=True)
        led.acquire("engine.fd", key="/a", owner=7)
        led.acquire("engine.fd", key="/a", owner=7)
        led.settle("engine.fd", key="/a", owner=7)
        out = led.outstanding()
        assert len(out) == 1 and out[0]["pair"] == "engine.fd"
        led.settle("engine.fd", key="/a", owner=7)
        assert led.outstanding() == []

    def test_amount_settle_retires_oldest_first(self):
        led = ResourceLedger(enabled=True)
        led.acquire("gauge.stage.inflight", key="g", amount=10)
        led.acquire("gauge.stage.inflight", key="g", amount=5)
        led.settle("gauge.stage.inflight", key="g", amount=12)
        out = led.outstanding()
        assert len(out) == 1 and out[0]["amount"] == 3

    def test_unmatched_settle_ignored(self):
        # arming the ledger mid-process must not turn pre-arming
        # acquires into phantom double-releases
        led = ResourceLedger(enabled=True)
        led.settle("pool.lease", key=9)
        assert led.outstanding() == []

    def test_drain_reports_once_with_stack(self):
        led = ResourceLedger(enabled=True)
        led.acquire("pool.lease", key=3, amount=64, detail="fixture")
        reports = led.drain("unit.test")
        assert len(reports) == 1
        r = reports[0]
        assert r["pair"] == "pool.lease" and r["point"] == "unit.test"
        assert "test_drain_reports_once_with_stack" in r["stack"]
        assert led.drain("unit.test") == []  # popped: reported ONCE
        assert len(led.leak_reports) == 1

    def test_drain_owner_scope(self):
        # one engine's drain point must not confiscate a live peer's
        # legitimately-open obligations (the killed-supplier shape)
        led = ResourceLedger(enabled=True)
        led.acquire("engine.fd", key="/a", owner=1)
        led.acquire("engine.fd", key="/a", owner=2)
        assert len(led.drain("stop", owner=1)) == 1
        assert len(led.outstanding()) == 1
        assert led.outstanding()[0]["owner"] == 2

    def test_drain_pair_filter(self):
        led = ResourceLedger(enabled=True)
        led.acquire("pool.lease", key=1)
        led.acquire("engine.fd", key="/a")
        assert len(led.drain("stop", pairs=("engine.fd",))) == 1
        assert led.outstanding()[0]["pair"] == "pool.lease"

    def test_note_gauge_balanced(self):
        led = ResourceLedger(enabled=True)
        led.note_gauge("stage.inflight.bytes", 100)
        led.note_gauge("stage.inflight.bytes", -100)
        assert led.outstanding() == []
        led.note_gauge("unpaired.gauge", 1)  # not in PAIRED_GAUGES
        assert led.outstanding() == []

    def test_settle_before_acquire_inversion_books_deficit(self):
        # the paired-gauge bumps ride OUTSIDE the state locks that
        # order the attempts, so a decrement can reach the books an
        # instant before its matching increment (watchdog-rescue
        # fail() racing _try_issue's +1); the shortfall must cancel
        # the late acquire instead of fabricating a phantom
        # obligation that false-leaks at the next drain
        led = ResourceLedger(enabled=True)
        led.note_gauge("fetch.on_air", -1)   # the settle wins the race
        led.note_gauge("fetch.on_air", 1)    # its increment lands late
        assert led.outstanding() == []
        assert led.drain("unit.test") == []
        # partial inversion: the deficit cancels only its own share
        led.note_gauge("stage.inflight.bytes", -40)
        led.note_gauge("stage.inflight.bytes", 100)
        open_now = led.outstanding()
        assert [r["amount"] for r in open_now] == [60]
        led.note_gauge("stage.inflight.bytes", -60)
        assert led.outstanding() == []

    def test_deficit_does_not_survive_a_drain(self):
        # a deficit is a transient in-flight inversion; at a quiescent
        # drain boundary it must not linger and swallow a LATER
        # legitimate acquire (which would hide a real leak)
        led = ResourceLedger(enabled=True)
        led.note_gauge("fetch.on_air", -1)
        led.drain("unit.test")               # quiescent boundary
        led.note_gauge("fetch.on_air", 1)    # fresh obligation
        assert len(led.outstanding()) == 1
        assert len(led.drain("unit.test")) == 1

    def test_json_report_appends(self, tmp_path, monkeypatch):
        path = str(tmp_path / "leaks.jsonl")
        monkeypatch.setenv("UDA_TPU_RESLEDGER_JSON", path)
        led = ResourceLedger(enabled=True, emit_json=True)
        led.acquire("pool.lease", key=4)
        led.drain("unit.json")
        with open(path) as f:
            recs = [json.loads(ln) for ln in f]
        assert len(recs) == 1 and recs[0]["point"] == "unit.json"

    def test_failpoints_scoped_is_ledgered(self, monkeypatch):
        led = ResourceLedger(enabled=True)
        import uda_tpu.utils.resledger as resledger_mod

        monkeypatch.setattr(resledger_mod, "resledger", led)
        with failpoints.scoped("data_engine.pread=delay:1:once"):
            assert len(led.outstanding()) == 1
            assert led.outstanding()[0]["pair"] == "ctx.failpoints.scoped"
        assert led.outstanding() == []


# -- the faults-marked mid-pipeline leak test --------------------------------


@pytest.mark.faults
def test_resledger_midpipeline_fault_and_seeded_leak(tmp_path, monkeypatch):
    """Two guarantees in one run. (1) A storage fault that aborts a
    REAL pipelined merger leaks zero obligations — the chaos rungs'
    zero-leaks gate in miniature. (2) A seeded stray pool lease (the
    lost-worker-buffer shape) is reported at the abort drain point
    exactly once, with the acquire stack pointing at this test."""
    priv = ResourceLedger(enabled=True)
    monkeypatch.setattr(merge_ops, "resledger", priv)
    monkeypatch.setattr(overlap_mod, "resledger", priv)

    make_mof_tree(str(tmp_path), "jobRL", 6, 1, 40, seed=11)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    cfg = Config({"uda.tpu.stage.pipeline": True,
                  "uda.tpu.stage.pool": 2,
                  "uda.tpu.fetch.retries": 0})
    mm = MergeManager(LocalFetchClient(engine), KT, cfg)
    try:
        with failpoints.scoped("data_engine.pread=error:prob:0.7:seed:5"):
            with pytest.raises(FallbackSignal):
                mm.run("jobRL", map_ids("jobRL", 6), 0, lambda b: None)
    finally:
        engine.stop()
    om = mm._active_overlap
    assert om is not None and om._aborted
    for t in om._threads:
        t.join(timeout=10)
        assert not t.is_alive()
    # (1) the fault-and-abort left the books EMPTY
    assert priv.leak_reports == []
    assert priv.outstanding() == []
    if om._buf_pool is None:
        pytest.skip("no host buffer pool on this engine config "
                    "(native rows merge unavailable)")
    # (2) seed the historical leak shape and re-drain
    stray = om._buf_pool.lease(64, 8)
    assert stray is not None
    om.abort()
    assert len(priv.leak_reports) == 1
    rep = priv.leak_reports[0]
    assert rep["pair"] == "pool.lease"
    assert rep["point"] == "merger.abort"
    assert "test_resledger_midpipeline_fault_and_seeded_leak" in rep["stack"]
    # reported exactly once: the drain popped it
    om.abort()
    assert len(priv.leak_reports) == 1


def test_rowbufferpool_lease_release_is_ledgered(monkeypatch):
    priv = ResourceLedger(enabled=True)
    monkeypatch.setattr(merge_ops, "resledger", priv)
    pool = merge_ops.RowBufferPool()
    buf = pool.lease(16, 4)
    out = priv.outstanding()
    assert len(out) == 1 and out[0]["pair"] == "pool.lease"
    assert out[0]["owner"] == id(pool)
    pool.release(buf)
    assert priv.outstanding() == []
    # reuse path settles under the same key (the base data pointer)
    again = pool.lease(16, 4)
    assert len(priv.outstanding()) == 1
    pool.release(again)
    assert priv.outstanding() == []


def test_fd_cache_pins_are_ledgered(tmp_path, monkeypatch):
    priv = ResourceLedger(enabled=True)
    import uda_tpu.mofserver.data_engine as de_mod

    monkeypatch.setattr(de_mod, "resledger", priv)
    path = tmp_path / "mof.bin"
    path.write_bytes(b"x" * 64)
    cache = de_mod._FdCache()
    cache.acquire(str(path))
    cache.acquire(str(path))
    assert len(priv.outstanding()) == 2
    cache.release(str(path))
    assert len(priv.outstanding()) == 1
    cache.release(str(path))
    assert priv.outstanding() == []
    cache.release(str(path))  # over-release: clamped, settle ignored
    assert priv.outstanding() == []
    cache.close_all()


def test_global_ledger_disabled_by_default():
    """UDA_TPU_RESLEDGER unset => every hook is one attribute check and
    the books stay empty (the zero-overhead-when-off contract)."""
    if resledger.enabled:
        pytest.skip("ledger armed in this environment")
    resledger.acquire("pool.lease", key=99)
    assert resledger.outstanding() == []
