"""Push-based pipelined shuffle (uda_tpu/net/push.py, ISSUE 19): wire
codecs, the reduce-side admission ladder, supplier->reducer end-to-end
pushes adopted into the merge, wire back-compat in both directions, and
the fault shapes (admission refusal, torn push frames, supplier kills
racing in-flight pushes). The pull path is the byte-identity oracle
throughout: every push-assisted run must produce the same bytes a pure
pull of the same tree produces."""

import io
import os
import socket
import threading
import time

import numpy as np
import pytest

from tests.helpers import default_partitioner, make_mof_tree, map_ids
from uda_tpu.merger import HostRoutingClient, LocalFetchClient, MergeManager
from uda_tpu.mofserver import DataEngine, DirIndexResolver, ShuffleRequest
from uda_tpu.mofserver.writer import MOFWriter
from uda_tpu.net import RemoteFetchClient, ShuffleServer, wire
from uda_tpu.net.push import (NACK_BUDGET, NACK_CLAIMED, NACK_GAP,
                              PushStaging)
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import ProtocolError, TransportError
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.ifile import IFileWriter, crack
from uda_tpu.utils.metrics import metrics

JOB = "jobPush"
KT = "uda.tpu.RawBytes"


# -- wire codecs -------------------------------------------------------------

def _parts(frame: bytes):
    msg_type, req_id, length = wire.decode_header(frame[:wire.HEADER.size])
    payload = frame[wire.HEADER.size:]
    assert len(payload) == length
    return msg_type, req_id, payload


def test_wire_push_roundtrip():
    body = b"\x01" * 777
    frame = wire.encode_push(31, job_id=JOB, map_id="m7", reduce_id=3,
                             offset=1 << 33, raw_length=(1 << 33) + 4096,
                             last=True, data=body)
    t, pid, payload = _parts(frame)
    assert (t, pid) == (wire.MSG_PUSH, 31)
    job, mid, rid, off, raw, last, data = \
        wire.decode_push_take(bytearray(payload))
    assert (job, mid, rid, off, raw, last, bytes(data)) == \
           (JOB, "m7", 3, 1 << 33, (1 << 33) + 4096, True, body)


def test_wire_push_sub_and_ack_nack_roundtrip():
    t, rid, payload = _parts(wire.encode_push_sub(
        9, job_id=JOB, reduce_id=5, window=8, chunk_size=1 << 20))
    assert (t, rid) == (wire.MSG_PUSH_SUB, 9)
    assert wire.decode_push_sub(payload) == (JOB, 5, 8, 1 << 20)

    t, pid, payload = _parts(wire.encode_push_ack(12))
    assert (t, pid, payload) == (wire.MSG_PUSH_ACK, 12, b"")

    t, pid, payload = _parts(wire.encode_push_nack(13, NACK_BUDGET))
    assert (t, pid) == (wire.MSG_PUSH_NACK, 13)
    assert wire.decode_push_nack(payload) == NACK_BUDGET
    # strictness: truncation and trailing bytes are torn frames
    with pytest.raises(TransportError):
        wire.decode_push_take(bytearray(b"\x00" * 4))
    with pytest.raises(TransportError):
        wire.decode_push_sub(payload + b"z")


def test_cap_push_rides_the_hello_banner():
    frame = wire.encode_hello(4, False, caps=wire.CAP_TRACE | wire.CAP_PUSH)
    _, _, payload = _parts(frame)
    _, _, caps = wire.decode_hello_ex(payload)
    assert caps & wire.CAP_PUSH
    # old decoders ignore the bit entirely (forward compat)
    assert wire.decode_hello(payload) == (4, False)


# -- reduce-side staging (the admission ladder) ------------------------------

def _blob(n_records=120, seed=3):
    """One partition's IFile-framed on-disk bytes."""
    rng = np.random.default_rng(seed)
    out = io.BytesIO()
    w = IFileWriter(out)
    for k, v in sorted((rng.bytes(10), rng.bytes(30))
                       for _ in range(n_records)):
        w.append(k, v)
    w.close()
    return out.getvalue()


def _offer_chunks(st, map_id, blob, chunk):
    """Push ``blob`` into staging as contiguous ``chunk``-byte offers;
    returns the verdict list."""
    verdicts = []
    for off in range(0, len(blob), chunk):
        piece = blob[off:off + chunk]
        verdicts.append(st.offer(map_id, off, len(blob),
                                 off + len(piece) >= len(blob), piece))
    return verdicts


def test_staging_take_trims_the_last_chunk():
    blob = _blob()
    st = PushStaging(JOB, 0, cfg=Config())
    try:
        assert _offer_chunks(st, "m0", blob, 1000) == \
               [0] * ((len(blob) + 999) // 1000)
        assert st.staged_bytes() == len(blob)
        kw = st.take("m0")
        # the final chunk is withheld: the pull path re-fetches the
        # tail and stays the byte-identity oracle
        usable = (len(blob) // 1000) * 1000
        assert kw["next_offset"] == usable
        assert kw["data"] == blob[:usable]
        assert kw["raw_length"] == len(blob)
        batch, consumed, _ = __import__(
            "uda_tpu.utils.ifile", fromlist=["crack_partial"]
        ).crack_partial(kw["data"], expect_eof=False)
        assert kw["carry_len"] == len(kw["data"]) - consumed
        assert kw["num_records"] == batch.num_records
        # taking settled the gauge; a second take is None (claimed)
        assert metrics.get_gauge("push.staged.bytes") == 0
        assert st.take("m0") is None
    finally:
        st.close()


def test_staging_gap_claimed_and_unknown_verdicts():
    blob = _blob(40)
    st = PushStaging(JOB, 1, cfg=Config())
    try:
        assert st.offer("m1", 0, len(blob), False, blob[:500]) == 0
        # non-contiguous offset: refused, the accepted prefix survives
        assert st.offer("m1", 900, len(blob), False, blob[900:1000]) \
               == NACK_GAP
        assert st.staged_bytes() == 500
        assert metrics.get("push.refused", reason="gap") == 1
        # take() claims even when nothing was staged for the map — the
        # dedup against the now in-flight fetch
        assert st.take("m_never_pushed") is None
        assert st.offer("m_never_pushed", 0, 100, False, blob[:100]) \
               == NACK_CLAIMED
        st.take("m1")
        assert st.offer("m1", 500, len(blob), False, blob[500:600]) \
               == NACK_CLAIMED
    finally:
        st.close()


def test_staging_budget_nack_keeps_prefix_spill_disabled():
    blob = _blob(200)
    st = PushStaging(JOB, 2, cfg=Config({
        "uda.tpu.push.eager.mb": 0.001,   # ~1 KB memory tier
        "uda.tpu.push.spill": False,
    }))
    try:
        assert st.offer("m2", 0, len(blob), False, blob[:1000]) == 0
        assert st.offer("m2", 1000, len(blob), False, blob[1000:2000]) \
               == NACK_BUDGET
        # refusal cost zero bytes: the prefix is still staged
        assert st.staged_bytes() == 1000
        assert metrics.get("push.refused", reason="budget") == 1
    finally:
        st.close()
    assert metrics.get_gauge("push.staged.bytes") == 0


def test_staging_spill_tier_preserves_bytes(tmp_path):
    blob = _blob(300)
    st = PushStaging(JOB, 3, cfg=Config({
        "uda.tpu.push.eager.mb": 0.001,
        "uda.tpu.push.staged.mb": 8.0,
        "uda.tpu.spill.dirs": str(tmp_path),
    }))
    try:
        chunk = 2048  # every chunk overflows the ~1 KB eager tier
        assert all(v == 0 for v in _offer_chunks(st, "m3", blob, chunk))
        assert metrics.get("push.spilled.bytes") > 0
        kw = st.take("m3")
        usable = (len(blob) // chunk) * chunk
        assert kw["data"] == blob[:usable]
    finally:
        st.close()


# -- end-to-end: supplier pushes, merge adopts -------------------------------

def _push_cfg(**extra):
    base = {"uda.tpu.push.enable": True,
            "mapred.rdma.buf.size": 4}  # 4 KB chunks: multi-chunk maps
    base.update(extra)
    return Config(base)


def _write_job(writer, num_maps, num_reducers, records_per_map, seed=11):
    """Drive the MOFWriter the way a map phase would; returns expected
    records per reducer."""
    rng = np.random.default_rng(seed)
    expected = {r: [] for r in range(num_reducers)}
    for m in range(num_maps):
        parts = {r: [] for r in range(num_reducers)}
        for _ in range(records_per_map):
            k, v = rng.bytes(10), rng.bytes(30)
            r = default_partitioner(k, num_reducers)
            parts[r].append((k, v))
            expected[r].append((k, v))
        writer.write(f"attempt_{JOB}_m_{m:06d}_0",
                     [sorted(parts[r]) for r in range(num_reducers)])
    return expected


def _reduce_bytes(port, cfg, reduce_id, num_maps, arm_first=False):
    """One reduce task over the wire -> its merged output bytes."""
    router = HostRoutingClient(config=cfg)
    mm = MergeManager(router, KT, cfg)
    blocks = []
    addr = f"127.0.0.1:{port}"
    maps = [(addr, m) for m in map_ids(JOB, num_maps)]
    try:
        if arm_first:
            mm.arm_push(JOB, reduce_id, hosts={addr})
        mm.run(JOB, maps, reduce_id, lambda b: blocks.append(bytes(b)))
        return b"".join(blocks)
    finally:
        router.stop()


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


def test_push_end_to_end_byte_identical_with_adoption(tmp_path):
    """Arm BEFORE the map phase: commits stream over as MSG_PUSH while
    the 'job' is still writing, the merge adopts the staged prefixes,
    and the output bytes equal a pure pull of the same tree."""
    cfg = _push_cfg()
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0).start()
    router = HostRoutingClient(config=cfg)
    mm = MergeManager(router, KT, cfg)
    addr = f"127.0.0.1:{server.port}"
    try:
        staging = mm.arm_push(JOB, 0, hosts={addr})
        assert staging is not None
        writer = MOFWriter(str(tmp_path), JOB,
                           on_commit=server.notify_commit)
        _write_job(writer, num_maps=4, num_reducers=1,
                   records_per_map=300)
        # the overlap win: pushed bytes land while no fetch is running
        _wait(lambda: staging.staged_bytes() > 0, msg="staged pushes")
        blocks = []
        mm.run(JOB, [(addr, m) for m in map_ids(JOB, 4)], 0,
               lambda b: blocks.append(bytes(b)))
        pushed = b"".join(blocks)
        assert metrics.get("push.commits") == 4
        assert metrics.get("push.chunks") > 0
        assert metrics.get("push.adopted") > 0
        assert metrics.get("push.adopted.bytes") > 0
    finally:
        router.stop()
        server.stop()
        engine.stop()

    # pure-pull oracle over the same tree
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    try:
        mm = MergeManager(LocalFetchClient(engine), KT, Config())
        blocks = []
        mm.run(JOB, map_ids(JOB, 4), 0, lambda b: blocks.append(bytes(b)))
        assert pushed == b"".join(blocks) and len(pushed) > 0
    finally:
        engine.stop()
    assert metrics.get_gauge("push.on_air") == 0
    assert metrics.get_gauge("push.staged.bytes") == 0


def test_push_catch_up_after_late_subscribe(tmp_path):
    """A SUB that arrives after every map already committed still gets
    the full set pushed (the catch-up path)."""
    cfg = _push_cfg()
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0).start()
    writer = MOFWriter(str(tmp_path), JOB, on_commit=server.notify_commit)
    _write_job(writer, num_maps=3, num_reducers=1, records_per_map=300)
    try:
        client = RemoteFetchClient("127.0.0.1", server.port, cfg)
        staging = PushStaging(JOB, 0, cfg=cfg)
        try:
            client.push_register(JOB, 0, staging)
            _wait(lambda: metrics.get("push.acks") > 0
                  and staging.staged_bytes() > 0, msg="catch-up pushes")
        finally:
            client.stop()
            staging.close()
    finally:
        server.stop()
        engine.stop()
    assert metrics.get("push.subs") == 1
    assert metrics.get_gauge("push.on_air") == 0


# -- wire back-compat (both directions degrade to pure pull) -----------------

def test_push_server_with_pushless_client_stays_pull(tmp_path):
    """A CAP_PUSH server facing a client that never subscribes must
    send zero pushes and serve pulls byte-identically."""
    cfg = _push_cfg()
    expected = make_mof_tree(str(tmp_path), JOB, num_maps=3,
                             num_reducers=1, records_per_map=80, seed=2)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0).start()
    try:
        got = _reduce_bytes(server.port, Config(), 0, num_maps=3)
        records = list(crack(got).iter_records())
        assert sorted(records) == sorted(expected[0])
        assert metrics.get("push.subs") == 0
        assert metrics.get("push.chunks") == 0
    finally:
        server.stop()
        engine.stop()


def test_push_client_with_pushless_server_stays_pull(tmp_path):
    """A push-armed reducer facing a server without CAP_PUSH in its
    banner must never send MSG_PUSH_SUB and still pull everything."""
    expected = make_mof_tree(str(tmp_path), JOB, num_maps=3,
                             num_reducers=1, records_per_map=80, seed=2)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, Config(), host="127.0.0.1",
                           port=0).start()
    try:
        got = _reduce_bytes(server.port, _push_cfg(), 0, num_maps=3,
                            arm_first=True)
        records = list(crack(got).iter_records())
        assert sorted(records) == sorted(expected[0])
        assert metrics.get("push.subs") == 0
        assert metrics.get("net.errors") == 0
    finally:
        server.stop()
        engine.stop()
    assert metrics.get_gauge("push.staged.bytes") == 0


def test_pushless_server_refuses_sub_with_typed_err(tmp_path):
    """Unknown-frame strictness is preserved: a PUSH_SUB at a push-less
    server draws the typed ERR refusal on the same req id and the
    connection keeps serving fetches."""
    make_mof_tree(str(tmp_path), JOB, num_maps=1, num_reducers=1,
                  records_per_map=10, seed=4)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, Config(), host="127.0.0.1",
                           port=0).start()
    sock = socket.create_connection(("127.0.0.1", server.port), timeout=5)
    try:
        sock.settimeout(5)
        t, _, payload = wire.recv_frame(sock)
        assert t == wire.MSG_HELLO
        _, _, caps = wire.decode_hello_ex(payload)
        assert not caps & wire.CAP_PUSH
        sock.sendall(wire.encode_push_sub(7, job_id=JOB, reduce_id=0,
                                          window=4, chunk_size=4096))
        t, rid, payload = wire.recv_frame(sock)
        assert (t, rid) == (wire.MSG_ERR, 7)
        assert isinstance(wire.decode_error(payload), ProtocolError)
        # same connection still serves data
        sock.sendall(wire.encode_request(8, ShuffleRequest(
            JOB, map_ids(JOB, 1)[0], 0, 0, 1 << 20)))
        t, rid, _ = wire.recv_frame(sock)
        assert (t, rid) == (wire.MSG_DATA, 8)
    finally:
        sock.close()
        server.stop()
        engine.stop()


# -- fault shapes ------------------------------------------------------------

def _push_run_with_fault(tmp_path, spec, ready):
    """Full push-armed reduce under an armed failpoint spec; returns
    the merged bytes (must equal the pull oracle's). ``ready()``
    delays the merge start until the fault under test has visibly
    fired on the push plane — otherwise the fetch wave can claim the
    target map before its pushes arrive and the injected shape never
    engages. The wait is best-effort, not an assertion: an AMBIENT
    chaos schedule (UDA_FAILPOINTS) can tear the idle push connection
    before the shape fires, and nothing re-dials until the fetch wave
    starts. The retry budget is chaos-sized: a torn push frame closes
    the whole connection (stream desync), failing every in-flight pull
    on it — with ONE supplier each tear costs a retry on every
    affected map, and the ambient chaos schedule can tear
    repeatedly."""
    cfg = _push_cfg(**{"uda.tpu.fetch.retries": 10})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0).start()
    router = HostRoutingClient(config=cfg)
    mm = MergeManager(router, KT, cfg)
    addr = f"127.0.0.1:{server.port}"
    try:
        with failpoints.scoped(spec):
            staging = mm.arm_push(JOB, 0, hosts={addr})
            assert staging is not None
            writer = MOFWriter(str(tmp_path), JOB,
                               on_commit=server.notify_commit)
            _write_job(writer, num_maps=4, num_reducers=1,
                       records_per_map=300)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not ready():
                time.sleep(0.01)
            blocks = []
            mm.run(JOB, [(addr, m) for m in map_ids(JOB, 4)], 0,
                   lambda b: blocks.append(bytes(b)))
            return b"".join(blocks)
    finally:
        router.stop()
        server.stop()
        engine.stop()


def _pull_oracle(tmp_path, num_maps=4):
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    try:
        mm = MergeManager(LocalFetchClient(engine), KT, Config())
        blocks = []
        mm.run(JOB, map_ids(JOB, num_maps), 0,
               lambda b: blocks.append(bytes(b)))
        return b"".join(blocks)
    finally:
        engine.stop()


@pytest.mark.faults
def test_push_admit_fault_converts_to_pull(tmp_path):
    """An injected admission failure NACKs pushes of one map; the
    supplier goes pull-only for it and the output is byte-identical."""
    mid = map_ids(JOB, 4)[1]  # match: is a substring test on the
    # "<job>:<map>" key — the map id alone selects exactly one map
    got = _push_run_with_fault(
        tmp_path, f"push.admit=error:match:{mid}",
        ready=lambda: metrics.get("push.refused", reason="budget") > 0)
    assert got == _pull_oracle(tmp_path) and len(got) > 0
    if not os.environ.get("UDA_FAILPOINTS"):
        # the precise refusal accounting only holds without an ambient
        # chaos schedule: an ambient torn frame can kill the idle push
        # connection before map 1's chunks ever reach the admission
        # ladder, and the re-pushed copies then race the fetch wave's
        # claims (refused as "claimed", not "budget")
        assert metrics.get("push.refused", reason="budget") > 0
    assert metrics.get_gauge("push.on_air") == 0
    assert metrics.get_gauge("push.staged.bytes") == 0


@pytest.mark.faults
def test_push_frame_faults_recover_via_pull(tmp_path):
    """Injected outbound push failures (typed error every other frame)
    must leave the run byte-identical — failed partitions fall back to
    pull, accepted prefixes stay valid."""
    got = _push_run_with_fault(
        tmp_path, "net.push=error:every:2",
        ready=lambda: metrics.get("push.errors") > 0)
    assert got == _pull_oracle(tmp_path) and len(got) > 0
    if not os.environ.get("UDA_FAILPOINTS"):
        # same ambient-schedule caveat as the admit test: the idle
        # push connection can die before any push frame goes out
        assert metrics.get("push.errors") > 0
    assert metrics.get_gauge("push.on_air") == 0
    assert metrics.get_gauge("push.staged.bytes") == 0


@pytest.mark.faults
def test_supplier_kill_races_inflight_pushes(tmp_path):
    """Stop the supplier while pushes are in flight: the window settles
    (no stranded push.on_air), the staged prefix survives, and a
    restarted supplier serves the remainder byte-identically."""
    cfg = _push_cfg(**{"uda.tpu.fetch.retries": 10})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0).start()
    port = server.port
    router = HostRoutingClient(config=cfg)
    mm = MergeManager(router, KT, cfg)
    addr = f"127.0.0.1:{port}"
    try:
        staging = mm.arm_push(JOB, 0, hosts={addr})
        writer = MOFWriter(str(tmp_path), JOB,
                           on_commit=server.notify_commit)
        _write_job(writer, num_maps=4, num_reducers=1,
                   records_per_map=300)
        # kill mid-push: no waiting for the window to drain
        server.stop()
        assert metrics.get_gauge("push.on_air") == 0
        server = ShuffleServer(engine, cfg, host="127.0.0.1",
                               port=port).start()
        blocks = []
        mm.run(JOB, [(addr, m) for m in map_ids(JOB, 4)], 0,
               lambda b: blocks.append(bytes(b)))
        got = b"".join(blocks)
    finally:
        router.stop()
        server.stop()
        engine.stop()
    assert got == _pull_oracle(tmp_path) and len(got) > 0
    assert metrics.get_gauge("push.staged.bytes") == 0
