"""Tier-1 coverage of the live telemetry plane (ISSUE 17).

Four layers, mirroring the subsystem split:

1. the rollup ring (utils/timeseries.py): counter deltas, per-interval
   histogram percentiles recomputed from bucket deltas, ring bounds,
   window queries and the one-timer listener contract;
2. the online anomaly detectors (utils/anomaly.py): warmup + hysteresis
   before a throughput collapse or p99 inflation fires, transition-edge
   dedup, detect-only default, and the rate-limited PROACTIVE
   flight-recorder dump (exactly one, before anything fails);
3. the per-tenant SLI book (tenant/sli.py): scheduled-vs-entitled share
   from the WDRR scheduler's granted-byte deltas, SLO compliance /
   attainment / burn rate, and starvation streaks;
4. MSG_STATS interop (CAP_OBS): a windowed poll returns the new
   sections, an old-style empty-payload poll returns the unchanged
   PR 11 snapshot, a wrong-length tail is a torn frame, and the
   udafleet console merges a live daemon end to end.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.net import ShuffleServer, wire
from uda_tpu.net.client import fetch_remote_stats
from uda_tpu.utils.anomaly import AnomalyEngine
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import TransportError
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.flightrec import flightrec
from uda_tpu.utils.metrics import Metrics, metrics
from uda_tpu.utils.timeseries import TimeSeries

REPO = __file__.rsplit("/tests/", 1)[0]
JOB = "jobTs"


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float = 1.0) -> None:
        self.t += dt


def make_ts(window: int = 16, stats: bool = True):
    m = Metrics(stats=stats)
    clock = FakeClock()
    ts = TimeSeries(m, interval_s=1.0, window=window, clock=clock)
    return ts, m, clock


# -- the rollup ring ----------------------------------------------------------


def test_rollup_carries_counter_deltas_not_cumulatives():
    ts, m, clock = make_ts()
    m.add("fetch.bytes", 1000)
    ts.sample()  # self-baseline: first rollup is all-zero deltas
    m.add("fetch.bytes", 500)
    m.add("fetch.chunks")
    m.gauge("fetch.on_air", 7)
    clock.tick()
    roll = ts.sample()
    assert roll["counters"]["fetch.bytes"] == 500  # delta, not 1500
    assert roll["counters"]["fetch.chunks"] == 1
    assert "idle.counter" not in roll["counters"]  # nonzero only
    assert roll["gauges"]["fetch.on_air"] == 7  # level, not delta
    assert roll["dt"] == pytest.approx(1.0)
    clock.tick()
    quiet = ts.sample()
    assert quiet["counters"] == {}  # an idle interval rolls up empty


def test_interval_percentiles_see_one_bad_interval():
    """The cumulative-summary blind spot the ring exists to fix: a p99
    step in ONE interval must show at that interval's percentile, not
    be averaged into a long healthy history."""
    ts, m, clock = make_ts()
    for _ in range(500):
        m.observe("fetch.latency_ms", 5.0)
    ts.sample()
    clock.tick()
    for _ in range(100):
        m.observe("fetch.latency_ms", 5.0)
    roll1 = ts.sample()
    p1 = roll1["percentiles"]["fetch.latency_ms"]
    assert p1["count"] == 100
    assert p1["p99"] < 50
    clock.tick()
    for _ in range(100):
        m.observe("fetch.latency_ms", 900.0)
    roll2 = ts.sample()
    p2 = roll2["percentiles"]["fetch.latency_ms"]
    assert p2["count"] == 100
    # the interval view: pure 900 ms traffic, the 600 earlier 5 ms
    # samples cannot drag it down (cumulatively p99 would be ~5 ms)
    assert p2["p99"] > 500
    cum = m.histogram_summaries()["fetch.latency_ms"]
    assert cum["count"] == 700


def test_ring_bound_and_window_queries():
    ts, m, clock = make_ts(window=5)
    for i in range(9):
        m.add("fetch.bytes", 100 * (i + 1))
        ts.sample()
        clock.tick()
    rolls = ts.window()
    assert len(rolls) == 5  # oldest rolled off
    assert [r["seq"] for r in rolls] == [5, 6, 7, 8, 9]
    assert len(ts.window(count=2)) == 2
    # the trailing-seconds cut: each interval spans 1 s
    assert len(ts.window(seconds=3.0)) == 3
    assert len(ts.counter_rate_series("fetch.bytes")) == 5
    blk = ts.wire_block(seconds=2.0)
    assert blk["samples"] == 5 and len(blk["rollups"]) == 2


def test_configure_rebounds_ring_keeping_newest():
    ts, m, clock = make_ts(window=8)
    for _ in range(6):
        ts.sample()
        clock.tick()
    ts.configure(window=3)
    assert [r["seq"] for r in ts.window()] == [4, 5, 6]
    assert ts.window_len == 3


def test_listener_failure_is_counted_and_isolated():
    ts, m, clock = make_ts()
    seen = []

    def bad(roll):
        raise RuntimeError("consumer bug")

    ts.add_listener(bad)
    ts.add_listener(seen.append)
    before = metrics.snapshot().get("ts.listener.errors", 0)
    ts.sample()
    # one consumer failing neither stops the clock nor the others
    assert len(seen) == 1
    assert metrics.snapshot()["ts.listener.errors"] == before + 1
    ts.remove_listener(bad)
    clock.tick()
    ts.sample()
    assert len(seen) == 2
    assert metrics.snapshot()["ts.listener.errors"] == before + 1


# -- anomaly detection --------------------------------------------------------


def _roll(seq, counters=None, percentiles=None, gauges=None, dt=1.0):
    return {"seq": seq, "ts": 0.0, "dt": dt,
            "counters": counters or {}, "gauges": gauges or {},
            "percentiles": percentiles or {}}


def _armed_engine(tmp_path, overrides=None, ts=None):
    cfg = Config(dict({"uda.tpu.anomaly.consec": 2,
                       "uda.tpu.anomaly.warmup": 3}, **(overrides or {})))
    eng = AnomalyEngine()
    own_ts = ts or TimeSeries(Metrics(stats=True), clock=FakeClock())
    assert eng.arm_from_config(cfg, own_ts)
    flightrec._dump_dir = str(tmp_path)
    return eng


def test_throughput_collapse_fires_once_and_clears(tmp_path):
    eng = _armed_engine(tmp_path)
    seq = 0
    for _ in range(5):  # healthy: 10 MB/s, builds the EWMA past warmup
        seq += 1
        eng.on_rollup(_roll(seq, {"fetch.bytes": 10e6}))
    assert eng.fired == 0
    for _ in range(4):  # collapsed: 2% of baseline, under the 25% frac
        seq += 1
        eng.on_rollup(_roll(seq, {"fetch.bytes": 0.2e6}))
    # consec=2 hysteresis: fired on the 2nd breach; transition-edge
    # dedup: still ONE anomaly after 4 breaching intervals
    assert eng.fired == 1
    active = eng.active()
    assert [a["kind"] for a in active] == ["throughput"]
    assert active[0]["key"] == "fetch.bytes"
    assert metrics.snapshot()["anomaly.fired"] == 1
    assert metrics.snapshot()["anomaly.throughput{key=fetch.bytes}"] == 1
    # detect-only default: no proactive dump
    assert eng.dumps == 0 and not list(tmp_path.iterdir())
    for _ in range(3):  # recovery: _CLEAR_AFTER clean intervals
        seq += 1
        eng.on_rollup(_roll(seq, {"fetch.bytes": 10e6}))
    assert eng.active() == []


def test_single_noisy_interval_stays_silent(tmp_path):
    eng = _armed_engine(tmp_path)
    for seq in range(1, 6):
        eng.on_rollup(_roll(seq, {"fetch.bytes": 10e6}))
    eng.on_rollup(_roll(6, {"fetch.bytes": 0.1e6}))  # one blip
    eng.on_rollup(_roll(7, {"fetch.bytes": 10e6}))
    eng.on_rollup(_roll(8, {"fetch.bytes": 0.1e6}))  # another blip
    assert eng.fired == 0  # never consec=2 in a row


def test_idle_process_cannot_alarm(tmp_path):
    """The absolute guard: an EWMA below the collapse floor is not
    'moving' — a near-idle counter dropping to zero is not a collapse."""
    eng = _armed_engine(tmp_path)
    for seq in range(1, 6):
        eng.on_rollup(_roll(seq, {"fetch.bytes": 1e4}))  # 0.01 MB/s
    for seq in range(6, 12):
        eng.on_rollup(_roll(seq, {"fetch.bytes": 0.0}))
    assert eng.fired == 0


def test_p99_inflation_detector(tmp_path):
    eng = _armed_engine(tmp_path)
    pct = {"fetch.latency_ms": {"count": 50, "p50": 4.0, "p95": 8.0,
                                "p99": 10.0}}
    seq = 0
    for _ in range(6):
        seq += 1
        eng.on_rollup(_roll(seq, percentiles=pct))
    bad = {"fetch.latency_ms": {"count": 50, "p50": 300.0, "p95": 700.0,
                                "p99": 900.0}}
    for _ in range(3):
        seq += 1
        eng.on_rollup(_roll(seq, percentiles=bad))
    assert eng.fired == 1
    assert eng.active()[0]["kind"] == "p99"


def test_gauge_leak_detector_needs_monotone_rise(tmp_path):
    ts, m, clock = make_ts(window=16)
    eng = _armed_engine(tmp_path, ts=ts)
    for i in range(8):  # fetch.on_air rises 32/interval, monotone
        m.gauge("fetch.on_air", 32 * (i + 1))
        eng.on_rollup(ts.sample())
        clock.tick()
    assert eng.fired == 1
    assert eng.active()[0]["kind"] == "leak"
    # a sawtooth (rises but returns) is traffic, not a leak
    eng2 = _armed_engine(tmp_path)
    ts2, m2, clock2 = make_ts(window=16)
    eng2.timeseries = ts2
    for i in range(8):
        m2.gauge("fetch.on_air", 256 if i % 2 else 0)
        eng2.on_rollup(ts2.sample())
        clock2.tick()
    assert eng2.fired == 0


def test_proactive_dump_fires_exactly_once_rate_limited(tmp_path):
    eng = _armed_engine(tmp_path, overrides={
        "uda.tpu.anomaly.dump": True,
        "uda.tpu.anomaly.dump.interval.s": 3600.0})
    assert eng.dump_enabled
    pct = {"fetch.latency_ms": {"count": 50, "p50": 4.0, "p95": 8.0,
                                "p99": 10.0}}
    seq = 0
    for _ in range(6):
        seq += 1
        eng.on_rollup(_roll(seq, {"fetch.bytes": 10e6}, pct))
    bad = {"fetch.latency_ms": {"count": 50, "p50": 300.0, "p95": 700.0,
                                "p99": 900.0}}
    for _ in range(4):  # BOTH detectors breach simultaneously
        seq += 1
        eng.on_rollup(_roll(seq, {"fetch.bytes": 0.1e6}, bad))
    assert eng.fired == 2  # two anomalies recognized...
    dumps = [p for p in tmp_path.iterdir() if "anomaly" in p.name]
    assert len(dumps) == 1  # ...ONE rate-limited black-box capture
    assert eng.dumps == 1
    doc = json.loads(dumps[0].read_text())
    assert doc["cause"] == "anomaly"
    assert doc["extra"]["anomaly"]["kind"] in ("throughput", "p99")
    # the events leading UP TO the anomaly are in the ring dump —
    # that is the whole point of capturing proactively
    assert any(e.get("kind") == "anomaly" for e in doc["events"])


# -- the per-tenant SLI book --------------------------------------------------


class FakeSched:
    """A WDRR scheduler the book can audit: scripted granted_cost."""

    def __init__(self, weights):
        self.weights = weights
        self.granted = {t: 0 for t in weights}
        self.parked = {t: 0 for t in weights}

    def grant(self, tenant, cost):
        self.granted[tenant] += cost

    def stats(self):
        return {"total": 4, "free": 4, "grants": 0, "tenants": {
            t: {"parked": self.parked[t], "parked_cost": 0,
                "granted_cost": self.granted[t], "inflight": 0,
                "deficit": 0.0, "weight": w, "boxed": False}
            for t, w in self.weights.items()}}


def _book(config=None, window=32):
    from uda_tpu.tenant.sli import SliBook

    ts = TimeSeries(Metrics(stats=True), window=window, clock=FakeClock())
    book = SliBook()
    book.arm_from_config(config or Config(), ts)
    return book


def test_share_tracks_scheduler_weights():
    book = _book()
    sched = FakeSched({"tA": 3, "tB": 1})
    book.attach(scheduler=sched, registry=None)
    for seq in range(1, 9):
        sched.parked = {"tA": 2, "tB": 2}  # both have demand
        sched.grant("tA", 300)
        sched.grant("tB", 100)
        book.on_rollup(_roll(seq))
    snap = book.snapshot()
    a, b = snap["tenants"]["tA"], snap["tenants"]["tB"]
    # granted-byte share vs weight-proportional entitlement: 3:1
    assert a["window_share"] == pytest.approx(0.75, abs=0.02)
    assert b["window_share"] == pytest.approx(0.25, abs=0.02)
    assert a["entitled"] == pytest.approx(0.75)
    assert a["sched_bytes"] == 8 * 300
    # both kept >= slo.share.frac (0.5) of entitlement: share SLO met
    assert a["slo"]["share"]["attainment"] == 1.0
    assert a["slo"]["share"]["burn"] == 0.0
    assert a["starved_s"] == 0.0


def test_starvation_streak_and_burn_rate():
    book = _book(Config({"uda.tpu.slo.objective": 0.9}))
    sched = FakeSched({"tA": 1, "tB": 1})
    book.attach(scheduler=sched, registry=None)
    for seq in range(1, 11):
        sched.parked = {"tA": 2, "tB": 2}
        sched.grant("tA", 100)  # tB: backlog, zero scheduled bytes
        book.on_rollup(_roll(seq))
    snap = book.snapshot()
    b = snap["tenants"]["tB"]
    assert b["starve_streak_s"] == pytest.approx(10.0)
    assert book.starving_tenants(5.0) == {"tB": pytest.approx(10.0)}
    # tB's share SLO burned every interval: attainment 0, burn capped
    # by the objective's error budget (1-0)/(1-0.9) = 10x
    assert b["slo"]["share"]["attainment"] == 0.0
    assert b["slo"]["share"]["burn"] == pytest.approx(10.0)
    assert metrics.snapshot()["sli.slo.breach{sli=share,tenant=tB}"] >= 1
    # a granted interval resets the STREAK but not the cumulative
    sched.grant("tB", 100)
    book.on_rollup(_roll(11))
    b = book.snapshot()["tenants"]["tB"]
    assert b["starve_streak_s"] == 0.0
    assert b["starved_s"] == pytest.approx(10.0)


def test_latency_slo_and_final_slo_block():
    book = _book(Config({"uda.tpu.slo.fetch.p99.ms": 50.0}))
    sched = FakeSched({"tA": 1})
    book.attach(scheduler=sched, registry=None)
    good = {"fetch.latency_ms{supplier=s1,tenant=tA}":
            {"count": 40, "p50": 5.0, "p95": 9.0, "p99": 10.0}}
    bad = {"fetch.latency_ms{supplier=s1,tenant=tA}":
           {"count": 40, "p50": 80.0, "p95": 180.0, "p99": 200.0}}
    for seq in range(1, 9):
        sched.parked = {"tA": 1}
        sched.grant("tA", 100)
        book.on_rollup(_roll(seq, percentiles=good if seq <= 6 else bad))
    snap = book.snapshot()["tenants"]["tA"]
    assert snap["p99_ms"]["fetch"] == pytest.approx(200.0)
    assert snap["slo"]["fetch_p99_ms"]["attainment"] == pytest.approx(
        6 / 8)
    blk = book.slo_block()
    assert blk["worst_attainment"] == pytest.approx(6 / 8)
    assert blk["tenants"]["tA"]["fetch_p99_ms"]["target"] == 50.0


def test_tenant_deltas_fold_labeled_series():
    from uda_tpu.tenant.sli import series_labels

    book = _book()
    roll = _roll(1, counters={
        "fetch.bytes{supplier=s1,tenant=tA}": 1000,
        "fetch.bytes{supplier=s2,tenant=tA}": 500,
        "fetch.bytes{supplier=s1,tenant=tB}": 200,
        "fetch.bytes": 1700})  # the unlabeled total is NOT a tenant
    book.on_rollup(roll)
    snap = book.snapshot()
    assert snap["tenants"]["tA"]["bytes_fetched"] == 1500
    assert snap["tenants"]["tB"]["bytes_fetched"] == 200
    assert set(snap["tenants"]) == {"tA", "tB"}
    assert series_labels("a.b{x=1,y=2}") == ("a.b", {"x": "1", "y": "2"})
    assert series_labels("a.b") == ("a.b", {})


# -- MSG_STATS interop (CAP_OBS) ----------------------------------------------


def _split(frame: bytes):
    msg_type, req_id, length = wire.decode_header(frame[:wire.HEADER.size])
    payload = frame[wire.HEADER.size:]
    assert len(payload) == length
    return msg_type, req_id, payload


def test_stats_request_tail_encode_decode():
    msg_type, req_id, payload = _split(
        wire.encode_stats_request(9, window_s=60))
    assert (msg_type, req_id) == (wire.MSG_STATS, 9)
    assert wire.decode_stats_request(payload) == (60, wire.STATS_SEC_ALL)
    # old shape: empty payload decodes to None (the PR 11 request)
    _, _, empty = _split(wire.encode_stats_request(9))
    assert len(empty) == 0 and wire.decode_stats_request(empty) is None
    with pytest.raises(TransportError):
        wire.decode_stats_request(b"\x01\x02\x03")  # torn tail


@pytest.fixture
def obs_supplier(tmp_path):
    expected = make_mof_tree(str(tmp_path), JOB, num_maps=2,
                             num_reducers=1, records_per_map=30, seed=7)
    cfg = Config({"uda.tpu.stats.enable": True,
                  "uda.tpu.ts.interval.s": 0.1})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0)
    server.start()
    yield expected, server
    server.stop()
    engine.stop()


def test_windowed_poll_returns_sections_plain_poll_does_not(obs_supplier):
    _, server = obs_supplier
    snap = fetch_remote_stats("127.0.0.1", server.port, window_s=30)
    assert snap["timeseries"]["window"] > 0
    assert isinstance(snap["timeseries"]["rollups"], list)
    assert "armed" in snap["sli"]
    assert "active" in snap["anomalies"]
    # an old-style poll (no tail) gets the PR 11 snapshot unchanged —
    # pre-observability pollers pay nothing for the new sections
    plain = fetch_remote_stats("127.0.0.1", server.port)
    assert "counters" in plain
    assert "timeseries" not in plain
    assert "sli" not in plain


def test_raw_old_peer_empty_stats_payload_still_served(obs_supplier):
    """A pre-CAP_OBS peer hand-rolling the empty MSG_STATS frame (the
    PR 11 wire shape) must keep working against a new server."""
    _, server = obs_supplier
    sock = socket.create_connection(("127.0.0.1", server.port),
                                    timeout=10.0)
    try:
        sock.settimeout(10.0)
        msg_type, _, payload = wire.recv_frame(sock)
        assert msg_type == wire.MSG_HELLO
        _, _, caps = wire.decode_hello_ex(payload)
        assert caps & wire.CAP_OBS  # the server advertises it...
        sock.sendall(wire.encode_frame(wire.MSG_STATS, 3, b""))
        msg_type, req_id, payload = wire.recv_frame(sock)
        assert (msg_type, req_id) == (wire.MSG_STATS_REPLY, 3)
        snap = wire.decode_stats_reply(payload)
        assert "counters" in snap and "timeseries" not in snap
    finally:
        wire.close_hard(sock)


def test_malformed_stats_tail_is_torn_frame(obs_supplier):
    """A wrong-length tail is indistinguishable from corruption — the
    length-IS-the-version discipline tears the connection down, exactly
    like the trace tail."""
    _, server = obs_supplier
    sock = socket.create_connection(("127.0.0.1", server.port),
                                    timeout=10.0)
    try:
        sock.settimeout(10.0)
        assert wire.recv_frame(sock)[0] == wire.MSG_HELLO
        sock.sendall(wire.encode_frame(wire.MSG_STATS, 4, b"\x00" * 3))
        assert wire.recv_frame(sock) is None  # peer hung up
    finally:
        wire.close_hard(sock)


def test_udafleet_once_merges_live_daemon(obs_supplier):
    """The fleet console end to end: one --once --json merge over a
    live daemon plus one dead endpoint — the dead one renders 'down',
    the live one 'ok', and the document carries the fleet sections."""
    _, server = obs_supplier
    dead_port = server.port + 1 if server.port < 65000 else server.port - 1
    out = subprocess.run(
        [sys.executable, f"{REPO}/scripts/udafleet.py",
         f"127.0.0.1:{server.port}", f"127.0.0.1:{dead_port}",
         "--once", "--json", "--window", "30", "--timeout", "5"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    fleet = json.loads(out.stdout.strip().splitlines()[-1])
    assert fleet["daemons"][f"127.0.0.1:{server.port}"] == "ok"
    assert fleet["daemons"][f"127.0.0.1:{dead_port}"] == "down"
    assert "throughput" in fleet and "tenants" in fleet
    assert isinstance(fleet["anomalies"], list)


# -- the anomaly chaos rung ---------------------------------------------------


@pytest.mark.faults
def test_anomaly_rung_slow_supplier_dumps_before_any_fallback(tmp_path):
    """The chaos-rung acceptance (scripts/run_chaos.sh anomaly rung):
    a slow-supplier degradation — DELAYS, not errors, so every fetch
    still completes — must fire the p99-inflation detector on the live
    fetch path and leave exactly one proactive black-box dump
    (cause=anomaly) while ``fallback.signals`` is still ZERO. That is
    the recorder's reason to exist: the minutes before a failure are on
    disk even though nothing has failed yet."""
    metrics.enable_stats()  # the rung runs UDA_TPU_STATS=1; tier-1
    # needs the histograms on explicitly for the p99 feed to exist
    mof = tmp_path / "mof"
    mof.mkdir()
    make_mof_tree(str(mof), JOB, num_maps=2, num_reducers=1,
                  records_per_map=30, seed=17)
    engine = DataEngine(DirIndexResolver(str(mof)), Config())
    client = LocalFetchClient(engine)
    # the detectors judge the GLOBAL metrics hub the real fetch path
    # writes into; collapse floor parked sky-high so this rung is
    # deterministic on the latency detector alone (the rung's ambient
    # seeded schedule may be delaying the baseline rounds too)
    ts = TimeSeries(interval_s=0.05, window=64)
    eng = AnomalyEngine()
    assert eng.arm_from_config(Config({
        "uda.tpu.anomaly.warmup": 3,
        "uda.tpu.anomaly.consec": 2,
        "uda.tpu.anomaly.p99.floor.ms": 50.0,
        "uda.tpu.anomaly.collapse.floor.mb_s": 1e9,
        "uda.tpu.anomaly.dump": True,
        "uda.tpu.anomaly.dump.interval.s": 3600.0}), ts)
    # dumps land where the rung archives them (UDA_TPU_FLIGHTREC_DIR)
    # or in the test's own dir; count only NEW anomaly dumps either way
    frdir = os.environ.get("UDA_TPU_FLIGHTREC_DIR") or str(tmp_path / "fr")
    saved_dir = flightrec._dump_dir
    flightrec._dump_dir = frdir

    def anomaly_dumps():
        import glob as _glob
        return set(_glob.glob(os.path.join(frdir,
                                           "flightrec_*_anomaly.json")))

    before = anomaly_dumps()

    def fetch_round():
        mm = MergeManager(client, "uda.tpu.RawBytes", Config())
        got = mm.run(JOB, map_ids(JOB, 2), 0, lambda b: None)
        assert got > 0
        ts.sample()  # one rollup interval per round -> detector feed

    try:
        for _ in range(4):      # healthy baseline past warmup=3
            fetch_round()
        assert eng.fired == 0
        # the slow supplier: every pread held 150 ms — far over the
        # 50 ms absolute floor and any ambient-chaos baseline jitter,
        # yet every fetch still SUCCEEDS
        with failpoints.scoped("data_engine.pread=delay:150"):
            for _ in range(3):  # consec=2 -> fires inside this window
                fetch_round()
        assert eng.fired >= 1
        assert any(a["kind"] == "p99" for a in eng.active())
        # proactive: the black box hit disk while nothing had failed
        assert metrics.get("fallback.signals") == 0
        new = anomaly_dumps() - before
        assert len(new) == 1, sorted(new)
        doc = json.loads(open(new.pop()).read())
        assert doc["cause"] == "anomaly"
        assert doc["extra"]["anomaly"]["kind"] == "p99"
        assert any(e.get("kind") == "anomaly" for e in doc["events"])
    finally:
        flightrec._dump_dir = saved_dir
        ts.reset()
        engine.stop()
