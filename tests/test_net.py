"""The network shuffle data plane (uda_tpu/net): wire framing,
ShuffleServer, RemoteFetchClient — the TCP stand-in for the reference's
RDMAServer/RDMAClient pair (reference src/DataNet/). The event-loop
core is the only data plane (the legacy threaded core and its dual-core
parametrization were deleted with it once BENCH_NET_r07.json recorded
the second evloop-only bench point)."""

import io
import socket
import threading
import time

import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.merger import (HostRoutingClient, LocalFetchClient,
                            MergeManager)
from uda_tpu.mofserver import (DataEngine, DirIndexResolver, FetchResult,
                               ShuffleRequest)
from uda_tpu.net import RemoteFetchClient, ShuffleServer, wire
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import StorageError, TransportError
from uda_tpu.utils.failpoints import failpoints, net_chaos_spec
from uda_tpu.utils.ifile import IFileReader
from uda_tpu.utils.metrics import metrics


# -- wire protocol -----------------------------------------------------------

def _frame_parts(frame: bytes):
    msg_type, req_id, length = wire.decode_header(frame[:wire.HEADER.size])
    payload = frame[wire.HEADER.size:]
    assert len(payload) == length
    return msg_type, req_id, payload


def test_wire_request_roundtrip():
    req = ShuffleRequest("job_1", "attempt_job_1_m_000003_0", 7,
                         offset=1 << 33, chunk_size=1 << 20)
    t, rid, payload = _frame_parts(wire.encode_request(41, req))
    assert (t, rid) == (wire.MSG_REQ, 41)
    got = wire.decode_request(payload)
    assert got == ShuffleRequest(req.job_id, req.map_id, req.reduce_id,
                                 req.offset, req.chunk_size)


@pytest.mark.parametrize("crc", [None, 0xDEADBEEF])
@pytest.mark.parametrize("data", [b"", b"x" * 1000])
def test_wire_result_roundtrip(crc, data):
    res = FetchResult(data, 12345, 2345, 512, "/mofs/file.out",
                      last=bool(data), crc=crc)
    t, rid, payload = _frame_parts(wire.encode_result(9, res))
    assert (t, rid) == (wire.MSG_DATA, 9)
    got = wire.decode_result(payload)
    assert (got.data, got.raw_length, got.part_length, got.offset,
            got.path, got.last, got.crc) == \
           (data, 12345, 2345, 512, "/mofs/file.out", bool(data), crc)


def test_wire_error_roundtrip_is_typed():
    t, rid, payload = _frame_parts(
        wire.encode_error(3, StorageError("no such MOF")))
    assert t == wire.MSG_ERR
    err = wire.decode_error(payload)
    assert isinstance(err, StorageError) and "no such MOF" in str(err)
    # unknown kinds degrade to TransportError, never crash the decoder
    unknown = wire.encode_error(4, ValueError("alien"))
    err2 = wire.decode_error(unknown[wire.HEADER.size:])
    assert isinstance(err2, TransportError) and "alien" in str(err2)


def test_wire_size_roundtrip():
    mids = [f"attempt_j_m_{i:06d}_0" for i in range(3)]
    t, rid, payload = _frame_parts(wire.encode_size_request(5, "j", mids, 2))
    assert t == wire.MSG_SIZE_REQ
    assert wire.decode_size_request(payload) == ("j", mids, 2)
    assert wire.decode_size(
        wire.encode_size(1, 12345)[wire.HEADER.size:]) == 12345
    assert wire.decode_size(
        wire.encode_size(1, None)[wire.HEADER.size:]) is None


def test_wire_decode_strictness():
    good = wire.encode_request(1, ShuffleRequest("j", "m", 0, 0, 64))
    # bad magic: not a uda_tpu endpoint / lost frame sync
    with pytest.raises(TransportError, match="magic"):
        wire.decode_header(b"XX" + good[2:wire.HEADER.size])
    # version mismatch names both versions
    bumped = bytes([good[0], good[1], wire.WIRE_VERSION + 1]) + good[3:]
    with pytest.raises(TransportError, match="v2.*v1"):
        wire.decode_header(bumped[:wire.HEADER.size])
    with pytest.raises(TransportError, match="unknown frame type"):
        wire.decode_header(good[:2] + bytes([wire.WIRE_VERSION, 99])
                           + good[4:wire.HEADER.size])
    # a desynced length field must be rejected before allocation
    huge = good[:12] + (1 << 31).to_bytes(4, "big")
    with pytest.raises(TransportError, match="cap"):
        wire.decode_header(huge[:wire.HEADER.size])
    with pytest.raises(TransportError, match="truncated"):
        wire.decode_header(good[:7])
    # truncated / trailing payload garbage
    with pytest.raises(TransportError, match="truncated"):
        wire.decode_request(good[wire.HEADER.size:-3])
    with pytest.raises(TransportError, match="trailing"):
        wire.decode_request(good[wire.HEADER.size:] + b"zz")
    with pytest.raises(TransportError):
        wire.decode_result(b"\x00" * 4)


def test_recv_frame_eof_and_mid_frame_cut():
    a, b = socket.socketpair()
    try:
        frame = wire.encode_request(1, ShuffleRequest("j", "m", 0, 0, 64))
        a.sendall(frame)
        assert wire.recv_frame(b)[0] == wire.MSG_REQ
        # clean EOF at a frame boundary -> None (normal hangup)
        a.sendall(frame)
        a.shutdown(socket.SHUT_WR)
        assert wire.recv_frame(b)[0] == wire.MSG_REQ
        assert wire.recv_frame(b) is None
    finally:
        a.close()
        b.close()
    # EOF inside a frame -> mid-frame disconnect
    a, b = socket.socketpair()
    try:
        a.sendall(frame[:-5])
        a.shutdown(socket.SHUT_WR)
        with pytest.raises(TransportError, match="mid-frame"):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


# -- server + client ---------------------------------------------------------

JOB = "jobNet"


@pytest.fixture
def supplier(tmp_path):
    """A MOF tree + DataEngine + ShuffleServer on an ephemeral loopback
    port -> (expected records per reducer, server)."""
    expected = make_mof_tree(str(tmp_path), JOB, num_maps=4,
                             num_reducers=2, records_per_map=50, seed=7)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    yield expected, server
    server.stop()
    engine.stop()


def _fetch_sync(client, req, timeout=10.0):
    """One fetch through the async InputClient API, synchronously."""
    box, done = [], threading.Event()

    def on_complete(res):
        box.append(res)
        done.set()

    client.start_fetch(req, on_complete)
    assert done.wait(timeout), "fetch never completed"
    return box[0]


def test_remote_fetch_roundtrip(supplier):
    expected, server = supplier
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    try:
        got = []
        for mid in map_ids(JOB, 4):
            res = _fetch_sync(client, ShuffleRequest(JOB, mid, 1, 0, 1 << 20))
            assert isinstance(res, FetchResult) and res.is_last
            from uda_tpu.utils.ifile import crack
            got += list(crack(res.data).iter_records())
        assert sorted(got) == sorted(expected[1])
    finally:
        client.stop()
    assert metrics.get("net.requests") >= 4
    assert metrics.get_gauge("net.client.connections") == 0


def test_remote_error_is_typed_and_connection_survives(supplier):
    _, server = supplier
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    try:
        err = _fetch_sync(client, ShuffleRequest(JOB, "no_such_map", 0, 0, 64))
        assert isinstance(err, StorageError)  # the engine's type, not a
        # generic transport fault: the Segment retry path must see it
        # exactly as the in-process client would deliver it
        ok = _fetch_sync(client, ShuffleRequest(JOB, map_ids(JOB, 1)[0],
                                                0, 0, 1 << 20))
        assert isinstance(ok, FetchResult)  # same connection still good
    finally:
        client.stop()
    assert metrics.get("net.errors") == 1


def test_many_concurrent_fetches_multiplex_one_connection(supplier):
    _, server = supplier
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    results, done = {}, threading.Event()
    lock = threading.Lock()
    reqs = [ShuffleRequest(JOB, mid, r, 0, 1 << 20)
            for mid in map_ids(JOB, 4) for r in range(2)]
    try:
        def on_complete(key, res):
            with lock:
                results[key] = res
                if len(results) == len(reqs):
                    done.set()

        for i, req in enumerate(reqs):
            client.start_fetch(req, lambda res, k=i: on_complete(k, res))
        assert done.wait(10.0)
        assert all(isinstance(r, FetchResult) for r in results.values())
    finally:
        client.stop()
    # ONE multiplexed connection carried all of them (RDMAClient.cc's
    # connect-once-per-host shape)
    assert metrics.get("net.connects") == 1
    assert metrics.get("net.accepts") == 1


def test_credit_cap_still_serves_everything(tmp_path):
    """A tiny per-connection credit cap bounds the pipeline but must
    never deadlock or drop requests (wqe.per.conn semantics)."""
    make_mof_tree(str(tmp_path), JOB, num_maps=6, num_reducers=1,
                  records_per_map=30, seed=1)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, Config({"mapred.rdma.wqe.per.conn": 2}),
                           host="127.0.0.1", port=0).start()
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    try:
        results, done = [], threading.Event()
        lock = threading.Lock()

        def on_complete(res):
            with lock:
                results.append(res)
                if len(results) == 6:
                    done.set()

        for mid in map_ids(JOB, 6):
            client.start_fetch(ShuffleRequest(JOB, mid, 0, 0, 1 << 20),
                               on_complete)
        assert done.wait(10.0)
        assert all(isinstance(r, FetchResult) for r in results)
    finally:
        client.stop()
        server.stop()
        engine.stop()
    assert metrics.get_gauge("net.server.inflight") == 0


def test_estimate_partition_bytes_over_the_wire(supplier):
    _, server = supplier
    engine = server.engine
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    try:
        mids = map_ids(JOB, 4)
        local = LocalFetchClient(engine).estimate_partition_bytes(
            JOB, mids, 0)
        assert local is not None and local > 0
        assert client.estimate_partition_bytes(JOB, mids, 0) == local
        # exact-or-unknown across the wire too
        assert client.estimate_partition_bytes(
            JOB, mids + ["no_such_map"], 0) is None
    finally:
        client.stop()


def test_host_routing_default_socket_factory(supplier):
    """HostRoutingClient with no connect callable dials host[:port]
    through RemoteFetchClient — and fans estimate_partition_bytes out
    per host (exact-or-unknown)."""
    _, server = supplier
    host = f"127.0.0.1:{server.port}"
    router = HostRoutingClient(config=Config())
    try:
        res = _fetch_sync(router, ShuffleRequest(
            JOB, map_ids(JOB, 1)[0], 0, 0, 1 << 20, host=host))
        assert isinstance(res, FetchResult)
        entries = [(host, m) for m in map_ids(JOB, 4)]
        est = router.estimate_partition_bytes(JOB, entries, 0)
        local = LocalFetchClient(server.engine).estimate_partition_bytes(
            JOB, map_ids(JOB, 4), 0)
        assert est == local
        # one unknown host poisons the whole estimate (never a partial
        # lower bound), and the fetch path reports the dial failure
        assert router.estimate_partition_bytes(
            JOB, entries + [("127.0.0.1:1", "m")], 0) is None
    finally:
        router.stop()


def test_default_factory_address_parsing():
    """host[:port], bracketed IPv6, bare IPv6 literals; malformed
    ports fail TYPED (the transport-error contract), never ValueError."""
    connect = HostRoutingClient._socket_factory(Config())
    c = connect("sup1:1234")
    assert (c.host, c.port) == ("sup1", 1234)  # lazy dial: no connect yet
    c2 = connect("sup2")
    assert (c2.host, c2.port) == ("sup2", Config().get("uda.tpu.net.port"))
    c3 = connect("[::1]:4567")
    assert (c3.host, c3.port) == ("::1", 4567)
    c4 = connect("fe80::1%eth0")  # bare IPv6 literal: no port split
    assert c4.host == "fe80::1%eth0"
    for bad in ("sup1:9o12", "[::1", "[::1]x"):
        with pytest.raises(TransportError):
            connect(bad)


def test_decompressing_client_forwards_estimate(supplier):
    """The codec wrapper must not swallow the size estimate: the auto
    merge-approach policy needs real sizes for compressed jobs too
    (estimates sum raw_length — the uncompressed domain this client
    delivers in)."""
    from uda_tpu.compress import DecompressingClient, get_codec

    inner = LocalFetchClient(supplier[1].engine)
    wrapped = DecompressingClient(inner, get_codec("zlib"))
    mids = map_ids(JOB, 4)
    est = wrapped.estimate_partition_bytes(JOB, mids, 0)
    assert est == inner.estimate_partition_bytes(JOB, mids, 0)
    assert est is not None and est > 0


def test_default_factory_rejects_empty_host():
    """An entry with no supplier host must fail loudly, not resolve to
    localhost and fetch from whatever listens there."""
    router = HostRoutingClient(config=Config())
    try:
        err = _fetch_sync(router, ShuffleRequest(JOB, "m", 0, 0, 64,
                                                 host=""))
        assert isinstance(err, TransportError) and "empty host" in str(err)
        # and the estimate fan-out degrades to unknown, not localhost
        assert router.estimate_partition_bytes(JOB, ["m"], 0) is None
    finally:
        router.stop()


def test_unreachable_supplier_fails_fetch_with_transport_error():
    # nothing listens on port 1; the dial error must arrive as a
    # completion, not an exception out of start_fetch
    client = RemoteFetchClient("127.0.0.1", 1,
                               Config({"uda.tpu.net.connect.timeout.s": 2.0}))
    try:
        err = _fetch_sync(client, ShuffleRequest("j", "m", 0, 0, 64))
        assert isinstance(err, TransportError)
    finally:
        client.stop()
    assert metrics.get("net.connect.failures") >= 1


def _run_reduce(port, reduce_id, cfg, out, num_maps=4):
    router = HostRoutingClient(config=cfg)
    mm = MergeManager(router, "uda.tpu.RawBytes", cfg)
    blocks = []
    maps = [(f"127.0.0.1:{port}", m) for m in map_ids(JOB, num_maps)]
    try:
        mm.run(JOB, maps, reduce_id, lambda b: blocks.append(bytes(b)))
        out[reduce_id] = b"".join(blocks)
    finally:
        router.stop()


def test_concurrent_reduce_clients_match_local_path(supplier):
    """The acceptance criterion: a full MergeManager shuffle over
    RemoteFetchClient -> ShuffleServer -> DataEngine on loopback, >= 2
    concurrent reduce clients, byte-identical to LocalFetchClient."""
    expected, server = supplier
    out = {}
    threads = [threading.Thread(target=_run_reduce,
                                args=(server.port, r, Config(), out))
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert sorted(out) == [0, 1]
    for r in range(2):
        mm = MergeManager(LocalFetchClient(server.engine),
                          "uda.tpu.RawBytes", Config())
        blocks = []
        mm.run(JOB, map_ids(JOB, 4), r, lambda b: blocks.append(bytes(b)))
        assert out[r] == b"".join(blocks)  # byte-identical to local
        got = list(IFileReader(io.BytesIO(out[r])))
        assert sorted(got) == sorted(expected[r])


@pytest.mark.faults
def test_mid_stream_disconnect_recovers_via_segment_retries(tmp_path):
    """A torn response frame (net.frame truncate) closes the connection
    mid-stream; the client fails every in-flight fetch with
    TransportError and the existing Segment retry/penalty machinery
    reconnects and completes byte-correct output."""
    expected = make_mof_tree(str(tmp_path), JOB, num_maps=5,
                             num_reducers=1, records_per_map=60, seed=5)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    # small chunks -> multi-chunk segments; generous retry budget (one
    # tear fails EVERY in-flight fetch, each burning a retry)
    cfg = Config({"mapred.rdma.buf.size": 4, "uda.tpu.fetch.retries": 8})
    out = {}
    try:
        with failpoints.scoped("net.frame=truncate:16:every:9"):
            _run_reduce(server.port, 0, cfg, out, num_maps=5)
    finally:
        server.stop()
        engine.stop()
    got = list(IFileReader(io.BytesIO(out[0])))
    assert sorted(got) == sorted(expected[0])
    if failpoints.hits.get("net.frame"):  # chaos may override the spec
        assert metrics.get("net.disconnects") >= 1
        assert metrics.get("fetch.retries") >= 1


@pytest.mark.faults
def test_server_stop_midfetch_then_restart_recovers(tmp_path):
    """Killed supplier: stop(drain=False) mid-stream fails the fetch
    with TransportError; a server restarted on the same port serves the
    segment's retry (the whole-segment re-fetch restarts from offset
    0, so chunks fetched before the kill are re-fetched consistently)."""
    expected = make_mof_tree(str(tmp_path), JOB, num_maps=3,
                             num_reducers=1, records_per_map=60, seed=9)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    port = server.port

    # plain stopped server: the fetch completes with TransportError.
    # The live-fetch prelude retries a few times: under the chaos
    # rung's ambient net.frame schedule an injected fault may land on
    # any one frame (the phase depends on how many frames ran before
    # this test), and this test's subject is stop/restart recovery,
    # not fault-free fetching
    client = RemoteFetchClient("127.0.0.1", port, Config())
    res = None
    for _ in range(6):
        res = _fetch_sync(client, ShuffleRequest(JOB, map_ids(JOB, 1)[0],
                                                 0, 0, 1 << 20))
        if isinstance(res, FetchResult):
            break
    assert isinstance(res, FetchResult), res
    server.stop(drain=False)
    err = _fetch_sync(client, ShuffleRequest(JOB, map_ids(JOB, 1)[0],
                                             0, 0, 1 << 20))
    assert isinstance(err, TransportError)
    client.stop()

    # restart on the SAME port; a merge with retry backoff spanning the
    # outage completes against the restarted server
    cfg = Config({"mapred.rdma.buf.size": 4, "uda.tpu.fetch.retries": 8,
                  "mapred.rdma.fetch.retry.backoff.ms": 50})
    out = {}
    outage = threading.Event()

    def delayed_restart():
        outage.wait(10.0)
        time.sleep(0.15)  # let some in-flight fetches die against the
        server2.start()   # closed port before the retries land

    server2 = ShuffleServer(engine, Config(), host="127.0.0.1", port=port)
    restarter = threading.Thread(target=delayed_restart)
    t = threading.Thread(target=_run_reduce,
                         args=(port, 0, cfg, out, 3))
    try:
        # kill the server as soon as the merge is underway, restart it
        # shortly after: segments ride their RetryPolicy across the gap
        restarter.start()
        t.start()
        outage.set()
        t.join(timeout=60)
        assert not t.is_alive(), "reduce wedged across the restart"
    finally:
        server2.stop()
        engine.stop()
    got = list(IFileReader(io.BytesIO(out[0])))
    assert sorted(got) == sorted(expected[0])


@pytest.mark.faults
def test_net_chaos_schedule_is_recoverable(tmp_path):
    """The network rung of scripts/run_chaos.sh, in miniature: a seeded
    net_chaos_spec schedule (torn frames OR send errors + slow
    accepts/dials) must degrade into retries, never into wrong bytes
    or a wedge."""
    expected = make_mof_tree(str(tmp_path), JOB, num_maps=4,
                             num_reducers=1, records_per_map=40, seed=3)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    cfg = Config({"mapred.rdma.buf.size": 4, "uda.tpu.fetch.retries": 10,
                  "mapred.rdma.fetch.retry.backoff.ms": 10})
    out = {}
    try:
        with failpoints.scoped(net_chaos_spec(1234)):
            _run_reduce(server.port, 0, cfg, out)
    finally:
        server.stop()
        engine.stop()
    got = list(IFileReader(io.BytesIO(out[0])))
    assert sorted(got) == sorted(expected[0])


def test_server_drain_on_stop_completes_inflight(tmp_path):
    """Graceful stop: a response the engine is still producing flushes
    before the connection closes (drain-on-stop), instead of the
    client seeing a disconnect."""
    make_mof_tree(str(tmp_path), JOB, num_maps=1, num_reducers=1,
                  records_per_map=40, seed=2)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    box, done = [], threading.Event()
    try:
        with failpoints.scoped("data_engine.pread=delay:150"):
            client.start_fetch(
                ShuffleRequest(JOB, map_ids(JOB, 1)[0], 0, 0, 1 << 20),
                lambda res: (box.append(res), done.set()))
            time.sleep(0.03)  # request reaches the engine
            server.stop()     # drain=True default
        assert done.wait(5.0)
        assert isinstance(box[0], FetchResult), f"drain lost: {box[0]}"
    finally:
        client.stop()
        engine.stop()


def test_bridge_starts_net_server_and_remote_bridge_fetches(tmp_path):
    """End-to-end through TWO bridges: a MOFSupplier bridge with
    uda.tpu.net.listen serving its engine, and a NetMerger bridge with
    uda.tpu.net.fetch routing FETCH-carried hosts over the socket
    plane (the deployable two-process shape, collapsed into one
    process over loopback)."""
    import os

    from uda_tpu.bridge import UdaBridge
    from uda_tpu.bridge.protocol import Cmd, form_cmd
    from uda_tpu.mofserver import read_index_file

    expected = make_mof_tree(str(tmp_path), JOB, num_maps=3,
                             num_reducers=1, records_per_map=30, seed=4)

    class SupplierCallable:
        def get_path_uda(self, job_id, map_id, reduce_id):
            d = os.path.join(str(tmp_path), job_id, map_id)
            return read_index_file(
                os.path.join(d, "file.out.index"),
                os.path.join(d, "file.out"))[reduce_id]

    supplier = UdaBridge()
    supplier.start(False, ["-w", "8"], SupplierCallable())
    supplier.cfg.set("uda.tpu.net.listen", True)
    supplier.cfg.set("uda.tpu.net.port", 0)  # ephemeral
    supplier.do_command(form_cmd(Cmd.INIT, []))  # -> server starts
    assert not supplier.failed and supplier.net_server() is not None
    port = supplier.net_server().port

    blocks = []

    class ReducerCallable:
        # the conf pull channel (getConfData) carries the net knobs, as
        # a Hadoop jobconf would; FETCH hosts then need no ':port'
        # suffix (the ':'-delimited command protocol could not carry
        # one anyway)
        def get_conf_data(self, name, default):
            return {"uda.tpu.net.fetch": "true",
                    "uda.tpu.net.port": str(port)}.get(name, "")

        def data_from_uda(self, data, length):
            blocks.append(bytes(data[:length]))

    reducer = UdaBridge()
    reducer.start(True, ["-w", "8"], ReducerCallable())
    try:
        reducer.do_command(form_cmd(
            Cmd.INIT, [JOB, "0", "3", "uda.tpu.RawBytes"]))
        for mid in map_ids(JOB, 3):
            reducer.do_command(form_cmd(
                Cmd.FETCH, ["127.0.0.1", JOB, mid, "0"]))
        assert not reducer.failed
        reducer.do_command(form_cmd(Cmd.FINAL, []))
        reducer.reduce_exit()
        assert not reducer.failed
        got = list(IFileReader(io.BytesIO(b"".join(blocks))))
        assert sorted(got) == sorted(expected[0])
    finally:
        supplier.do_command(form_cmd(Cmd.EXIT, []))  # stops the server
        assert supplier.net_server() is None


# -- event-loop core: zero-copy serve path + tuning --------------------------

def test_wire_result_head_scatter_matches_encode():
    """The buffer-donating encode: head + chunk bytes sent separately
    must be byte-identical to the monolithic encode_result frame."""
    for crc in (None, 0xCAFEF00D):
        res = FetchResult(b"y" * 500, 9000, 8000, 256, "/m/file.out",
                          last=True, crc=crc)
        head = wire.encode_result_head(
            7, raw_length=res.raw_length, part_length=res.part_length,
            offset=res.offset, last=res.last, path=res.path, crc=res.crc,
            data_len=len(res.data))
        assert head + res.data == wire.encode_result(7, res)


def test_zero_copy_fd_serve_path(tmp_path, monkeypatch):
    """The acceptance criterion: on the fd-cache hit path the DATA
    serve makes ZERO Python-heap copies of chunk payloads. Proven with
    a tracing wire shim: every serve-path allocation (the frame heads)
    is counted and size-bounded, and every chunk byte is accounted for
    by os.sendfile — bytes that leave via sendfile go disk-cache ->
    socket without ever existing as a Python object."""
    from uda_tpu.net import server as server_mod

    expected = make_mof_tree(str(tmp_path), JOB, num_maps=2,
                             num_reducers=1, records_per_map=2000,
                             seed=13, val_bytes=500)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(
        engine, Config({"uda.tpu.net.zerocopy.mode": "sendfile"}),
        host="127.0.0.1", port=0)
    server.start()

    sent = {"bytes": 0, "calls": 0}
    real_sendfile = server_mod.os.sendfile

    def traced_sendfile(out_fd, in_fd, offset, count):
        n = real_sendfile(out_fd, in_fd, offset, count)
        sent["bytes"] += n
        sent["calls"] += 1
        return n

    heads = []
    real_head = server_mod.wire.encode_result_head

    def traced_head(req_id, **kw):
        out = real_head(req_id, **kw)
        heads.append(len(out))
        return out

    monkeypatch.setattr(server_mod.os, "sendfile", traced_sendfile)
    monkeypatch.setattr(server_mod.wire, "encode_result_head",
                        traced_head)

    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    payload_bytes = 0
    fetched: dict = {}
    try:
        for mid in map_ids(JOB, 2):
            parts, offset, last = [], 0, False
            while not last:  # multi-chunk: 256 KB chunks over ~1 MB
                res = _fetch_sync(client, ShuffleRequest(
                    JOB, mid, 0, offset, 256 * 1024))
                assert isinstance(res, FetchResult), res
                parts.append(res.data)
                payload_bytes += len(res.data)
                offset += len(res.data)
                last = res.is_last
            fetched[mid] = b"".join(parts)
    finally:
        client.stop()
        server.stop()
        engine.stop()
    assert payload_bytes > 1 << 20  # the test must move real data
    # every chunk byte left through sendfile; none through the heap
    assert sent["bytes"] == payload_bytes
    assert metrics.get("net.sendfile.bytes") == payload_bytes
    assert metrics.get("net.serve.fd") == len(heads) > 0
    assert metrics.get("net.serve.copy") == 0
    # the only serve-path allocations are the frame heads — flat,
    # tiny, and independent of chunk size
    assert max(heads) < 256
    # byte-for-byte correctness of what crossed the zero-copy path
    from uda_tpu.utils.ifile import crack
    got = []
    for data in fetched.values():
        got += list(crack(data).iter_records())
    assert sorted(got) == sorted(expected[0])


def test_zero_copy_mmap_mode(tmp_path):
    """The mmap rung of the zero-copy ladder: chunks served as
    memoryviews of the MOF's page-cache mapping (sendmsg), still zero
    Python-heap copies — every chunk byte is accounted for by the
    net.mmap.bytes counter and the bytes are correct."""
    expected = make_mof_tree(str(tmp_path), JOB, num_maps=2,
                             num_reducers=1, records_per_map=400,
                             seed=19, val_bytes=200)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(
        engine, Config({"uda.tpu.net.zerocopy.mode": "mmap"}),
        host="127.0.0.1", port=0)
    server.start()
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    payload_bytes, got = 0, []
    try:
        from uda_tpu.utils.ifile import crack
        for mid in map_ids(JOB, 2):
            parts, offset, last = [], 0, False
            while not last:
                res = _fetch_sync(client, ShuffleRequest(
                    JOB, mid, 0, offset, 64 * 1024))
                assert isinstance(res, FetchResult), res
                parts.append(res.data)
                payload_bytes += len(res.data)
                offset += len(res.data)
                last = res.is_last
            got += list(crack(b"".join(parts)).iter_records())
    finally:
        client.stop()
        server.stop()
        engine.stop()
    assert sorted(got) == sorted(expected[0])
    assert metrics.get("net.mmap.bytes") == payload_bytes > 0
    assert metrics.get("net.sendfile.bytes") == 0
    assert metrics.get("net.serve.copy") == 0


def test_zero_copy_disabled_under_crc_and_failpoints(tmp_path):
    """The byte-path ladder: CRC stamping or an armed data_engine.pread
    failpoint must force chunks off the fd path (the checksum needs the
    bytes; injected corruption must keep mangling real bytes), and the
    output must stay correct either way."""
    expected = make_mof_tree(str(tmp_path), JOB, num_maps=2,
                             num_reducers=1, records_per_map=50, seed=17)
    engine = DataEngine(DirIndexResolver(str(tmp_path)),
                        Config({"uda.tpu.fetch.crc": True}))
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    try:
        from uda_tpu.utils.ifile import crack
        got = []
        for mid in map_ids(JOB, 2):
            res = _fetch_sync(client, ShuffleRequest(JOB, mid, 0, 0,
                                                     1 << 20))
            assert isinstance(res, FetchResult) and res.crc is not None
            got += list(crack(res.data).iter_records())
        assert sorted(got) == sorted(expected[0])
    finally:
        client.stop()
        server.stop()
        engine.stop()
    assert metrics.get("net.serve.fd") == 0
    assert metrics.get("net.serve.copy") >= 2
    assert metrics.get("net.sendfile.bytes") == 0


def test_compressed_job_byte_parity_over_wire(tmp_path):
    """The acceptance criterion's compressed half: a compressed job
    fetched over the socket plane (fd-backed on-disk chunks ride the
    zero-copy path; decompression happens reduce-side) must produce
    output byte-identical to the in-process LocalFetchClient path."""
    import numpy as np

    from uda_tpu.compress import DecompressingClient, get_codec
    from uda_tpu.mofserver.writer import MOFWriter

    codec = get_codec("zlib")
    job = "jobNetZ"
    writer = MOFWriter(str(tmp_path), job, codec=codec)
    rng = np.random.default_rng(29)
    for m in range(3):
        recs = sorted((rng.bytes(10), rng.bytes(60)) for _ in range(120))
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])

    cfg = Config({"mapred.rdma.buf.size": 4})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    try:
        def run(client, maps):
            mm = MergeManager(client, "uda.tpu.RawBytes", cfg)
            blocks = []
            mm.run(job, maps, 0, lambda b: blocks.append(bytes(b)))
            return b"".join(blocks)

        router = HostRoutingClient(config=cfg)
        try:
            remote = run(DecompressingClient(router, codec),
                         [(f"127.0.0.1:{server.port}", m)
                          for m in writer.map_ids])
        finally:
            router.stop()
        local = run(DecompressingClient(LocalFetchClient(engine), codec),
                    writer.map_ids)
    finally:
        server.stop()
        engine.stop()
    assert remote == local  # byte-identical, compressed job included
    assert len(remote) > 0


def test_socket_tuning_knobs(tmp_path):
    """uda.tpu.net.sockbuf.kb sizes SO_SNDBUF/SO_RCVBUF on data-plane
    sockets and TCP_NODELAY is set unconditionally, on both sides."""
    make_mof_tree(str(tmp_path), JOB, num_maps=1, num_reducers=1,
                  records_per_map=10, seed=3)
    cfg = Config({"uda.tpu.net.sockbuf.kb": 128})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0)
    server.start()
    client = RemoteFetchClient("127.0.0.1", server.port, cfg)
    try:
        res = _fetch_sync(client, ShuffleRequest(JOB, map_ids(JOB, 1)[0],
                                                 0, 0, 1 << 20))
        assert isinstance(res, FetchResult)
        sock = client._conn.sock
        assert sock.getsockopt(socket.IPPROTO_TCP,
                               socket.TCP_NODELAY) != 0
        # Linux reports back 2x the requested value; >= is the contract
        assert sock.getsockopt(socket.SOL_SOCKET,
                               socket.SO_SNDBUF) >= 128 * 1024
        assert sock.getsockopt(socket.SOL_SOCKET,
                               socket.SO_RCVBUF) >= 128 * 1024
    finally:
        client.stop()
        server.stop()
        engine.stop()


def test_parked_request_burst_drains_iteratively(tmp_path):
    """800 pipelined fetches against a tiny credit cap: the server's
    parked-request queue must drain ITERATIVELY — the recursive unpark
    (settle -> start -> inline serve -> settle -> ...) blew the Python
    stack at ~170 parked entries and tore the connection down under
    plain burst load, no fault injection."""
    make_mof_tree(str(tmp_path), JOB, num_maps=1, num_reducers=1,
                  records_per_map=20, seed=21)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine,
                           Config({"mapred.rdma.wqe.per.conn": 8}),
                           host="127.0.0.1", port=0).start()
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    n = 800
    results, done = [], threading.Event()
    lock = threading.Lock()

    def on_complete(res):
        with lock:
            results.append(res)
            if len(results) == n:
                done.set()

    try:
        for _ in range(n):
            client.start_fetch(
                ShuffleRequest(JOB, map_ids(JOB, 1)[0], 0, 0, 1 << 20),
                on_complete)
        assert done.wait(60.0), f"only {len(results)}/{n} completed"
        bad = [r for r in results if not isinstance(r, FetchResult)]
        assert not bad, f"{len(bad)} failed, first: {bad[:2]}"
    finally:
        client.stop()
        server.stop()
        engine.stop()
    assert metrics.get_gauge("net.server.inflight") == 0


def test_tune_socket_defaults_leave_os_buffers():
    """sockbuf.kb=0 must not touch the autotuned buffer sizes."""
    a, b = socket.socketpair()
    try:
        before = a.getsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF)
        wire.tune_socket(a, 0)
        assert a.getsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF) == before
        wire.tune_socket(b, 64)
        assert b.getsockopt(socket.SOL_SOCKET,
                            socket.SO_SNDBUF) >= 64 * 1024
    finally:
        a.close()
        b.close()
