"""Streaming bounded-memory online emission (uda_tpu.merger.streaming).

The contract under test: with ``uda.tpu.online.streaming`` on, the online
merge produces BYTE-IDENTICAL output to the memory-resident path while
(a) spooling every segment to a sorted run + releasing its fetched bytes,
(b) never allocating a shuffle-sized host buffer, and (c) cleaning up its
scratch runs on every exit path — the reference's staging-loop memory
model (reference src/Merger/StreamRW.cc:151-225, MergeManager.cc:155-182)
around the device permutation.
"""

import io
import os

import numpy as np
import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.merger import streaming as stream_mod
from uda_tpu.merger.overlap import OverlappedMerger
from uda_tpu.merger.streaming import RunStore, framed_lengths
from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.utils import comparators, vint
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import MergeError
from uda_tpu.utils.ifile import IFileReader, crack, write_records


def _merge_once(tmp_path, streaming, *, num_maps=6, num_reducers=2,
                records_per_map=120, key_bytes=10, seed=5,
                key_type="uda.tpu.RawBytes", extra_cfg=None):
    root = os.path.join(str(tmp_path), "stream" if streaming else "inmem")
    make_mof_tree(root, "jobS", num_maps, num_reducers, records_per_map,
                  seed=seed, key_bytes=key_bytes)
    cfg = Config(dict({"uda.tpu.online.streaming": streaming},
                      **(extra_cfg or {})))
    engine = DataEngine(DirIndexResolver(root), cfg)
    kt = comparators.get_key_type(key_type)
    streams = []
    try:
        for r in range(num_reducers):
            mm = MergeManager(LocalFetchClient(engine), kt, cfg)
            blocks = []
            total = mm.run("jobS", map_ids("jobS", num_maps), r,
                           lambda b: blocks.append(bytes(b)))
            s = b"".join(blocks)
            assert total == len(s)
            streams.append(s)
    finally:
        engine.stop()
    return streams


def test_framed_lengths_matches_writer():
    recs = [(bytes([i]) * (i % 200), b"v" * ((i * 37) % 500))
            for i in range(1, 120)]
    data = write_records(recs)
    b = crack(data)
    fl = framed_lengths(b.key_len, b.val_len)
    assert int(fl.sum()) + 2 == len(data)  # +2 = EOF marker
    for n in (0, 1, 127, 128, 255, 256, 65535, 65536, 2**31):
        assert int(stream_mod._vlong_sizes(np.array([n]))[0]) \
            == vint.vlong_size(n)


def test_streaming_byte_parity_with_inmem(tmp_path):
    a = _merge_once(tmp_path, False)
    b = _merge_once(tmp_path, True)
    assert a == b


def test_streaming_multi_slab(tmp_path, monkeypatch):
    # tiny slabs force many interleave rounds + sequential cursor reuse,
    # and a 2-cursor fd cap forces suspend/reopen-seek cycles on every
    # slab (the large-shuffle fd-bound path)
    monkeypatch.setattr(stream_mod, "SLAB_RECORDS", 64)
    monkeypatch.setattr(stream_mod, "MAX_OPEN_CURSORS", 2)
    a = _merge_once(tmp_path, False, records_per_map=211, num_maps=7)
    b = _merge_once(tmp_path, True, records_per_map=211, num_maps=7)
    assert a == b


def test_streaming_oversize_keys_fallback(tmp_path):
    # keys longer than the carried width -> comparator-sorted runs +
    # k-way merge fallback over the run files; bytes must still match
    a = _merge_once(tmp_path, False, key_bytes=40,
                    extra_cfg={"uda.tpu.key.width": 8})
    b = _merge_once(tmp_path, True, key_bytes=40,
                    extra_cfg={"uda.tpu.key.width": 8})
    assert a == b
    # and the result is truly sorted
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    recs = list(IFileReader(io.BytesIO(b[0])))
    keys = [k for k, _ in recs]
    assert keys == sorted(keys)


def test_streaming_oversize_python_heap_fallback(tmp_path):
    # a comparator outside the native k-way table exercises the Python
    # heap fallback over run-file cursors
    from uda_tpu.utils.ifile import set_native_enabled

    set_native_enabled(False)
    try:
        a = _merge_once(tmp_path, False, key_bytes=24,
                        extra_cfg={"uda.tpu.key.width": 8})
        b = _merge_once(tmp_path, True, key_bytes=24,
                        extra_cfg={"uda.tpu.key.width": 8})
    finally:
        set_native_enabled(True)
    assert a == b


def test_streaming_over_compressed_fetch(tmp_path):
    # streaming online mode composed with the decompressing transport:
    # chunks decompress, crack, stage to runs, release — output matches
    # the in-memory path byte for byte
    import functools

    from uda_tpu.compress import DecompressingClient, get_codec
    from uda_tpu.mofserver.writer import MOFWriter

    codec = get_codec("lzo")
    rng = np.random.default_rng(77)
    expected = []
    job = "jobZ"
    writer = MOFWriter(str(tmp_path), job, codec=codec)
    for m in range(4):
        recs = sorted((rng.bytes(8), rng.bytes(40)) for _ in range(120))
        expected += recs
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])
    out = {}
    for streaming in (False, True):
        cfg = Config({"mapred.rdma.buf.size": 1,
                      "uda.tpu.online.streaming": streaming})
        engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
        try:
            client = DecompressingClient(LocalFetchClient(engine), codec)
            mm = MergeManager(client, "uda.tpu.RawBytes", cfg)
            blocks = []
            mm.run(job, writer.map_ids, 0,
                   lambda b: blocks.append(bytes(b)))
        finally:
            engine.stop()
        out[streaming] = b"".join(blocks)
    assert out[False] == out[True]
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    got = list(IFileReader(io.BytesIO(out[True])))
    want = sorted(expected, key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want


def test_streaming_over_host_routing_client(tmp_path):
    # streaming mode over the per-host lazy transport table (the
    # reference's connect-per-host client, RDMAClient.cc:498-527)
    from uda_tpu.merger.segment import HostRoutingClient

    root = str(tmp_path)
    make_mof_tree(root, "jobH", 6, 1, 80, seed=11)
    cfg = Config({"uda.tpu.online.streaming": True})
    engines = {}

    def connect(host):
        engines[host] = DataEngine(DirIndexResolver(root), cfg)
        return LocalFetchClient(engines[host])

    kt = comparators.get_key_type("uda.tpu.RawBytes")
    try:
        mm = MergeManager(HostRoutingClient(connect), kt, cfg)
        mids = [(f"host{m % 2}", mid)
                for m, mid in enumerate(map_ids("jobH", 6))]
        blocks = []
        total = mm.run("jobH", mids, 0, lambda b: blocks.append(bytes(b)))
    finally:
        for e in engines.values():
            e.stop()
    assert len(engines) == 2  # one lazy transport per host
    recs = list(IFileReader(io.BytesIO(b"".join(blocks))))
    keys = [k for k, _ in recs]
    assert len(recs) == 480 and keys == sorted(keys) and total > 0


def test_streaming_releases_segment_bytes(tmp_path):
    root = str(tmp_path)
    make_mof_tree(root, "jobR", 4, 1, 60, seed=2)
    cfg = Config({"uda.tpu.online.streaming": True})
    engine = DataEngine(DirIndexResolver(root), cfg)
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    try:
        mm = MergeManager(LocalFetchClient(engine), kt, cfg)
        held = []
        orig = mm.fetch_all

        def spy(*args, **kwargs):
            segs = orig(*args, **kwargs)
            held.extend(segs)
            return segs

        mm.fetch_all = spy
        mm.run("jobR", map_ids("jobR", 4), 0, lambda b: None)
    finally:
        engine.stop()
    assert held and all(s.batches == [] for s in held)
    with pytest.raises(MergeError):
        held[0].record_batch()


def test_streaming_cleans_scratch_dir(tmp_path):
    root = str(tmp_path)
    make_mof_tree(root, "jobC", 3, 1, 40, seed=9)
    scratch = os.path.join(root, "scratch")
    cfg = Config({"uda.tpu.online.streaming": True,
                  "uda.tpu.spill.dirs": scratch})
    engine = DataEngine(DirIndexResolver(root), cfg)
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    try:
        mm = MergeManager(LocalFetchClient(engine), kt, cfg)
        mm.run("jobC", map_ids("jobC", 3), 0, lambda b: None)
    finally:
        engine.stop()
    assert os.listdir(scratch) == []  # run dirs removed after emission


def test_run_store_rejects_double_stage(tmp_path):
    store = RunStore(str(tmp_path))
    batch = crack(write_records([(b"a", b"1"), (b"b", b"2")]))
    order = np.arange(2, dtype=np.int64)
    store.write_run(0, batch, order)
    with pytest.raises(MergeError):
        store.write_run(0, batch, order)
    store.cleanup()
    assert not os.path.exists(store.dir)


def test_interleave_detects_lost_records(tmp_path):
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    store = RunStore(str(tmp_path))
    om = OverlappedMerger(kt, 16, run_store=store)
    batch = crack(write_records(
        [(bytes([i]), b"x") for i in range(10)]))
    om.feed(0, batch)

    class _Emitter:
        def emit_framed(self, pieces, consumer):
            total = 0
            for p in pieces:
                consumer(memoryview(p))
                total += len(p)
            return total

    # lie about the expected count -> accounting must catch it
    with pytest.raises(MergeError):
        om.finish_streaming(_Emitter(), lambda b: None, expected_records=11)


def test_backpressure_bounded_queue(tmp_path):
    # staging far slower than fetch: bounded queue must block feeders,
    # not grow; the run still completes with correct output
    import time

    kt = comparators.get_key_type("uda.tpu.RawBytes")
    store = RunStore(str(tmp_path))
    om = OverlappedMerger(kt, 16, run_store=store, max_pending=2)
    orig_stage = om._stage

    def slow_stage(i, src, fed_t):
        time.sleep(0.02)
        orig_stage(i, src, fed_t)

    om._stage = slow_stage
    batches = [crack(write_records(sorted(
        (bytes([s, i]), bytes([i])) for i in range(20))))
        for s in range(12)]
    for s, b in enumerate(batches):
        om.feed(s, b)  # blocks when > max_pending are queued
        assert om._q.qsize() <= 2

    class _Emitter:
        def emit_framed(self, pieces, consumer):
            return sum(len(p) for p in pieces)

        def emit(self, records, consumer):  # pragma: no cover
            return 0

    n = om.finish_streaming(_Emitter(), lambda b: None,
                            expected_records=240)
    assert n > 0
    assert not os.path.exists(store.dir)


def test_staging_pool_parity(tmp_path):
    # 4 stager threads must produce byte-identical output (forest
    # carries serialize under the lock; insertion order may differ but
    # the composite key is total, so the merged rows are identical)
    a = _merge_once(tmp_path, True, num_maps=9, records_per_map=150,
                    extra_cfg={"uda.tpu.online.stagers": 4})
    b = _merge_once(tmp_path, False, num_maps=9, records_per_map=150)
    assert a == b


def test_spill_dir_rotation(tmp_path):
    d1, d2 = os.path.join(str(tmp_path), "d1"), os.path.join(
        str(tmp_path), "d2")
    store = RunStore([d1, d2], tag="rot")
    batch = crack(write_records([(b"a", b"1")]))
    order = np.arange(1, dtype=np.int64)
    for seg in range(4):
        store.write_run(seg, batch, order)
    assert store.run_path(0).startswith(d1)
    assert store.run_path(1).startswith(d2)
    assert all(os.path.exists(store.run_path(s)) for s in range(4))
    store.cleanup()
    assert os.listdir(d1) == [] and os.listdir(d2) == []


@pytest.mark.slow
def test_staging_pool_stress_parity(tmp_path):
    # adversarial pool schedule: 64 segments of random sizes (empty,
    # tiny, big, oversize-key mix) staged by 4 workers with random
    # per-stage delays must produce byte-identical output to the
    # single-threaded run — the forest-carry and run-store locking
    # under real interleaving
    import random as _random
    import time

    from uda_tpu.merger.emitter import FramedEmitter

    rng = np.random.default_rng(31337)
    batches = []
    for s in range(64):
        n = int(rng.integers(0, 400))
        # key lengths straddle the width (16): the oversize-key
        # overflow branch runs under real pool interleaving too
        recs = sorted((rng.bytes(int(rng.integers(1, 25))),
                       rng.bytes(int(rng.integers(0, 30))))
                      for _ in range(n))
        batches.append(crack(write_records(recs)))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    outs = {}
    for stagers in (0, 4):
        store = RunStore(str(tmp_path), tag=f"stress{stagers}")
        om = OverlappedMerger(kt, 16, run_store=store, max_pending=8,
                              stagers=stagers)
        if stagers:
            orig = om._stage
            delay = _random.Random(7)

            def jitter_stage(i, src, fed_t, _orig=orig, _d=delay):
                time.sleep(_d.random() * 0.004)
                _orig(i, src, fed_t)

            om._stage = jitter_stage
        for s, b in enumerate(batches):
            om.feed(s, b)
        blocks = []
        emitter = FramedEmitter(1 << 14)
        om.finish_streaming(
            emitter, lambda mv: blocks.append(bytes(mv)),
            expected_records=sum(b.num_records for b in batches))
        outs[stagers] = b"".join(blocks)
    assert outs[0] == outs[4]


def test_abort_with_full_queue_does_not_deadlock(tmp_path):
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    store = RunStore(str(tmp_path))
    om = OverlappedMerger(kt, 16, run_store=store, max_pending=1)
    # wedge the stager so the queue stays full
    import threading
    gate = threading.Event()
    om._stage = lambda i, src, fed_t: gate.wait(5)
    b = crack(write_records([(b"k", b"v")]))
    om.feed(0, b)
    om.feed(1, b)
    om.abort()  # must return promptly and clean the store
    gate.set()
    assert not os.path.exists(store.dir)


def test_streaming_byte_parity_under_truncation_failpoint(tmp_path):
    # chunks truncated mid-record by an armed failpoint: the carry
    # buffer re-joins every split record from the re-fetched remainder,
    # and the streaming run stays byte-identical to the unfaulted
    # in-memory run (the spooled runs never see the damage)
    from uda_tpu.utils.failpoints import failpoints

    a = _merge_once(tmp_path, False, records_per_map=90,
                    extra_cfg={"mapred.rdma.buf.size": 1})
    hits0 = failpoints.hits["data_engine.pread"]
    with failpoints.scoped("data_engine.pread=truncate:23:every:2"):
        b = _merge_once(tmp_path, True, records_per_map=90,
                        extra_cfg={"mapred.rdma.buf.size": 1})
        assert failpoints.hits["data_engine.pread"] > hits0
    assert a == b
