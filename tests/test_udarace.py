"""udarace tier tests (ISSUE 20): the lockset static analysis
(UDA201-203), the wire-exhaustiveness lint (UDA204), the thread-root
registry, and the runtime Eraser race detector in utils/locks.py.

1. Per-rule bad/good fixtures, including the two historical shapes the
   tier exists to catch early: the PR 10 "gauge stuck at -1"
   double-settle (a settle path skipping the lock -> UDA202) and the
   PR 6 parked-request recursion (loop-callback state also touched by a
   helper thread with no lock -> UDA201).
2. The `# udarace: lockfree=` waiver contract: waivers silence the
   finding, bare waivers (no justification) are themselves findings.
3. The thread-root registry: every declared (file, func) pair resolves
   to a real function in the tree — a rename breaks the build, not the
   analysis silently.
4. Runtime half: a faults-marked seeded race (two threads, unguarded
   counter) is reported EXACTLY once with both stacks; a lock-guarded
   control stays clean; the static<->runtime inventories stay in
   lockstep; the disabled path leaves instrumented classes untouched.
"""

from __future__ import annotations

import ast
import json
import os
import sys
import threading
import textwrap

import pytest

from uda_tpu.analysis.cfg import build_cfg
from uda_tpu.analysis.core import Engine, Finding
from uda_tpu.analysis.flow import ObligationPair, ResourceBalanceRule
from uda_tpu.analysis.race import RaceLocksetRule, WireExhaustivenessRule
from uda_tpu.analysis import threads as threads_mod
from uda_tpu.utils import locks as locks_mod
from uda_tpu.utils.locks import (RaceDetector, TrackedLock,
                                 race_instrument)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src: str, rules=None, rel: str = "uda_tpu/fix.py") -> list:
    eng = Engine([RaceLocksetRule()] if rules is None else rules)
    out = eng.lint_source(textwrap.dedent(src), rel)
    out.extend(eng.finish())
    return out


def lint_tree(files: dict, rules) -> list:
    eng = Engine(rules)
    out: list[Finding] = []
    for rel, src in files.items():
        out.extend(eng.lint_source(textwrap.dedent(src), rel))
    out.extend(eng.finish())
    return out


def rule_ids(findings) -> list[str]:
    return [f.rule for f in findings]


# -- UDA201: unguarded shared attribute --------------------------------------


BAD_201 = """
    import threading
    from uda_tpu.utils.locks import TrackedLock

    class Table:
        def __init__(self):
            self._lock = TrackedLock("t")
            self._tab = {}

        def start(self):
            threading.Thread(target=self._writer).start()
            threading.Thread(target=self._reader).start()

        def _writer(self):
            self._tab["k"] = 1

        def _reader(self):
            return self._tab.get("k")
"""


class TestUDA201:
    def test_unguarded_two_root_write_fires(self):
        out = lint(BAD_201)
        assert rule_ids(out) == ["UDA201"]
        assert "Table._tab" in out[0].message
        assert "2 thread roots" in out[0].message
        # one witness per conflicting root
        assert len(out[0].data["witnesses"]) == 2

    def test_guarded_is_clean(self):
        out = lint(BAD_201.replace(
            'self._tab["k"] = 1',
            'with self._lock:\n                self._tab["k"] = 1'
        ).replace(
            'return self._tab.get("k")',
            'with self._lock:\n                return self._tab.get("k")'
        ))
        assert out == []

    def test_single_root_is_clean(self):
        # one spawn only: the attribute is never multi-thread reachable
        out = lint(BAD_201.replace(
            "threading.Thread(target=self._reader).start()", "pass"))
        assert out == []

    def test_lockless_class_not_convicted(self):
        # no TrackedLock attr and not declared shared: instance
        # confinement is presumed — the runtime machine covers these
        out = lint(BAD_201.replace(
            '            self._lock = TrackedLock("t")\n', ''))
        assert out == []

    def test_waiver_silences_with_justification(self):
        out = lint(BAD_201.replace(
            "self._tab = {}",
            "# udarace: lockfree=_tab - fixture: GIL-atomic dict ops\n"
            "        self._tab = {}"))
        assert out == []

    def test_bare_waiver_is_a_finding(self):
        out = lint(BAD_201.replace(
            "self._tab = {}",
            "# udarace: lockfree=_tab\n        self._tab = {}"))
        assert rule_ids(out) == ["UDA201"]
        assert "no justification" in out[0].message

    def test_parked_request_regression_shape(self):
        # PR 6 shape: @loop_callback state also drained by a helper
        # thread — the parked-request list raced the loop
        out = lint("""
            import threading
            from uda_tpu.utils.locks import TrackedLock
            from uda_tpu.net.evloop import loop_callback

            class Conn:
                def __init__(self):
                    self._lock = TrackedLock("conn")
                    self._parked = []

                def start(self):
                    threading.Thread(target=self._drain).start()

                @loop_callback
                def on_readable(self):
                    self._parked.append(1)

                def _drain(self):
                    while self._parked:
                        self._parked.pop()
        """)
        assert rule_ids(out) == ["UDA201"]
        assert "Conn._parked" in out[0].message


# -- UDA202: the check-then-act escape (historical double-settle) ------------


class TestUDA202:
    def test_double_settle_shape_fires(self):
        # PR 10 shape: the error path settles the gauge AGAIN, outside
        # the lock the normal path holds — the gauge stuck at -1
        out = lint("""
            import threading
            from uda_tpu.utils.locks import TrackedLock

            class Gauge:
                def __init__(self):
                    self._lock = TrackedLock("g")
                    self._outstanding = 0

                def start(self):
                    threading.Thread(target=self._settle).start()
                    threading.Thread(target=self._error_path).start()

                def _settle(self):
                    with self._lock:
                        self._outstanding -= 1

                def _error_path(self):
                    self._outstanding -= 1
        """)
        assert rule_ids(out) == ["UDA202"]
        f = out[0]
        assert "'self._lock'" in f.message and "_error_path" in f.message
        assert "with self._lock:" in f.hint

    def test_all_paths_locked_is_clean(self):
        out = lint("""
            import threading
            from uda_tpu.utils.locks import TrackedLock

            class Gauge:
                def __init__(self):
                    self._lock = TrackedLock("g")
                    self._outstanding = 0

                def start(self):
                    threading.Thread(target=self._settle).start()
                    threading.Thread(target=self._error_path).start()

                def _settle(self):
                    with self._lock:
                        self._outstanding -= 1

                def _error_path(self):
                    with self._lock:
                        self._outstanding -= 1
        """)
        assert out == []


# -- UDA203: different locks on different paths ------------------------------


class TestUDA203:
    def test_mixed_guards_fire(self):
        out = lint("""
            import threading
            from uda_tpu.utils.locks import TrackedLock

            class Split:
                def __init__(self):
                    self._lock = TrackedLock("a")
                    self._other_lock = TrackedLock("b")
                    self._n = 0

                def start(self):
                    threading.Thread(target=self._a).start()
                    threading.Thread(target=self._b).start()

                def _a(self):
                    with self._lock:
                        self._n += 1

                def _b(self):
                    with self._other_lock:
                        self._n += 1
        """)
        assert rule_ids(out) == ["UDA203"]
        assert "DIFFERENT locks" in out[0].message


# -- UDA204: wire-protocol exhaustiveness ------------------------------------


WIRE_OK = """
    MSG_A = 1
    MSG_B = 2

    WIRE_CODECS = {
        MSG_A: ("encode_a", "decode_a"),
        MSG_B: ("encode_b", None),  # header-only frame: no payload
    }

    def encode_a(x):
        return x

    def decode_a(x):
        return x

    def encode_b(x):
        return x
"""

DISPATCH_OK = """
    from uda_tpu.net.wire import MSG_A, MSG_B

    def handle(t):
        if t == MSG_A:
            return "a"
        if t == MSG_B:
            return "b"
"""


class TestUDA204:
    RULES = staticmethod(lambda: [WireExhaustivenessRule()])

    def _lint(self, wire, dispatch=DISPATCH_OK):
        return lint_tree({"uda_tpu/net/wire.py": wire,
                          "uda_tpu/net/server.py": dispatch},
                         [WireExhaustivenessRule()])

    def test_complete_table_is_clean(self):
        assert self._lint(WIRE_OK) == []

    def test_missing_codec_entry_fires(self):
        out = self._lint(WIRE_OK.replace(
            '        MSG_B: ("encode_b", None),  '
            '# header-only frame: no payload\n', ''))
        assert "UDA204" in rule_ids(out)
        assert any("MSG_B" in f.message for f in out)

    def test_missing_encoder_def_fires(self):
        out = self._lint(WIRE_OK.replace(
            "def encode_b(x):\n        return x", "pass"))
        assert rule_ids(out) == ["UDA204"]
        assert "encode_b" in out[0].message

    def test_none_decoder_without_comment_fires(self):
        out = self._lint(WIRE_OK.replace(
            '("encode_b", None),  # header-only frame: no payload',
            '("encode_b", None),'))
        assert rule_ids(out) == ["UDA204"]

    def test_missing_dispatch_arm_fires(self):
        out = self._lint(WIRE_OK, DISPATCH_OK.replace(
            'if t == MSG_B:\n            return "b"', "pass"))
        assert rule_ids(out) == ["UDA204"]
        assert "MSG_B" in out[0].message and "dispatch" in out[0].message

    def test_real_wire_module_is_exhaustive(self):
        # the actual net/ plane: every MSG_* wired end to end
        from uda_tpu.net import wire
        msgs = {n for n in dir(wire) if n.startswith("MSG_")}
        keyed = set()
        for const, (enc, dec) in wire.WIRE_CODECS.items():
            assert enc is None or hasattr(wire, enc)
            assert dec is None or hasattr(wire, dec)
            keyed.add(const)
        assert keyed == {getattr(wire, n) for n in msgs}


# -- the thread-root registry ------------------------------------------------


class TestThreadRoots:
    def test_declared_roots_resolve_to_real_functions(self):
        # a rename must break the build, not silently blind the tier
        for tr in threads_mod.THREAD_ROOTS:
            path = os.path.join(REPO, "uda_tpu", tr.file)
            assert os.path.exists(path), f"{tr.root}: no file {tr.file}"
            tree = ast.parse(open(path, encoding="utf-8").read())
            names = {n.name for n in ast.walk(tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))}
            assert tr.func in names, \
                f"{tr.root}: no def {tr.func} in {tr.file}"

    def test_declared_root_lookup(self):
        tr = threads_mod.declared_root("uda_tpu/net/evloop.py", "_run")
        assert tr is not None and tr.root == threads_mod.LOOP_ROOT
        assert threads_mod.declared_root("uda_tpu/net/evloop.py",
                                         "nope") is None

    def test_runtime_inventory_classes_importable(self):
        import importlib
        for key, attrs in threads_mod.RUNTIME_INSTRUMENTED.items():
            mod_name, cls_name = key.rsplit(".", 1)
            cls = getattr(importlib.import_module(mod_name), cls_name)
            assert attrs, key
            assert "__slots__" not in vars(cls), \
                f"{key}: race_instrument needs an instance dict"


# -- runtime half: the Eraser machine ----------------------------------------


class TestRaceDetectorRuntime:
    @pytest.mark.faults
    def test_seeded_race_reported_once_with_both_stacks(self, tmp_path,
                                                        monkeypatch):
        out = tmp_path / "races.jsonl"
        monkeypatch.setenv("UDA_TPU_RACEDET_JSON", str(out))
        det = RaceDetector(enabled=True, emit_metrics=True)

        @race_instrument("n", det=det)
        class Counter:
            def __init__(self):
                self.n = 0

        c = Counter()

        def bump():
            for _ in range(300):
                c.n += 1

        ts = [threading.Thread(target=bump, name=f"racer-{i}")
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # exactly once, despite ~600 racing accesses
        assert len(det.races) == 1
        rep = det.races[0]
        assert rep["class"] == "Counter" and rep["attr"] == "n"
        # both sides of the race carry a stack
        assert len(rep["stacks"]) == 2
        assert all(stk.strip() for stk in rep["stacks"].values())
        # JSONL artifact for the chaos ladder
        lines = [json.loads(ln) for ln in
                 out.read_text().splitlines()]
        assert len(lines) == 1 and lines[0]["attr"] == "n"

    @pytest.mark.faults
    def test_guarded_counter_is_clean(self):
        det = RaceDetector(enabled=True, emit_metrics=False)

        @race_instrument("n", det=det)
        class Guarded:
            def __init__(self):
                self._lock = TrackedLock("race.fixture")
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

        g = Guarded()
        ts = [threading.Thread(target=lambda: [g.bump()
                                               for _ in range(300)])
              for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        with g._lock:
            assert g.n == 600
        assert det.races == []

    def test_single_thread_never_reports(self):
        det = RaceDetector(enabled=True, emit_metrics=False)

        @race_instrument("n", det=det)
        class Solo:
            def __init__(self):
                self.n = 0

        s = Solo()
        for _ in range(100):
            s.n += 1
        assert det.races == []

    def test_racedet_races_metric_counts(self, monkeypatch):
        from uda_tpu.utils.metrics import METRICS_REGISTRY, metrics
        assert "racedet.races" in METRICS_REGISTRY
        det = RaceDetector(enabled=True, emit_metrics=True)

        @race_instrument("n", det=det)
        class C:
            def __init__(self):
                self.n = 0

        c = C()
        before = metrics.snapshot().get("racedet.races", 0)

        def bump():
            for _ in range(300):
                c.n += 1
        ts = [threading.Thread(target=bump) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(det.races) == 1
        assert metrics.snapshot().get("racedet.races", 0) == before + 1


class TestDisabledOverhead:
    def test_disabled_decorator_leaves_class_untouched(self):
        det = RaceDetector(enabled=False, emit_metrics=False)

        class Plain:
            def __init__(self):
                self.x = 0

        decorated = race_instrument("x", det=det)(Plain)
        # SAME object, no descriptor in the attribute path: the hot
        # tables pay literally nothing when the machine is off
        assert decorated is Plain
        assert "x" not in vars(Plain)
        p = Plain()
        p.x = 41
        assert p.x == 41

    def test_production_classes_untouched_when_off(self):
        # the four hot classes ride the same contract (this test runs
        # in the default, disarmed tier)
        if locks_mod.racedet.enabled:
            pytest.skip("UDA_TPU_RACEDET armed for this run")
        import importlib
        for key, attrs in threads_mod.RUNTIME_INSTRUMENTED.items():
            mod_name, cls_name = key.rsplit(".", 1)
            cls = getattr(importlib.import_module(mod_name), cls_name)
            for attr in attrs:
                assert not isinstance(vars(cls).get(attr), property), \
                    f"{key}.{attr} hooked while racedet is off"

    def test_armed_decorator_installs_properties(self):
        det = RaceDetector(enabled=True, emit_metrics=False)

        @race_instrument("x", det=det)
        class Hooked:
            def __init__(self):
                self.x = 0

        assert isinstance(vars(Hooked)["x"], property)
        h = Hooked()
        h.x = 7
        assert h.x == 7 and h.__dict__["x"] == 7

    def test_slots_class_rejected_when_armed(self):
        det = RaceDetector(enabled=True, emit_metrics=False)
        with pytest.raises(TypeError):
            @race_instrument("x", det=det)
            class Slotted:
                __slots__ = ("x",)


class TestStaticRuntimeLockstep:
    def test_inventories_match_exactly(self):
        # importing the four production modules populates the runtime
        # registry; it must equal what threads.py declares — neither
        # side may drift (the static tier scopes conviction by the
        # declared set, the runtime hooks by the decorator)
        import uda_tpu.mofserver.store    # noqa: F401
        import uda_tpu.net.push           # noqa: F401
        import uda_tpu.tenant.sched       # noqa: F401
        declared = {k: tuple(v) for k, v
                    in threads_mod.RUNTIME_INSTRUMENTED.items()}
        hooked = {k: tuple(v) for k, v
                  in locks_mod.RACE_INSTRUMENTED.items()
                  if k.startswith("uda_tpu.")}  # test fixtures also
        assert hooked == declared                # register; skip them


# -- CFG: match statements and 3.12 type aliases (satellite 3) ---------------


def _cfg_of(src: str):
    tree = ast.parse(textwrap.dedent(src))
    return build_cfg(tree.body[0])


MATCH_FN = """
    def route(self, msg):
        match msg.kind:
            case "data":
                return self._data(msg)
            case "ctrl" if msg.urgent:
                raise Urgent(msg)
            case _:
                self._drop(msg)
"""


class TestCFGMatch:
    def test_match_header_models_subject_and_guards(self):
        cfg = _cfg_of(MATCH_FN)
        headers = [n for n in cfg.nodes if n.kind == "match"]
        assert len(headers) == 1
        # subject + the one case guard ride the header node's exprs
        assert len(headers[0].exprs) == 2

    def test_case_bodies_reach_their_terminals(self):
        cfg = _cfg_of(MATCH_FN)
        kinds = {n.kind for n in cfg.nodes}
        assert "return" in kinds and "raise_stmt" in kinds

    def test_non_exhaustive_match_falls_through(self):
        # no wildcard: the header keeps a normal edge past the cases
        cfg = _cfg_of("""
            def f(x):
                match x:
                    case 1:
                        return "one"
        """)
        header = next(n for n in cfg.nodes if n.kind == "match")
        assert cfg.exit_id in header.norm_succs

    def test_uda101_sees_leak_inside_match_case(self):
        pairs = (ObligationPair("engine.admit",
                                acquire=("_admit_bytes",),
                                release=("_unadmit",)),)
        rule = lambda: [ResourceBalanceRule(pairs=pairs)]  # noqa: E731
        leaky = """
            def plan(self, req):
                self._admit_bytes(8)
                match req.kind:
                    case "fast":
                        return self._fast(req)
                    case _:
                        self._unadmit(8)
        """
        out = lint(leaky, rule())
        assert rule_ids(out) == ["UDA101"]
        guarded = """
            def plan(self, req):
                self._admit_bytes(8)
                try:
                    match req.kind:
                        case "fast":
                            return self._fast(req)
                finally:
                    self._unadmit(8)
        """
        assert lint(guarded, rule()) == []

    @pytest.mark.skipif(sys.version_info < (3, 12),
                        reason="PEP 695 type statements need 3.12")
    def test_type_alias_statement_is_a_plain_stmt(self):
        cfg = _cfg_of("def f():\n    type Alias = list[int]\n    "
                      "return 1\n")
        kinds = [n.kind for n in cfg.nodes]
        assert "return" in kinds  # alias didn't sever the chain


# -- regression pins for the two production fixes this tier found ------------


class TestConvictedProductionCode:
    def test_store_migrations_appended_under_lock(self):
        # StoreManager.migrate used to append the migration log with no
        # lock while validate_spilled iterated it from the merge thread
        # (the UDA201 finding this tier's sweep fixed); pin the source
        # shape: the append now sits inside `with self._lock:`
        src = open(os.path.join(
            REPO, "uda_tpu/mofserver/store.py"), encoding="utf-8").read()
        tree = ast.parse(src)
        hits = 0
        for node in ast.walk(tree):
            if isinstance(node, ast.With):
                body_src = ast.get_source_segment(src, node) or ""
                if "_migrations.append" in body_src \
                        and "self._lock" in body_src:
                    hits += 1
        assert hits == 1

    def test_overlap_leftovers_take_forest_lock(self):
        src = open(os.path.join(
            REPO, "uda_tpu/merger/overlap.py"), encoding="utf-8").read()
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "_merge_leftovers":
                seg = ast.get_source_segment(src, node) or ""
                assert "with self._forest_lock:" in seg
                return
        pytest.fail("no _merge_leftovers in overlap.py")

    def test_tree_is_clean_under_udarace_rules(self):
        # the whole tree under UDA201-204: zero findings (waivers carry
        # justifications; this is the ci.sh gate's tier-1 twin)
        eng = Engine([RaceLocksetRule(), WireExhaustivenessRule()],
                     root=REPO)
        out = eng.lint_paths([os.path.join(REPO, "uda_tpu"),
                              os.path.join(REPO, "scripts")])
        assert out == [], "\n".join(f.render() for f in out)
