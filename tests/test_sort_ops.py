"""Device sort/merge vs host comparator oracle (reference MergeQueue
semantics, src/Merger/MergeQueue.h:276-427)."""

import functools
import struct

import numpy as np
import pytest

from uda_tpu.ops import merge, packing, sort
from uda_tpu.utils import comparators, ifile, vint


def _batch(pairs):
    return ifile.crack(ifile.write_records(pairs))


def _raw():
    return comparators.get_key_type("uda.tpu.RawBytes")


def _host_order(batch, kt):
    idx = list(range(batch.num_records))
    return sorted(idx, key=functools.cmp_to_key(
        lambda i, j: kt.compare(batch.key(i), batch.key(j)) or (i > j) - (i < j)))


def _random_records(n, seed, max_key=24, max_val=40):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        klen = int(rng.integers(0, max_key))
        out.append((rng.bytes(klen), rng.bytes(int(rng.integers(0, max_val)))))
    return out


def test_device_sort_matches_host_random():
    recs = _random_records(500, seed=0)
    # inject adversarial keys: trailing NULs, shared prefixes past width
    recs += [(b"a", b"1"), (b"a\x00", b"2"), (b"a\x00\x00", b"3"),
             (b"prefix__prefix__AAAA", b"4"), (b"prefix__prefix__AAAB", b"5"),
             (b"prefix__prefix__", b"6"), (b"", b"7"), (b"\xff" * 30, b"8")]
    batch = _batch(recs)
    kt = _raw()
    order = merge.sorted_batch_order(batch, kt, width=16)
    host = _host_order(batch, kt)
    got = [batch.key(int(i)) for i in order]
    want = [batch.key(i) for i in host]
    assert got == want


def test_device_sort_stability_on_equal_keys():
    recs = [(b"dup", bytes([i])) for i in range(50)]
    batch = _batch(recs)
    order = merge.sorted_batch_order(batch, _raw(), width=8)
    # equal keys keep arrival order
    assert order.tolist() == list(range(50))


def test_text_keys_device_order():
    kt = comparators.get_key_type("org.apache.hadoop.io.Text")
    words = [b"pear", b"apple", b"fig", b"applesauce", b"app", b"", b"zzz",
             b"apple"]
    recs = [(vint.encode_vlong(len(w)) + w, b"v") for w in words]
    batch = _batch(recs)
    order = merge.sorted_batch_order(batch, kt, width=8)
    got = [kt.content(batch.key(int(i))) for i in order]
    assert got == sorted(words)


def test_int_writable_memcmp_semantics_on_device():
    kt = comparators.get_key_type("org.apache.hadoop.io.IntWritable")
    vals = [3, 1000, -5, 0, -(2**31), 2**31 - 1, 7]
    recs = [(struct.pack(">i", v), b"v") for v in vals]
    batch = _batch(recs)
    order = merge.sorted_batch_order(batch, kt, width=4)
    got = [struct.unpack(">i", batch.key(int(i)))[0] for i in order]
    # memcmp order: non-negatives ascending, then negatives ascending
    want = sorted([v for v in vals if v >= 0]) + sorted([v for v in vals if v < 0])
    assert got == want


def test_int_numeric_variant_on_device():
    kt = comparators.get_key_type("uda.tpu.IntNumeric")
    vals = [3, -5, 0, -(2**31), 2**31 - 1]
    recs = [(struct.pack(">i", v), b"v") for v in vals]
    batch = _batch(recs)
    order = merge.sorted_batch_order(batch, kt, width=4)
    got = [struct.unpack(">i", batch.key(int(i)))[0] for i in order]
    assert got == sorted(vals)


def test_merge_batches_device_vs_host():
    kt = _raw()
    runs = []
    for s in range(4):
        recs = sorted(_random_records(100, seed=10 + s), key=lambda r: r[0])
        runs.append(_batch(recs))
    dev = merge.merge_batches(runs, kt, width=16)
    host = merge.merge_batches_host(runs, kt)
    assert list(dev.iter_records()) == list(host.iter_records())


def test_merge_iter_host_streaming():
    kt = _raw()
    runs = []
    for s in range(3):
        recs = sorted(_random_records(50, seed=20 + s), key=lambda r: r[0])
        runs.append(_batch(recs))
    streamed = list(merge.merge_iter_host(runs, kt))
    bulk = list(merge.merge_batches_host(runs, kt).iter_records())
    assert streamed == bulk


def test_merge_runs_run_ids():
    kt = _raw()
    a = _batch([(b"a", b"0"), (b"c", b"0")])
    b = _batch([(b"b", b"1"), (b"d", b"1")])
    pa = packing.pack_keys(a, kt, 8)
    pb = packing.pack_keys(b, kt, 8)
    perm, run_id = sort.merge_runs([pa, pb])
    assert perm.tolist() == [0, 2, 1, 3]
    assert run_id.tolist() == [0, 1, 0, 1]


def test_fixed_stride_terasort_layout():
    # TeraSort: 10-byte keys, 90-byte values, fully device-resident
    rng = np.random.default_rng(42)
    n = 256
    recs = [(rng.bytes(10), rng.bytes(90)) for _ in range(n)]
    batch = _batch(recs)
    kt = _raw()
    packed = packing.pack_keys(batch, kt, width=12)
    payload = packing.pack_fixed_payload(batch, stride=90)
    sorted_payload, perm = sort.sort_records_fixed(packed, payload)
    perm = np.asarray(perm)
    want_order = _host_order(batch, kt)
    assert perm.tolist() == want_order
    vals = packing.unpack_fixed_payload(np.asarray(sorted_payload),
                                        batch.val_len[perm], 90)
    assert vals == [recs[i][1] for i in want_order]


def test_pack_fixed_payload_rejects_oversize():
    batch = _batch([(b"k", b"x" * 10)])
    with pytest.raises(Exception):
        packing.pack_fixed_payload(batch, stride=8)


def test_overflow_keys_rank_before_length():
    # regression: keys longer than the width sharing a prefix must order
    # by post-width bytes (rank), not by length — b"...Z" (17B) sorts
    # AFTER b"...AB" (18B)
    kt = _raw()
    recs = [(b"prefix__prefix__Z", b"1"), (b"prefix__prefix__AB", b"2"),
            (b"prefix__prefix__", b"3"), (b"prefix__prefix__A", b"4")]
    batch = _batch(recs)
    order = merge.sorted_batch_order(batch, kt, width=16)
    got = [batch.key(int(i)) for i in order]
    assert got == sorted(k for k, _ in recs)


def test_overflow_text_keys_rank_by_content_not_serialized():
    # regression: overflow ranks must compare comparator CONTENT, not the
    # serialized key — Text's VInt length prefix must not dominate
    kt = comparators.get_key_type("org.apache.hadoop.io.Text")
    contents = [b"0123456789012345Z",   # len 17, shorter VInt prefix
                b"0123456789012345AB",  # len 18 — must sort FIRST (A < Z)
                b"0123456789012345"]
    recs = [(vint.encode_vlong(len(c)) + c, b"v") for c in contents]
    batch = _batch(recs)
    order = merge.sorted_batch_order(batch, kt, width=16)
    got = [kt.content(batch.key(int(i))) for i in order]
    assert got == sorted(contents)


def test_overflow_equal_full_keys_stable():
    kt = _raw()
    recs = [(b"prefix__prefix__XX", bytes([i])) for i in range(5)]
    recs.insert(2, (b"prefix__prefix__W", b"w"))
    batch = _batch(recs)
    order = merge.sorted_batch_order(batch, kt, width=16)
    got = [(batch.key(int(i)), batch.value(int(i))) for i in order]
    want = sorted(recs, key=lambda r: r[0])
    # equal full keys keep arrival order (stable)
    assert got == want


def test_empty_batch():
    batch = _batch([])
    order = merge.sorted_batch_order(batch, _raw(), width=8)
    assert order.shape == (0,)
    merged = merge.merge_batches([batch, batch], _raw(), width=8)
    assert merged.num_records == 0


def test_apply_perm_chunked_all_sweep_widths():
    # every chunk width the hardware sweep times (scripts/
    # sweep_carrychunk.py: cc=6/8/12/23) plus the degenerate and
    # over-wide extremes must be a pure refactoring of the same
    # permutation apply — byte-identical outputs per column
    import jax
    import numpy as np

    from uda_tpu.ops.sort import apply_perm_chunked

    rng = np.random.default_rng(7)
    n, ncols = 257, 23
    cols = [rng.integers(0, 1 << 32, n, dtype=np.uint32)
            for _ in range(ncols)]
    perm = rng.permutation(n).astype(np.int32)
    want = [c[perm] for c in cols]
    for cc in (1, 2, 6, 8, 12, 23, 40):
        got = jax.jit(lambda p, cs: apply_perm_chunked(p, cs, cc))(
            perm, [np.asarray(c) for c in cols])
        assert len(got) == ncols, cc
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w, np.asarray(g), err_msg=str(cc))


def test_bench_step_carrychunk_sweep_widths_validate():
    # the sweep drives bench_step with explicit chunk_cols; the
    # in-graph validation (order + checksum) must hold at every width
    import jax
    import numpy as np

    from uda_tpu.models import terasort

    for cc in (6, 12, 23):
        viol, ck_in, ck_out = terasort.bench_step(
            jax.random.key(11), 1024, 1, path="carrychunk", tile=256,
            chunk_cols=cc)
        assert int(viol) == 0, cc
        assert np.uint32(ck_in) == np.uint32(ck_out), cc
