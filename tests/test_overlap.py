"""Overlapped fetch/merge (the network-levitated property,
uda_tpu.merger.overlap): runs stage + merge on device WHILE later
fetches are in flight, output byte-identical to the global re-sort."""

import functools
import io
import threading

import numpy as np
import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.merger.overlap import OverlappedMerger
from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.ops import merge as merge_ops
from uda_tpu.utils import comparators
from uda_tpu.utils.config import Config
from uda_tpu.utils.ifile import IFileReader, RecordBatch, crack, write_records


def _batch(recs):
    return crack(write_records(recs))


def _rand_recs(seed, n, dup_every=5):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        k = rng.bytes(6) if i % dup_every else b"dupkey"
        recs.append((k, rng.bytes(20)))
    return recs


def test_overlap_matches_global_resort():
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    batches = [_batch(_rand_recs(s, 40 + 7 * s)) for s in range(5)]
    om = OverlappedMerger(kt, width=16)
    # feed OUT of completion order: stability must still follow original
    # (segment, row) order, not completion order
    for i in (3, 0, 4, 1, 2):
        om.feed(i, batches[i])
    got = om.finish(batches)
    want = merge_ops.merge_batches(batches, kt, 16)
    assert list(got.iter_records()) == list(want.iter_records())
    assert om.stats["device_merges"] >= 1
    assert not om.stats["overflow"]


@pytest.mark.slow
def test_overlap_pallas_engine_matches_host():
    # force the device merge-path kernel (interpret mode on CPU): the
    # integration the TPU deployment runs, against the host twin
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    batches = [_batch(_rand_recs(100 + s, 30 + s)) for s in range(3)]
    om_p = OverlappedMerger(kt, width=16, engine="pallas")
    om_h = OverlappedMerger(kt, width=16, engine="host")
    for i, b in enumerate(batches):
        om_p.feed(i, b)
        om_h.feed(i, b)
    got_p = om_p.finish(batches)
    got_h = om_h.finish(batches)
    assert list(got_p.iter_records()) == list(got_h.iter_records())
    assert om_p.stats["device_merges"] >= 1


def test_overlap_oversize_keys_fall_back():
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    # keys longer than the carried width with colliding prefixes across
    # segments: exactly the case the fast path cannot order
    pre = b"P" * 16
    b0 = _batch([(pre + b"zz", b"v0"), (b"a", b"v1")])
    b1 = _batch([(pre + b"ab", b"v2"), (b"b", b"v3")])
    om = OverlappedMerger(kt, width=16)
    om.feed(0, b0)
    om.feed(1, b1)
    got = om.finish([b0, b1])
    want = merge_ops.merge_batches_host([b0, b1], kt)
    assert list(got.iter_records()) == list(want.iter_records())
    assert om.stats["overflow"]


def test_overlap_empty_and_single_segment():
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    empty = RecordBatch.concat([])
    one = _batch(_rand_recs(9, 17))
    om = OverlappedMerger(kt, width=16)
    om.feed(0, empty)
    om.feed(1, one)
    got = om.finish([empty, one])
    want = merge_ops.merge_batches([empty, one], kt, 16)
    assert list(got.iter_records()) == list(want.iter_records())


def test_merge_work_happens_before_last_fetch(tmp_path):
    """The VERDICT contract: device merge work completes while the last
    fetch is still outstanding (reference MergeManager.cc:47-182)."""
    num_maps = 9
    make_mof_tree(str(tmp_path), "jobO", num_maps, 1, 40, seed=21)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    release_last = threading.Event()
    state = {"completed": 0, "merges_at_last_start": None}
    lock = threading.Lock()

    class GatedClient(LocalFetchClient):
        """Holds back ONE map's fetch until the test observes overlap."""

        def start_fetch(self, req, on_complete):
            if req.map_id.endswith("000008_0") and req.offset == 0:
                def gated(res):
                    release_last.wait(timeout=30)
                    on_complete(res)
                super().start_fetch(req, gated)
            else:
                super().start_fetch(req, on_complete)

    cfg = Config({"mapred.rdma.wqe.per.conn": num_maps})  # all in flight
    mm = MergeManager(GatedClient(engine), "uda.tpu.RawBytes", cfg)
    result = {}

    def run():
        blocks = []
        result["total"] = mm.run("jobO", map_ids("jobO", num_maps), 0,
                                 lambda b: blocks.append(bytes(b)))
        result["stream"] = b"".join(blocks)

    t = threading.Thread(target=run)
    t.start()
    try:
        # wait until the 8 ungated segments have been staged AND merged
        # into the forest (binary counter: 8 runs => >= 4 device merges),
        # all while the gated fetch is still outstanding
        waiter = threading.Event()
        for _ in range(3000):
            if _overlap_stats(mm)["device_merges"] >= 4:
                break
            waiter.wait(0.01)
        stats = _overlap_stats(mm)
        state["merges_at_last_start"] = stats["device_merges"]
        assert stats["device_merges"] >= 4, (
            f"no overlap: only {stats} before last fetch released")
    finally:
        release_last.set()
        t.join(timeout=60)
        engine.stop()
    assert not t.is_alive()
    # and the result is still the correctly sorted stream
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    got = list(IFileReader(io.BytesIO(result["stream"])))
    assert len(got) == num_maps * 40
    keys = [k for k, _ in got]
    assert keys == sorted(keys, key=functools.cmp_to_key(kt.compare))


def _overlap_stats(mm):
    om = getattr(mm, "_active_overlap", None)
    return om.stats if om is not None else {"device_merges": 0}


def test_online_merge_with_overlap_disabled_still_works(tmp_path):
    make_mof_tree(str(tmp_path), "jobN", 4, 1, 25, seed=13)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    cfg = Config({"uda.tpu.merge.overlap": False})
    try:
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes", cfg)
        blocks = []
        mm.run("jobN", map_ids("jobN", 4), 0,
               lambda b: blocks.append(bytes(b)))
        got = list(IFileReader(io.BytesIO(b"".join(blocks))))
        assert len(got) == 100
    finally:
        engine.stop()
