"""TeraSort workload: single-chip and distributed (BASELINE configs 2/5)."""

import jax
import numpy as np
import pytest

from uda_tpu.models import terasort
from uda_tpu.parallel.mesh import make_mesh


def test_teragen_shape_and_pad():
    words = np.asarray(terasort.teragen(jax.random.key(0), 1024))
    assert words.shape == (1024, terasort.RECORD_WORDS)
    assert words.dtype == np.uint32
    # key pad bytes are zero (fixed-width memcmp contract)
    assert (words[:, 2] & 0xFFFF).max() == 0


def test_single_chip_sort_total_order():
    words = np.asarray(terasort.teragen(jax.random.key(1), 4096))
    out = np.asarray(terasort.single_chip_sort(words))
    keys = [tuple(r[:3]) for r in out]
    assert keys == sorted(keys)
    assert sorted(map(tuple, out)) == sorted(map(tuple, words))
    terasort.validate_sorted(out, words)


def test_single_chip_sort_gather_path_matches_carry():
    # the bounded-compile accelerator path must produce byte-identical
    # output to the operand-carry path (stability included: duplicate
    # keys keep arrival order in both)
    words = np.asarray(terasort.teragen(jax.random.key(7), 2048)).copy()
    words[100:300, :3] = words[700:900, :3]  # inject duplicate keys
    a = np.asarray(terasort.single_chip_sort(words, path="carry"))
    b = np.asarray(terasort.single_chip_sort(words, path="gather"))
    np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_single_chip_sort_all_engines_match_carry():
    # every public engine, byte-identical to the carry oracle — with a
    # non-power-of-two n (padding engages), duplicate keys (stability),
    # and records whose keys are all 0xFFFFFFFF (they TIE with the
    # padding lanes' +inf keys; the arrival tie-break must still place
    # every real record before the padding)
    words = np.asarray(terasort.teragen(jax.random.key(21), 1000)).copy()
    words[5:8, :3] = 0xFFFFFFFF
    words[100:200, :3] = words[300:400, :3]
    a = np.asarray(terasort.single_chip_sort(words, path="carry"))
    for path in ("lanes", "lanes2", "keys8", "keys8f", "gather",
                 "gather2", "carrychunk"):
        b = np.asarray(terasort.single_chip_sort(words, path=path,
                                                 tile=512, interpret=True))
        np.testing.assert_array_equal(a, b, err_msg=path)


def test_bench_step_both_paths_validate():
    for path in ("carry", "gather"):
        viol, ck_in, ck_out = terasort.bench_step(
            jax.random.key(5), 4096, 2, path=path)
        assert int(viol) == 0, path
        assert np.uint32(ck_in) == np.uint32(ck_out), path


def test_teragen_lanes_matches_layout():
    from uda_tpu.ops.pallas_sort import ROWS

    x = np.asarray(terasort.teragen_lanes(jax.random.key(9), 512))
    assert x.shape == (ROWS, 512)
    assert (x[2] & 0xFFFF).max() == 0          # key pad bytes zero
    assert x[terasort.RECORD_WORDS:].max() == 0  # layout pad rows zero


@pytest.mark.slow
def test_bench_step_lanes_path_validates():
    # interpret=True: Pallas kernels run on the CPU test backend
    viol, ck_in, ck_out = terasort.bench_step(
        jax.random.key(5), 2048, 2, path="lanes", tile=512, interpret=True)
    assert int(viol) == 0
    assert np.uint32(ck_in) == np.uint32(ck_out)


@pytest.mark.slow
def test_bench_step_keys8_path_validates():
    for path in ("keys8", "keys8f"):
        viol, ck_in, ck_out = terasort.bench_step(
            jax.random.key(5), 2048, 2, path=path, tile=512,
            interpret=True)
        assert int(viol) == 0, path
        assert np.uint32(ck_in) == np.uint32(ck_out), path


def test_bench_step_gather2_path_validates():
    viol, ck_in, ck_out = terasort.bench_step(
        jax.random.key(5), 2048, 2, path="gather2", tile=512)
    assert int(viol) == 0
    assert np.uint32(ck_in) == np.uint32(ck_out)


def test_bench_step_carrychunk_path_validates():
    viol, ck_in, ck_out = terasort.bench_step(
        jax.random.key(5), 2048, 2, path="carrychunk", tile=512)
    assert int(viol) == 0
    assert np.uint32(ck_in) == np.uint32(ck_out)


@pytest.mark.slow
def test_sort_lanes_keys8_matches_sort_lanes():
    # the keys8 engine (keys-only cascade + one global payload gather)
    # must be byte-identical to the 32-row pipeline, stability included,
    # in both the standard and folded cascade variants
    from uda_tpu.ops import pallas_sort

    x = np.asarray(terasort.teragen_lanes(jax.random.key(12), 2048)).copy()
    x[:3, 100:300] = x[:3, 700:900]  # duplicate keys
    a = np.asarray(pallas_sort.sort_lanes(x, num_keys=terasort.KEY_WORDS,
                                          tile=512, interpret=True))
    for folded in (False, True):
        b = np.asarray(terasort.sort_lanes_keys8(x, tile=512,
                                                 interpret=True,
                                                 folded=folded))
        np.testing.assert_array_equal(a, b, err_msg=f"folded={folded}")


@pytest.mark.slow
def test_bench_step_lanes_checksum_matches_oracle():
    # the lanes checksum must use the same per-column multipliers as the
    # SoA paths: a sorted output altered by a column swap fails
    import jax.numpy as jnp

    from uda_tpu.ops import pallas_sort

    x = terasort.teragen_lanes(jax.random.key(11), 1024)
    out = pallas_sort.sort_lanes(x, num_keys=terasort.KEY_WORDS, tile=512,
                                 interpret=True)
    got = np.asarray(pallas_sort.lanes_to_rows(out, terasort.RECORD_WORDS))
    rows = np.asarray(pallas_sort.lanes_to_rows(x, terasort.RECORD_WORDS))
    terasort.validate_sorted(got, rows)


def test_distributed_terasort_gather_payload_path():
    from uda_tpu.parallel.distributed import (distributed_sort_step,
                                              uniform_splitters)

    mesh = make_mesh(4)
    words = np.asarray(terasort.teragen(jax.random.key(6), 4 * 256))
    res = distributed_sort_step(words, uniform_splitters(4), mesh,
                                "shuffle", capacity=256, num_keys=3,
                                payload_path="gather")
    res.check()
    out = np.asarray(res.words).reshape(4, -1, terasort.RECORD_WORDS)
    nvalid = np.asarray(res.valid_counts).reshape(-1)
    rows = np.concatenate([out[d, :nvalid[d]] for d in range(4)])
    terasort.validate_sorted(rows, words)


def test_validate_sorted_catches_violation():
    words = np.asarray(terasort.teragen(jax.random.key(2), 256))
    out = np.asarray(terasort.single_chip_sort(words))
    bad = out[::-1].copy()
    with pytest.raises(AssertionError):
        terasort.validate_sorted(bad)


def test_validate_sorted_catches_corruption():
    words = np.asarray(terasort.teragen(jax.random.key(3), 256))
    out = np.asarray(terasort.single_chip_sort(words)).copy()
    out[10, 5] ^= 1  # flip one payload bit
    with pytest.raises(AssertionError):
        terasort.validate_sorted(out, words)


def test_validate_sorted_catches_column_swap():
    # distinct per-column multipliers in the checksum: swapping two
    # value columns in every row (a plausible gather-path indexing bug)
    # must fail even though row sums with a single multiplier would not
    words = np.asarray(terasort.teragen(jax.random.key(8), 256))
    out = np.asarray(terasort.single_chip_sort(words)).copy()
    out[:, [5, 7]] = out[:, [7, 5]]
    with pytest.raises(AssertionError):
        terasort.validate_sorted(out, words)


def test_distributed_terasort_8dev():
    mesh = make_mesh(8)
    words = np.asarray(terasort.teragen(jax.random.key(4), 8 * 256))
    res = terasort.distributed_terasort(words, mesh)
    res.check()
    out = np.asarray(res.words).reshape(8, -1, terasort.RECORD_WORDS)
    nvalid = np.asarray(res.valid_counts).reshape(-1)
    rows = np.concatenate([out[d, :nvalid[d]] for d in range(8)])
    assert rows.shape[0] == words.shape[0]
    keys = [tuple(r[:3]) for r in rows]
    assert keys == sorted(keys)
    terasort.validate_sorted(rows, words)


@pytest.mark.slow
def test_graft_entry_contract():
    """The driver's contract: a FRESH process can jit entry() and run
    dryrun_multichip on a virtual CPU mesh. Exercised in a subprocess
    because that is exactly how the driver consumes __graft_entry__ —
    and because the dryrun's dozen large 8-device XLA CPU compiles
    proved crash-flaky when run in-process late in the full suite
    (segfault inside backend_compile_and_load at this exact test,
    2026-07-31; not reproducible in isolation or in any half-suite
    subset, and MALLOC_CHECK_/ASan full-suite runs found no native
    heap misuse — see BENCH_NOTES_r05.md). A fresh interpreter is both
    the honest contract and the stable one."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = (
        "import jax, __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "assert out.shape == args[0].shape\n"
        "g.dryrun_multichip(8)\n"
        "g.dryrun_multichip(4)\n"
        "print('GRAFT_CONTRACT_OK')\n"
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                   " --xla_force_host_platform_device_count=8").strip(),
    )
    # keep the child off the accelerator pool even when this suite was
    # not started through conftest's re-exec (belt and braces; a wedged
    # pool hangs the child at interpreter startup otherwise)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, "-c", prog], cwd=repo, env=env,
                       capture_output=True, text=True, timeout=1200)
    assert r.returncode == 0, f"graft entry contract failed:\n{r.stdout}\n{r.stderr}"
    assert "GRAFT_CONTRACT_OK" in r.stdout
