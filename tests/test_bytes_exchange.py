"""Opaque-bytes mesh transport + MergeManager fed from the exchange.

The end-to-end the reference calls its reason to exist: supplier map
outputs crossing the wire (here: the device mesh) into the reduce-side
merge, joined only by the InputClient contract."""

import io

import numpy as np

from uda_tpu.parallel.bytes_exchange import (ExchangeFetchClient,
                                             exchange_blobs)
from uda_tpu.parallel.mesh import SHUFFLE_AXIS, make_mesh


def _random_blobs(p, rng, max_blobs=6, max_len=1500):
    blobs = []
    for _ in range(p):
        items = [(int(rng.integers(0, p)),
                  rng.bytes(int(rng.integers(0, max_len))))
                 for _ in range(int(rng.integers(0, max_blobs)))]
        blobs.append(items)
    return blobs


def _check_round_trip(blobs, out, p):
    for d in range(p):
        for s in range(p):
            want = [b for dst, b in blobs[s] if dst == d]
            assert out[d][s] == want, (d, s)


def test_exchange_blobs_round_trip():
    mesh = make_mesh(8)
    blobs = _random_blobs(8, np.random.default_rng(9))
    out = exchange_blobs(blobs, mesh, SHUFFLE_AXIS, row_payload_bytes=128)
    _check_round_trip(blobs, out, 8)


def test_exchange_blobs_multiround_and_empty():
    # capacity far below the biggest bucket: the windowed rounds must
    # reassemble byte-identically; empty blobs survive as b""
    mesh = make_mesh(4)
    rng = np.random.default_rng(17)
    blobs = _random_blobs(4, rng, max_blobs=5, max_len=900)
    blobs[1].append((2, b""))          # empty blob
    blobs[3] = [(0, rng.bytes(4000))] * 3  # skew: one hot destination
    out = exchange_blobs(blobs, mesh, SHUFFLE_AXIS, capacity=2,
                         row_payload_bytes=64)
    _check_round_trip(blobs, out, 4)


def test_exchange_blobs_multiaxis_mesh_single_axis():
    # a multi-axis mesh with one exchange axis: group size must be the
    # AXIS size (4), not the device count (8) — dests past the axis
    # size are rejected instead of silently dropped
    import jax
    import pytest
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dcn", SHUFFLE_AXIS))
    blobs = _random_blobs(4, np.random.default_rng(23), max_blobs=4)
    out = exchange_blobs(blobs, mesh, SHUFFLE_AXIS, row_payload_bytes=64)
    _check_round_trip(blobs, out, 4)
    with pytest.raises(ValueError, match="outside"):
        exchange_blobs([[(7, b"x")]] + [[]] * 3, mesh, SHUFFLE_AXIS)


def test_merge_manager_over_exchange():
    # the full reference flow: per-supplier sorted map-output partitions
    # -> mesh bytes transport -> reduce-side MergeManager merge
    from uda_tpu.merger import MergeManager
    from uda_tpu.models.wordcount import parse_text_key, text_key
    from uda_tpu.utils.ifile import IFileReader, IFileWriter

    p = 4
    mesh = make_mesh(p)
    rng = np.random.default_rng(5)
    map_ids = [f"attempt_m_{m:06d}_0" for m in range(p)]
    partition_records = {}
    blobs = []
    for m in range(p):
        items = []
        for r in range(p):
            recs = sorted(
                ((text_key(b"k%04d" % rng.integers(0, 300)),
                  b"v%d.%d.%d" % (m, r, i)) for i in range(30)),
                key=lambda kv: parse_text_key(kv[0]))
            buf = io.BytesIO()
            w = IFileWriter(buf)
            for k, v in recs:
                w.append(k, v)
            w.close()
            items.append((r, buf.getvalue()))
            partition_records[(m, r)] = recs
        blobs.append(items)

    delivered = exchange_blobs(blobs, mesh, SHUFFLE_AXIS)
    for r in range(p):
        segments = {map_ids[s]: delivered[r][s][0] for s in range(p)}
        mm = MergeManager(ExchangeFetchClient(segments),
                          "org.apache.hadoop.io.Text")
        blocks: list[bytes] = []
        mm.run("job_bx", map_ids, r, lambda b: blocks.append(bytes(b)))
        merged = list(IFileReader(io.BytesIO(b"".join(blocks))))
        want = [rec for m in range(p) for rec in partition_records[(m, r)]]
        assert sorted(merged) == sorted(want), f"reducer {r} lost records"
        contents = [parse_text_key(k) for k, _ in merged]
        assert contents == sorted(contents), f"reducer {r} unsorted"


try:
    from hypothesis import given, settings, strategies as st
    _HYP = True
except ImportError:           # pragma: no cover - hypothesis is baked in
    _HYP = False

if _HYP:
    import pytest as _pytest

    @_pytest.mark.slow
    @settings(max_examples=15, deadline=None)
    @given(st.lists(st.lists(st.tuples(st.integers(0, 3),
                                       st.binary(max_size=300)),
                             max_size=4),
                    min_size=4, max_size=4),
           st.integers(1, 3))
    def test_exchange_blobs_property(blobs, capacity):
        # arbitrary blob sizes (incl. empty), dest patterns, and round
        # windows must all reassemble byte-identically in send order
        mesh = make_mesh(4)
        out = exchange_blobs(blobs, mesh, SHUFFLE_AXIS, capacity=capacity,
                             row_payload_bytes=64)
        _check_round_trip(blobs, out, 4)


def test_pipeline_mesh_shuffle_matches_local(tmp_path):
    # the full MapReduce driver with the mesh as the wire: identical
    # output to the local DataEngine shuffle, plain and compressed
    from uda_tpu.utils.config import Config

    from uda_tpu.models import wordcount as wc
    from uda_tpu.models.pipeline import MapReduceJob

    text = (b"alpha beta gamma alpha beta alpha delta " * 40)
    mesh = make_mesh(4)
    splits = [text[: len(text) // 2], text[len(text) // 2:],
              b"alpha", b"", b"beta"]

    for tag, cfg in (("plain", None),
                     ("zlib", Config({"mapred.compress.map.output": True,
                                      "mapred.map.output.compression.codec":
                                      "zlib"}))):
        def job(sub):
            return MapReduceJob(f"wc_mesh_{tag}", wc._mapper, wc._reducer,
                                key_type="org.apache.hadoop.io.Text",
                                num_reducers=3, config=cfg,
                                work_dir=str(tmp_path / f"{tag}_{sub}"))

        # the documented contract is BYTE identity with the local path:
        # same reducer partitioning, same merged record order, same
        # serialized bytes
        local = job("local").run(splits)
        meshed = job("mesh").run(splits, mesh=mesh)
        assert meshed == local, tag


def test_run_wordcount_mesh_passthrough(tmp_path):
    from uda_tpu.models.wordcount import run_wordcount

    text = b"a b a c a b"
    local = run_wordcount(text, num_maps=2, num_reducers=2,
                          work_dir=str(tmp_path / "l"))
    meshed = run_wordcount(text, num_maps=2, num_reducers=2,
                           work_dir=str(tmp_path / "m"), mesh=make_mesh(4))
    assert meshed == local == {b"a": 3, b"b": 2, b"c": 1}


def test_exchange_fetch_client_unknown_map():
    import pytest

    from uda_tpu.utils.errors import MergeError

    client = ExchangeFetchClient({"m0": b"x"})
    got = []
    from uda_tpu.mofserver.data_engine import ShuffleRequest
    client.start_fetch(ShuffleRequest("j", "missing", 0, 0, 64), got.append)
    assert isinstance(got[0], MergeError)
