"""Bridge surface: command protocol, role dispatch, up-calls, fallback
(reference src/UdaBridge.cc, src/CommUtils/C2JNexus.cc)."""

import functools
import io
import threading

import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.bridge import Cmd, UdaBridge, form_cmd, parse_cmd
from uda_tpu.mofserver import DirIndexResolver
from uda_tpu.utils import comparators
from uda_tpu.utils.errors import ProtocolError
from uda_tpu.utils.ifile import IFileReader
from uda_tpu.utils.logging import get_logger


def teardown_function(_fn):
    get_logger().set_sink(None)


def test_protocol_round_trip():
    cmd = form_cmd(Cmd.FETCH, ["host1", "job_1", "attempt_x", "3"])
    assert cmd == "4:4:host1:job_1:attempt_x:3"
    header, params = parse_cmd(cmd)
    assert header == Cmd.FETCH
    assert params == ["host1", "job_1", "attempt_x", "3"]
    assert parse_cmd("0:2")[0] == Cmd.FINAL


def test_protocol_errors():
    with pytest.raises(ProtocolError):
        parse_cmd("nonsense")
    with pytest.raises(ProtocolError):
        parse_cmd("2:4:only_one")        # count mismatch
    with pytest.raises(ProtocolError):
        parse_cmd("0:99")                # unknown header
    with pytest.raises(ProtocolError):
        form_cmd(Cmd.INIT, ["has:colon"])


class Harness:
    """Embedder double: collects up-calls like UdaPluginRT would."""

    def __init__(self, root):
        self.root = root
        self.blocks = []
        self.fetch_over = threading.Event()
        self.failures = []
        self.conf = {}
        self.logs = []
        self._resolver = DirIndexResolver(root)

    def data_from_uda(self, data, length):
        self.blocks.append(bytes(data[:length]))

    def fetch_over_message(self):
        self.fetch_over.set()

    def get_path_uda(self, job_id, map_id, reduce_id):
        return self._resolver.resolve(job_id, map_id, reduce_id)

    def get_conf_data(self, name, default):
        return self.conf.get(name, "")

    def log_to(self, level, message):
        self.logs.append((level, message))

    def failure_in_uda(self, error):
        self.failures.append(error)
        self.fetch_over.set()


def _drive_reduce(tmp_path, job, num_maps=4, reducers=2, init_extra=None):
    expected = make_mof_tree(str(tmp_path), job, num_maps, reducers, 40,
                             seed=13)
    results = {}
    for r in range(reducers):
        harness = Harness(str(tmp_path))
        bridge = UdaBridge()
        bridge.start(True, ["-w", "8", "-s", "64"], harness)
        bridge.do_command(form_cmd(
            Cmd.INIT, [job, str(r), str(num_maps), "uda.tpu.RawBytes"]
            + (init_extra or [])))
        for mid in map_ids(job, num_maps):
            bridge.do_command(form_cmd(Cmd.FETCH, ["localhost", job, mid, str(r)]))
        bridge.do_command(form_cmd(Cmd.FINAL, []))
        assert harness.fetch_over.wait(timeout=30)
        bridge.reduce_exit()
        assert not harness.failures, harness.failures
        results[r] = list(IFileReader(io.BytesIO(b"".join(harness.blocks))))
    return expected, results


def test_reduce_role_end_to_end_via_upcall_resolution(tmp_path):
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    expected, results = _drive_reduce(tmp_path, "jobB1")
    for r, got in results.items():
        want = sorted(expected[r], key=functools.cmp_to_key(
            lambda a, b: kt.compare(a[0], b[0])))
        assert [k for k, _ in got] == [k for k, _ in want]


def test_reduce_role_with_local_dirs_param(tmp_path):
    # INIT's trailing params are local dirs -> DirIndexResolver path
    expected, results = _drive_reduce(tmp_path, "jobB2",
                                      init_extra=[str(tmp_path).replace(":", "")])
    assert sum(len(v) for v in results.values()) == sum(
        len(v) for v in expected.values())


def test_supplier_role_serves_and_exits(tmp_path):
    make_mof_tree(str(tmp_path), "jobB3", 2, 1, 10, seed=14)
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(False, ["-w", "8"], harness)
    from uda_tpu.mofserver import ShuffleRequest

    engine = bridge.data_engine()
    res = engine.fetch(ShuffleRequest("jobB3", map_ids("jobB3", 2)[0], 0,
                                      0, 1 << 20))
    assert res.is_last and len(res.data) > 0
    bridge.do_command(form_cmd(Cmd.JOB_OVER, ["jobB3"]))
    bridge.do_command(form_cmd(Cmd.EXIT, []))


def test_failure_triggers_fallback_upcall(tmp_path):
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(
        Cmd.INIT, ["jobNope", "0", "1", "uda.tpu.RawBytes"]))
    bridge.do_command(form_cmd(Cmd.FETCH,
                               ["h", "jobNope", "attempt_missing", "0"]))
    bridge.do_command(form_cmd(Cmd.FINAL, []))
    assert harness.fetch_over.wait(timeout=30)
    assert harness.failures  # failure_in_uda fired
    assert bridge.failed
    # bridge is inert afterwards (Java fell back to vanilla)
    bridge.do_command(form_cmd(Cmd.FINAL, []))  # no raise, no effect


def test_developer_mode_reraises(tmp_path):
    harness = Harness(str(tmp_path))
    harness.conf["mapred.rdma.developer.mode"] = "true"
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    with pytest.raises(Exception):
        bridge.do_command("garbage-not-a-command")


def test_unexpected_role_command_fails_softly(tmp_path):
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(Cmd.NEW_MAP, []))  # supplier-only cmd
    assert bridge.failed and harness.failures


def test_get_stats_round_trip(tmp_path):
    # GET_STATS is role-independent (like set_log_level) and returns a
    # JSON string from do_command — the on-demand stats pull channel
    import json

    job = "jobGS"
    expected, results = _drive_reduce_with_stats(tmp_path, job)
    assert results  # the merge completed

    # supplier role answers too
    make_mof_tree(str(tmp_path), "jobGS2", 1, 1, 5, seed=17)
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(False, [], harness)
    out = bridge.do_command(form_cmd(Cmd.GET_STATS, []))
    stats = json.loads(out)
    assert "counters" in stats and "gauges" in stats
    bridge.do_command(form_cmd(Cmd.EXIT, []))


def _drive_reduce_with_stats(tmp_path, job):
    """One reduce task; pulls GET_STATS mid-run and asserts the fetch
    counters round-trip."""
    import json

    expected = make_mof_tree(str(tmp_path), job, 3, 1, 20, seed=16)
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, ["-w", "4"], harness)
    bridge.do_command(form_cmd(
        Cmd.INIT, [job, "0", "3", "uda.tpu.RawBytes"]))
    for mid in map_ids(job, 3):
        bridge.do_command(form_cmd(Cmd.FETCH, ["localhost", job, mid, "0"]))
    bridge.do_command(form_cmd(Cmd.FINAL, []))
    assert harness.fetch_over.wait(timeout=30)
    bridge.reduce_exit()
    assert not harness.failures, harness.failures
    out = bridge.do_command(form_cmd(Cmd.GET_STATS, []))
    assert isinstance(out, str)
    stats = json.loads(out)
    assert stats["counters"]["fetch.bytes"] > 0
    assert stats["counters"]["emit.bytes"] > 0
    # non-stats commands still return None
    assert bridge.do_command(form_cmd(Cmd.EXIT, [])) is None
    return expected, {0: list(IFileReader(
        io.BytesIO(b"".join(harness.blocks))))}


def test_log_upcall_sink(tmp_path):
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, ["-t", "6"], harness)
    get_logger().info("hello bridge")
    assert any("hello bridge" in m for _, m in harness.logs)


def test_bridge_malformed_param_falls_back():
    # regression: a ValueError inside a well-formed command must flow
    # through failure_in_uda, not escape the bridge
    failures = []

    class H:
        def failure_in_uda(self, e):
            failures.append(e)

        def get_conf_data(self, n, d):
            return ""

    b = UdaBridge()
    b.start(True, [], H())
    b.do_command(form_cmd(Cmd.INIT, ["job", "not_an_int", "4",
                                     "uda.tpu.RawBytes"]))
    assert failures and b.failed


def test_developer_mode_merge_thread_failure_surfaces(tmp_path):
    # a failure on the BACKGROUND merge thread in developer mode must
    # not die silently in Thread.run: failure_in_uda still wakes
    # waiters, and the stored error re-raises on the next synchronous
    # call (here: reduce_exit)
    harness = Harness(str(tmp_path))
    harness.conf["mapred.rdma.developer.mode"] = "true"
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(
        Cmd.INIT, ["jobDevM", "0", "1", "uda.tpu.RawBytes"]))
    bridge.do_command(form_cmd(Cmd.FETCH,
                               ["h", "jobDevM", "attempt_missing", "0"]))
    bridge.do_command(form_cmd(Cmd.FINAL, []))
    assert harness.fetch_over.wait(timeout=30)  # waiter woke, no hang
    assert harness.failures
    with pytest.raises(Exception):
        bridge.reduce_exit()
    # error was consumed by the re-raise; bridge is clean again
    bridge.reduce_exit()


def test_reinit_stops_previous_engine(tmp_path):
    # a second INIT on the same bridge (new reduce attempt) must tear
    # down the previous task's engine instead of leaking its threads
    make_mof_tree(str(tmp_path), "jobRe", 1, 1, 5)
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(
        Cmd.INIT, ["jobRe", "0", "1", "uda.tpu.RawBytes"]))
    first_engine = bridge._owned_engine
    assert first_engine is not None
    bridge.do_command(form_cmd(
        Cmd.INIT, ["jobRe", "0", "1", "uda.tpu.RawBytes"]))
    assert not harness.failures
    assert bridge._owned_engine is not None
    assert bridge._owned_engine is not first_engine
    from uda_tpu.mofserver import ShuffleRequest
    from uda_tpu.utils.errors import StorageError

    with pytest.raises(StorageError):
        first_engine.fetch(ShuffleRequest("jobRe", "x", 0, 0, 10))
    bridge.reduce_exit()


def _ref_init_params(job, reduce_id, num_maps, key_class="uda.tpu.RawBytes",
                     lpq=0, buf=64 * 1024, min_buf=4096, codec="0",
                     comp_block=0, shuffle_mem=1 << 30, dirs=()):
    """The reference's 10-param INIT layout + num_dirs + dirs
    (reducer.cc:56-133)."""
    return ([str(num_maps), job, str(reduce_id), str(lpq), str(buf),
             str(min_buf), key_class, codec, str(comp_block),
             str(shuffle_mem), str(len(dirs))] + list(dirs))


def test_init_reference_layout_end_to_end(tmp_path):
    # the 10-param INIT must drive a full merge just like the short form
    import functools
    import io as _io

    from uda_tpu.utils.ifile import IFileReader

    job = "jobI10"
    expected = make_mof_tree(str(tmp_path), job, 3, 1, 25, seed=31)
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, ["-w", "8"], harness)
    bridge.do_command(form_cmd(Cmd.INIT, _ref_init_params(
        job, 0, 3, dirs=[str(tmp_path)])))
    for mid in map_ids(job, 3):
        bridge.do_command(form_cmd(Cmd.FETCH, ["localhost", job, mid, "0"]))
    bridge.do_command(form_cmd(Cmd.FINAL, []))
    assert harness.fetch_over.wait(timeout=30)
    bridge.reduce_exit()
    assert not harness.failures, harness.failures
    got = list(IFileReader(_io.BytesIO(b"".join(harness.blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = sorted(expected[0], key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want


def test_init_memory_budget_shrinks_buffer():
    # shuffleMemorySize caps the double-buffered pool: 8 maps -> 13
    # pairs; 1 MiB buffers would need 26 MiB+, only 1 MiB given ->
    # buffer shrinks to mem/(pairs*2), page-aligned
    bridge = UdaBridge()
    bridge.start(True, [], None)
    bridge.do_command(form_cmd(Cmd.INIT, _ref_init_params(
        "jobShrink", 0, 8, buf=1 << 20, min_buf=4096,
        shuffle_mem=13 * 2 * 12288)))
    assert not bridge.failed
    # 12288 -> page-aligned 8192 (12288 % 4096 == 0 -> stays 12288)
    assert bridge.cfg.get("mapred.rdma.buf.size") == 12288 // 1024
    bridge.reduce_exit()


def test_init_memory_budget_violation_falls_back():
    # budget so small the shrunken buffer is under the minimum ->
    # UdaException-equivalent -> fallback (reducer.cc:104-112)
    failures = []

    class FB:
        def failure_in_uda(self, e):
            failures.append(e)

    bridge = UdaBridge()
    bridge.start(True, [], FB())
    bridge.do_command(form_cmd(Cmd.INIT, _ref_init_params(
        "jobOOM", 0, 8, buf=1 << 20, min_buf=64 * 1024,
        shuffle_mem=1 << 20)))
    assert bridge.failed
    assert failures and "Not enough memory" in str(failures[0])


def test_init_tiny_aligned_buffer_falls_back():
    failures = []

    class FB:
        def failure_in_uda(self, e):
            failures.append(e)

    bridge = UdaBridge()
    bridge.start(True, [], FB())
    # 2048B buffer page-aligns to 0 -> "RDMA Buffer is too small"
    bridge.do_command(form_cmd(Cmd.INIT, _ref_init_params(
        "jobTiny", 0, 1, buf=2048, min_buf=1024)))
    assert bridge.failed
    assert failures and "too small" in str(failures[0])


def test_fetch_attempt_dedupe_and_obsolescence(tmp_path):
    # duplicate attempt -> ignored; a NEW attempt for the same map task
    # before FINAL replaces the stale one; after FINAL -> fallback
    # (reference UdaShuffleConsumerPluginShared.java:568-589)
    job = "jobDup"
    make_mof_tree(str(tmp_path), job, 2, 1, 10, seed=33)
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(
        Cmd.INIT, [job, "0", "2", "uda.tpu.RawBytes", str(tmp_path)]))
    a0, a1 = map_ids(job, 2)
    bridge.do_command(form_cmd(Cmd.FETCH, ["h", job, a0, "0"]))
    bridge.do_command(form_cmd(Cmd.FETCH, ["h", job, a0, "0"]))  # dup
    assert bridge._pending_maps == [("h", a0)]
    # speculative re-execution: attempt _1 obsoletes attempt _0
    a1_retry = a1[:-1] + "1"
    bridge.do_command(form_cmd(Cmd.FETCH, ["h", job, a1, "0"]))
    bridge.do_command(form_cmd(Cmd.FETCH, ["h", job, a1_retry, "0"]))
    assert bridge._pending_maps == [("h", a0), ("h", a1_retry)]
    bridge.do_command(form_cmd(Cmd.FINAL, []))
    assert harness.fetch_over.wait(timeout=30)
    # the retried attempt has no MOF on disk -> that failure is expected
    # here; what matters is the pre-FINAL bookkeeping above and the
    # post-FINAL contract below
    harness.failures.clear()
    bridge._failed = False
    bridge.do_command(form_cmd(Cmd.FETCH, ["h", job, a0[:-1] + "9", "0"]))
    assert harness.failures and "after the merge" in str(harness.failures[0])
    bridge.reduce_exit()


def test_init_reference_layout_compressed_job(tmp_path):
    # codec class + block size in INIT params 7/8 switch the client to
    # the decompressing path, with the compressed sub-buffer sized by
    # mapred.rdma.compression.buffer.ratio (calculateMemPool,
    # reducer.cc:453-496)
    import functools
    import io as _io

    from uda_tpu.compress import DecompressingClient, get_codec
    from uda_tpu.mofserver.writer import MOFWriter
    from uda_tpu.utils.ifile import IFileReader

    job = "jobIC"
    codec = get_codec("zlib")
    writer = MOFWriter(str(tmp_path), job, codec=codec)
    rng = __import__("numpy").random.default_rng(41)
    expected = []
    for m in range(2):
        recs = sorted((rng.bytes(10), rng.bytes(40)) for _ in range(60))
        expected += recs
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(Cmd.INIT, _ref_init_params(
        job, 0, 2, codec="zlib", comp_block=4096, dirs=[str(tmp_path)])))
    mm_client = bridge._mm.client
    assert isinstance(mm_client, DecompressingClient)
    buf_bytes = bridge.cfg.get("mapred.rdma.buf.size") * 1024
    ratio = float(bridge.cfg.get("mapred.rdma.compression.buffer.ratio"))
    assert mm_client.comp_chunk_size == int(buf_bytes * ratio)
    for mid in writer.map_ids:
        bridge.do_command(form_cmd(Cmd.FETCH, ["h", job, mid, "0"]))
    bridge.do_command(form_cmd(Cmd.FINAL, []))
    assert harness.fetch_over.wait(timeout=30)
    bridge.reduce_exit()
    assert not harness.failures, harness.failures
    got = list(IFileReader(_io.BytesIO(b"".join(harness.blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = sorted(expected, key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want


def test_short_form_init_with_many_dirs_not_misrouted(tmp_path):
    # a short-form INIT with 6+ local dirs has >= 10 params; the layout
    # discriminator (numeric num_maps/lpq_size) must still route it to
    # the short form instead of failing int(job_id)
    job = "jobDirs"
    make_mof_tree(str(tmp_path), job, 1, 1, 5, seed=51)
    dirs = [str(tmp_path)] + [str(tmp_path / f"d{i}") for i in range(6)]
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(
        Cmd.INIT, [job, "0", "1", "uda.tpu.RawBytes"] + dirs))
    assert not bridge.failed and not harness.failures
    bridge.reduce_exit()


def test_reinit_does_not_leak_compression_config(tmp_path):
    # INIT job A with a codec sets compress=True in the bridge config; a
    # re-INIT for an UNCOMPRESSED job B on the same bridge must get a
    # fresh config — a stale compress flag would wrap B's plain IFile
    # fetches in a DecompressingClient and hang the merge
    import functools
    import io as _io

    from uda_tpu.compress import get_codec
    from uda_tpu.mofserver.writer import MOFWriter
    from uda_tpu.utils.ifile import IFileReader

    jobA, jobB = "jobLeakA", "jobLeakB"
    MOFWriter(str(tmp_path), jobA, codec=get_codec("zlib")).write(
        f"attempt_{jobA}_m_000000_0", [[(b"k" * 10, b"v" * 10)]])
    expected = make_mof_tree(str(tmp_path), jobB, 2, 1, 20, seed=61)
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(Cmd.INIT, _ref_init_params(
        jobA, 0, 1, codec="zlib", dirs=[str(tmp_path)])))
    assert bridge.cfg.get("mapred.compress.map.output")
    # re-INIT (uncompressed job B, codec="0")
    bridge.do_command(form_cmd(Cmd.INIT, _ref_init_params(
        jobB, 0, 2, codec="0", dirs=[str(tmp_path)])))
    assert not bridge.cfg.get("mapred.compress.map.output")
    for mid in map_ids(jobB, 2):
        bridge.do_command(form_cmd(Cmd.FETCH, ["h", jobB, mid, "0"]))
    bridge.do_command(form_cmd(Cmd.FINAL, []))
    assert harness.fetch_over.wait(timeout=30)
    bridge.reduce_exit()
    assert not harness.failures, harness.failures
    got = list(IFileReader(_io.BytesIO(b"".join(harness.blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = sorted(expected[0], key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want
