"""Bridge surface: command protocol, role dispatch, up-calls, fallback
(reference src/UdaBridge.cc, src/CommUtils/C2JNexus.cc)."""

import functools
import io
import threading

import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.bridge import Cmd, UdaBridge, form_cmd, parse_cmd
from uda_tpu.mofserver import DirIndexResolver
from uda_tpu.utils import comparators
from uda_tpu.utils.errors import ProtocolError
from uda_tpu.utils.ifile import IFileReader
from uda_tpu.utils.logging import get_logger


def teardown_function(_fn):
    get_logger().set_sink(None)


def test_protocol_round_trip():
    cmd = form_cmd(Cmd.FETCH, ["host1", "job_1", "attempt_x", "3"])
    assert cmd == "4:4:host1:job_1:attempt_x:3"
    header, params = parse_cmd(cmd)
    assert header == Cmd.FETCH
    assert params == ["host1", "job_1", "attempt_x", "3"]
    assert parse_cmd("0:2")[0] == Cmd.FINAL


def test_protocol_errors():
    with pytest.raises(ProtocolError):
        parse_cmd("nonsense")
    with pytest.raises(ProtocolError):
        parse_cmd("2:4:only_one")        # count mismatch
    with pytest.raises(ProtocolError):
        parse_cmd("0:99")                # unknown header
    with pytest.raises(ProtocolError):
        form_cmd(Cmd.INIT, ["has:colon"])


class Harness:
    """Embedder double: collects up-calls like UdaPluginRT would."""

    def __init__(self, root):
        self.root = root
        self.blocks = []
        self.fetch_over = threading.Event()
        self.failures = []
        self.conf = {}
        self.logs = []
        self._resolver = DirIndexResolver(root)

    def data_from_uda(self, data, length):
        self.blocks.append(bytes(data[:length]))

    def fetch_over_message(self):
        self.fetch_over.set()

    def get_path_uda(self, job_id, map_id, reduce_id):
        return self._resolver.resolve(job_id, map_id, reduce_id)

    def get_conf_data(self, name, default):
        return self.conf.get(name, "")

    def log_to(self, level, message):
        self.logs.append((level, message))

    def failure_in_uda(self, error):
        self.failures.append(error)
        self.fetch_over.set()


def _drive_reduce(tmp_path, job, num_maps=4, reducers=2, init_extra=None):
    expected = make_mof_tree(str(tmp_path), job, num_maps, reducers, 40,
                             seed=13)
    results = {}
    for r in range(reducers):
        harness = Harness(str(tmp_path))
        bridge = UdaBridge()
        bridge.start(True, ["-w", "8", "-s", "64"], harness)
        bridge.do_command(form_cmd(
            Cmd.INIT, [job, str(r), str(num_maps), "uda.tpu.RawBytes"]
            + (init_extra or [])))
        for mid in map_ids(job, num_maps):
            bridge.do_command(form_cmd(Cmd.FETCH, ["localhost", job, mid, str(r)]))
        bridge.do_command(form_cmd(Cmd.FINAL, []))
        assert harness.fetch_over.wait(timeout=30)
        bridge.reduce_exit()
        assert not harness.failures, harness.failures
        results[r] = list(IFileReader(io.BytesIO(b"".join(harness.blocks))))
    return expected, results


def test_reduce_role_end_to_end_via_upcall_resolution(tmp_path):
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    expected, results = _drive_reduce(tmp_path, "jobB1")
    for r, got in results.items():
        want = sorted(expected[r], key=functools.cmp_to_key(
            lambda a, b: kt.compare(a[0], b[0])))
        assert [k for k, _ in got] == [k for k, _ in want]


def test_reduce_role_with_local_dirs_param(tmp_path):
    # INIT's trailing params are local dirs -> DirIndexResolver path
    expected, results = _drive_reduce(tmp_path, "jobB2",
                                      init_extra=[str(tmp_path).replace(":", "")])
    assert sum(len(v) for v in results.values()) == sum(
        len(v) for v in expected.values())


def test_supplier_role_serves_and_exits(tmp_path):
    make_mof_tree(str(tmp_path), "jobB3", 2, 1, 10, seed=14)
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(False, ["-w", "8"], harness)
    from uda_tpu.mofserver import ShuffleRequest

    engine = bridge.data_engine()
    res = engine.fetch(ShuffleRequest("jobB3", map_ids("jobB3", 2)[0], 0,
                                      0, 1 << 20))
    assert res.is_last and len(res.data) > 0
    bridge.do_command(form_cmd(Cmd.JOB_OVER, ["jobB3"]))
    bridge.do_command(form_cmd(Cmd.EXIT, []))


def test_failure_triggers_fallback_upcall(tmp_path):
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(
        Cmd.INIT, ["jobNope", "0", "1", "uda.tpu.RawBytes"]))
    bridge.do_command(form_cmd(Cmd.FETCH,
                               ["h", "jobNope", "attempt_missing", "0"]))
    bridge.do_command(form_cmd(Cmd.FINAL, []))
    assert harness.fetch_over.wait(timeout=30)
    assert harness.failures  # failure_in_uda fired
    assert bridge.failed
    # bridge is inert afterwards (Java fell back to vanilla)
    bridge.do_command(form_cmd(Cmd.FINAL, []))  # no raise, no effect


def test_developer_mode_reraises(tmp_path):
    harness = Harness(str(tmp_path))
    harness.conf["mapred.rdma.developer.mode"] = "true"
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    with pytest.raises(Exception):
        bridge.do_command("garbage-not-a-command")


def test_unexpected_role_command_fails_softly(tmp_path):
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(Cmd.NEW_MAP, []))  # supplier-only cmd
    assert bridge.failed and harness.failures


def test_log_upcall_sink(tmp_path):
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, ["-t", "6"], harness)
    get_logger().info("hello bridge")
    assert any("hello bridge" in m for _, m in harness.logs)


def test_bridge_malformed_param_falls_back():
    # regression: a ValueError inside a well-formed command must flow
    # through failure_in_uda, not escape the bridge
    failures = []

    class H:
        def failure_in_uda(self, e):
            failures.append(e)

        def get_conf_data(self, n, d):
            return ""

    b = UdaBridge()
    b.start(True, [], H())
    b.do_command(form_cmd(Cmd.INIT, ["job", "not_an_int", "4",
                                     "uda.tpu.RawBytes"]))
    assert failures and b.failed


def test_developer_mode_merge_thread_failure_surfaces(tmp_path):
    # a failure on the BACKGROUND merge thread in developer mode must
    # not die silently in Thread.run: failure_in_uda still wakes
    # waiters, and the stored error re-raises on the next synchronous
    # call (here: reduce_exit)
    harness = Harness(str(tmp_path))
    harness.conf["mapred.rdma.developer.mode"] = "true"
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(
        Cmd.INIT, ["jobDevM", "0", "1", "uda.tpu.RawBytes"]))
    bridge.do_command(form_cmd(Cmd.FETCH,
                               ["h", "jobDevM", "attempt_missing", "0"]))
    bridge.do_command(form_cmd(Cmd.FINAL, []))
    assert harness.fetch_over.wait(timeout=30)  # waiter woke, no hang
    assert harness.failures
    with pytest.raises(Exception):
        bridge.reduce_exit()
    # error was consumed by the re-raise; bridge is clean again
    bridge.reduce_exit()


def test_reinit_stops_previous_engine(tmp_path):
    # a second INIT on the same bridge (new reduce attempt) must tear
    # down the previous task's engine instead of leaking its threads
    make_mof_tree(str(tmp_path), "jobRe", 1, 1, 5)
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, [], harness)
    bridge.do_command(form_cmd(
        Cmd.INIT, ["jobRe", "0", "1", "uda.tpu.RawBytes"]))
    first_engine = bridge._owned_engine
    assert first_engine is not None
    bridge.do_command(form_cmd(
        Cmd.INIT, ["jobRe", "0", "1", "uda.tpu.RawBytes"]))
    assert not harness.failures
    assert bridge._owned_engine is not None
    assert bridge._owned_engine is not first_engine
    from uda_tpu.mofserver import ShuffleRequest
    from uda_tpu.utils.errors import StorageError

    with pytest.raises(StorageError):
        first_engine.fetch(ShuffleRequest("jobRe", "x", 0, 0, 10))
    bridge.reduce_exit()
