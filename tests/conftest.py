"""Test environment: run all JAX work on a virtual 8-device CPU mesh so
multi-chip sharding logic is exercised without a TPU pod (SURVEY §4's
"implication": the reference had no multi-node-without-a-cluster story;
we fix that here). Must run before jax is first imported."""

import os

# force CPU even when the ambient environment selects the axon TPU
# backend (JAX_PLATFORMS=axon): unit tests exercise sharding on 8
# virtual devices, not the single real chip. The axon sitecustomize
# imports jax at interpreter startup, so setting env vars here is too
# late for the env-var path — update jax.config post-import instead
# (backends are created lazily, so this still wins as long as no array
# has touched a device yet).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# exercise the cache wiring the TPU entry points rely on (a no-op on
# CPU unless UDA_TPU_COMPILE_CACHE is set — see compile_cache.enable)
from uda_tpu.utils import compile_cache  # noqa: E402

compile_cache.enable()
