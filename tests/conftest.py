"""Test environment: run all JAX work on a virtual 8-device CPU mesh so
multi-chip sharding logic is exercised without a TPU pod (SURVEY §4's
"implication": the reference had no multi-node-without-a-cluster story;
we fix that here). Must run before jax is first imported."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
