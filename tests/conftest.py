"""Test environment: run all JAX work on a virtual 8-device CPU mesh so
multi-chip sharding logic is exercised without a TPU pod (SURVEY §4's
"implication": the reference had no multi-node-without-a-cluster story;
we fix that here). Must run before jax is first imported."""

import os
import sys

# Hermetic suite: when the ambient environment carries the accelerator
# pool (PALLAS_AXON_POOL_IPS), the axon sitecustomize has ALREADY — at
# interpreter startup, before this file — loaded the axon PJRT plugin
# into this process and dialed the pool's relay. Unit tests must never
# depend on (or be taken down by) that machinery: with the pool wedged,
# plugin threads in the test process correlated with an unexplained
# suite-order-dependent SIGSEGV inside a late XLA CPU compile
# (2026-07-31, see test_graft_entry_contract's docstring), and every
# test-spawned python subprocess hung at startup inside register()'s
# bind loop. The plugin cannot be unloaded, so re-exec pytest ONCE with
# the pool env stripped; children (multihost workers, the graft-entry
# contract subprocess) then inherit a pool-free environment too. Bench
# and the hardware scripts keep the ambient env — only the test runner
# re-execs.
#
# Only CLI invocations (`pytest ...` / `python -m pytest ...`) are
# rebuilt from sys.argv — a programmatic pytest.main([...]) caller's
# argv is its own, not pytest's, so re-exec'ing from it would run the
# wrong thing; such callers keep the ambient process (and own its
# hygiene). The CLI check must look at the FULL argv[0] path: under
# `python -m pytest` it is `<site-packages>/pytest/__main__.py`, whose
# basename carries no "pytest". The exec itself happens in
# pytest_configure, NOT at module import: global capture has already
# dup2'ed fd1/fd2 into pytest's temp files by the time any conftest
# loads, so the fds must be restored through the capture manager first
# or the exec'ed runner's output silently vanishes.
_ARGV0 = sys.argv[0] or ""
_REEXEC = (bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
           and os.environ.get("UDA_TPU_TESTS_REEXECED") != "1"
           and ("pytest" in _ARGV0 or "py.test" in _ARGV0))


def pytest_configure(config):
    if _REEXEC:
        # restore the shell's real stdio first: pytest's global capture
        # has already dup2'ed fd1/fd2 into its own temp files, and the
        # exec'ed runner would inherit those (all output silently gone)
        capman = config.pluginmanager.getplugin("capturemanager")
        if capman is not None:
            capman.stop_global_capturing()
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["UDA_TPU_TESTS_REEXECED"] = "1"
        os.execve(sys.executable,
                  [sys.executable, "-m", "pytest"] + sys.argv[1:], env)


# The doomed pre-exec process skips the jax/platform setup below — it
# exists only long enough to reach pytest_configure.
if not _REEXEC:
    # force CPU even when the ambient environment selects the axon TPU
    # backend (JAX_PLATFORMS=axon): unit tests exercise sharding on 8
    # virtual devices, not the single real chip. The axon sitecustomize
    # imports jax at interpreter startup, so setting env vars here is
    # too late for the env-var path — update jax.config post-import
    # instead (backends are created lazily, so this still wins as long
    # as no array has touched a device yet).
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_ENABLE_X64", "0")

    import jax

    jax.config.update("jax_platforms", "cpu")

    # exercise the cache wiring the TPU entry points rely on (a no-op
    # on CPU unless UDA_TPU_COMPILE_CACHE is set — see
    # compile_cache.enable)
    from uda_tpu.utils import compile_cache

    compile_cache.enable()


# -- metrics hygiene + chaos telemetry ---------------------------------------
# Every test ends with a pristine global Metrics (reset() also restores
# span/histogram enablement to the env default, so a test that called
# enable_spans() cannot leak recording into the next test). The
# per-test snapshots accumulate into a session-level counter sum that
# pytest_sessionfinish dumps as a telemetry JSON when
# UDA_TPU_CHAOS_TELEMETRY names a path (scripts/run_chaos.sh does),
# giving chaos runs the same comparable telemetry block bench.py emits.

import collections  # noqa: E402

import pytest  # noqa: E402

_SESSION_COUNTERS: dict = collections.defaultdict(float)


@pytest.fixture(autouse=True)
def _failpoint_phase_reset():
    """Each test sees the ambient chaos schedule (UDA_FAILPOINTS, the
    run_chaos.sh rungs) from phase 0: trigger counters and seeded
    probability draws restart per test via the documented
    disarm-then-rearm idiom. Without this, whether an `every:N` error
    hits a given test depends on how many failpoint evaluations every
    EARLIER test consumed — suite composition becomes schedule phase
    (the PR 9 "suite doubling shifted failpoint phase" class), and a
    chaos-rung failure does not even reproduce standalone. Tests that
    arm their own scoped() schedules are unaffected (the scope saves
    and restores around this)."""
    from uda_tpu.utils.failpoints import failpoints

    for site, spec in failpoints.active().items():
        failpoints.disarm(site)
        failpoints.arm(site, spec)
    yield


@pytest.fixture(autouse=True)
def _metrics_hygiene():
    yield
    from uda_tpu.utils.metrics import metrics
    from uda_tpu.utils.resledger import PAIRED_GAUGES, resledger

    # paired-gauge balance: every +N on the increment-must-meet-
    # decrement set (fetch.on_air, stage.inflight.bytes, ...) must have
    # met its -N by test end — metrics.reset() starts each test at
    # zero, so a nonzero here is THIS test's leak, reported at the
    # leaking test instead of silently polluting a later assertion
    unbalanced = {
        name: val
        for name, val in metrics.gauges_snapshot().items()
        if name in PAIRED_GAUGES and abs(val) > 1e-9
    }
    # runtime obligation books (armed runs only, e.g. the chaos rungs
    # under UDA_TPU_RESLEDGER=1): anything still open is a leak —
    # drain() reports each with its acquire stack, counts
    # resledger.leaks and appends to UDA_TPU_RESLEDGER_JSON, and the
    # pop guarantees the NEXT test starts with empty books
    leaked = resledger.drain("test.teardown")
    for name, value in metrics.snapshot().items():
        _SESSION_COUNTERS[name] += value
    metrics.reset()
    # flight-recorder hygiene: events/dump bookkeeping are per-test
    # (the ring is process-global and always on), and a test that
    # configured a dump directory must not leak it into later tests'
    # dumps
    from uda_tpu.utils.flightrec import flightrec
    flightrec.reset()
    flightrec._dump_dir = ""
    # profiler hygiene: a test that armed the global sampling profiler
    # must not keep its daemon thread (and the thread-span registry
    # writes it enables) running into later tests' timing assertions
    from uda_tpu.utils.profiler import profiler
    profiler.stop()
    profiler.reset()
    # observability-plane hygiene: a test that armed the rollup ring
    # (and with it the anomaly detectors, SLI book, or OpenMetrics
    # endpoint) must not keep its sampler thread, listeners, or HTTP
    # port alive into later tests
    from uda_tpu.utils.timeseries import disarm_observability_plane
    disarm_observability_plane()
    if unbalanced or leaked:
        parts = []
        if unbalanced:
            parts.append(f"paired gauges not back to zero: {unbalanced}")
        if leaked:
            opened = ", ".join(sorted({r["pair"] for r in leaked}))
            parts.append(f"{len(leaked)} leaked resledger obligation(s) "
                         f"({opened}) — acquire stacks in the log")
        pytest.fail("resource-balance teardown: " + "; ".join(parts))


def pytest_sessionfinish(session, exitstatus):
    path = os.environ.get("UDA_TPU_CHAOS_TELEMETRY")
    if not path or _REEXEC:
        return
    import json

    with open(path, "w") as f:
        json.dump({"counters": dict(sorted(_SESSION_COUNTERS.items()))},
                  f, indent=1, sort_keys=True)
