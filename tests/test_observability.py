"""Cluster-scope observability tier-1 coverage (ISSUE 11).

Four layers:

1. wire plumbing: the optional REQ/SIZE_REQ trace-context tail
   (length-versioned, old shapes decode), HELLO capability bits old
   decoders ignore, MSG_STATS/MSG_STATS_REPLY frames, and the typed-ERR
   refusal of unknown frame types (no disconnects);
2. cross-process trace correlation end to end: a real
   server<->client shuffle whose supplier-side ``net.serve`` /
   ``engine.pread`` spans carry the reduce task's trace id with correct
   parentage, stitched into one Chrome trace by
   ``scripts/trace_merge.py``;
3. the live introspection plane: ``MSG_STATS`` round-trips live
   counters/gauges/percentiles, ResourceLedger obligations and the
   server conn table (the ``scripts/udatop.py`` scrape surface);
4. the flight recorder: ring bounds, dump contents, and the
   faults-marked guarantee that a forced FallbackSignal produces
   exactly ONE black-box dump containing the injected failpoint event
   and the terminal cause.
"""

from __future__ import annotations

import json
import socket
import subprocess
import sys
import threading
import time

import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.merger import HostRoutingClient, LocalFetchClient, MergeManager
from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.mofserver.data_engine import ShuffleRequest
from uda_tpu.net import ShuffleServer, wire
from uda_tpu.net.client import RemoteFetchClient, fetch_remote_stats
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import (FallbackSignal, ProtocolError,
                                  StorageError, TransportError)
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.flightrec import FlightRecorder, flightrec
from uda_tpu.utils.metrics import SPAN_REGISTRY, metrics
from uda_tpu.utils.stats import (StatsReporter, introspection_snapshot,
                                 register_stats_provider,
                                 unregister_stats_provider)

REPO = __file__.rsplit("/tests/", 1)[0]
JOB = "jobObs"


# -- wire: trace context + HELLO caps + stats frames -------------------------


def test_request_trace_tail_roundtrip():
    req = ShuffleRequest(JOB, "m_0", 3, 4096, 1 << 20)
    plain = wire.encode_request(7, req)
    traced = wire.encode_request(7, req, trace=(0xABCDEF0012345678, 42))
    assert len(traced) == len(plain) + 16
    for frame, want in ((plain, None),
                        (traced, (0xABCDEF0012345678, 42))):
        msg_type, req_id, length = wire.decode_header(
            frame[:wire.HEADER.size])
        assert (msg_type, req_id) == (wire.MSG_REQ, 7)
        got, trace = wire.decode_request_ex(frame[wire.HEADER.size:])
        assert got == req
        assert trace == want
    # the old decode surface is oblivious to the tail
    assert wire.decode_request(traced[wire.HEADER.size:]) == req


def test_size_request_trace_tail_roundtrip():
    plain = wire.encode_size_request(9, JOB, ["a", "b"], 1)
    traced = wire.encode_size_request(9, JOB, ["a", "b"], 1,
                                      trace=(5, 6))
    body, trace = wire.decode_size_request_ex(traced[wire.HEADER.size:])
    assert body == (JOB, ["a", "b"], 1) and trace == (5, 6)
    assert wire.decode_size_request(plain[wire.HEADER.size:]) == \
        (JOB, ["a", "b"], 1)


def test_trace_tail_wrong_length_is_torn_frame():
    req = ShuffleRequest(JOB, "m_0", 0, 0, 64)
    payload = wire.encode_request(1, req)[wire.HEADER.size:] + b"junk"
    with pytest.raises(TransportError, match="trailing"):
        wire.decode_request_ex(payload)


def test_hello_caps_bit_and_old_decoder_ignores_it():
    frame = wire.encode_hello(17, True)  # caps default CAP_TRACE
    payload = frame[wire.HEADER.size:]
    # the old (PR 8) decode surface: generation + warm only — the
    # capability bit must be invisible to it (same struct size)
    assert wire.decode_hello(payload) == (17, True)
    gen, warm, caps = wire.decode_hello_ex(payload)
    assert (gen, warm) == (17, True) and caps & wire.CAP_TRACE
    # a capability-less banner (old server shape)
    old = wire.encode_hello(3, False, caps=0)[wire.HEADER.size:]
    assert wire.decode_hello_ex(old)[2] & wire.CAP_TRACE == 0


def test_stats_frames_roundtrip():
    snap = {"counters": {"net.requests": 4}, "nested": {"p95": 1.5}}
    frame = wire.encode_stats_reply(11, snap)
    msg_type, req_id, _ = wire.decode_header(frame[:wire.HEADER.size])
    assert (msg_type, req_id) == (wire.MSG_STATS_REPLY, 11)
    assert wire.decode_stats_reply(frame[wire.HEADER.size:]) == snap
    req = wire.encode_stats_request(11)
    assert wire.decode_header(req[:wire.HEADER.size])[0] == wire.MSG_STATS


def test_unknown_type_in_reserved_range_passes_header():
    frame = wire.encode_frame(25, 1, b"")
    assert wire.decode_header(frame[:wire.HEADER.size])[0] == 25
    with pytest.raises(TransportError, match="unknown frame type"):
        wire.decode_header(wire.encode_frame(200, 1,
                                             b"")[:wire.HEADER.size])


# -- the live server plane ---------------------------------------------------


@pytest.fixture
def supplier(tmp_path):
    expected = make_mof_tree(str(tmp_path), JOB, num_maps=3,
                             num_reducers=1, records_per_map=40, seed=11)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    yield expected, server
    server.stop()
    engine.stop()


def _fetch_sync(client, req, timeout=10.0):
    box, done = [], threading.Event()
    client.start_fetch(req, lambda res: (box.append(res), done.set()))
    assert done.wait(timeout), "fetch never completed"
    return box[0]


def test_msg_stats_roundtrip_returns_live_state(supplier):
    """The acceptance criterion: MSG_STATS against a supplier that has
    served traffic returns live counters/gauges/percentiles, the
    ResourceLedger summary and the conn table."""
    _, server = supplier
    metrics.enable_stats()  # histograms -> percentiles populated
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    try:
        for mid in map_ids(JOB, 3):
            res = _fetch_sync(client,
                              ShuffleRequest(JOB, mid, 0, 0, 1 << 20))
            assert not isinstance(res, Exception)
        # poll over the wire WHILE the fetch connection is still open:
        # the conn table must show it
        snap = fetch_remote_stats("127.0.0.1", server.port)
    finally:
        client.stop()
    assert snap["counters"]["net.requests"] >= 3
    assert snap["counters"]["supplier.bytes"] > 0
    assert "percentiles" in snap
    p = snap["percentiles"].get("supplier.read.latency_ms")
    if p is not None:  # zero-copy plans may skip the pool histogram
        assert p["p95"] >= 0
    led = snap["resledger"]
    assert {"armed", "outstanding", "by_pair",
            "leak_reports"} <= set(led)
    srv = snap["providers"]["net.server"]
    assert srv["generation"] == server.generation
    assert any(c["peer"] for c in srv["connections"])
    assert srv["loop"]["alive"]
    # the in-process multiplexed surface answers too
    client2 = RemoteFetchClient("127.0.0.1", server.port, Config())
    try:
        snap2 = client2.fetch_stats(timeout=10.0)
    finally:
        client2.stop()
    assert snap2 is not None and snap2["counters"]["net.stats.requests"] >= 1


def test_unknown_msg_type_gets_typed_err_without_disconnect(supplier):
    """A frame type the server does not handle is refused with a typed
    ERR on the same req id and the connection keeps working — the
    forward-compat acceptance criterion."""
    _, server = supplier
    sock = socket.create_connection(("127.0.0.1", server.port),
                                    timeout=10.0)
    try:
        sock.settimeout(10.0)
        msg_type, _, _ = wire.recv_frame(sock)  # the HELLO banner
        assert msg_type == wire.MSG_HELLO
        sock.sendall(wire.encode_frame(25, 77, b""))
        msg_type, req_id, payload = wire.recv_frame(sock)
        assert (msg_type, req_id) == (wire.MSG_ERR, 77)
        err = wire.decode_error(payload)
        assert isinstance(err, ProtocolError)
        # same connection still serves: a stats poll round-trips
        sock.sendall(wire.encode_stats_request(78))
        msg_type, req_id, payload = wire.recv_frame(sock)
        assert (msg_type, req_id) == (wire.MSG_STATS_REPLY, 78)
        assert "counters" in wire.decode_stats_reply(payload)
    finally:
        wire.close_hard(sock)


def test_old_peer_request_without_trace_fields_serves(supplier):
    """An old-version client (no trace tail, ignores the caps bit) must
    interoperate: a hand-rolled pre-observability REQ gets its DATA."""
    _, server = supplier
    sock = socket.create_connection(("127.0.0.1", server.port),
                                    timeout=10.0)
    try:
        sock.settimeout(10.0)
        assert wire.recv_frame(sock)[0] == wire.MSG_HELLO
        req = ShuffleRequest(JOB, map_ids(JOB, 1)[0], 0, 0, 1 << 20)
        sock.sendall(wire.encode_request(5, req))  # no trace kwarg
        msg_type, req_id, payload = wire.recv_frame(sock)
        assert (msg_type, req_id) == (wire.MSG_DATA, 5)
        assert wire.decode_result(payload).is_last
    finally:
        wire.close_hard(sock)


def test_udatop_once_renders_live_supplier(supplier):
    """The console script end to end: one --once --json sample against
    a live supplier parses and carries the snapshot."""
    _, server = supplier
    out = subprocess.run(
        [sys.executable, f"{REPO}/scripts/udatop.py",
         f"127.0.0.1:{server.port}", "--once", "--json"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    snap = json.loads(out.stdout.strip().splitlines()[-1])
    assert snap[f"127.0.0.1:{server.port}"]["counters"] is not None


# -- cross-process trace correlation (the tentpole e2e) ----------------------


def test_serve_spans_carry_reduce_trace_id_and_merge(tmp_path):
    """Two-bridge-shaped loopback e2e (the test_net pattern): a full
    MergeManager shuffle over RemoteFetchClient with spans on. The
    supplier-side ``net.serve`` spans must share the reduce task's
    trace id and parent under the reduce-side ``net.fetch`` spans
    (wire-carried trace context), ``engine.pread`` must hang under the
    serve spans, and ``scripts/trace_merge.py`` must stitch the
    \"two processes'\" span files into one valid Chrome trace."""
    mof = tmp_path / "mof"
    mof.mkdir()
    make_mof_tree(str(mof), JOB, num_maps=3, num_reducers=1,
                  records_per_map=50, seed=5)
    metrics.enable_spans()
    engine = DataEngine(DirIndexResolver(str(mof)), Config())
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    try:
        router = HostRoutingClient(config=Config())
        mm = MergeManager(router, "uda.tpu.RawBytes", Config())
        blocks: list[bytes] = []
        maps = [(f"127.0.0.1:{server.port}", m)
                for m in map_ids(JOB, 3)]
        mm.run(JOB, maps, 0, lambda b: blocks.append(bytes(b)))
        router.stop()
    finally:
        server.stop()
        engine.stop()
    assert blocks
    spans = list(metrics.spans)
    roots = [s for s in spans if s["name"] == "reduce_task"]
    assert len(roots) == 1
    trace = roots[0]["trace"]
    fetch_ids = {s["id"] for s in spans if s["name"] == "net.fetch"}
    serves = [s for s in spans if s["name"] == "net.serve"]
    # >= 1 supplier-side serve span in the reduce task's trace, with
    # correct parentage under a reduce-side net.fetch span
    assert any(s["trace"] == trace and s["parent"] in fetch_ids
               for s in serves), \
        f"no wire-stitched serve span (serves={len(serves)})"
    serve_ids = {s["id"] for s in serves}
    preads = [s for s in spans if s["name"] == "engine.pread"]
    assert any(s["trace"] == trace and s["parent"] in serve_ids
               for s in preads), "engine.pread not under net.serve"
    # every explicit span name this run produced is declared (the
    # UDA009 contract, observed live)
    assert {"reduce_task", "net.fetch", "net.serve",
            "engine.pread"} <= SPAN_REGISTRY.keys() & \
        {s["name"] for s in spans}

    # -- trace_merge over simulated per-process files --------------------
    all_jsonl = tmp_path / "all.jsonl"
    n = metrics.export_spans_jsonl(str(all_jsonl))
    assert n == len(spans)
    supplier_names = {"net.serve", "engine.pread", "supplier_read"}
    reducer_f = tmp_path / "reducer.jsonl"
    supplier_f = tmp_path / "supplier.jsonl"
    with open(all_jsonl) as f, open(reducer_f, "w") as rf, \
            open(supplier_f, "w") as sf:
        for line in f:
            rec = json.loads(line)
            if rec["name"] in supplier_names:
                rec["pid"] += 1  # the supplier "process"
                sf.write(json.dumps(rec) + "\n")
            else:
                rf.write(json.dumps(rec) + "\n")
    merged = tmp_path / "merged.json"
    out = subprocess.run(
        [sys.executable, f"{REPO}/scripts/trace_merge.py",
         str(reducer_f), str(supplier_f), "--out", str(merged),
         "--require-cross-process"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr + out.stdout
    trace_json = json.loads(merged.read_text())
    events = trace_json["traceEvents"]
    assert events and all(e["ph"] in ("X", "M") for e in events)
    stitched = [e for e in events
                if e.get("args", {}).get("cross_process_parent")]
    assert stitched, "merged trace lost the cross-process links"


def test_shard_streams_adopt_owning_fetch_span():
    """Satellite: coding/recovery.py shard streams issue from transport
    completion threads — every start_fetch (the chained candidates
    included) must run under the owning fetch span so transport spans
    join the trace tree instead of starting parentless roots."""
    from uda_tpu.coding import parse_scheme
    from uda_tpu.coding.recovery import StripeContext, start_recovery

    metrics.enable_spans()
    scheme = parse_scheme("rs:2:3")
    ctx = StripeContext(scheme, ["h1", "h2", "h3"])
    seen = []
    done = threading.Event()

    class FailingClient:
        def start_fetch(self, req, on_complete):
            seen.append(metrics.current_span())
            threading.Thread(target=on_complete,
                             args=(TransportError("shard down"),),
                             daemon=True).start()

    root = metrics.start_span("fetch.segment", map="m_0")
    with metrics.use_span(root):
        start_recovery(FailingClient(),
                       ShuffleRequest(JOB, "m_0", 0, 0, 1024, host="h1"),
                       ctx, lambda res: done.set())
    assert done.wait(5.0), "reconstruction never finished"
    root.end()
    assert len(seen) == 3  # every candidate was tried
    assert all(s is root for s in seen), \
        "a chained shard issue lost the owning fetch span"


# -- flight recorder ---------------------------------------------------------


def test_flightrec_ring_is_bounded_and_ordered():
    fr = FlightRecorder(capacity=16, enabled=True)
    for i in range(40):
        fr.record("tick", i=i)
    evs = fr.events()
    assert len(evs) == 16
    assert [e["i"] for e in evs] == list(range(24, 40))  # newest kept


def test_flightrec_disabled_is_noop(tmp_path):
    fr = FlightRecorder(enabled=False, dump_dir=str(tmp_path))
    fr.record("tick")
    assert fr.events() == [] and fr.dump("x") is None
    assert not list(tmp_path.iterdir())


def test_flightrec_dump_file_contents(tmp_path):
    fr = FlightRecorder(capacity=64, enabled=True,
                        dump_dir=str(tmp_path / "fr"))
    fr.record("segment.start", map="m_1")
    fr.record("failpoint", site="data_engine.pread", action="error")
    path = fr.dump("unit_test", extra={"why": "coverage"})
    assert path is not None
    rep = json.loads(open(path).read())
    assert rep["cause"] == "unit_test" and rep["extra"]["why"] == "coverage"
    kinds = [e["kind"] for e in rep["events"]]
    assert kinds == ["segment.start", "failpoint"]
    assert fr.dump_paths == [path] and len(fr.reports) == 1
    # no dir configured -> in-memory report only
    fr2 = FlightRecorder(enabled=True)
    fr2.record("tick")
    assert fr2.dump("mem_only") is None and len(fr2.reports) == 1


@pytest.mark.faults
def test_fallback_produces_exactly_one_dump_with_injected_fault(tmp_path):
    """Acceptance: a forced FallbackSignal dumps the black box exactly
    once, and the dump's event stream contains the injected failpoint
    event and the terminal cause."""
    mof = tmp_path / "mof"
    mof.mkdir()
    make_mof_tree(str(mof), JOB, num_maps=2, num_reducers=1,
                  records_per_map=20, seed=2)
    frdir = tmp_path / "fr"
    engine = DataEngine(DirIndexResolver(str(mof)), Config())
    cfg = Config({"uda.tpu.fetch.retries": 0,
                  "uda.tpu.flightrec.dir": str(frdir)})
    try:
        with failpoints.scoped("data_engine.pread=error"):
            mm = MergeManager(LocalFetchClient(engine),
                              "uda.tpu.RawBytes", cfg)
            with pytest.raises(FallbackSignal) as ei:
                mm.run(JOB, map_ids(JOB, 2), 0, lambda b: None)
        assert isinstance(ei.value.cause, StorageError)
    finally:
        engine.stop()
    dumps = sorted(frdir.glob("flightrec_*_fallback.json"))
    assert len(dumps) == 1, [p.name for p in dumps]
    rep = json.loads(dumps[0].read_text())
    assert rep["cause"] == "fallback"
    assert rep["extra"]["error"] == "StorageError"
    fired = [e for e in rep["events"] if e["kind"] == "failpoint"]
    assert fired and fired[0]["site"] == "data_engine.pread"
    # the terminal segment transition is in the stream too
    assert any(e["kind"] == "segment.done" and e["error"]
               for e in rep["events"])


# -- stats reporter satellites -----------------------------------------------


def test_reporter_percentiles_every_record_and_final_blocks():
    metrics.enable_stats()
    metrics.observe("fetch.latency_ms", 10.0)
    metrics.observe("fetch.latency_ms", 100.0)
    clock = [100.0]
    rep = StatsReporter(interval_s=1.0, out=open("/dev/null", "w"),
                        clock=lambda: clock[0])
    record = rep.report_once()
    p = record["percentiles"]["fetch.latency_ms"]
    assert set(p) == {"p50", "p95", "p99"} and p["p95"] >= p["p50"] > 0

    def provider():
        return {"penalty_box": {"boxed": ["h2"]},
                "ledger": {"counts": {"fault": 3}}}

    register_stats_provider("recovery.r7", provider)
    try:
        clock[0] = 101.0
        final = rep.report_once(final=True)
    finally:
        unregister_stats_provider("recovery.r7")
    assert final["recovery"]["recovery.r7"]["penalty_box"]["boxed"] == \
        ["h2"]
    assert "resledger" in final and "outstanding" in final["resledger"]
    assert "percentiles" in final


def test_introspection_snapshot_degrades_broken_provider():
    def broken():
        raise RuntimeError("component torn down")

    register_stats_provider("bad.provider", broken)
    try:
        snap = introspection_snapshot()
    finally:
        unregister_stats_provider("bad.provider")
    assert snap["providers"]["bad.provider"] == {"error": "RuntimeError"}
    assert {"counters", "gauges", "percentiles", "resledger",
            "pid"} <= set(snap)
