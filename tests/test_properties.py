"""Property-based tests for the L0 byte-level contracts.

Everything above L0 (device sorts, exchange, bridge, JVM) assumes these
byte formats are exact; property testing sweeps the corners example
tests miss (the reference had NO unit tests at all for its VInt/IFile
code, SURVEY §4 — "we must do better" was the stated test strategy).
"""

import io

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from uda_tpu.compress.lzo import lzo1x_compress_py, lzo1x_decompress_py
from uda_tpu.utils import comparators, vint
from uda_tpu.utils.ifile import (IFileReader, IFileWriter, crack,
                                 crack_partial, write_records)

pytestmark = pytest.mark.slow  # property sweeps (hypothesis) dominate the suite

# CI-fast but NOT derandomized: a frozen example set would never
# explore new inputs across runs (reproduce failures via the printed
# @reproduce_failure blob / hypothesis example database)
settings.register_profile("uda", max_examples=80, deadline=None)
settings.load_profile("uda")


@given(st.integers(min_value=-(2 ** 63), max_value=2 ** 63 - 1))
def test_vlong_round_trip(value):
    buf = vint.encode_vlong(value)
    out, consumed = vint.decode_vlong(buf)
    assert (out, consumed) == (value, len(buf))
    # the (signed) first byte alone determines the encoded size
    signed = buf[0] - 256 if buf[0] > 127 else buf[0]
    assert vint.decode_vint_size(signed) == len(buf)


@given(st.lists(st.integers(min_value=-(2 ** 63), max_value=2 ** 63 - 1),
                max_size=50))
def test_vlong_stream_round_trip(values):
    arr = np.asarray(values, np.int64)
    blob = np.frombuffer(vint.encode_vlong_array(arr), np.uint8)
    out, _ = vint.decode_vlong_stream(blob, count=len(values))
    assert out.tolist() == values


_record = st.tuples(st.binary(min_size=0, max_size=40),
                    st.binary(min_size=0, max_size=60))


@pytest.mark.parametrize("use_native", [False, True])
@given(st.lists(_record, max_size=30))
def test_ifile_write_crack_round_trip(use_native, records):
    from uda_tpu.utils import ifile

    # pad one record so the blob crosses the native-dispatch threshold:
    # both the pure-Python and (when built) the C++ crack paths must
    # uphold the contract
    if use_native:
        if not ifile.native_enabled():
            pytest.skip("native codec not built")
        records = records + [(b"k" * 64, b"v" * 8192)]
    blob = write_records(records)
    batch = crack(blob, expect_eof=True)
    assert list(batch.iter_records()) == records


@given(st.lists(_record, min_size=1, max_size=12), st.data())
def test_crack_partial_at_any_split(records, data):
    # splitting the stream at ANY byte boundary must yield: a prefix of
    # complete records + a carry that, prepended to the rest, round-trips
    blob = write_records(records)
    cut = data.draw(st.integers(min_value=0, max_value=len(blob)))
    head, consumed, saw_eof = crack_partial(blob[:cut], expect_eof=False)
    got = list(head.iter_records())
    if saw_eof:
        # the whole stream (incl. EOF marker) fit in the prefix
        assert consumed == cut == len(blob)
    else:
        tail = crack(blob[:cut][consumed:] + blob[cut:], expect_eof=True)
        got += list(tail.iter_records())
    assert got == records


@given(st.lists(_record, max_size=20))
def test_ifile_writer_reader_agree_with_batch_path(records):
    buf = io.BytesIO()
    w = IFileWriter(buf)
    for k, v in records:
        w.append(k, v)
    w.close()
    assert list(IFileReader(io.BytesIO(buf.getvalue()))) == records
    assert (list(crack(buf.getvalue(), expect_eof=True).iter_records())
            == records)


@given(st.binary(max_size=30), st.binary(max_size=30))
def test_rawbytes_comparator_matches_memcmp(a, b):
    # independent oracle: hand-rolled byte loop + length tiebreak (NOT
    # Python's bytes comparison, which is what the implementation uses)
    def oracle(x, y):
        for xb, yb in zip(x, y):
            if xb != yb:
                return -1 if xb < yb else 1
        return (len(x) > len(y)) - (len(x) < len(y))

    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = oracle(a, b)
    got = kt.compare(a, b)
    assert (got > 0) == (want > 0) and (got < 0) == (want < 0) \
        and (got == 0) == (want == 0)


@given(st.binary(max_size=4096))
def test_lzo_pure_python_round_trip(data):
    assert lzo1x_decompress_py(lzo1x_compress_py(data), len(data)) == data


@settings(max_examples=8, deadline=None)
@given(st.integers(min_value=1, max_value=400), st.integers(0, 2 ** 32 - 1),
       st.floats(0.0, 1.0))
def test_sort_engines_agree(n, seed, dup_rate):
    # every payload-movement engine must produce byte-identical output
    # (stability included) for arbitrary record counts, key
    # distributions, and duplicate rates — the equivalence the fly-off
    # depends on
    import jax

    from uda_tpu.models import terasort

    words = np.asarray(terasort.teragen(jax.random.key(seed % 1000), n))
    words = words.copy()
    ndup = int(dup_rate * n / 2)
    if ndup:
        words[:ndup, :3] = words[n - ndup:, :3]  # forced duplicate keys
    want = np.asarray(terasort.single_chip_sort(words, path="carry"))
    for path in ("gather", "gather2", "carrychunk", "keys8", "keys8f",
                 "lanes", "lanes2"):
        # tile=256 lets keys8f fold when n > 128 (pad_pow2 clamps the
        # tile for smaller n and keys8f falls back to the standard
        # cascade; tests/test_pallas_fold.py covers folding
        # deterministically)
        got = np.asarray(terasort.single_chip_sort(
            words, path=path, tile=256, interpret=True))
        np.testing.assert_array_equal(want, got, err_msg=path)
