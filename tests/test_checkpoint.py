"""Crash-consistent checkpoint/resume of a half-merged reduce
(uda_tpu.merger.checkpoint, ISSUE 16).

The contract under test, layer by layer:

- RunStore fixed-dir mode: run files spool into the checkpoint's
  directory with a CRC recorded per run, survive cleanup(), and can be
  adopted back by a successor attempt.
- Segment offset-ledger export/preload: the framed-batches+carry
  snapshot round-trips byte-exactly and re-arms the mid-partition
  resume (fetch.resumed.bytes), with the first-chunk identity check
  still guarding it.
- TaskCheckpoint manifests: atomic (write-to-temp + fsync + rename),
  versioned, consumed-on-load (zombie fencing via the tenant epoch),
  and torn-manifest-tolerant — a kill mid-snapshot (or an injected
  ``ckpt.save`` truncate) falls back to the previous manifest, never a
  broken one, never a crash.
- MergeManager resume: a restarted attempt produces BYTE-IDENTICAL
  output to the uninterrupted run, refetches ZERO bytes of the maps
  whose run files the manifest recorded (``ckpt.runs.adopted``), and
  counts ``ckpt.resumed`` — a silent restart-from-scratch is a test
  failure, not a pass.
- The faults-marked tests are the chaos resume rung
  (scripts/run_chaos.sh): a seeded kill -9 of the reduce process
  mid-merge, once mid-checkpoint, then the resume asserts above.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import zlib

import numpy as np
import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.merger import checkpoint as ckpt_mod
from uda_tpu.merger.checkpoint import TaskCheckpoint, read_run
from uda_tpu.merger.segment import InputClient, Segment
from uda_tpu.merger.streaming import RunStore
from uda_tpu.mofserver import DataEngine, DirIndexResolver, ShuffleRequest
from uda_tpu.utils import comparators
from uda_tpu.utils.budget import MemoryBudget
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import FallbackSignal, MergeError, StorageError
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.ifile import EOF_MARKER, crack, crack_partial, \
    write_records
from uda_tpu.utils.metrics import metrics

KT = comparators.get_key_type("uda.tpu.RawBytes")


def _counter(name: str) -> float:
    """Unlabeled counter total (labeled adds advance it too)."""
    return float(metrics.snapshot().get(name, 0))


def _recs(n, seed=0, key_bytes=10, val_bytes=24):
    rng = np.random.default_rng(seed)
    return [(rng.bytes(key_bytes), rng.bytes(val_bytes)) for _ in range(n)]


# -- RunStore fixed-dir mode -------------------------------------------------

def test_runstore_fixed_dir_crc_adopt_discard(tmp_path):
    fixed = os.path.join(str(tmp_path), "runs")
    store = RunStore(tag="t", fixed_dir=fixed)
    recs = sorted(_recs(50, seed=1), key=lambda kv: kv[0])
    batch = crack(write_records(recs))
    store.write_run(3, batch, np.arange(50, dtype=np.int64))
    man = store.manifest()
    assert set(man) == {3}
    n, nbytes, crc = man[3]
    assert n == 50
    run_path = store.run_path(3)
    with open(run_path, "rb") as f:
        data = f.read()
    assert len(data) == nbytes + len(EOF_MARKER)
    assert zlib.crc32(data) & 0xFFFFFFFF == crc  # whole file incl. EOF
    # fixed mode: cleanup() keeps the files — they ARE the resume state
    store.cleanup()
    assert os.path.exists(run_path)
    # a successor adopts the same accounting without rewriting
    store2 = RunStore(tag="t", fixed_dir=fixed)
    store2.adopt(3, n, nbytes, crc)
    assert store2.manifest() == {3: (n, nbytes, crc)}
    with pytest.raises(MergeError):
        store2.adopt(3, n, nbytes, crc)  # staged twice
    store2.discard(3)
    assert not os.path.exists(run_path)


def test_read_run_validates_length_crc_offsets(tmp_path):
    fixed = os.path.join(str(tmp_path), "runs")
    store = RunStore(tag="t", fixed_dir=fixed)
    recs = sorted(_recs(40, seed=2), key=lambda kv: kv[0])
    store.write_run(0, crack(write_records(recs)),
                    np.arange(40, dtype=np.int64))
    n, nbytes, crc = store.manifest()[0]
    rec = {"records": n, "bytes": nbytes,
           "length": nbytes + len(EOF_MARKER), "crc": crc}
    run_path, off_path = store._paths(0)
    batch = read_run(run_path, off_path, rec)
    assert batch.num_records == 40
    # torn spool: truncated file fails the length check
    with open(run_path, "rb") as f:
        data = f.read()
    with open(run_path, "wb") as f:
        f.write(data[:-7])
    with pytest.raises(StorageError):
        read_run(run_path, off_path, rec)
    # right length, flipped byte: fails the CRC check
    with open(run_path, "wb") as f:
        f.write(data[:10] + bytes([data[10] ^ 0xFF]) + data[11:])
    with pytest.raises(StorageError):
        read_run(run_path, off_path, rec)


# -- Segment offset-ledger export/preload ------------------------------------

def _null_segment(tmp_path, chunk=1 << 16):
    class _Null(InputClient):
        def start_fetch(self, req, on_complete):
            raise AssertionError("no fetch expected")

    return Segment(_Null(), "j", "m_0", 0, chunk)


def test_segment_export_preload_roundtrip(tmp_path):
    recs = _recs(30, seed=3)
    framed = write_records(recs)[:-len(EOF_MARKER)]
    carry = write_records(_recs(1, seed=4))[:3]  # a torn record head
    data = framed + carry
    seg = _null_segment(tmp_path)
    seg.ckpt_preload(data=data, carry_len=len(carry),
                     next_offset=len(data), raw_length=4096,
                     num_records=30)
    ex = seg.ckpt_export()
    assert ex is not None
    assert ex["next_offset"] == len(data)
    assert ex["raw_length"] == 4096
    assert ex["num_records"] == 30
    assert ex["carry_len"] == len(carry)
    assert ex["data"] == data  # byte-exact round trip
    # nothing fetched yet -> nothing to export
    assert _null_segment(tmp_path).ckpt_export() is None


def test_segment_preload_rejects_mismatch(tmp_path):
    recs = _recs(10, seed=5)
    framed = write_records(recs)[:-len(EOF_MARKER)]
    with pytest.raises(StorageError):  # record count drifted
        _null_segment(tmp_path).ckpt_preload(
            data=framed, carry_len=0, next_offset=len(framed),
            raw_length=None, num_records=11)
    with pytest.raises(StorageError):  # carry longer than the payload
        _null_segment(tmp_path).ckpt_preload(
            data=b"xy", carry_len=5, next_offset=2, raw_length=None,
            num_records=0)


def test_segment_preload_resumes_mid_partition(tmp_path):
    """A preloaded ledger picks the fetch up at next_offset: the final
    batch equals the full fetch, fetch.resumed counts it, and only the
    tail bytes move."""
    root = os.path.join(str(tmp_path), "mof")
    make_mof_tree(root, "jobL", 1, 1, 400, seed=7)
    cfg = Config()
    engine = DataEngine(DirIndexResolver(root), cfg)
    try:
        mid = map_ids("jobL", 1)[0]
        chunk = 2048
        res = engine.submit(
            ShuffleRequest("jobL", mid, 0, 0, chunk)).result()
        first = bytes(res.data)
        assert not res.is_last  # the partition must span chunks
        batch, consumed, _ = crack_partial(first, expect_eof=False)
        from uda_tpu import native

        data = native.frame_batch(batch, write_eof=False) + \
            first[consumed:]
        r0 = _counter("fetch.resumed")
        b0 = _counter("fetch.resumed.bytes")
        seg = Segment(LocalFetchClient(engine), "jobL", mid, 0, chunk)
        seg.ckpt_preload(data=data, carry_len=len(first) - consumed,
                         next_offset=len(first),
                         raw_length=res.raw_length,
                         num_records=batch.num_records)
        seg.start()
        seg.wait()
        resumed = seg.record_batch()  # raises if the resume errored
        full = Segment(LocalFetchClient(engine), "jobL", mid, 0,
                       1 << 20)
        full.start()
        full.wait()
        ref = full.record_batch()
        assert resumed.num_records == ref.num_records
        assert list(resumed.iter_records()) == list(ref.iter_records())
        assert _counter("fetch.resumed") == r0 + 1
        assert _counter("fetch.resumed.bytes") == b0 + len(first)
    finally:
        engine.stop()


# -- TaskCheckpoint manifests ------------------------------------------------

def _collect_factory(payload_runs=None, ledgers=None, parts=None):
    def collect():
        payload = {"maps": ["m_0"], "runs": dict(payload_runs or {}),
                   "ledgers": {k: dict(v)
                               for k, v in (ledgers or {}).items()},
                   "journal": [], "penalty": {}, "forest": {}}
        return payload, dict(parts or {})
    return collect


def test_manifest_atomic_roundtrip_and_consume(tmp_path):
    ck = TaskCheckpoint(str(tmp_path), "jobM", 0, interval_s=0.0)
    part = b"ledger-bytes" * 9
    ck.save(_collect_factory(
        payload_runs={"0": {"map": "m_0", "records": 1}},
        ledgers={"1": {"map": "m_1"}}, parts={1: part}))
    assert ck.version >= 2  # part write + manifest write
    # the part file landed and is integrity-checked on the way back
    loaded = TaskCheckpoint(str(tmp_path), "jobM", 0)
    man = loaded.load()
    assert man is not None and man["seq"] == 1
    assert man["runs"]["0"]["records"] == 1
    assert loaded.part_bytes(man["ledgers"]["1"]) == part
    # consumed-on-load: a second claimant finds nothing
    assert TaskCheckpoint(str(tmp_path), "jobM", 0).load() is None
    # corrupt part entry -> StorageError (caller refetches from zero)
    bad = dict(man["ledgers"]["1"], part_crc=123)
    with pytest.raises(StorageError):
        loaded.part_bytes(bad)
    with pytest.raises(StorageError):
        loaded.part_bytes({"part": "../../etc/passwd",
                           "part_len": 1, "part_crc": 0})


def test_torn_manifest_falls_back_to_previous(tmp_path):
    ck = TaskCheckpoint(str(tmp_path), "jobT", 1, interval_s=0.0)
    ck.save(_collect_factory(payload_runs={"0": {"gen": 1}}))
    ck.save(_collect_factory(payload_runs={"0": {"gen": 2}}))
    newest = sorted(glob.glob(os.path.join(ck.task_dir,
                                           "manifest-*.uckp")))[-1]
    with open(newest, "rb") as f:
        raw = f.read()
    with open(newest, "wb") as f:
        f.write(raw[:len(raw) // 2])  # the kill-mid-snapshot shape
    t0 = _counter("ckpt.invalidated")
    man = TaskCheckpoint(str(tmp_path), "jobT", 1).load()
    assert man is not None and man["seq"] == 1  # previous, never broken
    assert man["runs"]["0"]["gen"] == 1
    assert _counter("ckpt.invalidated") == t0 + 1


def test_torn_manifest_via_ckpt_save_failpoint(tmp_path):
    """The injectable version of the same guarantee: a ckpt.save
    truncate fault writes a torn manifest; load skips it cleanly."""
    ck = TaskCheckpoint(str(tmp_path), "jobF", 2, interval_s=0.0)
    ck.save(_collect_factory(payload_runs={"0": {"gen": 1}}))
    with failpoints.scoped("ckpt.save=truncate"):
        ck.save(_collect_factory(payload_runs={"0": {"gen": 2}}))
    man = TaskCheckpoint(str(tmp_path), "jobF", 2).load()
    assert man is not None and man["runs"]["0"]["gen"] == 1


def test_ckpt_save_error_is_absorbed(tmp_path):
    ck = TaskCheckpoint(str(tmp_path), "jobE", 3, interval_s=0.0)
    e0 = _counter("ckpt.save.errors")
    with failpoints.scoped("ckpt.save=error"):
        assert ck.maybe_save(_collect_factory(), force=True) is False
    assert _counter("ckpt.save.errors") == e0 + 1
    assert TaskCheckpoint(str(tmp_path), "jobE", 3).load() is None


def test_ckpt_load_failpoint_degrades_to_fresh_start(tmp_path):
    ck = TaskCheckpoint(str(tmp_path), "jobG", 4, interval_s=0.0)
    ck.save(_collect_factory())
    with failpoints.scoped("ckpt.load=error"):
        assert TaskCheckpoint(str(tmp_path), "jobG", 4).load() is None
    # the manifest itself survived the failed load attempt
    assert TaskCheckpoint(str(tmp_path), "jobG", 4).load() is not None


def test_epoch_fence_refuses_successor_manifest(tmp_path):
    ck2 = TaskCheckpoint(str(tmp_path), "jobZ", 5, interval_s=0.0,
                         epoch=2)
    ck2.save(_collect_factory(payload_runs={"0": {"gen": 1}}))
    # the epoch-1 zombie must not consume its successor's state
    zombie = TaskCheckpoint(str(tmp_path), "jobZ", 5, epoch=1)
    assert zombie.load() is None
    assert glob.glob(os.path.join(ck2.task_dir, "manifest-*.uckp"))
    # the rightful epoch-2 owner still can
    assert TaskCheckpoint(str(tmp_path), "jobZ", 5,
                          epoch=2).load() is not None


def test_manifest_prune_keeps_recent_generations(tmp_path):
    ck = TaskCheckpoint(str(tmp_path), "jobP", 6, interval_s=0.0,
                        keep=2)
    for g in range(5):
        ck.save(_collect_factory(
            ledgers={"0": {"map": "m_0"}}, parts={0: b"x%d" % g}))
    manifests = sorted(glob.glob(os.path.join(ck.task_dir,
                                              "manifest-*.uckp")))
    assert len(manifests) == 2
    # retained manifests only reference parts of their own seq; older
    # part files are pruned with their manifests
    parts = sorted(os.listdir(ck.parts_dir))
    assert parts == ["p00000004-s00000.part", "p00000005-s00000.part"]


# -- MergeManager wiring -----------------------------------------------------

def test_budget_route_prefer_streaming(tmp_path):
    b = MemoryBudget.from_config(Config({
        "uda.tpu.hbm.budget.mb": 4096, "uda.tpu.host.budget.mb": 4096}))
    small = 1 << 20
    assert b.route(small, 1 << 30).decision == "hybrid"
    adm = b.route(small, 1 << 30, prefer_streaming=True)
    assert adm.decision == "streaming"
    assert adm.cause == "ckpt"
    # budget-forced decisions are unaffected by the preference
    assert b.route(None, 1 << 30, prefer_streaming=True).cause == ""


def test_watchdog_token_tracks_ckpt_version(tmp_path):
    class _Null(InputClient):
        def start_fetch(self, req, on_complete):
            raise AssertionError("no fetch expected")

    mm = MergeManager(_Null(), KT, Config())
    t0 = mm._progress_token()
    mm._ckpt = TaskCheckpoint(str(tmp_path), "jobW", 0, interval_s=0.0)
    t1 = mm._progress_token()
    mm._ckpt.save(_collect_factory())
    t2 = mm._progress_token()
    # a completed snapshot (long fsync included) IS progress
    assert t2 != t1
    assert t1[:-1] == t2[:-1] == t0[:-1]


def test_generation_mismatch_drops_ledger_keeps_runs(tmp_path):
    """The revalidation ladder's generation rung: a cold supplier
    restart (recorded generation != live one) drops that source's
    offset ledger but still adopts its self-contained run files."""
    root = os.path.join(str(tmp_path), "mof")
    make_mof_tree(root, "jobD", 2, 1, 60, seed=9)
    engine = DataEngine(DirIndexResolver(root), Config())
    try:
        class GenClient(LocalFetchClient):
            def generation(self, host=""):
                return 7  # the supplier restarted since the manifest

        mm = MergeManager(GenClient(engine), KT, Config())
        mids = map_ids("jobD", 2)
        ck = TaskCheckpoint(str(tmp_path), "jobD", 0, interval_s=0.0)
        store = RunStore(tag="jobD.r0", fixed_dir=ck.runs_dir)
        recs = sorted(_recs(20, seed=10), key=lambda kv: kv[0])
        store.write_run(0, crack(write_records(recs)),
                        np.arange(20, dtype=np.int64))
        n, nbytes, crc = store.manifest()[0]
        part = write_records(_recs(5, seed=11))[:-len(EOF_MARKER)]
        ck.save(_collect_factory(
            payload_runs={"0": {"map": mids[0], "records": n,
                                "bytes": nbytes,
                                "length": nbytes + len(EOF_MARKER),
                                "crc": crc}},
            ledgers={"1": {"map": mids[1], "host": "", "generation": 3,
                           "next_offset": len(part), "carry_len": 0,
                           "raw_length": None, "num_records": 5}},
            parts={1: part}))
        # patch the maps list to the real two-map identity
        man = TaskCheckpoint(str(tmp_path), "jobD", 0).load()
        man["maps"] = list(mids)

        class _Forest:
            adopted = []

            def adopt_run(self, i, batch):
                self.adopted.append((i, batch.num_records))

        om = _Forest()
        store2 = RunStore(tag="jobD.r0", fixed_dir=ck.runs_dir)
        g0 = _counter("ckpt.invalidated")
        adopted, preload, nrec = mm._resume_from_manifest(
            man, mids, store2, om, ck)
        assert adopted == {0} and nrec == 20
        assert om.adopted == [(0, 20)]
        assert preload == {}  # the gen-3 ledger was dropped
        assert _counter("ckpt.invalidated") == g0 + 1
    finally:
        engine.stop()


# -- end-to-end resume -------------------------------------------------------

class CountingClient(LocalFetchClient):
    """LocalFetchClient that counts start_fetch calls per map — the
    zero-refetch assertion's probe."""

    def __init__(self, engine):
        super().__init__(engine)
        self.fetches: dict = {}

    def start_fetch(self, req, on_complete):
        self.fetches[req.map_id] = self.fetches.get(req.map_id, 0) + 1
        super().start_fetch(req, on_complete)


def _run_merge(root, ckdir, *, fault=None, client_cls=LocalFetchClient,
               num_maps=6, interval=0.0, extra=None):
    cfg = Config(dict({"uda.tpu.online.streaming": True,
                       "uda.tpu.ckpt.dir": ckdir,
                       "uda.tpu.ckpt.interval.s": interval},
                      **(extra or {})))
    engine = DataEngine(DirIndexResolver(root), cfg)
    client = client_cls(engine)
    mm = MergeManager(client, KT, cfg)
    blocks = []
    try:
        if fault:
            with failpoints.scoped(fault):
                mm.run("jobK", map_ids("jobK", num_maps), 0,
                       lambda b: blocks.append(bytes(b)))
        else:
            mm.run("jobK", map_ids("jobK", num_maps), 0,
                   lambda b: blocks.append(bytes(b)))
        return b"".join(blocks), client, None
    except FallbackSignal as e:
        return b"".join(blocks), client, e
    finally:
        engine.stop()


def _manifest_runs(ckdir):
    """Maps whose run files the newest on-disk manifest records (read
    WITHOUT consuming — the probe the zero-refetch assert keys on)."""
    paths = sorted(glob.glob(os.path.join(ckdir, "*",
                                          "manifest-*.uckp")))
    assert paths, "no manifest survived the failed attempt"
    man = TaskCheckpoint._read_manifest(paths[-1])
    assert man is not None
    return [rec["map"] for rec in man.get("runs", {}).values()]


def test_resume_is_byte_identical_and_refetches_nothing(tmp_path):
    root = os.path.join(str(tmp_path), "mof")
    make_mof_tree(root, "jobK", 6, 1, 120, seed=5)
    ref, _, err = _run_merge(root, os.path.join(str(tmp_path), "ck0"))
    assert err is None and ref
    ckdir = os.path.join(str(tmp_path), "ck")
    # attempt 1 dies on a terminal injected fault mid-fetch
    _, _, err1 = _run_merge(
        root, ckdir, fault="segment.fetch=error:match:m_000005",
        extra={"uda.tpu.fetch.retries": 0})
    assert isinstance(err1, FallbackSignal)
    checkpointed = _manifest_runs(ckdir)
    assert checkpointed  # at least one run spooled before the death
    # attempt 2 resumes: byte-identical, resumed-not-restarted, and
    # ZERO refetch of any checkpointed run's source bytes
    r0, a0 = _counter("ckpt.resumed"), _counter("ckpt.runs.adopted")
    out, client, err2 = _run_merge(root, ckdir,
                                   client_cls=CountingClient)
    assert err2 is None
    assert out == ref
    assert _counter("ckpt.resumed") == r0 + 1
    assert _counter("ckpt.runs.adopted") >= a0 + len(checkpointed)
    for mid in checkpointed:
        assert client.fetches.get(mid, 0) == 0, \
            f"checkpointed run {mid} was refetched"
    # success discards the checkpoint: nothing left to resume
    assert not os.path.exists(os.path.join(ckdir, "jobK.r0"))


def test_ckpt_save_fault_never_fails_the_task(tmp_path):
    root = os.path.join(str(tmp_path), "mof")
    make_mof_tree(root, "jobK", 4, 1, 80, seed=6)
    ref, _, err = _run_merge(root, os.path.join(str(tmp_path), "ck0"),
                             num_maps=4)
    assert err is None
    e0 = _counter("ckpt.save.errors")
    out, _, err2 = _run_merge(root, os.path.join(str(tmp_path), "ck"),
                              num_maps=4, fault="ckpt.save=error")
    assert err2 is None  # best-effort: the task never fails for its ckpt
    assert out == ref
    assert _counter("ckpt.save.errors") > e0


# -- chaos: kill -9 mid-merge / mid-checkpoint (the resume rung) -------------

_CHILD = r"""
import os, signal, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.merger.checkpoint import TaskCheckpoint
from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.utils import comparators
from uda_tpu.utils.config import Config
from uda_tpu.utils.failpoints import failpoints
from tests.helpers import map_ids

kill_after = int(sys.argv[1])     # SIGKILL after this many saves
torn_spec = sys.argv[2]           # "" or a ckpt.save spec to arm

saves = [0]
orig = TaskCheckpoint._save_locked
def killing_save(self, collect):
    orig(self, collect)
    saves[0] += 1
    if saves[0] >= kill_after:
        os.kill(os.getpid(), signal.SIGKILL)  # no unwind, no atexit
TaskCheckpoint._save_locked = killing_save

if torn_spec:
    failpoints.arm_spec(torn_spec)
cfg = Config({{"uda.tpu.online.streaming": True,
              "uda.tpu.ckpt.dir": {ckdir!r},
              "uda.tpu.ckpt.interval.s": 0.0}})
engine = DataEngine(DirIndexResolver({root!r}), cfg)
mm = MergeManager(LocalFetchClient(engine),
                  comparators.get_key_type("uda.tpu.RawBytes"), cfg)
mm.run("jobK", map_ids("jobK", 6), 0, lambda b: None)
sys.exit(7)  # the kill must preempt completion
"""


def _kill9_attempt(root, ckdir, kill_after, torn_spec=""):
    code = _CHILD.format(repo=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ckdir=ckdir, root=root)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the chaos tier's ambient schedule targets the PARENT's tests; the
    # child arms only its own torn-save spec
    env.pop("UDA_FAILPOINTS", None)
    proc = subprocess.run(
        [sys.executable, "-c", code, str(kill_after), torn_spec],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == -signal.SIGKILL, \
        f"child survived: rc={proc.returncode}\n{proc.stderr[-2000:]}"


@pytest.mark.faults
def test_chaos_kill9_mid_merge_resumes_byte_identical(tmp_path):
    """The resume rung's core guarantee: kill -9 at a seeded point
    mid-merge; the restarted task produces byte-identical output,
    reuses every checkpointed run file (zero refetch of their source
    bytes) and counts ckpt.resumed — restart-from-scratch FAILS."""
    seed = int(os.environ.get("UDA_TPU_CHAOS_SEED", "42"))
    root = os.path.join(str(tmp_path), "mof")
    make_mof_tree(root, "jobK", 6, 1, 120, seed=5)
    ref, _, err = _run_merge(root, os.path.join(str(tmp_path), "ck0"))
    assert err is None
    ckdir = os.path.join(str(tmp_path), "ck")
    _kill9_attempt(root, ckdir, kill_after=seed % 3 + 1)
    checkpointed = _manifest_runs(ckdir)
    r0 = _counter("ckpt.resumed")
    out, client, err2 = _run_merge(root, ckdir,
                                   client_cls=CountingClient)
    assert err2 is None
    assert out == ref  # byte-identical vs the uninterrupted run
    assert _counter("ckpt.resumed") == r0 + 1  # resumed, NOT restarted
    for mid in checkpointed:
        assert client.fetches.get(mid, 0) == 0, \
            f"checkpointed run {mid} was refetched"
    assert not os.path.exists(os.path.join(ckdir, "jobK.r0"))


@pytest.mark.faults
def test_chaos_ledger_resume_banks_bytes(tmp_path):
    """The rung's fetch.resumed.bytes>0 guarantee, deterministically: a
    crashed attempt's manifest carries a MID-PARTITION offset ledger
    (first chunk banked, no run files yet); the restart must bank those
    bytes — resume the fetch at next_offset, never offset 0 — and still
    finish byte-identical."""
    # quiesce the rung's ambient schedule: the in-process analogue of
    # the kill -9 subprocesses scrubbing UDA_FAILPOINTS from their env
    with failpoints.quiesced():
        root = os.path.join(str(tmp_path), "mof")
        make_mof_tree(root, "jobK", 6, 1, 400, seed=8)
        # 2 KB chunks: every map spans several fetch rounds
        extra = {"mapred.rdma.buf.size": 2}
        ref, _, err = _run_merge(root,
                                 os.path.join(str(tmp_path), "ck0"),
                                 extra=extra)
        assert err is None
        mids = map_ids("jobK", 6)
        ckdir = os.path.join(str(tmp_path), "ck")
        # craft the crash state: fetch map 0's first chunk for real,
        # bank it as a checkpointed ledger exactly as a mid-flight
        # snapshot would
        cfg = Config(dict({"uda.tpu.online.streaming": True}, **extra))
        engine = DataEngine(DirIndexResolver(root), cfg)
        try:
            res = engine.submit(
                ShuffleRequest("jobK", mids[0], 0, 0, 2048)).result()
        finally:
            engine.stop()
        first = bytes(res.data)
        assert not res.is_last
        batch, consumed, _ = crack_partial(first, expect_eof=False)
        from uda_tpu import native

        part = native.frame_batch(batch, write_eof=False) + \
            first[consumed:]
        ck = TaskCheckpoint(ckdir, "jobK", 0, interval_s=0.0)
        ck.save(lambda: (
            {"maps": list(mids), "runs": {},
             "ledgers": {"0": {"map": mids[0], "host": "",
                               "generation": None,
                               "next_offset": len(first),
                               "carry_len": len(first) - consumed,
                               "raw_length": res.raw_length,
                               "num_records": batch.num_records}},
             "journal": [], "penalty": {}, "forest": {}},
            {0: part}))
        r0 = _counter("ckpt.resumed")
        b0 = _counter("fetch.resumed.bytes")
        out, _, err2 = _run_merge(root, ckdir, extra=extra)
        assert err2 is None
        assert out == ref
        assert _counter("ckpt.resumed") == r0 + 1
        # the banked first chunk was NOT refetched: its bytes count as
        # resumed, the fetch restarted at next_offset
        assert _counter("fetch.resumed.bytes") >= b0 + len(first)


@pytest.mark.faults
def test_chaos_kill9_mid_checkpoint_falls_back(tmp_path):
    """Kill -9 DURING a snapshot (ckpt.save truncate tears the second
    manifest, then the kill lands): resume must load the previous
    manifest cleanly — never the torn one, never a crash — and still
    finish byte-identical."""
    root = os.path.join(str(tmp_path), "mof")
    make_mof_tree(root, "jobK", 6, 1, 120, seed=5)
    ref, _, err = _run_merge(root, os.path.join(str(tmp_path), "ck0"))
    assert err is None
    ckdir = os.path.join(str(tmp_path), "ck")
    _kill9_attempt(root, ckdir, kill_after=2,
                   torn_spec="ckpt.save=truncate:every:2")
    # the torn manifest is on disk next to the good seq-1 one
    paths = sorted(glob.glob(os.path.join(ckdir, "*",
                                          "manifest-*.uckp")))
    assert len(paths) == 2
    assert TaskCheckpoint._read_manifest(paths[-1]) is None  # torn
    t0, r0 = _counter("ckpt.invalidated"), _counter("ckpt.resumed")
    out, _, err2 = _run_merge(root, ckdir)
    assert err2 is None
    assert out == ref
    assert _counter("ckpt.resumed") == r0 + 1
    assert _counter("ckpt.invalidated") >= t0 + 1  # the torn skip
