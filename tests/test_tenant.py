"""The multi-tenant service plane (uda_tpu/tenant/ + the net/engine
integration): registry lifecycle + epoch fencing, the weighted-fair
CreditScheduler's DRR invariants, per-tenant admission isolation, the
tenant-keyed warm-restart watermarks, and the two-tenant loopback e2e
(byte parity against sequential single-tenant runs; the faults-marked
abusive-tenant rung proves one tenant's injected faults never touch a
victim's bytes)."""

import threading
import time

import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.mofserver import (DataEngine, DirIndexResolver, FetchResult,
                               ShuffleRequest)
from uda_tpu.net import RemoteFetchClient, ShuffleServer, wire
from uda_tpu.tenant import (DEFAULT_TENANT, CreditScheduler,
                            TenantRegistry, sign_job)
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import StorageError, TenantError
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.ifile import crack
from uda_tpu.utils.metrics import metrics


# -- registry lifecycle ------------------------------------------------------

def test_registry_register_heartbeat_retire_lifecycle():
    reg = TenantRegistry()
    rec = reg.register("acme", "job_1", epoch=1, weight=3)
    assert rec.active and rec.epoch == 1 and rec.weight == 3
    assert reg.weight_of("acme") == 3
    # same-epoch re-register is a heartbeat (idempotent)
    again = reg.register("acme", "job_1", epoch=1, weight=3)
    assert again is rec
    reg.validate("acme", "job_1", epoch=1)  # a validated REQ heartbeats
    reg.retire("acme", "job_1", epoch=1)
    with pytest.raises(TenantError, match="retired"):
        reg.validate("acme", "job_1", epoch=1)
    # a retired epoch cannot resume; a HIGHER epoch (restart) can
    with pytest.raises(TenantError, match="retired"):
        reg.register("acme", "job_1", epoch=1)
    rec2 = reg.register("acme", "job_1", epoch=2)
    assert rec2.active and rec2.epoch == 2


def test_registry_epoch_fencing():
    reg = TenantRegistry()
    reg.register("t", "j", epoch=3)
    # a stale-epoch registration is refused outright
    with pytest.raises(TenantError, match="stale epoch"):
        reg.register("t", "j", epoch=2)
    # a higher epoch fences the old one: old validates fail typed, the
    # new epoch serves
    reg.register("t", "j", epoch=4)
    with pytest.raises(TenantError, match="stale epoch"):
        reg.validate("t", "j", epoch=3)
    assert reg.validate("t", "j", epoch=4).epoch == 4
    assert metrics.get("tenant.epoch.fenced") == 1


def test_registry_unknown_job_and_auth():
    reg = TenantRegistry(secret="s3cret")
    with pytest.raises(TenantError, match="unknown job"):
        reg.validate("t", "nope")
    # wrong/missing token -> typed auth refusal
    with pytest.raises(TenantError, match="authentication"):
        reg.register("t", "j", epoch=1, token="bogus")
    tok = sign_job("s3cret", "t", "j", 1)
    assert reg.register("t", "j", epoch=1, token=tok).active
    # the token binds the exact (tenant, job, epoch) triple
    with pytest.raises(TenantError, match="authentication"):
        reg.register("t", "j", epoch=2, token=tok)


def test_registry_ttl_expires_idle_jobs(monkeypatch):
    import uda_tpu.tenant.registry as regmod

    now = [100.0]
    monkeypatch.setattr(regmod.time, "monotonic", lambda: now[0])
    reg = TenantRegistry(ttl_s=5.0)
    reg.register("t", "j", epoch=1)
    now[0] += 3.0
    reg.validate("t", "j")          # activity refreshes the clock
    now[0] += 4.0
    reg.validate("t", "j")          # 4s idle < ttl: still there
    now[0] += 6.0
    with pytest.raises(TenantError, match="unknown job"):
        reg.validate("t", "j")      # expired past the ttl


def test_registry_share_bytes_partitions_by_weight():
    reg = TenantRegistry()
    reg.register("a", "ja", epoch=1, weight=2)
    # a lone tenant owns the whole budget (partitions bind only under
    # contention — the single-job deployment keeps PR 3's admission)
    assert reg.share_bytes("a", 900) == 900
    reg.register("b", "jb", epoch=1, weight=1)
    assert reg.share_bytes("a", 900) == 600
    assert reg.share_bytes("b", 900) == 300
    # an unknown tenant is unconstrained by the partition layer (the
    # global budget still bounds it)
    assert reg.share_bytes("zz", 900) == 900


# -- the weighted-fair scheduler ---------------------------------------------

class _Conn:
    """Stand-in for the parked item's connection slot."""


def test_wdrr_weight_proportionality_and_deficit_bounds():
    weights = {"a": 2, "b": 1, "c": 1}
    sched = CreditScheduler(4, weight_of=lambda t: weights.get(t, 1))
    conn = _Conn()
    # saturate: 4 credits granted inline, the rest parks
    order = [t for _ in range(40) for t in ("a", "b", "c")]
    live, parked = [], 0
    for i, t in enumerate(order):
        if sched.admit(t, (conn, (t, i))):
            live.append((t, i))
        else:
            parked += 1
    assert parked == len(order) - 4
    served = []  # parked entries in GRANT order (the fairness record)
    while live:
        t, _i = live.pop(0)
        sched.release(t)
        for _conn, entry in sched.grant_parked():
            served.append(entry)
            live.append(entry)
    counts = {t: sum(1 for e in served if e[0] == t) for t in weights}
    # every parked request was eventually served (no starvation)
    assert sum(counts.values()) == parked
    assert sched.backlog() == 0 and sched.free == sched.total
    # weight proportionality over the contended window: a(2) is served
    # ~2x b(1)/c(1) while every queue has backlog (a's queue drains
    # first; the tail is b/c leftovers, so compare the first half)
    window = served[: len(served) // 2]
    wc = {t: sum(1 for e in window if e[0] == t) for t in weights}
    assert wc["a"] > 1.5 * wc["b"]
    assert 0.5 <= wc["b"] / max(1, wc["c"]) <= 2.0
    # deficit bound: quantum x weight, never more
    for t, tq in sched._tenants.items():
        assert tq.deficit <= sched.quantum * weights[t] + 1e-9


def test_wdrr_byte_cost_proportionality_mixed_chunk_sizes():
    # ISSUE 15 satellite (ROADMAP item 1 follow-up): deficits earned/
    # charged in BYTES — a tenant fetching big chunks must not
    # out-draw an equal-weight tenant fetching small ones. a(2x
    # weight, 64 KB chunks) vs b(1x, 256 KB) vs c(1x, 16 KB): granted
    # BYTES converge to the 2:1:1 weight ratio over the contended
    # window even though the request COUNTS wildly differ
    weights = {"a": 2, "b": 1, "c": 1}
    sizes = {"a": 64 << 10, "b": 256 << 10, "c": 16 << 10}
    sched = CreditScheduler(4, weight_of=lambda t: weights.get(t, 1),
                            quantum=float(64 << 10))
    conn = _Conn()
    live, parked_cost = [], {t: 0 for t in weights}
    order = ([t for _ in range(120) for t in ("c",) * 8]
             + [t for _ in range(120) for t in ("b",) * 1]
             + [t for _ in range(120) for t in ("a",) * 2])
    # interleave arrivals so every queue holds backlog throughout
    arrivals = [t for trio in zip(order[:960:8], order[960:1080],
                                  order[1080:1320:2]) for t in trio]
    for i, t in enumerate(arrivals):
        if sched.admit(t, (conn, (t, i)), cost=sizes[t]):
            live.append((t, i))
        else:
            parked_cost[t] += sizes[t]
    assert all(parked_cost[t] > 0 for t in weights)
    served_bytes = {t: 0 for t in weights}
    guard = 0
    while live and guard < 100_000:
        guard += 1
        t, _i = live.pop(0)
        sched.release(t)
        for _conn, entry in sched.grant_parked():
            served_bytes[entry[0]] += sizes[entry[0]]
            live.append(entry)
        # stop once the contended window ends (some queue drained)
        if any(sched.backlog(t) == 0 for t in weights):
            break
    total = sum(served_bytes.values())
    assert total > 0
    # byte shares within the contended window: a ~1/2, b ~1/4, c ~1/4
    share = {t: served_bytes[t] / total for t in weights}
    assert 0.35 <= share["a"] <= 0.65, share
    assert abs(share["b"] - share["c"]) < 0.15, share


def test_wdrr_oversized_heads_keep_weighted_byte_shares():
    # review hardening (round 4): when EVERY head costs far more than
    # one turn's earning (4 MB chunks vs 64 KB quantum — the bench
    # regime), deficits must keep accumulating weight-proportionally
    # (uncapped while backlogged); the saturating cap degenerated
    # grants to round-robin and 2x weight earned ~1.3x bytes
    weights = {"a": 2, "b": 1, "c": 1}
    cost = 4 << 20
    sched = CreditScheduler(4, weight_of=lambda t: weights.get(t, 1),
                            quantum=float(64 << 10))
    conn = _Conn()
    live = []
    for i in range(240):
        t = ("a", "b", "c")[i % 3]
        if sched.admit(t, (conn, (t, i)), cost=cost):
            live.append((t, i))
    served = {t: 0 for t in weights}
    guard = 0
    while live and guard < 50_000:
        guard += 1
        t, _i = live.pop(0)
        sched.release(t)
        for _conn, entry in sched.grant_parked():
            served[entry[0]] += 1
            live.append(entry)
        if any(sched.backlog(t) == 0 for t in weights):
            break
    total = sum(served.values())
    assert total > 20
    share = served["a"] / total
    # 2:1:1 weights -> a should take ~half the bytes (all costs equal,
    # so grant counts are byte shares); the round-robin failure mode
    # gave ~1/3
    assert share >= 0.42, (share, served)
    assert abs(served["b"] - served["c"]) <= max(4, 0.25 * served["b"])


def test_wdrr_oversized_head_accumulates_never_starves():
    # a head request dearer than one turn's earning accumulates
    # deficit across turns; an otherwise-empty sweep force-serves the
    # most-indebted head instead of idling free credits
    sched = CreditScheduler(1, quantum=float(1 << 10))  # 1 KB quantum
    conn = _Conn()
    assert sched.admit("big", (conn, ("big", 0)), cost=1 << 10)
    assert sched.admit("big", (conn, ("big", 1)), cost=1 << 20) is False
    sched.release("big")
    granted = []
    for _ in range(10):
        granted += [e for _, e in sched.grant_parked()]
        if granted:
            break
    assert granted == [("big", 1)]          # served, never stranded
    assert sched.backlog() == 0
    # the byte debt is on the books: deficit went negative
    assert sched._tenants["big"].deficit < 0
    assert sched.granted_cost["big"] == (1 << 10) + (1 << 20)


def test_wdrr_fifo_within_tenant_and_inline_grant():
    sched = CreditScheduler(1)
    conn = _Conn()
    assert sched.admit("t", (conn, ("t", 0))) is True   # inline grant
    assert sched.admit("t", (conn, ("t", 1))) is False  # parks
    assert sched.admit("t", (conn, ("t", 2))) is False
    sched.release("t")
    granted = sched.grant_parked()
    assert [e for _, e in granted] == [("t", 1)]        # FIFO
    sched.release("t")
    assert [e for _, e in sched.grant_parked()] == [("t", 2)]


def test_penalty_box_deprioritizes_but_never_starves():
    sched = CreditScheduler(1, penalty_threshold=2, penalty_ms=60_000)
    conn = _Conn()
    sched.admit("bad", (conn, ("bad", 0)))  # takes the only credit
    sched.admit("bad", (conn, ("bad", 1)))
    sched.admit("good", (conn, ("good", 0)))
    sched.note_fault("bad")
    sched.note_fault("bad")
    assert sched.boxed("bad") and not sched.boxed("good")
    sched.release("bad")
    # the boxed tenant's parked entry yields to the unboxed neighbor
    g1 = sched.grant_parked()
    assert [e for _, e in g1] == [("good", 0)]
    sched.release("good")
    # no unboxed backlog left: the boxed tenant is served, not starved
    g2 = sched.grant_parked()
    assert [e for _, e in g2] == [("bad", 1)]
    assert metrics.get("tenant.penalties", tenant="bad") == 1


def test_drop_conn_removes_only_that_conns_parked_items():
    sched = CreditScheduler(1)
    c1, c2 = _Conn(), _Conn()
    sched.admit("t", (c1, ("t", 0)))
    sched.admit("t", (c1, ("t", 1)))
    sched.admit("t", (c2, ("t", 2)))
    assert sched.drop_conn(c1) == 1
    sched.release("t")
    assert [e for _, e in sched.grant_parked()] == [("t", 2)]


# -- wire framing ------------------------------------------------------------

def test_wire_job_roundtrip_and_strictness():
    frame = wire.encode_job(7, "acme", "job_9", 3, weight=2,
                            token="tok", retire=False)
    msg_type, req_id, length = wire.decode_header(frame[:wire.HEADER.size])
    assert (msg_type, req_id) == (wire.MSG_JOB, 7)
    payload = frame[wire.HEADER.size:]
    assert wire.decode_job(payload) == ("acme", "job_9", 3, 2, "tok",
                                        False)
    retire = wire.encode_job(8, "acme", "job_9", 3, retire=True)
    assert wire.decode_job(retire[wire.HEADER.size:])[5] is True
    from uda_tpu.utils.errors import TransportError
    with pytest.raises(TransportError, match="trailing"):
        wire.decode_job(payload + b"z")
    ok = wire.encode_job_ok(7, 3)
    assert wire.decode_job_ok(ok[wire.HEADER.size:]) == 3
    with pytest.raises(TransportError, match="malformed"):
        wire.decode_job_ok(b"\x00" * 3)


# -- server integration ------------------------------------------------------

JOB_A = "jobTenA"
JOB_B = "jobTenB"

TEN_CFG = {"uda.tpu.tenant.enable": True}


def _tenant_cfg(tenant, **extra):
    cfg = {"uda.tpu.tenant.id": tenant}
    cfg.update(extra)
    return Config(cfg)


def _await(predicate, timeout=3.0):
    """Wait for loop-marshalled settles to land (a client completion
    can beat the server loop's credit-settle callback by a tick)."""
    deadline = time.monotonic() + timeout
    while not predicate() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert predicate()


def _fetch_sync(client, req, timeout=10.0):
    box, done = [], threading.Event()

    def on_complete(res):
        box.append(res)
        done.set()

    client.start_fetch(req, on_complete)
    assert done.wait(timeout), "fetch never completed"
    return box[0]


def _fetch_job(client, job, num_maps, reduce_id=0, retries=8):
    """All of one reducer's records for a job over ``client``.

    Bounded per-map retry: this raw helper has none of the merge
    path's offset-ledger revalidation, so under an ambient chaos
    schedule (UDA_FAILPOINTS arming data_engine.pread) a truncated or
    errored pread surfaces here directly and must be absorbed by
    re-requesting the map — the same absorb-and-refetch contract the
    product path honors.  Without faults the first attempt always
    succeeds.
    """
    got = []
    for mid in map_ids(job, num_maps):
        for attempt in range(retries):
            res = _fetch_sync(client, ShuffleRequest(job, mid, reduce_id, 0,
                                                     1 << 20))
            if not isinstance(res, FetchResult):
                assert attempt < retries - 1, f"fetch failed: {res!r}"
                continue
            try:
                got += list(crack(res.data).iter_records())
                break
            except StorageError:       # truncated pread served whole
                if attempt == retries - 1:
                    raise
    return got


@pytest.fixture
def two_job_supplier(tmp_path):
    """One daemon serving TWO jobs' MOF trees (the multi-tenant
    shape) -> (expected_a, expected_b, server, engine)."""
    expected_a = make_mof_tree(str(tmp_path), JOB_A, num_maps=3,
                               num_reducers=1, records_per_map=40, seed=3)
    expected_b = make_mof_tree(str(tmp_path), JOB_B, num_maps=3,
                               num_reducers=1, records_per_map=40, seed=4)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(engine, Config(TEN_CFG), host="127.0.0.1",
                           port=0).start()
    yield expected_a, expected_b, server, engine
    server.stop()
    engine.stop()


def test_hello_advertises_cap_tenant(two_job_supplier):
    _, _, server, _ = two_job_supplier
    client = RemoteFetchClient("127.0.0.1", server.port,
                               _tenant_cfg("a"))
    try:
        client._ensure_connected()
        assert client._hello_seen.wait(2.0)
        with client._lock:
            assert client._peer_caps & wire.CAP_TENANT
    finally:
        client.stop()


def test_bind_then_fetch_and_epoch_fence_e2e(two_job_supplier):
    expected_a, _, server, _ = two_job_supplier
    old = RemoteFetchClient("127.0.0.1", server.port,
                            _tenant_cfg("a", **{"uda.tpu.tenant.epoch": 1}))
    new = RemoteFetchClient("127.0.0.1", server.port,
                            _tenant_cfg("a", **{"uda.tpu.tenant.epoch": 2}))
    try:
        assert old.bind_job(JOB_A) == 1
        got = _fetch_job(old, JOB_A, 3)
        assert sorted(got) == sorted(expected_a[0])
        # the restarted attempt registers epoch 2: the predecessor's
        # NEXT fetch draws a typed TenantError (stale epoch) — it can
        # never read its successor's chunks
        assert new.bind_job(JOB_A) == 2
        err = _fetch_sync(old, ShuffleRequest(JOB_A,
                                              map_ids(JOB_A, 1)[0],
                                              0, 0, 1 << 20))
        assert isinstance(err, TenantError) and "stale epoch" in str(err)
        # the successor serves
        assert sorted(_fetch_job(new, JOB_A, 3)) == sorted(expected_a[0])
        # a stale-epoch REGISTRATION is refused typed too
        with pytest.raises(TenantError, match="stale epoch"):
            old.bind_job(JOB_A)
    finally:
        old.stop()
        new.stop()


def test_retired_job_draws_typed_errors(two_job_supplier):
    expected_a, _, server, _ = two_job_supplier
    client = RemoteFetchClient("127.0.0.1", server.port,
                               _tenant_cfg("a"))
    try:
        client.bind_job(JOB_A)
        assert sorted(_fetch_job(client, JOB_A, 3)) == \
            sorted(expected_a[0])
        client.retire_job(JOB_A)
        err = _fetch_sync(client, ShuffleRequest(
            JOB_A, map_ids(JOB_A, 1)[0], 0, 0, 1 << 20))
        assert isinstance(err, TenantError) and "retired" in str(err)
    finally:
        client.stop()


def test_unbound_old_client_rides_default_tenant(two_job_supplier):
    """Back-compat: a client with NO tenant configured never sends
    MSG_JOB and serves exactly as before (the default tenant)."""
    expected_a, _, server, _ = two_job_supplier
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    try:
        assert sorted(_fetch_job(client, JOB_A, 3)) == \
            sorted(expected_a[0])
    finally:
        client.stop()
    assert metrics.get("tenant.sched.grants",
                       tenant=DEFAULT_TENANT) >= 3


def test_strict_mode_rejects_unregistered_jobs(tmp_path):
    make_mof_tree(str(tmp_path), JOB_A, num_maps=1, num_reducers=1,
                  records_per_map=10, seed=1)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(
        engine, Config(dict(TEN_CFG, **{"uda.tpu.tenant.strict": True})),
        host="127.0.0.1", port=0).start()
    unbound = RemoteFetchClient("127.0.0.1", server.port, Config())
    bound = RemoteFetchClient("127.0.0.1", server.port,
                              _tenant_cfg("a"))
    try:
        err = _fetch_sync(unbound, ShuffleRequest(
            JOB_A, map_ids(JOB_A, 1)[0], 0, 0, 1 << 20))
        assert isinstance(err, TenantError) and "registration" in str(err)
        # a registered job serves in strict mode
        assert _fetch_job(bound, JOB_A, 1)
    finally:
        unbound.stop()
        bound.stop()
        server.stop()
        engine.stop()


def test_msg_job_auth_end_to_end(tmp_path):
    make_mof_tree(str(tmp_path), JOB_A, num_maps=1, num_reducers=1,
                  records_per_map=10, seed=1)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    server = ShuffleServer(
        engine,
        Config(dict(TEN_CFG, **{"uda.tpu.tenant.secret": "hunter2"})),
        host="127.0.0.1", port=0).start()
    bad = RemoteFetchClient("127.0.0.1", server.port,
                            _tenant_cfg("a"))  # no secret
    good = RemoteFetchClient(
        "127.0.0.1", server.port,
        _tenant_cfg("a", **{"uda.tpu.tenant.secret": "hunter2"}))
    try:
        with pytest.raises(TenantError, match="authentication"):
            bad.bind_job(JOB_A)
        # the refused binding FENCES the job's REQs on that connection
        err = _fetch_sync(bad, ShuffleRequest(
            JOB_A, map_ids(JOB_A, 1)[0], 0, 0, 1 << 20))
        assert isinstance(err, TenantError) and "refused" in str(err)
        good.bind_job(JOB_A)
        assert _fetch_job(good, JOB_A, 1)
    finally:
        bad.stop()
        good.stop()
        server.stop()
        engine.stop()


def test_two_tenant_concurrent_e2e_byte_parity(two_job_supplier):
    """THE multi-tenant acceptance shape in miniature: two tenants'
    jobs fetch CONCURRENTLY through one daemon under a small shared
    credit pool, and each job's bytes equal its sequential solo run."""
    expected_a, expected_b, server, engine = two_job_supplier
    # solo oracles first (sequential single-tenant runs)
    solo_a = RemoteFetchClient("127.0.0.1", server.port,
                               _tenant_cfg("a"))
    solo_b = RemoteFetchClient("127.0.0.1", server.port,
                               _tenant_cfg("b"))
    try:
        solo_a.bind_job(JOB_A)
        oracle_a = _fetch_job(solo_a, JOB_A, 3)
        solo_b.bind_job(JOB_B)
        oracle_b = _fetch_job(solo_b, JOB_B, 3)
    finally:
        solo_a.stop()
        solo_b.stop()
    ca = RemoteFetchClient("127.0.0.1", server.port, _tenant_cfg("a"))
    cb = RemoteFetchClient("127.0.0.1", server.port, _tenant_cfg("b"))
    out = {}
    errs = []

    def run(tag, client, job):
        try:
            client.bind_job(job)
            out[tag] = _fetch_job(client, job, 3)
        except Exception as e:  # noqa: BLE001 - surfaced by the assert
            errs.append((tag, e))

    try:
        ta = threading.Thread(target=run, args=("a", ca, JOB_A))
        tb = threading.Thread(target=run, args=("b", cb, JOB_B))
        ta.start()
        tb.start()
        ta.join(20)
        tb.join(20)
        assert not errs, errs
        assert sorted(out["a"]) == sorted(oracle_a) == \
            sorted(expected_a[0])
        assert sorted(out["b"]) == sorted(oracle_b) == \
            sorted(expected_b[0])
    finally:
        ca.stop()
        cb.stop()
    # both tenants drew scheduler grants; the pool settled back to full
    assert metrics.get("tenant.sched.grants", tenant="a") >= 3
    assert metrics.get("tenant.sched.grants", tenant="b") >= 3
    _await(lambda: server._sched.free == server._sched.total)
    _await(lambda:
           metrics.get_gauge("tenant.read.bytes.on_air") == 0)


def test_per_tenant_admission_isolation(two_job_supplier):
    """One tenant over ITS read-budget share -> StorageError for that
    tenant only; the neighbor's requests ride its own share."""
    _, _, _, engine = two_job_supplier
    reg = TenantRegistry()
    reg.register("hog", "jh", epoch=1, weight=1)
    reg.register("calm", "jc", epoch=1, weight=1)
    engine.set_tenant_registry(reg)
    # each tenant's share = half the budget; hog fills its share
    share = reg.share_bytes("hog", engine.read_budget_bytes)
    engine._admit_bytes(share, "hog")
    with pytest.raises(StorageError, match="read share"):
        engine._admit_bytes(1 << 20, "hog")
    assert metrics.get("tenant.admission.rejections",
                       tenant="hog") == 1
    # the calm tenant admits fine inside its own share
    engine._admit_bytes(1 << 20, "calm")
    engine._unadmit(1 << 20, "calm")
    engine._unadmit(share, "hog")
    assert metrics.get_gauge("tenant.read.bytes.on_air") == 0


def test_watermarks_keyed_by_tenant(tmp_path):
    """The satellite regression: the served-offset watermark table is
    keyed by (tenant, job, partition) — two tenants carrying the SAME
    job/map/reduce ids get separate marks, so a warm bounce can never
    resume one job's offsets into another's fetch ledger."""
    expected = make_mof_tree(str(tmp_path), JOB_A, num_maps=1,
                             num_reducers=1, records_per_map=20, seed=5)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    handoff = str(tmp_path / "handoff.json")
    server = ShuffleServer(
        engine,
        Config(dict(TEN_CFG, **{"uda.tpu.net.handoff.path": handoff})),
        host="127.0.0.1", port=0).start()
    ca = RemoteFetchClient("127.0.0.1", server.port, _tenant_cfg("a"))
    cb = RemoteFetchClient("127.0.0.1", server.port, _tenant_cfg("b"))
    try:
        ca.bind_job(JOB_A)
        cb.bind_job(JOB_A)  # same job id, DIFFERENT tenant
        assert sorted(_fetch_job(ca, JOB_A, 1)) == sorted(expected[0])
        assert sorted(_fetch_job(cb, JOB_A, 1)) == sorted(expected[0])
        mid = map_ids(JOB_A, 1)[0]
        marks = dict(server._marks)
        assert f"a|{JOB_A}|{mid}|0" in marks
        assert f"b|{JOB_A}|{mid}|0" in marks
    finally:
        ca.stop()
        cb.stop()
        server.stop()
        engine.stop()


def test_tenancy_off_stamps_nothing(tmp_path):
    """The off switch is the PR 4-13 data plane bit for bit: no
    registry, no scheduler state, empty tenant stamps, unkeyed-by-
    tenant watermarks."""
    make_mof_tree(str(tmp_path), JOB_A, num_maps=1, num_reducers=1,
                  records_per_map=10, seed=1)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    handoff = str(tmp_path / "handoff.json")
    server = ShuffleServer(
        engine, Config({"uda.tpu.net.handoff.path": handoff}),
        host="127.0.0.1", port=0).start()
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    try:
        assert _fetch_job(client, JOB_A, 1)
        mid = map_ids(JOB_A, 1)[0]
        assert f"|{JOB_A}|{mid}|0" in server._marks  # empty tenant key
        assert server.registry is None and server._sched is None
        assert metrics.get("tenant.sched.grants") == 0
    finally:
        client.stop()
        server.stop()
        engine.stop()


def test_introspection_carries_tenancy_block(two_job_supplier):
    _, _, server, _ = two_job_supplier
    client = RemoteFetchClient("127.0.0.1", server.port,
                               _tenant_cfg("a"))
    try:
        client.bind_job(JOB_A)
        snap = server._stats_snapshot()
        assert snap["tenancy"]["scheduler"]["total"] == \
            server._sched.total
        jobs = snap["tenancy"]["registry"]["jobs"]
        assert any(j["tenant"] == "a" and j["job"] == JOB_A
                   for j in jobs)
    finally:
        client.stop()


def test_fenced_epoch_is_terminal_through_merge_manager(two_job_supplier):
    """The reduce-side contract end to end: a MergeManager whose
    client binds a FENCED epoch fails into FallbackSignal without
    burning the retry/backoff budget — TenantError is terminal in the
    Segment state machine (a registry refusal cannot be retried into
    legality)."""
    from uda_tpu.merger import HostRoutingClient, MergeManager
    from uda_tpu.utils.errors import FallbackSignal

    expected_a, _, server, _ = two_job_supplier
    # the successor attempt fences epoch 2 in
    fencer = RemoteFetchClient("127.0.0.1", server.port,
                               _tenant_cfg("a", **{
                                   "uda.tpu.tenant.epoch": 2}))
    cfg = _tenant_cfg("a", **{"uda.tpu.tenant.epoch": 1,
                              "uda.tpu.fetch.retries": 5,
                              "mapred.rdma.fetch.retry.backoff.ms": 500})
    router = HostRoutingClient(config=cfg)
    mm = MergeManager(router, "uda.tpu.RawBytes", cfg)
    maps = [(f"127.0.0.1:{server.port}", m) for m in map_ids(JOB_A, 3)]
    try:
        fencer.bind_job(JOB_A)
        t0 = time.monotonic()
        with pytest.raises(FallbackSignal) as ei:
            mm.run(JOB_A, maps, 0, lambda b: None)
        # terminal, not retried: no retry counters, no 500 ms backoffs
        assert isinstance(ei.value.cause, TenantError)
        assert metrics.get("fetch.retries") == 0
        assert time.monotonic() - t0 < 3.0
    finally:
        router.stop()
        mm.stop()
        fencer.stop()


# -- the abusive-tenant rung (chaos) -----------------------------------------

@pytest.mark.faults
def test_abusive_tenant_degrades_only_itself(two_job_supplier):
    """The isolation contract under injected faults: tenant 'abuser'
    is armed with tenant.validate errors (every REQ of its jobs draws
    a typed TenantError) while tenant 'victim' runs the same daemon
    concurrently — the victim's job completes byte-correct with zero
    faults, and the abuser lands in the scheduler's penalty box."""
    expected_a, expected_b, server, _ = two_job_supplier
    abuser = RemoteFetchClient("127.0.0.1", server.port,
                               _tenant_cfg("abuser"))
    victim = RemoteFetchClient("127.0.0.1", server.port,
                               _tenant_cfg("victim"))
    with failpoints.scoped("tenant.validate=error:match:abuser"):
        try:
            abuser.bind_job(JOB_A)
            victim.bind_job(JOB_B)
            out = {}
            errs = {}

            def run_victim():
                out["b"] = _fetch_job(victim, JOB_B, 3)

            def run_abuser():
                for mid in map_ids(JOB_A, 3):
                    res = _fetch_sync(abuser, ShuffleRequest(
                        JOB_A, mid, 0, 0, 1 << 20))
                    errs.setdefault("a", []).append(res)

            tv = threading.Thread(target=run_victim)
            ta = threading.Thread(target=run_abuser)
            tv.start()
            ta.start()
            tv.join(20)
            ta.join(20)
            # the abuser's every request failed typed
            assert all(isinstance(r, TenantError) for r in errs["a"])
            # the victim is byte-correct and untouched by the faults
            assert sorted(out["b"]) == sorted(expected_b[0])
        finally:
            abuser.stop()
            victim.stop()
    # the repeated faults boxed the abuser (threshold default 4; three
    # maps x validate fire once per REQ -> note_fault per error)
    assert metrics.get("tenant.rejected") == 0  # failpoint, not registry
    assert metrics.get("failpoint.tenant.validate") >= 3
    # victim served zero errors and the credit pool drained clean
    _await(lambda: server._sched.free == server._sched.total)


def test_wdrr_inline_grant_deepens_existing_debt():
    # review hardening (round 5): a debtor's uncontended inline draw
    # stays granted (work conservation) but the byte debt keeps
    # growing — it cannot be laundered by arriving one-at-a-time into
    # free credits
    sched = CreditScheduler(1, quantum=float(1 << 10))
    conn = _Conn()
    assert sched.admit("big", (conn, ("big", 0)), cost=1 << 10)
    assert sched.admit("big", (conn, ("big", 1)), cost=1 << 20) is False
    sched.release("big")
    while not sched.grant_parked():
        pass                                 # force-serve books debt
    debt0 = sched._tenants["big"].deficit
    assert debt0 < 0
    sched.release("big")
    assert sched.admit("big", (conn, ("big", 2)), cost=1 << 20) is True
    assert sched._tenants["big"].deficit == debt0 - (1 << 20)
