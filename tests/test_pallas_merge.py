"""Pallas merge-path kernel vs host oracle (interpret mode on CPU)."""

import numpy as np
import pytest

from uda_tpu.ops import pallas_merge

pytestmark = pytest.mark.slow  # interpret-mode Pallas kernels


def _sorted_run(n, w, num_keys, seed, dup_rate=0.0):
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, 2**32, size=(n, w), dtype=np.uint32)
    if dup_rate:
        # force many duplicate keys to exercise tie-breaking
        rows[:, :num_keys] = rng.integers(0, 4, size=(n, num_keys),
                                          dtype=np.uint32)
    order = np.lexsort(tuple(rows[:, c] for c in reversed(range(num_keys))))
    return rows[order]


def _host_merge(a, b, num_keys):
    # stable merge: A rows before B rows on equal keys
    cat = np.concatenate([a, b])
    src = np.concatenate([np.zeros(len(a), np.int64),
                          np.ones(len(b), np.int64)])
    idx = np.concatenate([np.arange(len(a)), np.arange(len(b))])
    keys = tuple(cat[:, c] for c in reversed(range(num_keys)))
    order = np.lexsort((idx, src) + keys)
    return cat[order]


@pytest.mark.parametrize("na,nb", [(300, 500), (512, 512), (1, 1000),
                                   (1000, 1), (7, 5), (1024, 1024)])
def test_merge_pair_matches_host(na, nb):
    num_keys, w = 3, 6
    a = _sorted_run(na, w, num_keys, seed=na)
    b = _sorted_run(nb, w, num_keys, seed=nb + 10_000)
    got = np.asarray(pallas_merge.merge_sorted_pair(
        a, b, num_keys, tile=256, interpret=True))
    want = _host_merge(a, b, num_keys)
    assert got.shape == want.shape
    assert (got == want).all()


def test_merge_pair_duplicate_keys_stable():
    num_keys, w = 2, 4
    a = _sorted_run(400, w, num_keys, seed=1, dup_rate=1.0)
    b = _sorted_run(300, w, num_keys, seed=2, dup_rate=1.0)
    got = np.asarray(pallas_merge.merge_sorted_pair(
        a, b, num_keys, tile=128, interpret=True))
    want = _host_merge(a, b, num_keys)
    assert (got == want).all()


def test_merge_pair_empty_side():
    a = _sorted_run(50, 4, 2, seed=3)
    empty = np.zeros((0, 4), np.uint32)
    out = np.asarray(pallas_merge.merge_sorted_pair(a, empty, 2,
                                                    interpret=True))
    assert (out == a).all()
    out2 = np.asarray(pallas_merge.merge_sorted_pair(empty, a, 2,
                                                     interpret=True))
    assert (out2 == a).all()


def test_merge_splits_diagonals():
    num_keys = 1
    a = np.asarray([[1], [3], [5], [7]], np.uint32)
    b = np.asarray([[2], [4], [6], [8]], np.uint32)
    splits = np.asarray(pallas_merge.merge_splits(a, b, 2, num_keys))
    # merged: 1 2 | 3 4 | 5 6 | 7 8 -> A rows before each tile: 0,1,2,3
    assert splits.tolist() == [0, 1, 2, 3]
    # ties: A first
    a2 = np.asarray([[5], [5]], np.uint32)
    b2 = np.asarray([[5], [5]], np.uint32)
    s2 = np.asarray(pallas_merge.merge_splits(a2, b2, 2, 1))
    assert s2.tolist() == [0, 2]


def test_pallas_tile_power_of_two_guard():
    a = np.zeros((4, 4), np.uint32)
    with pytest.raises(ValueError):
        pallas_merge.merge_sorted_pair(a, a, 2, tile=384)


def test_merge_pair_max_width_31():
    # W=31 fits: record words occupy rows 0..30, tie-break at row 31
    a = _sorted_run(40, 31, 2, seed=7)
    b = _sorted_run(30, 31, 2, seed=8)
    got = np.asarray(pallas_merge.merge_sorted_pair(a, b, 2,
                                                    interpret=True))
    assert (got == _host_merge(a, b, 2)).all()
    with pytest.raises(ValueError):
        pallas_merge.merge_sorted_pair(
            np.zeros((4, 32), np.uint32), np.zeros((4, 32), np.uint32), 2,
            interpret=True)


def test_merge_pair_two_phase_matches_default():
    a = _sorted_run(700, 7, 3, seed=11, dup_rate=1.0)
    b = _sorted_run(500, 7, 3, seed=12, dup_rate=1.0)
    d = np.asarray(pallas_merge.merge_sorted_pair(a, b, 3, interpret=True))
    t = np.asarray(pallas_merge.merge_sorted_pair(a, b, 3, interpret=True,
                                                  two_phase=True))
    np.testing.assert_array_equal(d, t)


def test_merge_pair_keys8_matches_default():
    # the keys-only merge + row gather must be byte-identical to the
    # full-width pass, duplicate keys (stability) included
    a = _sorted_run(700, 7, 3, seed=13, dup_rate=1.0)
    b = _sorted_run(500, 7, 3, seed=14, dup_rate=1.0)
    d = np.asarray(pallas_merge.merge_sorted_pair(a, b, 3, interpret=True))
    k = np.asarray(pallas_merge.merge_sorted_pair(a, b, 3, interpret=True,
                                                  keys8=True))
    np.testing.assert_array_equal(d, k)


def test_merge_pair_keys8_wide_records():
    # keys8 has no 31-word width limit: 40-word records merge fine
    a = _sorted_run(96, 40, 2, seed=15)
    b = _sorted_run(64, 40, 2, seed=16)
    got = np.asarray(pallas_merge.merge_sorted_pair(a, b, 2, keys8=True,
                                                    interpret=True))
    assert (got == _host_merge(a, b, 2)).all()
    # 7 keys still fit (rows 0-6 + tie-break at 7); 8 do not
    got7 = np.asarray(pallas_merge.merge_sorted_pair(a, b, 7, keys8=True,
                                                     interpret=True))
    assert (got7 == _host_merge(a, b, 7)).all()
    import pytest

    with pytest.raises(ValueError, match="num_keys"):
        pallas_merge.merge_sorted_pair(a, b, 8, keys8=True, interpret=True)
