"""Observability layer: labeled counters/gauges/histograms, the span
tree, StatsReporter deltas/rates, the metrics-name lint, the Xprof
device-trace hook, and the end-to-end acceptance run (JSONL stream +
span-tree trace out of a real bridge-driven shuffle)."""

import importlib.util
import io
import json
import os
import threading

import pytest

from uda_tpu.utils.metrics import Metrics, device_trace, metrics
from uda_tpu.utils.stats import StatsReporter, telemetry_block


def test_counters_and_timer_spans():
    m = Metrics()
    m.record_spans = True
    m.add("fetched_bytes", 100)
    m.add("fetched_bytes", 50)
    with m.timer("merge"):
        pass
    snap = m.snapshot()
    assert snap["fetched_bytes"] == 150
    assert snap["merge_time"] >= 0
    assert [s["name"] for s in m.spans] == ["merge"]
    m.reset()
    assert m.snapshot() == {} and m.spans == []


def test_chrome_trace_export(tmp_path):
    m = Metrics()
    m.record_spans = True
    with m.timer("phase_a"):
        pass
    out = tmp_path / "trace.json"
    m.export_chrome_trace(str(out))
    events = json.loads(out.read_text())["traceEvents"]
    assert events and events[0]["name"] == "phase_a"
    assert events[0]["ph"] == "X" and events[0]["dur"] >= 0


# -- labeled counters / gauges / histograms ----------------------------------


def test_labeled_counters_accumulate_total_and_series():
    m = Metrics()
    m.add("fetch.bytes", 100, supplier="hostA")
    m.add("fetch.bytes", 50, supplier="hostB")
    m.add("fetch.bytes", 25, supplier="hostA")
    assert m.get("fetch.bytes") == 175  # unlabeled total always advances
    assert m.get("fetch.bytes", supplier="hostA") == 125
    assert m.get("fetch.bytes", supplier="hostB") == 50
    snap = m.snapshot()
    assert snap["fetch.bytes{supplier=hostA}"] == 125
    assert snap["fetch.bytes{supplier=hostB}"] == 50


def test_gauges_set_and_add():
    m = Metrics()
    m.gauge("arena.slots_in_use", 3)
    assert m.get_gauge("arena.slots_in_use") == 3
    m.gauge_add("fetch.on_air", 1)
    m.gauge_add("fetch.on_air", 1)
    m.gauge_add("fetch.on_air", -1)
    assert m.get_gauge("fetch.on_air") == 1
    m.gauge("fetch.on_air", 7, host="h1")
    assert m.get_gauge("fetch.on_air", host="h1") == 7
    assert m.gauges_snapshot()["fetch.on_air{host=h1}"] == 7


def test_histogram_percentiles():
    m = Metrics(stats=True)
    for v in range(1, 101):  # 1..100, uniform
        m.observe("fetch.latency_ms", float(v))
    s = m.histogram_summaries()["fetch.latency_ms"]
    assert s["count"] == 100 and s["sum"] == 5050
    assert s["min"] == 1 and s["max"] == 100
    # power-of-two buckets: estimates land within the containing bucket
    assert 32 <= s["p50"] <= 64
    assert 64 <= s["p95"] <= 100
    assert 64 <= s["p99"] <= 100
    assert s["p50"] <= s["p95"] <= s["p99"]


def test_histogram_labels_make_series():
    m = Metrics(stats=True)
    m.observe("fetch.latency_ms", 5.0, supplier="a")
    m.observe("fetch.latency_ms", 7.0, supplier="b")
    hs = m.histogram_summaries()
    assert hs["fetch.latency_ms"]["count"] == 2  # base series sees all
    assert hs["fetch.latency_ms{supplier=a}"]["count"] == 1


def test_disabled_stats_record_nothing():
    m = Metrics()  # default: histograms + spans off
    m.observe("fetch.latency_ms", 5.0)
    assert m.histogram_summaries() == {}
    with m.timer("merge"):
        pass
    assert m.spans == []  # no span append on the disabled path
    s = m.start_span("x")
    s.end()
    assert m.spans == [] and m.current_span() is None
    # counters stay live regardless
    m.add("fetch.bytes", 1)
    assert m.get("fetch.bytes") == 1


def test_enable_disable_spans_idempotent_and_reset_pristine():
    m = Metrics()
    m.enable_spans()
    m.enable_spans()  # idempotent
    assert m.record_spans
    with m.timer("merge"):
        pass
    m.add("fetch.bytes", 9, supplier="s")
    m.gauge("fetch.on_air", 2)
    m.enable_stats()
    m.observe("fetch.latency_ms", 1.0)
    m.reset()
    assert m.snapshot() == {} and m.spans == []
    assert m.gauges_snapshot() == {} and m.histogram_summaries() == {}
    assert not m.record_spans  # reset restores the pristine default
    m.disable_spans()
    m.disable_spans()  # idempotent
    assert not m.record_spans


# -- span tree ---------------------------------------------------------------


def test_span_tree_parent_child_across_threads(tmp_path):
    m = Metrics()
    m.enable_spans()
    with m.span("reduce_task", job="j1", reduce=0) as root:
        with m.timer("fetch"):
            fetch = m.current_span()
            assert fetch is not None and fetch.parent_id == root.span_id
            # explicit parent propagation onto a foreign thread (the
            # transport completion thread pattern)
            child = m.start_span("fetch.segment", parent=fetch,
                                 map="m_000001", supplier="hostA")

            def finish_on_other_thread():
                child.end(status="ok")

            t = threading.Thread(target=finish_on_other_thread)
            t.start()
            t.join()
        # adopting a span on a worker (use_span) parents nested timers
        def worker():
            with m.use_span(root):
                with m.timer("overlap_stage"):
                    pass

        t2 = threading.Thread(target=worker)
        t2.start()
        t2.join()
    by_name = {s["name"]: s for s in m.spans}
    assert by_name["reduce_task"]["parent"] is None
    assert by_name["fetch"]["parent"] == by_name["reduce_task"]["id"]
    seg = by_name["fetch.segment"]
    assert seg["parent"] == by_name["fetch"]["id"]
    assert seg["attrs"]["supplier"] == "hostA"
    assert seg["attrs"]["status"] == "ok"  # end-time attr merged
    assert by_name["overlap_stage"]["parent"] == by_name["reduce_task"]["id"]
    # one trace id spans the whole tree
    assert len({s["trace"] for s in m.spans}) == 1
    # chrome export carries the ids + attrs in args
    out = tmp_path / "t.json"
    m.export_chrome_trace(str(out))
    events = {e["name"]: e for e in
              json.loads(out.read_text())["traceEvents"]}
    assert events["fetch.segment"]["args"]["map"] == "m_000001"
    assert events["fetch.segment"]["args"]["parent_id"] == \
        events["fetch"]["args"]["span_id"]
    assert events["fetch.segment"]["args"]["trace_id"] == \
        events["reduce_task"]["args"]["trace_id"]


# -- StatsReporter -----------------------------------------------------------


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_stats_reporter_deltas_and_rates_with_fake_clock():
    m = Metrics()
    clock = FakeClock()
    out = io.StringIO()
    rep = StatsReporter(m, interval_s=1.0, out=out, clock=clock)
    m.add("fetch.bytes", 10_000_000)
    m.add("merge.records", 5000)
    clock.advance(2.0)
    rec1 = rep.report_once()
    assert rec1["interval_s"] == 2.0
    assert rec1["rates"]["fetch_mb_s"] == pytest.approx(5.0)
    assert rec1["rates"]["merge_records_s"] == pytest.approx(2500.0)
    assert rec1["rates"]["retry_per_s"] == 0.0
    # second interval: only the DELTA counts
    m.add("fetch.bytes", 1_000_000)
    m.add("fetch.retries", 4, supplier="s")
    clock.advance(4.0)
    rec2 = rep.report_once()
    assert rec2["rates"]["fetch_mb_s"] == pytest.approx(0.25)
    assert rec2["rates"]["retry_per_s"] == pytest.approx(1.0)
    # the JSONL stream has one parseable record per line
    lines = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert len(lines) == 2
    assert lines[1]["counters"]["fetch.retries{supplier=s}"] == 4


def test_stats_reporter_final_record_carries_parity_trio():
    m = Metrics()
    out = io.StringIO()
    rep = StatsReporter(m, out=out, clock=FakeClock())
    with m.timer("fetch"):
        pass
    rep.stop(final=True)
    final = json.loads(out.getvalue().splitlines()[-1])
    assert final["final"] is True
    for name in ("total_wait_mem_time", "total_fetch_time",
                 "total_merge_time"):
        assert name in final["counters"]
    assert final["counters"]["total_fetch_time"] == \
        final["counters"]["fetch_time"]
    rep.stop(final=False)  # idempotent


def test_telemetry_block_shape():
    m = Metrics(stats=True)
    m.add("emit.bytes", 10)
    m.observe("fetch.latency_ms", 2.0)
    blk = telemetry_block(m)
    assert blk["counters"]["emit.bytes"] == 10
    assert blk["counters"]["total_merge_time"] == 0.0  # trio always there
    assert blk["histograms"]["fetch.latency_ms"]["count"] == 1


def test_stats_progress_line_routes_through_uda_stats_logger():
    from uda_tpu.utils.logging import get_logger

    root_msgs, seen = [], []
    root = get_logger()
    stats_log = get_logger("uda.stats")
    old_sink = root.sink
    root.set_sink(lambda lvl, msg: (root_msgs.append(msg),
                                    seen.append(lvl)))
    try:
        stats_log.set_level(0)  # silence ONLY the stats stream
        rep = StatsReporter(Metrics(), out=io.StringIO(),
                            clock=FakeClock())
        rep.report_once()
        assert not root_msgs  # progress line silenced independently
        stats_log.set_level(4)
        rep.report_once()
        assert any("shuffle stats:" in m for m in root_msgs)
    finally:
        root.set_sink(old_sink)
        stats_log.clear_level()


# -- metrics-name lint (CI gate) ---------------------------------------------


def test_metrics_names_lint():
    here = os.path.dirname(os.path.abspath(__file__))
    script = os.path.join(here, os.pardir, "scripts",
                          "check_metrics_names.py")
    spec = importlib.util.spec_from_file_location("check_metrics_names",
                                                  script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    violations = mod.check()
    assert violations == [], "\n".join(
        f"{f}:{ln}: {name}: {why}" for f, ln, name, why in violations)


# -- end-to-end acceptance: bridge shuffle with UDA_TPU_STATS=1 --------------


def test_observability_end_to_end(tmp_path, monkeypatch):
    """ISSUE 2 acceptance: a bridge-driven shuffle with UDA_TPU_STATS=1
    produces (a) a JSONL stream whose final record has the reference
    trio + per-supplier labeled fetch counters, (b) a Chrome trace whose
    fetch spans are children of the reduce-task root with supplier/map
    attrs, and (c) a GET_STATS pull that round-trips as JSON."""
    from tests.helpers import make_mof_tree, map_ids
    from tests.test_bridge import Harness
    from uda_tpu.bridge import Cmd, UdaBridge, form_cmd

    jsonl = tmp_path / "stats.jsonl"
    monkeypatch.setenv("UDA_TPU_STATS", "1")
    monkeypatch.setenv("UDA_TPU_STATS_JSONL", str(jsonl))
    job = "jobObs"
    make_mof_tree(str(tmp_path), job, 4, 1, 40, seed=71)
    harness = Harness(str(tmp_path))
    bridge = UdaBridge()
    bridge.start(True, ["-w", "4", "-s", "64"], harness)
    try:
        bridge.do_command(form_cmd(
            Cmd.INIT, [job, "0", "4", "uda.tpu.RawBytes"]))
        for i, mid in enumerate(map_ids(job, 4)):
            bridge.do_command(form_cmd(Cmd.FETCH,
                                       [f"host{i % 2}", job, mid, "0"]))
        bridge.do_command(form_cmd(Cmd.FINAL, []))
        assert harness.fetch_over.wait(timeout=30)
        # GET_STATS round-trips while the bridge is live
        stats = json.loads(bridge.do_command(form_cmd(Cmd.GET_STATS, [])))
        assert "counters" in stats
        bridge.do_command(form_cmd(Cmd.EXIT, []))  # final record + stop
        assert bridge._stats is None  # EXIT tore the reporter down
        assert not harness.failures, harness.failures
    finally:
        if bridge._stats is not None:  # only on assertion failure above
            bridge._stats.stop(final=False)

    # (a) JSONL stream, final record: parity trio + labeled series
    records = [json.loads(ln) for ln in
               jsonl.read_text().splitlines() if ln.strip()]
    finals = [r for r in records if r.get("final")]
    assert finals, "no final-flagged stats record"
    counters = finals[-1]["counters"]
    for name in ("total_wait_mem_time", "total_fetch_time",
                 "total_merge_time"):
        assert name in counters
    assert counters["total_fetch_time"] > 0
    labeled = sorted(k for k in counters
                     if k.startswith("fetch.bytes{supplier="))
    assert labeled == ["fetch.bytes{supplier=host0}",
                       "fetch.bytes{supplier=host1}"]
    assert counters["fetch.bytes"] == sum(counters[k] for k in labeled)

    # (b) span tree: fetch.segment spans -> fetch -> reduce_task root
    spans = {s["id"]: s for s in metrics.spans}
    roots = [s for s in spans.values() if s["name"] == "reduce_task"]
    assert len(roots) == 1 and roots[0]["parent"] is None
    segs = [s for s in spans.values() if s["name"] == "fetch.segment"]
    assert len(segs) == 4
    for s in segs:
        assert s["attrs"]["supplier"] and s["attrs"]["map"]
        # walk to the root through parent ids
        node, hops = s, 0
        while node["parent"] is not None and hops < 10:
            node = spans[node["parent"]]
            hops += 1
        assert node is roots[0]
    trace = tmp_path / "trace.json"
    metrics.export_chrome_trace(str(trace))
    events = json.loads(trace.read_text())["traceEvents"]
    assert any(e["name"] == "fetch.segment"
               and e["args"].get("supplier") for e in events)


# -- device trace hook -------------------------------------------------------


def test_device_trace_noop_without_config(monkeypatch):
    monkeypatch.delenv("UDA_TPU_XPROF", raising=False)
    ran = []
    with device_trace():
        ran.append(1)
    assert ran == [1]


def test_device_trace_captures_profile(tmp_path):
    # on the CPU test backend jax.profiler works; the hook must run the
    # block and leave a profile directory behind
    import jax
    import jax.numpy as jnp

    with device_trace(str(tmp_path)):
        jnp.arange(8).sum().block_until_ready()
    produced = list(tmp_path.rglob("*"))
    assert produced, "no profile artifacts written"


def test_device_trace_survives_profiler_failure(tmp_path):
    # a second concurrent trace normally raises inside start_trace; the
    # hook must degrade to a no-op instead of failing the job
    import jax

    jax.profiler.start_trace(str(tmp_path / "outer"))
    try:
        ran = []
        with device_trace(str(tmp_path / "inner")):
            ran.append(1)
        assert ran == [1]
    finally:
        jax.profiler.stop_trace()
