"""Counters/spans/trace export + the Xprof device-trace hook."""

import json

from uda_tpu.utils.metrics import Metrics, device_trace


def test_counters_and_timer_spans():
    m = Metrics()
    m.record_spans = True
    m.add("fetched_bytes", 100)
    m.add("fetched_bytes", 50)
    with m.timer("merge"):
        pass
    snap = m.snapshot()
    assert snap["fetched_bytes"] == 150
    assert snap["merge_time"] >= 0
    assert [s["name"] for s in m.spans] == ["merge"]
    m.reset()
    assert m.snapshot() == {} and m.spans == []


def test_chrome_trace_export(tmp_path):
    m = Metrics()
    m.record_spans = True
    with m.timer("phase_a"):
        pass
    out = tmp_path / "trace.json"
    m.export_chrome_trace(str(out))
    events = json.loads(out.read_text())["traceEvents"]
    assert events and events[0]["name"] == "phase_a"
    assert events[0]["ph"] == "X" and events[0]["dur"] >= 0


def test_device_trace_noop_without_config(monkeypatch):
    monkeypatch.delenv("UDA_TPU_XPROF", raising=False)
    ran = []
    with device_trace():
        ran.append(1)
    assert ran == [1]


def test_device_trace_captures_profile(tmp_path):
    # on the CPU test backend jax.profiler works; the hook must run the
    # block and leave a profile directory behind
    import jax
    import jax.numpy as jnp

    with device_trace(str(tmp_path)):
        jnp.arange(8).sum().block_until_ready()
    produced = list(tmp_path.rglob("*"))
    assert produced, "no profile artifacts written"


def test_device_trace_survives_profiler_failure(tmp_path):
    # a second concurrent trace normally raises inside start_trace; the
    # hook must degrade to a no-op instead of failing the job
    import jax

    jax.profiler.start_trace(str(tmp_path / "outer"))
    try:
        ran = []
        with device_trace(str(tmp_path / "inner")):
            ran.append(1)
        assert ran == [1]
    finally:
        jax.profiler.stop_trace()
