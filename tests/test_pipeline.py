"""Staged fetch->decompress->pack->stage pipeline (ISSUE 9): the
bounded stage pool + merge consumer must be byte-identical to the
serial staging twin on every engine/compression/spool combination,
drain cleanly (no leaked in-flight budget bytes) when a fault lands
mid-pipeline, and bound in-flight bytes under a slow consumer."""

import io
import threading
import time

import numpy as np
import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.compress import DecompressingClient, get_codec
from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.merger.emitter import FramedEmitter
from uda_tpu.merger.overlap import OverlappedMerger
from uda_tpu.merger.streaming import RunStore
from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.mofserver.writer import MOFWriter
from uda_tpu.ops import merge as merge_ops
from uda_tpu.ops import sort as sort_ops
from uda_tpu.utils import comparators
from uda_tpu.utils.budget import STAGE_INFLIGHT_FLOOR_MB, stage_inflight_cap
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import FallbackSignal
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.ifile import IFileReader, RecordBatch, crack, write_records
from uda_tpu.utils.metrics import metrics

KT = "uda.tpu.RawBytes"


def _batch(recs):
    return crack(write_records(recs))


def _rand_recs(seed, n, dup_every=5, key_bytes=6):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        k = rng.bytes(key_bytes) if i % dup_every else b"dupkey"
        recs.append((k, rng.bytes(20)))
    return recs


def _finish_bytes(batches, pipeline, engine="host", spool=False,
                  tmp=None, stagers=2):
    store = RunStore([str(tmp)], tag="pipetest") if spool else None
    kt = comparators.get_key_type(KT)
    om = OverlappedMerger(kt, 16, engine=engine, run_store=store,
                          stagers=stagers if pipeline else 1,
                          pipeline=pipeline, inflight_bytes=8 << 20)
    out = io.BytesIO()
    for i, b in enumerate(batches):
        om.feed(i, b)
    emitter = FramedEmitter(1 << 14)
    if spool:
        om.finish_streaming(
            emitter, lambda blk: out.write(bytes(blk)),
            expected_records=sum(b.num_records for b in batches))
    else:
        om.emit_stream(batches, emitter, lambda blk: out.write(bytes(blk)))
    return out.getvalue()


# -- byte-identity: pipelined vs serial staging ------------------------------

def test_pipeline_identity_host_engine():
    batches = [_batch(_rand_recs(s, 60 + 11 * s)) for s in range(7)]
    a = _finish_bytes(batches, pipeline=False)
    b = _finish_bytes(batches, pipeline=True)
    assert a == b and len(a) > 0


def test_pipeline_identity_out_of_order_feed():
    # completion order never decides anything: feed in a scrambled
    # order on BOTH paths, results stay identical to in-order serial
    batches = [_batch(_rand_recs(40 + s, 50)) for s in range(6)]
    kt = comparators.get_key_type(KT)
    want = merge_ops.merge_batches(batches, kt, 16)
    om = OverlappedMerger(kt, 16, engine="host", pipeline=True, stagers=3)
    for i in (4, 0, 5, 2, 1, 3):
        om.feed(i, batches[i])
    got = om.finish(batches)
    assert list(got.iter_records()) == list(want.iter_records())
    assert om.stats["pipeline"]


def test_pipeline_identity_spool(tmp_path):
    batches = [_batch(_rand_recs(s, 80)) for s in range(5)]
    a = _finish_bytes(batches, False, spool=True, tmp=tmp_path)
    b = _finish_bytes(batches, True, spool=True, tmp=tmp_path)
    assert a == b and len(a) > 0


@pytest.mark.slow
def test_pipeline_identity_pallas_engine():
    batches = [_batch(_rand_recs(70 + s, 30)) for s in range(4)]
    a = _finish_bytes(batches, pipeline=False, engine="pallas")
    b = _finish_bytes(batches, pipeline=True, engine="pallas")
    assert a == b and len(a) > 0


def test_pipeline_identity_overflow_keys():
    # oversize keys disable the fast path on both paths identically
    pre = b"Q" * 17
    batches = [_batch([(pre + b"z", b"v0"), (b"a", b"v1")]),
               _batch([(pre + b"b", b"v2"), (b"c", b"v3")])]
    a = _finish_bytes(batches, pipeline=False)
    b = _finish_bytes(batches, pipeline=True)
    assert a == b and len(a) > 0


def _compressed_run(tmp_path, cfg_extra):
    codec = get_codec("zlib")
    rng = np.random.default_rng(11)
    job = "jobPC"
    writer = MOFWriter(str(tmp_path / f"c{len(cfg_extra)}"), job,
                       codec=codec)
    for m in range(4):
        recs = sorted((rng.bytes(8), rng.bytes(24)) for _ in range(120))
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])
    cfg = Config({"mapred.rdma.buf.size": 8, **cfg_extra})
    engine = DataEngine(DirIndexResolver(str(tmp_path /
                                             f"c{len(cfg_extra)}")), cfg)
    try:
        mm = MergeManager(DecompressingClient(LocalFetchClient(engine),
                                              codec), KT, cfg)
        blocks = []
        mm.run(job, writer.map_ids, 0, lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    return b"".join(blocks)


def test_pipeline_identity_compressed_e2e(tmp_path):
    a = _compressed_run(tmp_path, {"uda.tpu.stage.pipeline": False})
    b = _compressed_run(tmp_path, {"uda.tpu.stage.pipeline": True,
                                   "uda.tpu.stage.pool": 2})
    assert a == b and len(a) > 0


# -- merge-path split + buffer pool (the pipeline's merge half) --------------

def _sorted_rows(rng, n, k=5):
    r = rng.integers(0, 4, (n, k)).astype(np.uint32)  # heavy ties
    order = np.lexsort(tuple(r[:, c] for c in range(k - 1, -1, -1)))
    return np.ascontiguousarray(r[order])


def test_merge_split_point_is_the_stable_partition():
    rng = np.random.default_rng(5)
    a, b = _sorted_rows(rng, 37), _sorted_rows(rng, 53)
    ref = None
    nat = merge_ops.resolve_native_rows_merge()
    if nat is not None:
        ref = nat(a, b)
    for m in (0, 1, 17, 45, 89, 90):
        ia = merge_ops.merge_split_point(a, b, m)
        ib = m - ia
        assert 0 <= ia <= a.shape[0] and 0 <= ib <= b.shape[0]
        # partition invariants of the ties-to-a merge path
        if ia > 0 and ib < b.shape[0]:
            assert tuple(a[ia - 1]) <= tuple(b[ib])
        if ib > 0 and ia < a.shape[0]:
            assert tuple(b[ib - 1]) < tuple(a[ia])
    if ref is not None:
        out = np.empty_like(ref)
        assert merge_ops.merge_rows_split_into(a, b, out, parts=3)
        assert np.array_equal(out, ref)


def test_merge_rows_split_identical_across_part_counts():
    nat = merge_ops.resolve_native_rows_merge()
    if nat is None:
        pytest.skip("native library not built")
    rng = np.random.default_rng(9)
    for na, nb in ((0, 40), (40, 0), (1, 1), (1000, 3), (517, 801)):
        a, b = _sorted_rows(rng, na), _sorted_rows(rng, nb)
        ref = nat(a, b)
        for parts in (1, 2, 4):
            out = np.empty_like(ref)
            assert merge_ops.merge_rows_split_into(a, b, out, parts)
            assert np.array_equal(out, ref), (na, nb, parts)


def test_row_buffer_pool_reuses_and_bounds():
    pool = merge_ops.RowBufferPool("stage.bufpool")
    before = metrics.get("stage.buffer.reuses")
    a = pool.lease(100, 7)
    assert a.shape == (100, 7) and a.dtype == np.uint32
    pool.release(a)
    b = pool.lease(50, 7)  # smaller fits in the released buffer
    assert b.shape == (50, 7)
    assert metrics.get("stage.buffer.reuses") == before + 1
    pool.release(b)
    pool.release(None)  # tolerated: fallback paths pass leaseless runs
    for _ in range(pool.MAX_FREE + 4):
        pool.release(np.empty((8, 7), np.uint32))
    assert len(pool._free) == pool.MAX_FREE


# -- two-phase device sort + engine routing ----------------------------------

def test_two_phase_matches_resort():
    kt = comparators.get_key_type(KT)
    batches = [_batch(_rand_recs(s, 45 + 9 * s)) for s in range(6)]
    want = merge_ops.merge_batches(batches, kt, 16)
    got = merge_ops.merge_batches_two_phase(batches, kt, 16, engine="host")
    assert list(got.iter_records()) == list(want.iter_records())


def test_two_phase_overflow_falls_back():
    kt = comparators.get_key_type(KT)
    pre = b"W" * 20
    batches = [_batch([(pre + b"x", b"1"), (b"k", b"2")]),
               _batch([(pre + b"a", b"3")])]
    want = merge_ops.merge_batches(batches, kt, 16)
    got = merge_ops.merge_batches_two_phase(batches, kt, 16, engine="host")
    assert list(got.iter_records()) == list(want.iter_records())


def test_two_phase_empty_and_single():
    kt = comparators.get_key_type(KT)
    empty = RecordBatch.concat([])
    one = _batch(_rand_recs(3, 12))
    got = merge_ops.merge_batches_two_phase([empty, one], kt, 16,
                                            engine="host")
    want = merge_ops.merge_batches([empty, one], kt, 16)
    assert list(got.iter_records()) == list(want.iter_records())


def test_resolve_merge_mode_routing():
    assert merge_ops.resolve_merge_mode("off", 8) == "resort"
    assert merge_ops.resolve_merge_mode("on", 8) == "two_phase"
    assert merge_ops.resolve_merge_mode("on", 1) == "resort"  # nothing to merge
    # auto on the CPU backend keeps the single lexsort-shaped re-sort
    assert merge_ops.resolve_merge_mode("auto", 8) == "resort"
    with pytest.raises(Exception):
        merge_ops.resolve_merge_mode("sideways", 2)


def test_route_engine_honors_explicit_and_refines_auto():
    # explicit path is never overridden by batch-size routing
    assert sort_ops.route_engine(1 << 10, "gather") == "gather"
    # auto on CPU resolves like resolve_sort_path (no TPU steering here)
    assert sort_ops.route_engine(1 << 10, "auto") == \
        sort_ops.resolve_sort_path("auto")
    assert sort_ops.SMALL_BATCH_ROWS == 1 << 20
    for cc in sort_ops.CC_LADDER:
        assert cc in (8, 12, 23)


def test_route_engine_steers_deployed_gather_engine(monkeypatch):
    # the steering branch is live once a gather-bound fly-off winner
    # deploys as the auto default (UDA_TPU_SORT_PATH); the built-in
    # defaults are never gather-bound, so this is its reachability test
    monkeypatch.setattr(sort_ops, "DEPLOYED_SORT_PATH", "keys8f")
    monkeypatch.setattr(sort_ops.jax, "default_backend", lambda: "tpu")
    # big batch: the deployed winner is honored
    assert sort_ops.route_engine(1 << 22, "auto", lanes_ok=True) == "keys8f"
    # small batch on TPU: steered off the gather-bound engine
    assert sort_ops.route_engine(1 << 16, "auto",
                                 lanes_ok=True) == "carrychunk"
    # a lanes-incapable caller ignores the lanes-engine deploy rather
    # than failing (pure-XLA paths must survive any deploy value)
    assert sort_ops.resolve_sort_path("auto") == "carrychunk"
    # explicit path still honored at any size
    assert sort_ops.route_engine(1 << 16, "keys8f", lanes_ok=True) == "keys8f"
    # a typo'd deploy value fails loudly, not silently
    monkeypatch.setattr(sort_ops, "DEPLOYED_SORT_PATH", "sideways")
    with pytest.raises(ValueError):
        sort_ops.resolve_sort_path("auto")


def test_feed_racing_abort_releases_charge():
    # the narrow window: _charge() sees the abort flag unset, abort()
    # then completes fully (threads joined, queue reaped) before the
    # item lands in the queue — nothing would ever release its charge.
    # Forced deterministically by completing abort() inside _charge.
    kt = comparators.get_key_type(KT)
    b = _batch(_rand_recs(50, 10))
    om = OverlappedMerger(kt, 16, pipeline=True, inflight_bytes=1 << 20)
    orig_charge = om._charge

    def charge_then_abort(source):
        c = orig_charge(source)
        om.abort()  # runs to completion: workers joined, queues reaped
        return c

    om._charge = charge_then_abort
    om.feed(0, b)
    assert om._inflight == 0  # the post-put re-drain reaped the charge


def test_merge_split_reports_part_failure(monkeypatch):
    # a part whose native merge refuses (e.g. the .so momentarily
    # unloaded by a concurrent rebuild) leaves stale bytes in its out
    # slice — the split must return False so the caller falls back
    from uda_tpu import native

    calls = []

    def flaky(a, b, o):
        calls.append(o.shape[0])
        return len(calls) != 1  # exactly one part refuses

    monkeypatch.setattr(native, "merge_rows_native_into", flaky)
    monkeypatch.setattr(native, "available", lambda: True)
    a = np.zeros((64, 5), np.uint32)
    b = np.ones((64, 5), np.uint32)
    out = np.empty((128, 5), np.uint32)
    assert merge_ops.merge_rows_split_into(a, b, out, parts=2) is False
    assert len(calls) == 2  # both parts ran; one refusal fails the whole


# -- overflow comparator fast path -------------------------------------------

def test_overflow_lexsort_matches_comparator_path():
    kt = comparators.get_key_type(KT)
    assert comparators.uses_default_bytewise(kt)
    rng = np.random.default_rng(17)
    recs = []
    for i in range(120):
        # oversize keys with shared prefixes and length-tiebreak cases
        k = bytes([i % 3]) * (17 + int(rng.integers(0, 12)))
        recs.append((k, rng.bytes(8)))
    batch = _batch(recs)
    om = OverlappedMerger(kt, 16, engine="host")
    fast = om._overflow_order(batch, batch.num_records)

    class CmpOnly(type(kt)):
        def compare(self, a, b):  # force the cmp_to_key slow path
            return super().compare(a, b)

    cmp_kt = CmpOnly.__new__(CmpOnly)
    cmp_kt.__dict__.update(kt.__dict__)
    assert not comparators.uses_default_bytewise(cmp_kt)
    om_slow = OverlappedMerger(kt, 16, engine="host")
    om_slow.key_type = cmp_kt
    slow = om_slow._overflow_order(batch, batch.num_records)
    assert np.array_equal(fast, slow)


def test_stage_inflight_cap_resolution():
    # explicit MB wins
    cfg = Config({"uda.tpu.stage.inflight.mb": 64})
    assert stage_inflight_cap(cfg, 4, 1 << 20) == 64 << 20
    # auto: floor dominates small windows
    assert stage_inflight_cap(Config(), 4, 1 << 20) == \
        STAGE_INFLIGHT_FLOOR_MB << 20
    # auto: big windows scale 2x
    assert stage_inflight_cap(Config(), 512, 1 << 20) == 2 * 512 * (1 << 20)


# -- faults: a failure mid-pipeline drains clean -----------------------------

@pytest.mark.faults
def test_pipeline_pread_fault_drains_clean(tmp_path):
    """A storage fault mid-pipeline surfaces as FallbackSignal; the
    stage pool drains and the in-flight byte gauge returns to zero."""
    make_mof_tree(str(tmp_path), "jobPF", 6, 1, 40, seed=3)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    cfg = Config({"uda.tpu.stage.pipeline": True,
                  "uda.tpu.stage.pool": 2,
                  "uda.tpu.fetch.retries": 0})
    mm = MergeManager(LocalFetchClient(engine), KT, cfg)
    try:
        with failpoints.scoped("data_engine.pread=error:prob:0.7:seed:5"):
            with pytest.raises(FallbackSignal):
                mm.run("jobPF", map_ids("jobPF", 6), 0, lambda b: None)
    finally:
        engine.stop()
    om = mm._active_overlap
    assert om is not None and om._aborted
    for t in om._threads:
        t.join(timeout=10)
        assert not t.is_alive()
    assert om.stats["inflight_bytes"] == 0
    assert metrics.get_gauge("stage.inflight.bytes") == 0


@pytest.mark.faults
def test_pipeline_decompress_fault_drains_clean(tmp_path):
    """decompress.block mid-pipeline: the typed CompressionError is the
    stream's terminal error; abort drains workers, no budget leak."""
    codec = get_codec("zlib")
    rng = np.random.default_rng(23)
    job = "jobDF"
    writer = MOFWriter(str(tmp_path), job, codec=codec)
    for m in range(3):
        recs = sorted((rng.bytes(8), rng.bytes(24)) for _ in range(100))
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])
    cfg = Config({"uda.tpu.stage.pipeline": True,
                  "uda.tpu.fetch.retries": 0})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    mm = MergeManager(DecompressingClient(LocalFetchClient(engine), codec),
                      KT, cfg)
    try:
        with failpoints.scoped("decompress.block=error:once"):
            with pytest.raises(FallbackSignal):
                mm.run(job, writer.map_ids, 0, lambda b: None)
    finally:
        engine.stop()
    om = mm._active_overlap
    assert om is not None
    for t in om._threads:
        t.join(timeout=10)
        assert not t.is_alive()
    assert metrics.get_gauge("stage.inflight.bytes") == 0


# -- backpressure: bounded in-flight bytes under a slow consumer -------------

def test_pipeline_backpressure_bounds_inflight(monkeypatch):
    kt = comparators.get_key_type(KT)
    batches = [_batch(_rand_recs(s, 150)) for s in range(8)]
    one = OverlappedMerger._source_bytes(batches[0])
    assert one > 0
    cap = int(2.5 * one)  # at most two batches in flight

    real_insert = OverlappedMerger._insert

    def slow_insert(self, run):
        time.sleep(0.05)  # a slow device consumer
        real_insert(self, run)

    monkeypatch.setattr(OverlappedMerger, "_insert", slow_insert)
    om = OverlappedMerger(kt, 16, engine="host", pipeline=True, stagers=2,
                          inflight_bytes=cap)
    peak = {"v": 0}
    done = threading.Event()

    def watch():
        while not done.is_set():
            peak["v"] = max(peak["v"], om._inflight)
            time.sleep(0.002)

    w = threading.Thread(target=watch, daemon=True)
    w.start()
    before = metrics.get("stage.backpressure_events")
    for i, b in enumerate(batches):
        om.feed(i, b)  # blocks past the cap — that IS the test
    got = om.finish(batches)
    done.set()
    w.join(timeout=5)
    assert peak["v"] <= cap
    assert metrics.get("stage.backpressure_events") > before
    assert om._inflight == 0
    want = merge_ops.merge_batches(batches, kt, 16)
    assert list(got.iter_records()) == list(want.iter_records())


def test_pipeline_abort_releases_blocked_feed():
    kt = comparators.get_key_type(KT)
    batches = [_batch(_rand_recs(s, 120)) for s in range(4)]
    one = OverlappedMerger._source_bytes(batches[0])
    om = OverlappedMerger(kt, 16, engine="host", pipeline=True, stagers=1,
                          inflight_bytes=int(1.5 * one))
    # wedge the consumer (abort-responsive) so charges stay held
    hold = threading.Event()
    orig = OverlappedMerger._consume_run

    def wedge(self, staged):
        while not hold.is_set() and not self._aborted:
            time.sleep(0.01)
        orig(self, staged)

    om._consume_run = wedge.__get__(om)
    fed = threading.Event()

    def feeder():
        for i, b in enumerate(batches):
            om.feed(i, b)  # blocks on the budget
        fed.set()

    t = threading.Thread(target=feeder, daemon=True)
    t.start()
    time.sleep(0.3)
    assert not fed.is_set()  # feeder is blocked on the in-flight budget
    om.abort()
    hold.set()
    t.join(timeout=10)
    assert not t.is_alive()
    for th in om._threads:
        th.join(timeout=10)
        assert not th.is_alive()
    assert om._inflight == 0
    assert metrics.get_gauge("stage.inflight.bytes") == 0
