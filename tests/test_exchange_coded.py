"""Coded multicast exchange (ISSUE 15): the GF(2^8)-coded stage-B
path vs the hierarchical and flat bodies — byte-identity on every
workload shape, the coding-aware window plan, the multicast-model
ledger (coded + saved == uncoded payload), the uncodable-case
zero-overhead guarantees, and the in-round decode-failure fallback.

Runs on the conftest 8-virtual-device CPU mesh shaped (dcn=2, ici=4)
and (dcn=4, ici=2); the 4x4/8x8 shapes ride scripts/exchange_bench.py
(the shared subprocess driver, gated in ci.sh --quick and committed
as MULTICHIP_SCALE_r15.json)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from uda_tpu.parallel import (distributed_sort_step, make_mesh,
                              mesh_topology, plan_rounds,
                              shuffle_exchange, uniform_splitters)
from uda_tpu.parallel.exchange import resolve_exchange_mode
from uda_tpu.parallel.planner import CODED_CHUNK_ROWS, CODED_WIN_FACTOR
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.metrics import metrics

AXIS = "shuffle"
AXIS2 = ("dcn", AXIS)


def _mesh2(p=2, c=4):
    devs = np.asarray(jax.devices()[:p * c])
    return Mesh(devs.reshape(p, c), ("dcn", AXIS))


def _random_words(n, w, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 2**32, size=(n, w), dtype=np.uint32)


def _assert_rounds_identical(a, b):
    assert len(a) == len(b)
    for r, ((aw, ac), (bw, bc)) in enumerate(zip(a, b)):
        np.testing.assert_array_equal(np.asarray(ac), np.asarray(bc),
                                      err_msg=f"counts, round {r}")
        np.testing.assert_array_equal(np.asarray(aw), np.asarray(bw),
                                      err_msg=f"words, round {r}")


# -- the on-device GF kernel -------------------------------------------------

def test_gfjax_encode_decode_roundtrip():
    # the jitted field arithmetic must invert exactly — and agree with
    # the host codec's byte-level matmul on the word byte view
    import jax.numpy as jnp

    from uda_tpu.coding import gf256
    from uda_tpu.coding.gfjax import (coded_matrices, gf_decode_row,
                                      gf_matmul_words)

    rng = np.random.default_rng(3)
    for c in (2, 4, 8):
        enc, dec = coded_matrices(c)
        assert np.array_equal(gf256.inv_matrix(enc), dec)
        # enc @ dec == identity over the field
        eye = gf256.matmul(enc, dec)
        assert np.array_equal(eye, np.eye(c, dtype=np.uint8))
        blocks = rng.integers(0, 2**32, size=(c, 5, 3), dtype=np.uint32)
        coded = np.asarray(gf_matmul_words(enc, jnp.asarray(blocks)))
        # host reference: the same product on the byte view
        host = gf256.matmul(enc, blocks.view(np.uint8).reshape(c, -1))
        assert np.array_equal(coded.view(np.uint8).reshape(c, -1), host)
        for row in range(c):
            got = np.asarray(gf_decode_row(dec, jnp.int32(row),
                                           jnp.asarray(coded)))
            np.testing.assert_array_equal(got, blocks[row])


def test_gfjax_rejects_bad_block_counts():
    from uda_tpu.coding.gfjax import coded_matrices
    from uda_tpu.utils.errors import ConfigError

    for bad in (0, 1, 129):
        with pytest.raises(ConfigError):
            coded_matrices(bad)


# -- mode resolution ---------------------------------------------------------

def test_resolve_coded_mode_flags():
    mesh2 = _mesh2(2, 4)
    topo, hier, coded = resolve_exchange_mode(mesh2, AXIS2, "coded")
    assert topo.hierarchical and hier and coded
    assert topo.coded_capable
    # a 1-axis mesh degrades to the flat path — zero coded overhead,
    # not an error (unlike mode="hierarchical")
    mesh1 = make_mesh(8, AXIS)
    topo1, hier1, coded1 = resolve_exchange_mode(mesh1, AXIS, "coded")
    assert not hier1 and not coded1
    # auto never arms coding (opt-in dispatch)
    _, _, coded_auto = resolve_exchange_mode(mesh2, AXIS2, "auto")
    assert not coded_auto


def test_coded_on_flat_mesh_runs_plain():
    mesh1 = make_mesh(8, AXIS)
    words = _random_words(64, 2, seed=1)
    dest = (words[:, 0] % 8).astype(np.int32)
    metrics.reset()
    results, lay = shuffle_exchange(words, dest, mesh1, AXIS, capacity=8,
                                    mode="coded")
    assert not lay.coded and not lay.hierarchical and len(results) == 1
    assert metrics.get("exchange.dcn.coded.bytes") == 0.0


# -- byte-identity vs flat/hier across workload shapes -----------------------

@pytest.mark.parametrize("shape", [(2, 4), (4, 2)])
def test_coded_matches_flat_and_hier_uniform(shape):
    p, c = shape
    mesh = _mesh2(p, c)
    words = _random_words(8 * 32, 3, seed=2)
    words[:64, 0] = words[64:128, 0]        # duplicate keys ride along
    dest = (words[:, 1] % 8).astype(np.int32)
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=9,
                               mode="flat")
    hier, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=9,
                               mode="hierarchical")
    coded, lay = shuffle_exchange(words, dest, mesh, AXIS2, capacity=9,
                                  mode="coded")
    assert lay.coded and lay.hierarchical
    _assert_rounds_identical(coded, flat)
    _assert_rounds_identical(coded, hier)


def test_coded_skew_single_dest_identity_and_zero_overhead():
    # every record to ONE chip: single-destination pairs are uncodable
    # — the plan routes every window to the plain tile, the multiround
    # backlog drains identically, zero coded bytes ever booked
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 16, 2, seed=3)
    dest = np.zeros(8 * 16, np.int32)
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=4,
                               mode="flat")
    metrics.reset()
    coded, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=4,
                                mode="coded")
    assert len(coded) == 4
    _assert_rounds_identical(coded, flat)
    assert metrics.get("exchange.dcn.coded.bytes") == 0.0
    assert metrics.get("exchange.dcn.saved.bytes") == 0.0


def test_coded_empty_pod_edge():
    # every record lands in pod 0: pod 1 only sends; its pair codes
    # across pod 0's four member chips
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 24, 2, seed=4)
    dest = (words[:, 0] % 4).astype(np.int32)    # devices 0..3 = pod 0
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=24,
                               mode="flat")
    metrics.reset()
    coded, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=24,
                                mode="coded")
    _assert_rounds_identical(coded, flat)
    # only pod1 -> pod0 traffic; source-pod labels follow the charge
    assert metrics.get("exchange.dcn.messages") == 1.0
    if metrics.get("exchange.dcn.coded.bytes"):
        assert metrics.get("exchange.dcn.coded.bytes", pod=1) > 0
        assert metrics.get("exchange.dcn.coded.bytes", pod=0) == 0.0


def test_coded_capacity_one_many_rounds():
    # capacity 1 windows hold <= 1 row per (src, dst): blocks pad far
    # past their payload, the break-even guard declines every window
    # and the round ladder still drains byte-identically
    mesh = _mesh2(4, 2)
    words = _random_words(8 * 6, 2, seed=5)
    dest = (words[:, 0] % 8).astype(np.int32)
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=1,
                               mode="flat")
    metrics.reset()
    coded, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=1,
                                mode="coded")
    assert len(coded) > 1
    _assert_rounds_identical(coded, flat)
    assert metrics.get("exchange.dcn.coded.bytes") == 0.0


def test_coded_pod_local_zero_dcn():
    mesh = _mesh2(2, 4)
    n = 8 * 16
    words = _random_words(n, 2, seed=6)
    dest = np.zeros(n, np.int32)
    shard = n // 8
    for s in range(8):
        base = (s // 4) * 4
        dest[s * shard:(s + 1) * shard] = \
            base + words[s * shard:(s + 1) * shard, 1] % 4
    metrics.reset()
    coded, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=16,
                                mode="coded")
    assert metrics.get("exchange.dcn.bytes") == 0.0
    assert metrics.get("exchange.dcn.coded.bytes") == 0.0
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=16,
                               mode="flat")
    _assert_rounds_identical(coded, flat)


# -- the multicast-model ledger ----------------------------------------------

def test_coded_ledger_sum_and_acceptance_ratio():
    # THE acceptance gates at test scale: coded + saved == the uncoded
    # payload, and the uniform cross-pod charge is <= 0.67x
    # hierarchical (pod size 4 -> the plan's chunk cut approaches 4x)
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 32, 3, seed=7)
    dest = (words[:, 1] % 8).astype(np.int32)
    metrics.reset()
    shuffle_exchange(words, dest, mesh, AXIS2, capacity=32,
                     mode="hierarchical")
    hier_dcn = metrics.get("exchange.dcn.bytes")
    assert hier_dcn > 0
    metrics.reset()
    shuffle_exchange(words, dest, mesh, AXIS2, capacity=32,
                     mode="coded")
    coded_dcn = metrics.get("exchange.dcn.bytes")
    cb = metrics.get("exchange.dcn.coded.bytes")
    sb = metrics.get("exchange.dcn.saved.bytes")
    assert coded_dcn == cb > 0
    assert cb + sb == hier_dcn            # the ledger-sum invariant
    assert cb <= 0.67 * hier_dcn          # the acceptance figure
    # messages stay the pod-pair coalesced count
    assert metrics.get("exchange.dcn.messages") == 2.0


# -- the coding-aware window plan --------------------------------------------

def test_plan_rounds_coded_window_decision():
    mesh = _mesh2(2, 4)
    topo = mesh_topology(mesh, AXIS2)
    counts = np.zeros((8, 8), np.int64)
    # pair pod0 -> pod1: 4 destination chips, 8 rows each = 32 rows;
    # max block 8 -> L pads to 8, 8 * FACTOR <= 32 -> codable
    for j in range(4):
        counts[j, 4 + j] = 8
    plan = plan_rounds(counts, 8, topo, record_bytes=8,
                       hierarchical=True, coded=True)
    assert plan.coded
    w0 = plan.windows[0]
    assert w0.coded
    assert w0.l_rows == 8 and plan.coded_l_rows == 8
    assert w0.coded_rows == 8 and w0.saved_rows == 24
    assert w0.coded_rows + w0.saved_rows == w0.dcn_rows == 32
    assert w0.per_pod_coded == ((0, 8, 24),)
    # the coded stage-C broadcast charges ICI: (c-1) * c * L per pair
    assert w0.ici_rows_coded >= (4 - 1) * 4 * 8
    # chunk granularity: a 5-row max block pads to CODED_CHUNK_ROWS
    counts2 = np.zeros((8, 8), np.int64)
    counts2[0, 4] = 5
    counts2[1, 5] = 5
    counts2[2, 6] = 5
    counts2[3, 7] = 5
    plan2 = plan_rounds(counts2, 8, topo, record_bytes=8,
                        hierarchical=True, coded=True)
    w = plan2.windows[0]
    assert w.coded
    assert w.l_rows == -(-5 // CODED_CHUNK_ROWS) * CODED_CHUNK_ROWS


def test_plan_rounds_break_even_guard_declines_skew():
    mesh = _mesh2(2, 4)
    topo = mesh_topology(mesh, AXIS2)
    # one dominant destination chip: L ~ S, coding is a loss -> the
    # whole window rides plain (and a window with ONE uncodable pair
    # among codable ones also rides plain)
    counts = np.zeros((8, 8), np.int64)
    counts[0, 4] = 30
    counts[1, 5] = 2
    plan = plan_rounds(counts, 32, topo, record_bytes=8,
                       hierarchical=True, coded=True)
    assert plan.coded                      # dispatch armed...
    assert not plan.windows[0].coded       # ...but the window declined
    assert plan.coded_l_rows == 0
    assert CODED_WIN_FACTOR >= 2           # the guard the test pins
    # coded=False planning never sets coded fields (the hier baseline)
    plan_h = plan_rounds(counts, 32, topo, record_bytes=8,
                         hierarchical=True)
    assert not plan_h.coded and not plan_h.windows[0].coded


# -- distributed-step dispatch ------------------------------------------------

def test_multiround_coded_matches_flat_mesh():
    mesh1 = make_mesh(8, AXIS)
    mesh2 = _mesh2(2, 4)
    words = _random_words(1024, 4, seed=8)
    spl = uniform_splitters(8)
    metrics.reset()
    a = distributed_sort_step(words, spl, mesh2, AXIS2, capacity=32,
                              num_keys=2, multiround="always",
                              exchange_mode="coded")
    coded_bytes = metrics.get("exchange.dcn.coded.bytes")
    b = distributed_sort_step(words, spl, mesh1, AXIS, capacity=32,
                              num_keys=2, multiround="always")
    a.check()
    b.check()
    np.testing.assert_array_equal(np.asarray(a.words),
                                  np.asarray(b.words))
    assert coded_bytes > 0                # the windows really coded


def test_fused_step_coded_downgrades_to_staged_body():
    # the fused single-round program has no host plan: coded dispatch
    # runs the plain staged body, byte-identical to the flat mesh
    mesh1 = make_mesh(8, AXIS)
    mesh2 = _mesh2(2, 4)
    words = _random_words(1024, 4, seed=9)
    spl = uniform_splitters(8)
    metrics.reset()
    a = distributed_sort_step(words, spl, mesh2, AXIS2, capacity=256,
                              num_keys=2, exchange_mode="coded")
    b = distributed_sort_step(words, spl, mesh1, AXIS, capacity=256,
                              num_keys=2)
    a.check()
    b.check()
    np.testing.assert_array_equal(np.asarray(a.words),
                                  np.asarray(b.words))
    assert metrics.get("exchange.dcn.coded.bytes") == 0.0


# -- failure semantics -------------------------------------------------------

@pytest.mark.faults
def test_coded_decode_failpoint_falls_back_within_round():
    # a forced decode failure on a coded window must complete the
    # round byte-correct on the plain coalesced tile, count the
    # fallback, and book the PLAIN ledger for that window
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 32, 3, seed=10)
    dest = (words[:, 1] % 8).astype(np.int32)
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=32,
                               mode="flat")
    metrics.reset()
    shuffle_exchange(words, dest, mesh, AXIS2, capacity=32,
                     mode="hierarchical")
    hier_dcn = metrics.get("exchange.dcn.bytes")
    metrics.reset()
    with failpoints.scoped("exchange.decode=error"):
        coded, _ = shuffle_exchange(words, dest, mesh, AXIS2,
                                    capacity=32, mode="coded")
    _assert_rounds_identical(coded, flat)
    assert metrics.get("exchange.decode.fallbacks") >= 1.0
    assert metrics.get("exchange.dcn.coded.bytes") == 0.0
    assert metrics.get("exchange.dcn.bytes") == hier_dcn


@pytest.mark.faults
def test_coded_decode_failpoint_multiround_scatter():
    # same contract through the multiround accumulator path
    mesh1 = make_mesh(8, AXIS)
    mesh2 = _mesh2(2, 4)
    words = _random_words(1024, 4, seed=11)
    spl = uniform_splitters(8)
    metrics.reset()
    with failpoints.scoped("exchange.decode=error"):
        a = distributed_sort_step(words, spl, mesh2, AXIS2, capacity=32,
                                  num_keys=2, multiround="always",
                                  exchange_mode="coded")
    b = distributed_sort_step(words, spl, mesh1, AXIS, capacity=32,
                              num_keys=2, multiround="always")
    a.check()
    b.check()
    np.testing.assert_array_equal(np.asarray(a.words),
                                  np.asarray(b.words))
    assert metrics.get("exchange.decode.fallbacks") >= 1.0
    assert metrics.get("exchange.dcn.coded.bytes") == 0.0


@pytest.mark.faults
def test_coded_seeded_chaos_rung():
    # the run_chaos.sh coded rung shape: a seeded PROBABILISTIC decode
    # schedule — some windows code, some fall back mid-round — and the
    # exchange must stay byte-identical to flat with the ledger-sum
    # invariant holding for WHATEVER mix executed:
    #   dcn.bytes + saved.bytes == the uncoded payload (hier figure)
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 32, 3, seed=12)
    dest = (words[:, 1] % 8).astype(np.int32)
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=4,
                               mode="flat")
    metrics.reset()
    shuffle_exchange(words, dest, mesh, AXIS2, capacity=4,
                     mode="hierarchical")
    hier_dcn = metrics.get("exchange.dcn.bytes")
    metrics.reset()
    with failpoints.scoped("exchange.decode=error:prob:0.5:seed:12"):
        coded, _ = shuffle_exchange(words, dest, mesh, AXIS2,
                                    capacity=4, mode="coded")
    _assert_rounds_identical(coded, flat)
    assert (metrics.get("exchange.dcn.bytes")
            + metrics.get("exchange.dcn.saved.bytes")) == hier_dcn
