"""Native embedding shim (libuda_tpu_bridge.so): the C-ABI analogue of
the reference's JNI bridge, driven by a standalone C++ embedder — the
role of the reference's JNI mechanism tests (reference tests/jni*/README:
callback registration, DirectByteBuffer-style data hand-off, command
dispatch), but asserting the FULL reduce flow end-to-end."""

import os
import shutil
import subprocess

import pytest

from tests.helpers import make_mof_tree

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "uda_tpu", "native")


def _build() -> str:
    # toolchain presence is handled by pytestmark; with a toolchain, a
    # failing build is a regression, not a skip
    exe = os.path.join(NATIVE_DIR, "bridge_shim_test")
    r = subprocess.run(["make", "-C", NATIVE_DIR, "shim"],
                       capture_output=True, text=True, check=False)
    assert r.returncode == 0 and os.path.exists(exe), \
        f"bridge shim build failed: {r.stderr[-800:]}"
    return exe


pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("python3-config") is None,
    reason="no native toolchain")


def _run(exe, root, job, num_maps, reduce_id, upcall=False):
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(NATIVE_DIR))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # the embedded interpreter must target CPU in tests (the ambient
    # sitecustomize force-selects the TPU backend)
    env["UDA_TPU_PY_BOOTSTRAP"] = (
        'import jax; jax.config.update("jax_platforms", "cpu")')
    return subprocess.run(
        [exe, root, job, str(num_maps), str(reduce_id)] +
        (["upcall"] if upcall else []),
        capture_output=True, text=True, timeout=120, env=env, check=False)


def test_shim_full_reduce_flow(tmp_path):
    exe = _build()
    expected = make_mof_tree(str(tmp_path), "job_shim", 3, 2, 30, seed=7)
    for r in (0, 1):
        proc = _run(exe, str(tmp_path), "job_shim", 3, r)
        assert proc.returncode == 0, (proc.stdout, proc.stderr[-800:])
        out = proc.stdout.strip().split()
        assert out[0] == "MERGED" and out[2] == "RECORDS"
        assert int(out[3]) == len(expected[r])


def test_shim_get_path_uda_upcall_resolution(tmp_path):
    # no local dir in INIT: every first fetch resolves through the C
    # get_path_uda callback (index triples parsed by the embedder),
    # covering the C->Python IndexRecord marshalling
    exe = _build()
    expected = make_mof_tree(str(tmp_path), "job_up", 3, 2, 25, seed=9)
    proc = _run(exe, str(tmp_path), "job_up", 3, 1, upcall=True)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-800:])
    assert int(proc.stdout.strip().split()[3]) == len(expected[1])


def test_shim_missing_job_signals_failure(tmp_path):
    exe = _build()
    # no MOF tree: the fetch fails inside the engine; the shim must
    # surface it through failure_in_uda (exit code 8 in the driver),
    # not hang or crash
    proc = _run(exe, str(tmp_path), "job_absent", 2, 0)
    assert proc.returncode == 8, (proc.returncode, proc.stderr[-500:])
