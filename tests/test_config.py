"""Config registry: argv channel, overrides, pull channel (SURVEY §5)."""

import pytest

from uda_tpu.utils.config import Config, FLAGS
from uda_tpu.utils.errors import ConfigError


def test_defaults():
    cfg = Config()
    assert cfg.get("mapred.rdma.wqe.per.conn") == 256
    assert cfg.get("mapred.rdma.cma.port") == 9011
    assert cfg.get("mapred.rdma.buf.size") == 1024
    assert cfg.get("mapred.netmerger.merge.approach") == 1
    assert cfg.get("mapred.rdma.num.parallel.lpqs") == 0


def test_argv_channel():
    # the reference's getopt short options (C2JNexus.cc:43-137)
    cfg = Config.from_argv(["-w", "128", "-r", "9012", "-a", "2",
                            "-m", "0", "-g", "/tmp/l", "-t", "5", "-s", "512"])
    assert cfg.get("mapred.rdma.wqe.per.conn") == 128
    assert cfg.get("mapred.rdma.cma.port") == 9012
    assert cfg.get("mapred.netmerger.merge.approach") == 2
    assert cfg.get("uda.log.dir") == "/tmp/l"
    assert cfg.get("uda.log.level") == 5
    assert cfg.get("mapred.rdma.buf.size") == 512


def test_argv_errors():
    with pytest.raises(ConfigError):
        Config.from_argv(["-z", "1"])
    with pytest.raises(ConfigError):
        Config.from_argv(["-w"])


def test_pull_channel():
    pulled = {}

    def source(key, default):
        pulled[key] = default
        return "2048" if key == "mapred.rdma.buf.size" else ""

    cfg = Config(conf_source=source)
    assert cfg.get("mapred.rdma.buf.size") == 2048
    assert pulled["mapred.rdma.buf.size"] == "1024"  # default passed through
    # empty pull -> default
    assert cfg.get("mapred.rdma.cma.port") == 9011


def test_bool_coercion_and_unknown():
    cfg = Config({"mapred.rdma.developer.mode": "true"})
    assert cfg.get("mapred.rdma.developer.mode") is True
    with pytest.raises(ConfigError):
        cfg.get("no.such.key")
    assert cfg.get("no.such.key", default=7) == 7


def test_flag_inventory_complete():
    # every reference flag from SURVEY §5 is declared
    for key in [
        "mapred.rdma.wqe.per.conn", "mapred.rdma.cma.port",
        "mapred.netmerger.merge.approach", "mapred.rdma.buf.size",
        "mapred.rdma.buf.size.min", "mapred.rdma.shuffle.total.size",
        "mapred.job.shuffle.input.buffer.percent",
        "mapred.netmerger.hybrid.lpq.size", "mapred.rdma.num.parallel.lpqs",
        "mapred.rdma.compression.buffer.ratio",
        "mapred.uda.log.to.unique.file",
        "mapred.uda.provider.blocked.threads.per.disk",
        "mapred.rdma.developer.mode",
    ]:
        assert key in FLAGS, key
