"""Multi-chip exchange on the 8-device CPU mesh (the multi-node-without-
a-cluster capability the reference never had, SURVEY §4.5)."""

import numpy as np
import pytest

from uda_tpu.parallel import (distributed_sort_step, exchange_record_batches,
                              exchange_round, make_mesh, prepare_layout,
                              sample_splitters, shuffle_exchange,
                              uniform_splitters)
from uda_tpu.utils.errors import TransportError
from uda_tpu.utils.ifile import RecordBatch, crack, write_records

AXIS = "shuffle"


def _mesh():
    return make_mesh(8, AXIS)


def _random_words(n, w, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 2**32, size=(n, w), dtype=np.uint32)


def test_prepare_layout_counts():
    mesh = _mesh()
    n, p = 64 * 8, 8
    words = _random_words(n, 4)
    dest = (words[:, 0] % p).astype(np.int32)
    layout = prepare_layout(words, dest, mesh, AXIS)
    counts = np.asarray(layout.counts)
    assert counts.shape == (p, p)
    # row i = histogram of dest among device i's shard
    shard = n // p
    for i in range(p):
        want = np.bincount(dest[i * shard:(i + 1) * shard], minlength=p)
        assert counts[i].tolist() == want.tolist()


def test_single_round_exchange_regroups():
    mesh = _mesh()
    p, shard = 8, 32
    words = _random_words(p * shard, 3, seed=1)
    dest = (words[:, 1] % p).astype(np.int32)
    layout = prepare_layout(words, dest, mesh, AXIS)
    cap = int(layout.counts.max())
    recv, recv_counts = exchange_round(layout, cap, 0)
    recv = np.asarray(recv).reshape(p, p, cap, 3)   # [dst, src, slot, w]
    recv_counts = np.asarray(recv_counts).reshape(p, p)
    got = {d: [] for d in range(p)}
    for d in range(p):
        for s in range(p):
            for i in range(recv_counts[d, s]):
                got[d].append(tuple(recv[d, s, i]))
    for d in range(p):
        want = sorted(map(tuple, words[dest == d]))
        assert sorted(got[d]) == want


def test_multi_round_skew_all_to_one():
    mesh = _mesh()
    p, shard = 8, 16
    words = _random_words(p * shard, 2, seed=2)
    dest = np.zeros(p * shard, np.int32)  # extreme skew: everything to 0
    results, layout = shuffle_exchange(words, dest, mesh, AXIS, capacity=4)
    assert len(results) == 4  # 16 per bucket / capacity 4
    collected = []
    for recv, counts in results:
        recv = np.asarray(recv).reshape(p, p, 4, 2)
        counts = np.asarray(counts).reshape(p, p)
        for s in range(p):
            for i in range(counts[0, s]):
                collected.append(tuple(recv[0, s, i]))
        # nothing lands on devices != 0
        assert counts[1:].sum() == 0
    assert sorted(collected) == sorted(map(tuple, words))


def test_shuffle_exchange_max_rounds_guard():
    mesh = _mesh()
    words = _random_words(64, 2, seed=3)
    dest = np.zeros(64, np.int32)
    with pytest.raises(TransportError):
        shuffle_exchange(words, dest, mesh, AXIS, capacity=1, max_rounds=2)


def test_distributed_sort_step_total_order():
    mesh = _mesh()
    p = 8
    n = p * 128
    words = _random_words(n, 5, seed=4)  # 3 key words + 2 payload words
    splitters = uniform_splitters(p)
    res = distributed_sort_step(words, splitters, mesh, AXIS,
                                capacity=n // p, num_keys=3)
    res.check()
    out = np.asarray(res.words).reshape(p, -1, 5)
    nvalid = np.asarray(res.valid_counts).reshape(-1)
    rows = [out[d, :nvalid[d]] for d in range(p)]
    got = np.concatenate(rows)
    assert got.shape[0] == n
    # global total order on the 3 key words
    keys = [tuple(r[:3]) for r in got]
    assert keys == sorted(keys)
    # the full multiset of records survived
    assert sorted(map(tuple, got)) == sorted(map(tuple, words))


def test_distributed_sort_step_overflow_detected():
    mesh = _mesh()
    p = 8
    words = _random_words(p * 64, 2, seed=5)
    words[:, 0] = 0  # all keys in partition 0 -> massive skew
    res = distributed_sort_step(words, uniform_splitters(p), mesh, AXIS,
                                capacity=8, num_keys=1, multiround="never")
    with pytest.raises(TransportError):
        res.check()


@pytest.mark.slow
def test_distributed_sort_auto_multiround_completes_skew():
    # same massive skew, default policy: the multi-round backlog path
    # must drain it completely with capacity << bucket size
    mesh = _mesh()
    p = 8
    n = p * 64
    words = _random_words(n, 3, seed=15)
    words[:, 0] = 0  # every record to partition 0
    res = distributed_sort_step(words, uniform_splitters(p), mesh, AXIS,
                                capacity=8, num_keys=1)
    res.check()
    out = np.asarray(res.words).reshape(p, -1, 3)
    nvalid = np.asarray(res.valid_counts).reshape(-1)
    assert nvalid[0] == n and nvalid[1:].sum() == 0
    got = out[0, :n]
    assert sorted(map(tuple, got)) == sorted(map(tuple, words))
    keys = got[:, 0].tolist()
    assert keys == sorted(keys)


@pytest.mark.slow
def test_multiround_matches_fused_exactly():
    # on non-overflowing data, "always" must produce the same per-shard
    # valid rows as the fused single-round program (incl. duplicate-key
    # (src, arrival) stability)
    mesh = _mesh()
    p = 8
    n = p * 64
    words = _random_words(n, 4, seed=16)
    words[: n // 2, 0] = words[n // 2:, 0]  # duplicate first key words
    spl = uniform_splitters(p)
    fused = distributed_sort_step(words, spl, mesh, AXIS, capacity=n // p,
                                  num_keys=2, multiround="never")
    fused.check()
    multi = distributed_sort_step(words, spl, mesh, AXIS, capacity=16,
                                  num_keys=2, multiround="always")
    multi.check()
    fw = np.asarray(fused.words).reshape(p, -1, 4)
    mw = np.asarray(multi.words).reshape(p, -1, 4)
    fv = np.asarray(fused.valid_counts).reshape(-1)
    mv = np.asarray(multi.valid_counts).reshape(-1)
    assert fv.tolist() == mv.tolist()
    for d in range(p):
        np.testing.assert_array_equal(fw[d, :fv[d]], mw[d, :mv[d]])


@pytest.mark.slow
def test_lanes_payload_path_matches_gather_exactly():
    # the Pallas lanes engine (interpret mode on the CPU mesh) must
    # reproduce the gather path byte-for-byte: identical sort key
    # (masked key words, invalid flag) and identical equal-key arrival
    # order — including the invalid tail rows and the non-power-of-two
    # shard sizes that exercise the +inf lane padding
    mesh = _mesh()
    p = 8
    n = p * 48  # cap = n//p = 48, so each shard sorts p*cap = 384 rows:
    #             not a power of two -> exercises the +inf lane padding
    words = _random_words(n, 5, seed=23)
    words[: n // 2, 0] = words[n // 2:, 0]  # duplicate first key words
    spl = uniform_splitters(p)
    kw = dict(capacity=n // p, num_keys=2, multiround="never")
    gather = distributed_sort_step(words, spl, mesh, AXIS,
                                   payload_path="gather", **kw)
    gather.check()
    lanes = distributed_sort_step(words, spl, mesh, AXIS,
                                  payload_path="lanes", **kw)
    lanes.check()
    np.testing.assert_array_equal(np.asarray(gather.valid_counts),
                                  np.asarray(lanes.valid_counts))
    np.testing.assert_array_equal(np.asarray(gather.words),
                                  np.asarray(lanes.words))


@pytest.mark.slow
def test_lanes_payload_path_multiround_skew():
    # lanes engine under the windowed multi-round accumulator sort
    mesh = _mesh()
    p = 8
    n = p * 64
    words = _random_words(n, 3, seed=24)
    words[:, 0] = 0  # every record to partition 0
    res = distributed_sort_step(words, uniform_splitters(p), mesh, AXIS,
                                capacity=8, num_keys=1,
                                payload_path="lanes")
    res.check()
    out = np.asarray(res.words).reshape(p, -1, 3)
    nvalid = np.asarray(res.valid_counts).reshape(-1)
    assert nvalid[0] == n and nvalid[1:].sum() == 0
    got = out[0, :n]
    assert sorted(map(tuple, got)) == sorted(map(tuple, words))
    assert got[:, 0].tolist() == sorted(got[:, 0].tolist())


def test_sample_splitters_balance():
    rng = np.random.default_rng(6)
    # skewed distribution: half the mass near zero
    w0 = np.concatenate([rng.integers(0, 1000, 5000),
                         rng.integers(0, 2**32, 5000)]).astype(np.uint32)
    spl = sample_splitters(w0, 8)
    assert spl.shape == (7,)
    assert (np.sort(spl) == spl).all()
    dest = np.searchsorted(spl, w0, side="right")
    counts = np.bincount(dest, minlength=8)
    assert counts.max() < 0.35 * w0.size  # vs 0.625 with uniform splitters


def test_exchange_record_batches_host():
    def batch(recs):
        return crack(write_records(recs))

    by_dest = [
        [batch([(b"a", b"1")]), batch([(b"b", b"2")])],
        [batch([(b"c", b"3")]), batch([])],
    ]
    out = exchange_record_batches(by_dest)
    assert [list(b.iter_records()) for b in out] == [
        [(b"a", b"1"), (b"c", b"3")],
        [(b"b", b"2")],
    ]


def test_lanes_engines_type_check_with_check_vma():
    # the real (interpret=False) lanes path must trace clean under
    # shard_map's strict varying-manual-axes checker — the r4 wholesale
    # bypass is now scoped to interpret mode only (the Pallas
    # interpreter's own grid dynamic_slice mis-types; committed repro:
    # scripts/repro_check_vma.py). eval_shape runs the vma check at
    # trace time without compiling any Mosaic kernel, so this pins the
    # property on CPU.
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from uda_tpu.parallel import SHARD_MAP_NATIVE_VMA, shard_map

    if not SHARD_MAP_NATIVE_VMA:
        pytest.skip("vma checker needs a jax.shard_map with check_vma "
                    "(legacy check_rep has no pallas_call rule)")

    from uda_tpu.parallel import distributed as D

    mesh = make_mesh(8, AXIS)
    n = 8 * 4096  # multiple tiles per shard: the merge fori_loop engages
    spec = jax.ShapeDtypeStruct((n, 4), jnp.uint32)
    for eng in ("lanes", "lanes2", "keys8", "keys8f"):
        @partial(shard_map, mesh=mesh, in_specs=(P(AXIS),),
                 out_specs=P(AXIS), check_vma=True)
        def go(w, eng=eng):
            row = jnp.arange(w.shape[0], dtype=jnp.int32)
            return D._sort_valid_rows(w, row >= 0, 2, eng,
                                      interpret=False)

        out = jax.eval_shape(go, spec)
        assert out.shape == (n, 4)


@pytest.mark.slow
def test_two_axis_dcn_ici_mesh_matches_flat():
    # multi-pod shape: a (dcn=2, shuffle=4) mesh with rows sharded over
    # BOTH axes must produce byte-identical results to the flat 8-way
    # mesh (XLA routes the all_to_all per axis: ICI within a pod, DCN
    # across; the exchange logic only sees the linearized device index)
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    if devs.size < 8:
        pytest.skip("needs 8 devices")
    mesh1 = Mesh(devs[:8].reshape(8), (AXIS,))
    mesh2 = Mesh(devs[:8].reshape(2, 4), ("dcn", AXIS))
    words = _random_words(1024, 4, seed=29)
    spl = uniform_splitters(8)
    r1 = distributed_sort_step(words, spl, mesh1, AXIS, capacity=256,
                               num_keys=2)
    r1.check()
    r2 = distributed_sort_step(words, spl, mesh2, ("dcn", AXIS),
                               capacity=256, num_keys=2)
    r2.check()
    np.testing.assert_array_equal(np.asarray(r1.words),
                                  np.asarray(r2.words))
    np.testing.assert_array_equal(np.asarray(r1.valid_counts),
                                  np.asarray(r2.valid_counts))
    # skew across both axes engages the multi-round path
    skew = _random_words(512, 3, seed=30)
    skew[:, 0] = 0
    r3 = distributed_sort_step(skew, spl, mesh2, ("dcn", AXIS),
                               capacity=16, num_keys=1)
    r3.check()
    nv = np.asarray(r3.valid_counts).reshape(-1)
    assert nv[0] == 512 and nv[1:].sum() == 0


@pytest.mark.slow
@pytest.mark.parametrize("seed", [41, 42, 43])
def test_distributed_sort_randomized_boundaries(seed):
    # randomized shapes/capacities around the rounding boundaries the
    # dryrun's tiny shapes never reach: per-device rows not divisible
    # by p, capacities exactly at / one under the max bucket, duplicate
    # keys, and 1-record buckets
    rng = np.random.default_rng(seed)
    mesh = _mesh()
    p = 8
    n = p * int(rng.integers(50, 400))
    w = int(rng.integers(2, 7))
    nk = int(rng.integers(1, min(3, w) + 1))
    words = _random_words(n, w, seed=seed)
    if seed % 2:
        # heavy duplication stresses stability + splitter ties
        words[:, 0] = rng.integers(0, 5, size=n).astype(np.uint32) << 29
    spl = uniform_splitters(p)
    # max bucket size determines the exact-fit capacity
    dest = np.searchsorted(spl, words[:, 0], side="right")
    shard = n // p
    counts = np.zeros((p, p), np.int64)
    for s in range(p):
        np.add.at(counts[s], dest[s * shard:(s + 1) * shard], 1)
    maxb = int(counts.max())
    for cap in (maxb, max(1, maxb - 1), max(1, maxb // 3)):
        res = distributed_sort_step(words, spl, mesh, AXIS, capacity=cap,
                                    num_keys=nk)
        res.check()
        out = np.asarray(res.words).reshape(p, -1, w)
        nv = np.asarray(res.valid_counts).reshape(-1)
        got = np.concatenate([out[d, :nv[d]] for d in range(p)])
        assert got.shape[0] == n, (cap, got.shape)
        keys = [tuple(r[:nk]) for r in got]
        assert keys == sorted(keys), f"cap={cap}: unsorted"
        assert sorted(map(tuple, got)) == sorted(map(tuple, words)), \
            f"cap={cap}: multiset changed"


def test_distributed_sort_realistic_size():
    # 64K x 6-word records over the 8-device mesh — two orders of
    # magnitude beyond the dryrun's 1,024-record shapes; checks order,
    # multiset survival and the per-device partition totality contract
    # (every record lands on exactly the device its key range owns,
    # reference MOFServlet.cc:28-96)
    mesh = _mesh()
    p, n, w = 8, 1 << 16, 6
    words = _random_words(n, w, seed=55)
    spl = uniform_splitters(p)
    res = distributed_sort_step(words, spl, mesh, AXIS,
                                capacity=2 * n // (p * p), num_keys=3)
    res.check()
    out = np.asarray(res.words).reshape(p, -1, w)
    nv = np.asarray(res.valid_counts).reshape(-1)
    edges = np.concatenate([[0], spl.astype(np.uint64), [1 << 32]])
    rows = []
    for d in range(p):
        shard = out[d, :nv[d]]
        rows.append(shard)
        if nv[d]:
            assert shard[:, 0].astype(np.uint64).min() >= edges[d]
            assert shard[:, 0].astype(np.uint64).max() < edges[d + 1]
    got = np.concatenate(rows)
    assert got.shape[0] == n
    keys = got[:, :3]
    assert np.array_equal(
        keys, keys[np.lexsort((keys[:, 2], keys[:, 1], keys[:, 0]))])
    # true ROW multiset check (per-column sorts would miss payload words
    # swapped between records — the gather-bug corruption class)
    def by_rows(a):
        return a[np.lexsort(tuple(a[:, c] for c in range(w - 1, -1, -1)))]

    assert np.array_equal(by_rows(got), by_rows(words))


def test_lanes2_payload_path_matches_lanes():
    # the two-phase engine behind the distributed step must be
    # byte-identical to the one-phase lanes path
    mesh = _mesh()
    p = 8
    n = p * 48
    words = _random_words(n, 5, seed=67)
    words[: n // 2, 0] = words[n // 2:, 0]
    spl = uniform_splitters(p)
    kw = dict(capacity=n // p, num_keys=2, multiround="never")
    one = distributed_sort_step(words, spl, mesh, AXIS,
                                payload_path="lanes", **kw)
    two = distributed_sort_step(words, spl, mesh, AXIS,
                                payload_path="lanes2", **kw)
    one.check()
    two.check()
    np.testing.assert_array_equal(np.asarray(one.words),
                                  np.asarray(two.words))


def test_gather2_and_carrychunk_payload_paths_match_gather():
    # one minor-dim take / chunked carry sorts vs per-column takes:
    # byte-identical output for every permutation-apply strategy
    mesh = _mesh()
    p = 8
    n = p * 48
    words = _random_words(n, 5, seed=69)
    words[: n // 2, 0] = words[n // 2:, 0]
    spl = uniform_splitters(p)
    kw = dict(capacity=n // p, num_keys=2, multiround="never")
    a = distributed_sort_step(words, spl, mesh, AXIS,
                              payload_path="gather", **kw)
    a.check()
    for path in ("gather2", "carrychunk"):
        b = distributed_sort_step(words, spl, mesh, AXIS,
                                  payload_path=path, **kw)
        b.check()
        np.testing.assert_array_equal(np.asarray(a.words),
                                      np.asarray(b.words), err_msg=path)


def test_keys8_payload_path_matches_lanes():
    # the keys8 engine (keys-only cascade + one global payload gather)
    # behind the distributed step must be byte-identical to the
    # one-phase lanes path, duplicate keys included
    mesh = _mesh()
    p = 8
    n = p * 48
    words = _random_words(n, 5, seed=68)
    words[: n // 2, 0] = words[n // 2:, 0]
    spl = uniform_splitters(p)
    kw = dict(capacity=n // p, num_keys=2, multiround="never")
    one = distributed_sort_step(words, spl, mesh, AXIS,
                                payload_path="lanes", **kw)
    k8 = distributed_sort_step(words, spl, mesh, AXIS,
                               payload_path="keys8", **kw)
    one.check()
    k8.check()
    np.testing.assert_array_equal(np.asarray(one.words),
                                  np.asarray(k8.words))
