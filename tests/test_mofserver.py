"""Supplier side: index files, resolver cache, data engine chunk serving
(reference src/MOFServer/)."""

import os
import threading

import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.mofserver import (DataEngine, DirIndexResolver, ShuffleRequest,
                               read_index_file, write_index_file)
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import StorageError
from uda_tpu.utils.ifile import crack


def test_index_file_round_trip(tmp_path):
    path = str(tmp_path / "file.out.index")
    triples = [(0, 100, 100), (100, 250, 250), (350, 0, 2)]
    write_index_file(path, triples)
    recs = read_index_file(path, "/data/file.out")
    assert [(r.start_offset, r.raw_length, r.part_length) for r in recs] == triples
    assert all(r.path == "/data/file.out" for r in recs)


def test_index_file_corrupt(tmp_path):
    path = str(tmp_path / "bad.index")
    with open(path, "wb") as f:
        f.write(b"\x00" * 23)  # not a multiple of 24
    with pytest.raises(StorageError):
        read_index_file(path, "x")


def test_resolver_caches_lookup(tmp_path):
    make_mof_tree(str(tmp_path), "job1", num_maps=1, num_reducers=2,
                  records_per_map=10)
    calls = []
    inner = DirIndexResolver(str(tmp_path))
    orig = inner._lookup

    def counting(job, mapid):
        calls.append(mapid)
        return orig(job, mapid)

    inner._lookup = counting
    mid = map_ids("job1", 1)[0]
    a = inner.resolve("job1", mid, 0)
    b = inner.resolve("job1", mid, 1)
    assert len(calls) == 1  # first-fetch-only up-call (IndexInfo.cc:237-251)
    assert a.start_offset == 0 and b.start_offset > 0
    with pytest.raises(StorageError):
        inner.resolve("job1", mid, 5)


def test_data_engine_serves_partitions(tmp_path):
    expected = make_mof_tree(str(tmp_path), "job2", num_maps=3, num_reducers=2,
                             records_per_map=50)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    try:
        for r in range(2):
            got = []
            for mid in map_ids("job2", 3):
                res = engine.fetch(ShuffleRequest("job2", mid, r, 0, 1 << 20))
                assert res.is_last
                got += list(crack(res.data).iter_records())
            assert sorted(got) == sorted(expected[r])
    finally:
        engine.stop()


def test_data_engine_chunked_reads(tmp_path):
    make_mof_tree(str(tmp_path), "job3", num_maps=1, num_reducers=1,
                  records_per_map=100, val_bytes=100)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    try:
        mid = map_ids("job3", 1)[0]
        # fetch in small chunks and reassemble
        chunks = []
        offset = 0
        while True:
            res = engine.fetch(ShuffleRequest("job3", mid, 0, offset, 512))
            chunks.append(res.data)
            offset += len(res.data)
            if res.is_last:
                break
        assert offset == res.raw_length
        batch = crack(b"".join(chunks))
        assert batch.num_records == 100
    finally:
        engine.stop()


def test_data_engine_bad_offset(tmp_path):
    make_mof_tree(str(tmp_path), "job4", num_maps=1, num_reducers=1,
                  records_per_map=5)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    try:
        mid = map_ids("job4", 1)[0]
        with pytest.raises(StorageError):
            engine.fetch(ShuffleRequest("job4", mid, 0, 10**9, 512))
    finally:
        engine.stop()


def test_data_engine_concurrent(tmp_path):
    make_mof_tree(str(tmp_path), "job5", num_maps=8, num_reducers=4,
                  records_per_map=40)
    cfg = Config({"mapred.uda.provider.blocked.threads.per.disk": 4})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    errors = []

    def worker(r):
        try:
            for mid in map_ids("job5", 8):
                res = engine.fetch(ShuffleRequest("job5", mid, r, 0, 1 << 20))
                crack(res.data)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    engine.stop()
    assert not errors


def test_multi_root_resolution_and_per_disk_threads(tmp_path):
    """Map outputs spread across local dirs resolve (the reference's
    LocalDirAllocator search) and reader threads scale per disk
    (AsyncReaderManager.cc:16-50)."""
    from tests.helpers import make_mof_tree, map_ids
    from uda_tpu.mofserver import DataEngine, DirIndexResolver, ShuffleRequest
    from uda_tpu.utils.config import Config

    r1, r2 = tmp_path / "d0", tmp_path / "d1"
    make_mof_tree(str(r1), "jobMR", 2, 1, 10, seed=31)
    make_mof_tree(str(r2), "jobMR", 4, 1, 10, seed=31)
    # keep only maps 2..3 in r2 so each root holds a disjoint subset
    import shutil
    for mid in map_ids("jobMR", 2):
        shutil.rmtree(r2 / "jobMR" / mid)
    cfg = Config({"mapred.uda.provider.blocked.threads.per.disk": 2})
    engine = DataEngine(DirIndexResolver([str(r1), str(r2)]), cfg,
                        num_disks=2)
    try:
        assert engine._pool._max_workers == 4  # 2 threads x 2 disks
        for mid in map_ids("jobMR", 4):
            res = engine.fetch(ShuffleRequest("jobMR", mid, 0, 0, 1 << 20))
            assert res.is_last and len(res.data) > 0
    finally:
        engine.stop()


def test_chained_fetches_under_delay_failpoint_no_deadlock(tmp_path):
    """DataEngine.submit's docstring warns that blocking in completion
    callbacks can deadlock the pool. The fetch path's chained re-issue
    (a Segment's completion callback submitting its next chunk) must
    therefore stay non-blocking: with ONE pool thread, multi-chunk
    segments and a delay failpoint slowing every read, the whole fetch
    must still complete inside a bounded wall clock — a wedge here is
    the deadlock shape the warning describes."""
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.utils.failpoints import failpoints

    make_mof_tree(str(tmp_path), "jobDl", num_maps=4, num_reducers=1,
                  records_per_map=60, seed=41)
    cfg = Config({"mapred.uda.provider.blocked.threads.per.disk": 1,
                  "mapred.rdma.buf.size": 1,       # 1 KB -> many chunks
                  "mapred.rdma.wqe.per.conn": 4})  # window > pool threads
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    done = threading.Event()
    out = {}

    def fetch_everything():
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes", cfg)
        out["segs"] = mm.fetch_all("jobDl", map_ids("jobDl", 4), 0)
        done.set()

    t = threading.Thread(target=fetch_everything, daemon=True)
    try:
        with failpoints.scoped("data_engine.pread=delay:5"):
            t.start()
            assert done.wait(timeout=60), \
                "chained fetches deadlocked the 1-thread pool"
    finally:
        engine.stop()
    assert all(s.ready for s in out["segs"])
    assert sum(s.num_records for s in out["segs"]) == 240


@pytest.mark.faults
def test_sync_fetch_timeout_releases_admission_budget(tmp_path):
    """fetch() is deadline-bounded (derived from mapred.rdma.fetch.*)
    AND accounting-clean on both timeout shapes: a request cancelled
    while still QUEUED (its _serve never runs) must hand back its
    admission bytes and gauges, or repeated timeouts pin the read
    budget on an idle engine."""
    import time

    from uda_tpu.utils.failpoints import failpoints
    from uda_tpu.utils.metrics import metrics

    make_mof_tree(str(tmp_path), "job9", num_maps=1, num_reducers=1,
                  records_per_map=20)
    cfg = Config({"mapred.uda.provider.blocked.threads.per.disk": 1,
                  "mapred.rdma.fetch.attempt.timeout.ms": 200})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    assert engine.sync_fetch_timeout_s == pytest.approx(0.2)
    mid = map_ids("job9", 1)[0]
    try:
        with failpoints.scoped("data_engine.pread=delay:800"):
            # occupy the single reader thread...
            running = engine.submit(ShuffleRequest("job9", mid, 0, 0, 512))
            time.sleep(0.05)
            # ...so this one times out QUEUED and gets truly cancelled
            with pytest.raises(StorageError, match="did not complete"):
                engine.fetch(ShuffleRequest("job9", mid, 0, 0, 512))
            running.result(timeout=5.0)
        # the running read settled in _serve, the cancelled one in
        # fetch(): all admission state must be back to idle
        deadline = time.monotonic() + 5.0
        while engine._admitted_bytes and time.monotonic() < deadline:
            time.sleep(0.01)
        assert engine._admitted_bytes == 0
        assert metrics.get_gauge("supplier.read.bytes.on_air") == 0
        assert metrics.get_gauge("supplier.reads.on_air") == 0
        # and the engine is NOT spuriously "exhausted" afterwards —
        # probed with the ambient chaos-rung pread schedule pinned out
        # (this fetch asserts admission recovery, not fault recovery;
        # an injected error here would fail the wrong invariant)
        with failpoints.scoped(""):
            failpoints.disarm("data_engine.pread")
            res = engine.fetch(ShuffleRequest("job9", mid, 0, 0, 1 << 20))
        assert res.data
    finally:
        engine.stop()


def test_try_plan_unwinds_admission_on_open_failure(tmp_path):
    """The zero-copy fast path's charge must pair with an unwind: a
    cached index entry whose MOF was deleted underneath (job-cleanup
    race) fails the fd open AFTER admission — repeated failures must
    leave the read budget untouched, not leak it until the supplier
    wedges on 'read pool exhausted'."""
    job = "jobLeak"
    make_mof_tree(str(tmp_path), job, num_maps=1, num_reducers=1,
                  records_per_map=10, seed=1)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    mid = map_ids(job, 1)[0]
    req = ShuffleRequest(job, mid, 0, 0, 1 << 20)
    try:
        # warm the index cache (try_plan only fires on cache hits)
        engine.fetch(req)
        plan = engine.try_plan(req)
        assert plan is not None  # sanity: planable while the MOF exists
        plan.release()           # a live slice HOLDS its charge
        # engine-visible state back to idle before the breakage
        engine._fds.close_all()
        os.remove(os.path.join(str(tmp_path), job, mid, "file.out"))
        assert engine._admitted_bytes == 0
        for _ in range(3):
            with pytest.raises(OSError):
                engine.try_plan(req)
        assert engine._admitted_bytes == 0  # no leak, no wedge
    finally:
        engine.stop()
