"""The regression harness itself (scripts/regression) — the CI-gate
contract of the reference's cases/ wrapper (reference cases/uda.cases,
runRegression_2.sh): exit 0 + report on pass, nonzero on failure."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "regression", "run_regression.py")


def _run(tmp_path, workloads):
    return subprocess.run(
        [sys.executable, SCRIPT, "--size", "small", "--out", str(tmp_path),
         "--workloads", workloads],
        capture_output=True, text=True, timeout=300, check=False,
        cwd=REPO)


def test_harness_pass_produces_report(tmp_path):
    proc = _run(tmp_path, "secondary_sort,compressed_shuffle")
    assert proc.returncode == 0, proc.stderr[-800:]
    report = json.load(open(os.path.join(tmp_path, "results.json")))
    assert report["failed"] == []
    assert {r["workload"] for r in report["results"]} == {
        "secondary_sort", "compressed_shuffle"}
    assert all(r["status"] == "PASS" for r in report["results"])
    assert os.path.exists(os.path.join(tmp_path, "results.md"))


def test_harness_unknown_workload_errors(tmp_path):
    proc = _run(tmp_path, "not_a_workload")
    assert proc.returncode == 2
