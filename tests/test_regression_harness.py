"""The regression harness itself (scripts/regression) — the CI-gate
contract of the reference's cases/ wrapper (reference cases/uda.cases,
runRegression_2.sh): exit 0 + report on pass, nonzero on failure."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "regression", "run_regression.py")


def _run(tmp_path, workloads):
    return subprocess.run(
        [sys.executable, SCRIPT, "--size", "small", "--out", str(tmp_path),
         "--workloads", workloads],
        capture_output=True, text=True, timeout=300, check=False,
        cwd=REPO)


def test_harness_pass_produces_report(tmp_path):
    proc = _run(tmp_path, "secondary_sort,compressed_shuffle")
    assert proc.returncode == 0, proc.stderr[-800:]
    report = json.load(open(os.path.join(tmp_path, "results.json")))
    assert report["failed"] == []
    assert {r["workload"] for r in report["results"]} == {
        "secondary_sort", "compressed_shuffle"}
    assert all(r["status"] == "PASS" for r in report["results"])
    assert os.path.exists(os.path.join(tmp_path, "results.md"))


def test_push_streaming_workload_passes(tmp_path):
    # the ISSUE 19 rung: map outputs commit while the reducer drains,
    # gated on sortedness + record-multiset across the push/pull seam
    proc = _run(tmp_path, "push_streaming")
    assert proc.returncode == 0, proc.stderr[-800:]
    report = json.load(open(os.path.join(tmp_path, "results.json")))
    assert report["failed"] == []
    detail = report["results"][0]["detail"]
    assert detail["push_chunks"] > 0
    assert detail["push_adopted_bytes"] > 0


def test_harness_unknown_workload_errors(tmp_path):
    proc = _run(tmp_path, "not_a_workload")
    assert proc.returncode == 2


def test_analyzer_single_and_comparison(tmp_path):
    # the analizeTerasort.sh equivalent: tables from report JSONs
    def report(platform, wall, status="PASS"):
        return {"platform": platform, "size": "small", "results": [
            {"workload": "terasort", "rep": 0, "size": "small",
             "status": status, "wall_s": wall, "cpu_user_s": wall,
             "cpu_sys_s": 0.0, "max_rss_mb": 100.0, "detail": {},
             "error": ""}]}

    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(report("cpu", 4.0)))
    b.write_text(json.dumps(report("tpu", 2.0)))
    script = os.path.join(REPO, "scripts", "regression", "analyze.py")
    one = subprocess.run([sys.executable, script, str(a)],
                         capture_output=True, text=True, check=False)
    assert one.returncode == 0 and "| terasort | PASS | 4.00 |" in one.stdout
    cmp_ = subprocess.run([sys.executable, script, str(a), str(b)],
                          capture_output=True, text=True, check=False)
    assert cmp_.returncode == 0 and "2.00x" in cmp_.stdout  # tpu 2x faster
    # a failing run flips the exit code and is named
    b.write_text(json.dumps(report("tpu", 2.0, status="FAIL")))
    bad = subprocess.run([sys.executable, script, str(a), str(b)],
                         capture_output=True, text=True, check=False)
    assert bad.returncode == 1 and "FAILURES" in bad.stdout
    # a FAIL rep must not be masked by a faster PASS rep of the same
    # workload (the table keeps best-of, the gate scans every rep)
    rep = report("cpu", 1.0)
    slow_fail = dict(rep["results"][0], rep=1, wall_s=5.0, status="FAIL")
    rep["results"].append(slow_fail)
    a.write_text(json.dumps(rep))
    masked = subprocess.run([sys.executable, script, str(a)],
                            capture_output=True, text=True, check=False)
    assert masked.returncode == 1 and "rep1" in masked.stdout
