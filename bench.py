"""Benchmark: single-chip TeraSort shuffle+merge throughput.

Measures the flagship path of BASELINE.json config 2 — HBM-resident
TeraSort records, device shuffle+merge (stable lexicographic sort of
100-byte records by their 10-byte keys) — on whatever accelerator is
ambient (the driver runs this on one real TPU chip).

Protocol: data is TeraGen'd ON DEVICE (the deployment stages records
into HBM once; the host never holds record bytes). Each timed dispatch
runs K independent gen->sort->validate rounds inside ONE device program
(terasort.bench_step), so fixed per-dispatch host latency amortizes and
the number reflects sustained device throughput. Every round uses a
fresh PRNG stream (nothing cacheable) and is validated IN-GRAPH (order
violations + multiset checksum), which the host asserts on afterwards —
the validation cost is included in the measured time, making the figure
conservative.

Baseline: the reference's data plane tops out at FDR InfiniBand line
rate, 56 Gb/s ~= 6.8 GB/s per node (BASELINE.md: "beat FDR-InfiniBand
UDA shuffle+merge wall-clock"; the reference repo publishes no absolute
figures, SURVEY §6). vs_baseline = achieved GB/s / 6.8.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

BASELINE_GBPS = 6.8  # FDR IB line rate, the reference data plane ceiling
LOG2_RECORDS = 23    # 8M records x 100 B = 0.8 GB resident per round
ROUNDS_PER_DISPATCH = 4   # keeps remote-compile time bounded
DISPATCHES = 2


def main() -> None:
    from uda_tpu.models import terasort

    n = 1 << LOG2_RECORDS
    gb_per_dispatch = n * terasort.RECORD_BYTES * ROUNDS_PER_DISPATCH / 1e9

    # warmup/compile (int() forces host readback — on the tunneled axon
    # backend block_until_ready does NOT wait for device compute, so all
    # timing must synchronize through a scalar readback)
    viol, ck_in, ck_out = terasort.bench_step(jax.random.key(999), n,
                                              ROUNDS_PER_DISPATCH)
    assert int(viol) == 0

    best = float("inf")
    for i in range(DISPATCHES):
        t0 = time.perf_counter()
        viol, ck_in, ck_out = terasort.bench_step(jax.random.key(i), n,
                                                  ROUNDS_PER_DISPATCH)
        ok = (int(viol) == 0, np.uint32(ck_in) == np.uint32(ck_out))
        dt = time.perf_counter() - t0
        assert all(ok), f"validation failed: {ok}"
        best = min(best, dt)

    gbps = gb_per_dispatch / best
    print(json.dumps({
        "metric": "terasort_singlechip_shuffle_merge_gbps",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 3),
    }))


if __name__ == "__main__":
    main()
