"""Benchmark: single-chip TeraSort shuffle+merge throughput.

Measures the flagship path of BASELINE.json config 2 — HBM-resident
TeraSort records, device shuffle+merge (stable lexicographic sort of
100-byte records by their 10-byte keys) — on whatever accelerator is
ambient (the driver runs this on one real TPU chip).

Protocol: data is TeraGen'd ON DEVICE (the deployment stages records
into HBM once; the host never holds record bytes). Each timed dispatch
runs K independent gen->sort->validate rounds inside ONE device program
(terasort.bench_step), so fixed per-dispatch host latency (~75 ms on
the tunneled backend) amortizes and the number reflects sustained
device throughput. Every round uses a fresh PRNG stream (nothing
cacheable) and is validated IN-GRAPH (order violations + multiset
checksum), which the host asserts on afterwards — the validation cost
is included in the measured time, making the figure conservative.

Compile robustness: the fast "carry" program (payload rides the sort
network) can take very long to compile COLD on remote-compile backends
(XLA variadic-sort compile time grows superlinearly in operand count),
while the "gather" program always compiles in ~1 min. Each candidate is
compiled in a timed SUBPROCESS (``bench.py --probe <path>``) so a
pathological compile cannot hang the benchmark; results persist in the
uda_tpu compile cache (utils/compile_cache.py), so any path that ever
compiled — here or in a previous run — is picked up instantly.

Baseline: the reference's data plane tops out at FDR InfiniBand line
rate, 56 Gb/s ~= 6.8 GB/s per node (BASELINE.md: "beat FDR-InfiniBand
UDA shuffle+merge wall-clock"; the reference repo publishes no absolute
figures, SURVEY §6). vs_baseline = achieved GB/s / 6.8.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_GBPS = 6.8  # FDR IB line rate, the reference data plane ceiling
# 8M records x 100 B = 0.8 GB resident per round (override for smoke
# tests of the bench plumbing itself)
LOG2_RECORDS = int(os.environ.get("UDA_TPU_BENCH_LOG2", 23))
ROUNDS_PER_DISPATCH = 4   # amortizes the ~75 ms dispatch+readback cost
DISPATCHES = 2
# lanes-path sort tile; 4096 measured fastest on v5e (fewer merge
# passes at the same total stage count — scripts/profile_lanes.py:
# 0.85/1.07/1.18 GB/s at 1024/2048/4096); clamped so smoke-sized runs
# (UDA_TPU_BENCH_LOG2) still satisfy sort_lanes' n % tile == 0 contract
LANES_TILE = min(4096, 1 << LOG2_RECORDS)
# the keys8 cascade works on 8-row arrays, so VMEM admits much larger
# tiles (fewer merge passes); default 8192 pending a hardware sweep
# (scripts/profile_lanes.py sweeps 4096/8192/16384[/32768 for keys8f])
KEYS8_TILE = min(int(os.environ.get("UDA_TPU_BENCH_KEYS8_TILE", 8192)),
                 1 << LOG2_RECORDS)
# keys8f's slim layout halves merge-kernel VMEM, so a much larger tile
# (= fewer whole merge passes) is in play: when keys8f compiles at
# KEYS8_TILE, a SECOND fly-off candidate probes at this tile too
# (0 disables)
KEYS8F_TILE2 = min(int(os.environ.get("UDA_TPU_BENCH_KEYS8F_TILE2",
                                      32768)), 1 << LOG2_RECORDS)
# NB: the fly-off threads each candidate's timing tile explicitly as a
# (path, tile) tuple; _tile_for only provides the DEFAULT (what the
# probe subprocess compiles at via env, and what single-candidate runs
# time at)


def _tile_for(path: str) -> int:
    return KEYS8_TILE if path in ("keys8", "keys8f") else LANES_TILE
# run the Pallas kernels in interpret mode (CPU smoke runs of the lanes
# path; useless on TPU and at full size)
INTERPRET = os.environ.get("UDA_TPU_BENCH_INTERPRET") == "1"
# cold-compile budget per candidate path, seconds (warm = cache hit,
# returns in seconds regardless)
PROBE_TIMEOUT = float(os.environ.get("UDA_TPU_BENCH_PROBE_TIMEOUT", 600))
# Path order: "lanes" (the Pallas bitonic pipeline) first — it is the
# fast path AND the bounded-compile path (two Mosaic kernels regardless
# of n), so it is also the safe cold-compile bet. "gather" is the
# always-compilable XLA fallback.
# IMPORTANT: "carry" is opt-in. On remote-compile backends the 26-operand
# sort compile (a) can run for hours and (b) keeps running SERVER-SIDE
# after the client is killed, serializing every later compile in the
# session behind it — one failed carry probe poisons the whole service.
# Opt in with UDA_TPU_BENCH_TRY_CARRY=1 only where compiles are local
# (CPU) or known-fast.
# "lanes2" = the two-phase (keys-network + one in-kernel payload
# gather) variant: fastest when Mosaic lowers the dynamic lane gather,
# and the probe falls through in seconds when it does not. "keys8" =
# the whole cascade on an 8-row keys-only array + ONE global XLA
# payload gather (the same idea with the gather hoisted out of Mosaic —
# it lowers everywhere).
# Probe order = risk order: carrychunk FIRST — the measured champion
# (BENCH_HW_r05.json: 3.04 GB/s) with bounded compile — so a pool
# window that dies mid-sequence has already warmed the guaranteed-
# number engine's cache; gather2 (always-compilable runner-up) next;
# then the speculative Mosaic engines whose probes may burn budget.
PATHS = (("carrychunk", "gather2", "keys8f", "lanes2", "keys8", "lanes",
          "carry", "gather")
         if os.environ.get("UDA_TPU_BENCH_TRY_CARRY") == "1"
         else ("carrychunk", "gather2", "keys8f", "lanes2", "keys8",
               "lanes", "gather"))
# explicit candidate-list override (comma-separated), e.g. a short pool
# window where only the known-good path should be timed:
#   UDA_TPU_BENCH_PATHS=lanes python bench.py
# Path names come from the single source of truth in uda_tpu.ops.sort
# (safe at module scope: importing jax does not lock the platform —
# only the first device use does, after _enable_cache has re-applied
# any JAX_PLATFORMS override).
from uda_tpu.ops.sort import ALL_SORT_PATHS, BENCH_FLYOFF  # noqa: E402

if os.environ.get("UDA_TPU_BENCH_PATHS"):
    PATHS = tuple(p.strip()
                  for p in os.environ["UDA_TPU_BENCH_PATHS"].split(",")
                  if p.strip())
    bad = [p for p in PATHS if p not in ALL_SORT_PATHS]
    if bad or not PATHS:
        raise SystemExit(f"UDA_TPU_BENCH_PATHS: unknown or empty path "
                         f"list {bad or '(empty)'}; known: {ALL_SORT_PATHS}")
FLYOFF_PATHS = frozenset(BENCH_FLYOFF)


def _enable_cache() -> None:
    # The probe-warms-cache contract must hold on EVERY backend, CPU
    # included (compile_cache skips CPU unless explicitly opted in):
    # without it, a minutes-long carry compile in the probe would be
    # repaid in the main process, outside the probe's timeout guard.
    os.environ.setdefault("UDA_TPU_COMPILE_CACHE",
                          os.path.join(os.path.dirname(
                              os.path.abspath(__file__)), ".jax_cache"))
    from uda_tpu.utils import compile_cache

    # honor an explicit JAX_PLATFORMS over the deployment sitecustomize
    # (else a CPU smoke run hangs waiting on the TPU relay)
    compile_cache.apply_platform_env()
    compile_cache.enable()


def _compile_and_check(path: str) -> None:
    """Compile + smoke-run bench_step for `path` at the real benchmark
    shape (executables are shape-specialized, so probing a smaller n
    would warm the wrong cache entry). Checks BOTH gates: order
    violations AND the multiset checksum — a mis-lowered kernel that
    preserves order while corrupting/duplicating records (precedent:
    hardware pltpu.roll on negative shifts) must fail the probe, not
    the benchmark run."""
    _enable_cache()
    import jax
    import numpy as np

    from uda_tpu.models import terasort

    viol, ck_in, ck_out = terasort.bench_step(
        jax.random.key(999), 1 << LOG2_RECORDS, ROUNDS_PER_DISPATCH,
        path=path, tile=_tile_for(path), interpret=INTERPRET)
    assert int(viol) == 0
    assert np.uint32(ck_in) == np.uint32(ck_out), "checksum mismatch"


def _probe(path: str, timeout: float, extra_env=None,
           log_name: str = "") -> bool:
    """Compile `path` in a subprocess under a wall-clock cap.

    Failures must stay diagnosable after the fact: the subprocess runs
    with JAX_TRACEBACK_FILTERING=off and its FULL stderr persists to
    .bench_probe_<log_name or path>.log next to this file (the
    last-3-lines tail of a filtered JAX traceback is boilerplate,
    useless for debugging). Retries pass a distinct ``log_name`` so a
    prior failure's log survives the retry's success-path cleanup."""
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_TRACEBACK_FILTERING="off",
               **(extra_env or {}))
    log = os.path.join(here, f".bench_probe_{log_name or path}.log")
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe", path],
            cwd=here, env=env,
            timeout=None if timeout <= 0 else timeout,
            capture_output=True, text=True, check=False,
        )
        ok, stderr = proc.returncode == 0, proc.stderr or ""
        verdict = "ok" if ok else "failed"
    except subprocess.TimeoutExpired as e:
        ok = False
        err = e.stderr  # whatever the subprocess wrote before the kill
        stderr = (err.decode(errors="replace")
                  if isinstance(err, bytes) else err) or ""
        verdict = f"compile exceeded {timeout:.0f}s budget"
    dt = time.perf_counter() - t0
    print(f"# probe {path}: {verdict} in {dt:.0f}s", file=sys.stderr)
    if ok:
        # drop any stale failure log so post-hoc diagnosis never reads
        # a traceback that predates the code that fixed it
        if os.path.exists(log):
            os.remove(log)
    else:
        with open(log, "w") as f:
            f.write(stderr)
        for line in stderr.strip().splitlines()[-10:]:
            print(f"#   {line}", file=sys.stderr)
        print(f"#   full stderr: {log}", file=sys.stderr)
    return ok


def _backend_alive(timeout: float = 180.0) -> bool:
    """Cheap liveness gate: one tiny device op in a capped subprocess.
    A wedged accelerator pool hangs INSIDE client creation (observed on
    the tunneled backend: a stuck device claim blocks make_c_api_client
    forever), which would otherwise cost one full probe timeout PER
    candidate path before the bench could report anything."""
    # honor an explicit JAX_PLATFORMS like _enable_cache does
    from uda_tpu.utils.compile_cache import PLATFORM_PRELUDE

    code = (PLATFORM_PRELUDE +
            "import numpy as np, jax.numpy as jnp; "
            "x = jnp.asarray(np.arange(8)); assert int(x.sum()) == 28; "
            "print('alive')")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              timeout=timeout, capture_output=True,
                              text=True, check=False)
        return proc.returncode == 0 and "alive" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


_USAGE = """\
usage: python bench.py [--help] [--probe <path>]

Single-chip TeraSort shuffle+merge benchmark. Prints ONE JSON line:

  {"metric": "terasort_singlechip_shuffle_merge_gbps",
   "value": <GB/s>, "unit": "GB/s", "vs_baseline": <value/6.8>,
   "telemetry": {"counters": {...}, "gauges": {...},
                 "histograms": {<name>: {"count","sum","min","max",
                                         "p50","p95","p99"}, ...}}}

The "telemetry" block is the final metrics snapshot of the bench
process (uda_tpu.utils.stats.telemetry_block): counters always include
the reference-parity per-task trio total_wait_mem_time /
total_fetch_time / total_merge_time; histogram percentiles appear when
the run recorded samples (UDA_TPU_STATS=1 enables histograms+spans).
BENCH_*.json files across rounds stay directly diffable on this block.

The "small_batch" block is the interactive-traffic tier (2^16-2^19
rows): per size, the engine chosen by the batch-size-aware router
(uda_tpu.ops.sort.route_engine) and its measured GB/s — the take-ramp
regime the headline number cannot see.

env knobs: UDA_TPU_BENCH_LOG2 (records=2^N), UDA_TPU_BENCH_PATHS,
UDA_TPU_BENCH_PROBE_TIMEOUT, UDA_TPU_BENCH_INTERPRET=1,
UDA_TPU_BENCH_TRY_CARRY=1, UDA_TPU_BENCH_SMALL=0 (skip the
small-batch tier), UDA_TPU_XPROF=<dir> (device trace),
UDA_TPU_STATS=1 (host-side histograms/spans in the telemetry block).
"""


def main() -> None:
    if len(sys.argv) >= 2 and sys.argv[1] in ("--help", "-h"):
        print(_USAGE, end="")
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--probe":
        _compile_and_check(sys.argv[2])
        return

    if not _backend_alive():
        raise SystemExit(
            "backend liveness check failed: device op did not complete "
            "(accelerator pool unreachable or wedged); not probing")

    # Candidate selection: every fly-off engine that compiles enters a
    # measured fly-off and the FASTEST wins (compile success alone
    # would let a slowly-lowered variant shadow a faster one); the
    # slow-or-risky fallbacks ("gather": measured 0.3 GB/s; "carry":
    # pathological compile) are probed only when NO fly-off engine
    # compiles, first success wins.
    flyoff_variants = [p for p in PATHS if p in FLYOFF_PATHS]
    fallbacks = [p for p in PATHS if p not in FLYOFF_PATHS]
    candidates: list = []  # (path, tile) pairs
    for p in flyoff_variants:
        if _probe(p, PROBE_TIMEOUT):
            candidates.append((p, _tile_for(p)))
            if (p == "keys8f" and KEYS8F_TILE2
                    and KEYS8F_TILE2 != _tile_for(p)
                    and _probe(p, PROBE_TIMEOUT,
                               extra_env={"UDA_TPU_BENCH_KEYS8_TILE":
                                          str(KEYS8F_TILE2)},
                               log_name=f"keys8f_tile{KEYS8F_TILE2}")):
                # the big-tile variant joins as its OWN candidate: the
                # measured fly-off decides, never the guess
                candidates.append((p, KEYS8F_TILE2))
        elif p in ("keys8", "keys8f") and KEYS8_TILE != LANES_TILE:
            # the bigger keys8 tile is a bet pending the hardware
            # sweep; a failed compile must not drop the engine from
            # the fly-off — retry at the validated lanes tile, under a
            # DISTINCT log name so the big-tile failure log survives
            print(f"# {p} tile={KEYS8_TILE} failed; retrying at "
                  f"{LANES_TILE}", file=sys.stderr)
            if _probe(p, PROBE_TIMEOUT,
                      extra_env={"UDA_TPU_BENCH_KEYS8_TILE":
                                 str(LANES_TILE)},
                      log_name=f"{p}_tile{LANES_TILE}"):
                candidates.append((p, LANES_TILE))
    for path in fallbacks:
        if candidates:
            break
        if _probe(path, PROBE_TIMEOUT):
            candidates = [(path, _tile_for(path))]
    if not candidates:
        raise SystemExit("no bench path compiled within budget")

    _enable_cache()
    import jax
    import numpy as np

    from uda_tpu.models import terasort

    n = 1 << LOG2_RECORDS
    gb_per_dispatch = n * terasort.RECORD_BYTES * ROUNDS_PER_DISPATCH / 1e9

    def timed_dispatch(path, seed, tile):
        """One timed dispatch (int() forces host readback — on the
        tunneled axon backend block_until_ready does NOT wait for
        device compute, so all timing synchronizes through a scalar
        readback)."""
        t0 = time.perf_counter()
        viol, ck_in, ck_out = terasort.bench_step(jax.random.key(seed), n,
                                                  ROUNDS_PER_DISPATCH,
                                                  path=path, tile=tile,
                                                  interpret=INTERPRET)
        ok = (int(viol) == 0, np.uint32(ck_in) == np.uint32(ck_out))
        dt = time.perf_counter() - t0
        assert all(ok), f"validation failed on {path}@{tile}: {ok}"
        return dt

    if len(candidates) > 1:
        # warm each candidate first: the deciding dispatch must not
        # absorb one-time main-process costs (backend init, executable
        # deserialization, tracing) that would bias against whichever
        # candidate runs first
        timings = {}
        for p, tile in candidates:
            timed_dispatch(p, 999, tile)  # warmup
            timings[(p, tile)] = timed_dispatch(p, 998, tile)
        chosen = min(timings, key=timings.get)
        for (p, tile), dt in timings.items():
            print(f"# fly-off {p}@{tile}: {gb_per_dispatch/dt:.3f} GB/s",
                  file=sys.stderr)
    else:
        chosen = candidates[0]
        timed_dispatch(chosen[0], 999, chosen[1])  # warmup (cache hit)

    # UDA_TPU_XPROF=<dir> captures a device profile of the timed
    # dispatches (no-op otherwise)
    from uda_tpu.utils.metrics import device_trace

    with device_trace():
        best = min(timed_dispatch(chosen[0], i, chosen[1])
                   for i in range(DISPATCHES))
    gbps = gb_per_dispatch / best
    from uda_tpu.utils.stats import telemetry_block

    print(json.dumps({
        "metric": "terasort_singlechip_shuffle_merge_gbps",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 3),
        "engine": {"path": chosen[0], "tile": chosen[1]},
        "small_batch": _small_batch_tier(),
        "telemetry": telemetry_block(),
    }))


# interactive-traffic tier: the take-ramp showed the gather-bound
# engines collapse to 0.15 GB/s at 2^16 rows (latency-bound regime,
# BENCH_NOTES_r05) — these sizes track that shape per round, and the
# per-size engine chosen by the batch-size-aware router
# (ops.sort.route_engine) rides the same JSON so routing regressions
# are diffable across BENCH_*.json artifacts. UDA_TPU_BENCH_SMALL=0
# skips the tier (short pool windows).
SMALL_BATCH_LOG2 = (16, 17, 19)


def _small_batch_tier() -> dict:
    if os.environ.get("UDA_TPU_BENCH_SMALL") == "0":
        return {}
    import jax
    import numpy as np

    from uda_tpu.models import terasort
    from uda_tpu.ops import sort as sort_ops

    tier: dict = {}
    for log2 in SMALL_BATCH_LOG2:
        if log2 >= LOG2_RECORDS:
            continue  # smoke-sized runs: no tier below the headline
        n = 1 << log2
        entry: dict = {"rows": n}
        try:
            # lanes_ok mirrors the production surface (single_chip_sort):
            # a deployed lanes-engine winner routes here exactly as it
            # would in the real sort. Inside the try: a bad
            # UDA_TPU_SORT_PATH must cost this tier entry, not the
            # headline JSON line.
            path = sort_ops.route_engine(n, "auto", lanes_ok=True)
            tile = min(_tile_for(path), n)
            entry["engine"] = path
            entry["tile"] = tile
            gb = n * terasort.RECORD_BYTES * ROUNDS_PER_DISPATCH / 1e9

            def one(seed):
                t0 = time.perf_counter()
                viol, ck_in, ck_out = terasort.bench_step(
                    jax.random.key(seed), n, ROUNDS_PER_DISPATCH,
                    path=path, tile=tile, interpret=INTERPRET)
                assert int(viol) == 0
                assert np.uint32(ck_in) == np.uint32(ck_out)
                return time.perf_counter() - t0

            one(999)  # warmup/compile (small shapes compile fast)
            entry["gbps"] = round(gb / min(one(998), one(997)), 3)
        except Exception as e:  # noqa: BLE001 - the headline must print
            entry["error"] = f"{type(e).__name__}: {e}"
            print(f"# small-batch 2^{log2} failed: {e}", file=sys.stderr)
        tier[str(n)] = entry
    return tier


if __name__ == "__main__":
    main()
