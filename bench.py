"""Benchmark: single-chip TeraSort shuffle+merge throughput.

Measures the flagship path of BASELINE.json config 2 — HBM-resident
TeraSort records, device shuffle+merge (stable lexicographic sort of
100-byte records by their 10-byte keys) — on whatever accelerator is
ambient (the driver runs this on one real TPU chip).

Protocol: data is TeraGen'd ON DEVICE (the deployment stages records
into HBM once; the host never holds record bytes), a warmup iteration
compiles, then ``ITERS`` timed iterations each sort a FRESH dataset
(different PRNG seed — no result can be cached) and are validated for
sort order on device.

Baseline: the reference's data plane tops out at FDR InfiniBand line
rate, 56 Gb/s ~= 6.8 GB/s per node (BASELINE.md: "beat FDR-InfiniBand
UDA shuffle+merge wall-clock"; the reference repo publishes no absolute
figures, SURVEY §6). vs_baseline = achieved GB/s / 6.8.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax

BASELINE_GBPS = 6.8  # FDR IB line rate, the reference data plane ceiling
LOG2_RECORDS = 24    # 16M records x 100 B = 1.6 GB of records in HBM
ITERS = 5


def main() -> None:
    from uda_tpu.models import terasort

    n = 1 << LOG2_RECORDS
    gb = n * terasort.RECORD_BYTES / 1e9

    # warmup/compile on a throwaway dataset
    words = terasort.teragen(jax.random.key(999), n)
    out = terasort.single_chip_sort(words)
    jax.block_until_ready(out)
    terasort.validate_sorted(out, words)

    times = []
    for i in range(ITERS):
        words = terasort.teragen(jax.random.key(i), n)
        jax.block_until_ready(words)
        t0 = time.perf_counter()
        out = terasort.single_chip_sort(words)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
        terasort.validate_sorted(out, words)
        del words, out

    best = min(times)
    gbps = gb / best
    print(json.dumps({
        "metric": "terasort_singlechip_shuffle_merge_gbps",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 3),
    }))


if __name__ == "__main__":
    main()
