// VENDORED COMPILE-TIME STUB — NOT Hadoop code and never deployed.
//
// The build image carries no Hadoop jars, so the uda_tpu plugin layer
// (com.mellanox.hadoop.mapred.*) compiles against this minimal shape
// of the Hadoop API instead. Signatures follow hadoop-2.x so the same
// plugin sources compile unchanged against a real hadoop-common jar
// (exclude java/hadoop-stubs from the sourcepath there). Behavior here
// is the least that the plugin + tests need: a string map.
package org.apache.hadoop.conf;

import java.util.HashMap;
import java.util.Map;

public class Configuration {

    private final Map<String, String> props = new HashMap<>();

    public Configuration() {
    }

    public Configuration(Configuration other) {
        props.putAll(other.props);
    }

    public String get(String name) {
        return props.get(name);
    }

    public String get(String name, String defaultValue) {
        String v = props.get(name);
        return v == null ? defaultValue : v;
    }

    public void set(String name, String value) {
        props.put(name, value);
    }

    public boolean getBoolean(String name, boolean defaultValue) {
        String v = props.get(name);
        return v == null ? defaultValue : Boolean.parseBoolean(v.trim());
    }

    public void setBoolean(String name, boolean value) {
        props.put(name, Boolean.toString(value));
    }

    public int getInt(String name, int defaultValue) {
        String v = props.get(name);
        return v == null ? defaultValue : Integer.parseInt(v.trim());
    }

    public long getLong(String name, long defaultValue) {
        String v = props.get(name);
        return v == null ? defaultValue : Long.parseLong(v.trim());
    }

    public float getFloat(String name, float defaultValue) {
        String v = props.get(name);
        return v == null ? defaultValue : Float.parseFloat(v.trim());
    }

    /** Comma-separated values, trimmed; null when unset. */
    public String[] getTrimmedStrings(String name) {
        String v = props.get(name);
        if (v == null || v.trim().isEmpty()) {
            return new String[0];
        }
        String[] parts = v.split(",");
        for (int i = 0; i < parts.length; i++) {
            parts[i] = parts[i].trim();
        }
        return parts;
    }
}
