// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.mapred;

public class MapTaskCompletionEventsUpdate {

    private final TaskCompletionEvent[] events;
    private final boolean reset;

    public MapTaskCompletionEventsUpdate(TaskCompletionEvent[] events,
                                         boolean reset) {
        this.events = events;
        this.reset = reset;
    }

    public TaskCompletionEvent[] getMapTaskCompletionEvents() {
        return events;
    }

    public boolean shouldReset() {
        return reset;
    }
}
