// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.mapred;

import java.io.IOException;

import org.apache.hadoop.io.DataInputBuffer;
import org.apache.hadoop.util.Progress;

public interface RawKeyValueIterator {
    DataInputBuffer getKey() throws IOException;

    DataInputBuffer getValue() throws IOException;

    boolean next() throws IOException;

    void close() throws IOException;

    Progress getProgress();
}
