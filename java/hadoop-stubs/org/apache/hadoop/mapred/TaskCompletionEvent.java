// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.mapred;

public class TaskCompletionEvent {

    public enum Status { SUCCEEDED, FAILED, KILLED, OBSOLETE, TIPFAILED }

    private final Status status;
    private final TaskAttemptID attemptId;
    private final String taskTrackerHttp;

    public TaskCompletionEvent(Status status, TaskAttemptID attemptId,
                               String taskTrackerHttp) {
        this.status = status;
        this.attemptId = attemptId;
        this.taskTrackerHttp = taskTrackerHttp;
    }

    public Status getTaskStatus() {
        return status;
    }

    public TaskAttemptID getTaskAttemptId() {
        return attemptId;
    }

    public String getTaskTrackerHttp() {
        return taskTrackerHttp;
    }
}
