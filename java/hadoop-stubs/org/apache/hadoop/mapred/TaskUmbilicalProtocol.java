// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.mapred;

import java.io.IOException;

public interface TaskUmbilicalProtocol {
    MapTaskCompletionEventsUpdate getMapCompletionEvents(
            JobID jobId, int fromEventId, int maxLocs,
            TaskAttemptID reduceId) throws IOException;
}
