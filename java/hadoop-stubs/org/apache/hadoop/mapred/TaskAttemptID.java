// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
// String-backed ids: attempt_<jt>_<job>_<m|r>_<task>_<attempt>.
package org.apache.hadoop.mapred;

public class TaskAttemptID {

    private final String id;

    public TaskAttemptID(String id) {
        this.id = id;
    }

    public static TaskAttemptID forName(String s) {
        return new TaskAttemptID(s);
    }

    public TaskID getTaskID() {
        int us = id.lastIndexOf('_');
        String task = id.startsWith("attempt_")
                ? "task_" + id.substring("attempt_".length(), us)
                : id.substring(0, us);
        return new TaskID(task);
    }

    public JobID getJobID() {
        return getTaskID().getJobID();
    }

    @Override
    public String toString() {
        return id;
    }

    @Override
    public boolean equals(Object o) {
        return o instanceof TaskAttemptID && id.equals(((TaskAttemptID) o).id);
    }

    @Override
    public int hashCode() {
        return id.hashCode();
    }
}
