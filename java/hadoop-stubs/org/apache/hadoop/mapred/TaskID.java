// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.mapred;

public class TaskID {

    private final String id;  // task_<jt>_<job>_<m|r>_<task>

    public TaskID(String id) {
        this.id = id;
    }

    public JobID getJobID() {
        String[] parts = id.split("_");
        // task_<jtIdentifier>_<jobId>_<type>_<num>
        return new JobID(parts[1], Integer.parseInt(parts[2]));
    }

    /** The task number within the job. */
    public int getId() {
        String[] parts = id.split("_");
        return Integer.parseInt(parts[parts.length - 1]);
    }

    @Override
    public String toString() {
        return id;
    }

    @Override
    public boolean equals(Object o) {
        return o instanceof TaskID && id.equals(((TaskID) o).id);
    }

    @Override
    public int hashCode() {
        return id.hashCode();
    }
}
