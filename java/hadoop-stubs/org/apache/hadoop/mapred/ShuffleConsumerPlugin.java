// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
// The hadoop-2.x pluggable-shuffle SPI (MAPREDUCE-4049): the class a
// job's mapreduce.job.reduce.shuffle.consumer.plugin.class must
// implement for the ReduceTask to load it.
package org.apache.hadoop.mapred;

import java.io.IOException;

public interface ShuffleConsumerPlugin<K, V> {

    void init(Context<K, V> context);

    RawKeyValueIterator run() throws IOException, InterruptedException;

    void close();

    class Context<K, V> {
        private final TaskAttemptID reduceId;
        private final JobConf jobConf;
        private final Reporter reporter;
        private final TaskUmbilicalProtocol umbilical;

        public Context(TaskAttemptID reduceId, JobConf jobConf,
                       Reporter reporter, TaskUmbilicalProtocol umbilical) {
            this.reduceId = reduceId;
            this.jobConf = jobConf;
            this.reporter = reporter;
            this.umbilical = umbilical;
        }

        public TaskAttemptID getReduceId() {
            return reduceId;
        }

        public JobConf getJobConf() {
            return jobConf;
        }

        public Reporter getReporter() {
            return reporter;
        }

        public TaskUmbilicalProtocol getUmbilical() {
            return umbilical;
        }
    }
}
