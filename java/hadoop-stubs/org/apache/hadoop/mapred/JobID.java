// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.mapred;

public class JobID {

    private final String jtIdentifier;
    private final int id;

    public JobID(String jtIdentifier, int id) {
        this.jtIdentifier = jtIdentifier;
        this.id = id;
    }

    public static JobID forName(String s) {
        // job_<jtIdentifier>_<id>
        String[] parts = s.split("_");
        return new JobID(parts[1], Integer.parseInt(parts[2]));
    }

    public String getJtIdentifier() {
        return jtIdentifier;
    }

    public int getId() {
        return id;
    }

    @Override
    public String toString() {
        return String.format("job_%s_%04d", jtIdentifier, id);
    }

    @Override
    public boolean equals(Object o) {
        return o instanceof JobID && toString().equals(o.toString());
    }

    @Override
    public int hashCode() {
        return toString().hashCode();
    }
}
