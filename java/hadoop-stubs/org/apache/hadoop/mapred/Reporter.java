// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.mapred;

public interface Reporter {
    void progress();

    void setStatus(String status);
}
