// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.mapred;

import org.apache.hadoop.conf.Configuration;

public class JobConf extends Configuration {

    public JobConf() {
    }

    public JobConf(Configuration conf) {
        super(conf);
    }

    public Class<?> getOutputKeyClass() {
        String name = get("mapreduce.job.output.key.class",
                "org.apache.hadoop.io.Text");
        try {
            return Class.forName(name);
        } catch (ClassNotFoundException e) {
            throw new IllegalArgumentException("unknown key class " + name, e);
        }
    }

    public boolean getCompressMapOutput() {
        return getBoolean("mapreduce.map.output.compress",
                getBoolean("mapred.compress.map.output", false));
    }

    public String[] getLocalDirs() {
        String[] modern = getTrimmedStrings("mapreduce.cluster.local.dir");
        return modern.length > 0 ? modern
                : getTrimmedStrings("mapred.local.dir");
    }

    public boolean getSpeculativeExecution() {
        return getBoolean("mapreduce.map.speculative", false)
                || getBoolean("mapreduce.reduce.speculative", false);
    }

    public int getNumMapTasks() {
        return getInt("mapreduce.job.maps", 1);
    }
}
