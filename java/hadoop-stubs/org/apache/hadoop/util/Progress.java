// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.util;

public class Progress {

    private volatile float progress;

    public void set(float progress) {
        this.progress = progress;
    }

    public float get() {
        return progress;
    }
}
