// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.yarn.api.records;

public class ApplicationId {

    private final long clusterTimestamp;
    private final int id;

    private ApplicationId(long clusterTimestamp, int id) {
        this.clusterTimestamp = clusterTimestamp;
        this.id = id;
    }

    public static ApplicationId newInstance(long clusterTimestamp, int id) {
        return new ApplicationId(clusterTimestamp, id);
    }

    public long getClusterTimestamp() {
        return clusterTimestamp;
    }

    public int getId() {
        return id;
    }

    @Override
    public String toString() {
        return "application_" + clusterTimestamp + "_"
                + String.format("%04d", id);
    }
}
