// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.yarn.server.api;

import org.apache.hadoop.yarn.api.records.ApplicationId;

public class ApplicationInitializationContext {

    private final String user;
    private final ApplicationId applicationId;

    public ApplicationInitializationContext(String user,
                                            ApplicationId applicationId) {
        this.user = user;
        this.applicationId = applicationId;
    }

    public String getUser() {
        return user;
    }

    public ApplicationId getApplicationId() {
        return applicationId;
    }
}
