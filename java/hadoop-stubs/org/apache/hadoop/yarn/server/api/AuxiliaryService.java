// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
// The NodeManager auxiliary-service base class a provider plugin must
// extend to be loaded via yarn.nodemanager.aux-services.
package org.apache.hadoop.yarn.server.api;

import java.nio.ByteBuffer;

import org.apache.hadoop.conf.Configuration;

public abstract class AuxiliaryService {

    private final String name;

    protected AuxiliaryService(String name) {
        this.name = name;
    }

    public String getName() {
        return name;
    }

    public void init(Configuration conf) {
    }

    public void start() {
    }

    public void stop() {
    }

    public abstract void initializeApplication(
            ApplicationInitializationContext initAppContext);

    public abstract void stopApplication(
            ApplicationTerminationContext stopAppContext);

    public abstract ByteBuffer getMetaData();
}
