// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.yarn.server.api;

import org.apache.hadoop.yarn.api.records.ApplicationId;

public class ApplicationTerminationContext {

    private final ApplicationId applicationId;

    public ApplicationTerminationContext(ApplicationId applicationId) {
        this.applicationId = applicationId;
    }

    public ApplicationId getApplicationId() {
        return applicationId;
    }
}
