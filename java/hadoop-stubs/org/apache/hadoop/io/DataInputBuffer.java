// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
package org.apache.hadoop.io;

import java.io.ByteArrayInputStream;
import java.io.DataInputStream;

public class DataInputBuffer extends DataInputStream {

    private static final class Buffer extends ByteArrayInputStream {
        Buffer() {
            super(new byte[0]);
        }

        void reset(byte[] input, int start, int length) {
            this.buf = input;
            this.pos = start;
            this.count = Math.min(start + length, input.length);
            this.mark = start;
        }

        byte[] data() {
            return buf;
        }

        int position() {
            return pos;
        }

        int length() {
            return count;
        }
    }

    private final Buffer buffer;

    public DataInputBuffer() {
        this(new Buffer());
    }

    private DataInputBuffer(Buffer buffer) {
        super(buffer);
        this.buffer = buffer;
    }

    public void reset(byte[] input, int length) {
        buffer.reset(input, 0, length);
    }

    public void reset(byte[] input, int start, int length) {
        buffer.reset(input, start, length);
    }

    public byte[] getData() {
        return buffer.data();
    }

    public int getPosition() {
        return buffer.position();
    }

    /** End of the valid region (start + length of the last reset). */
    public int getLength() {
        return buffer.length();
    }
}
