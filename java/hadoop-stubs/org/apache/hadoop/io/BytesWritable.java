// VENDORED COMPILE-TIME STUB — key-class marker; see Configuration.java.
package org.apache.hadoop.io;

public class BytesWritable {
}
