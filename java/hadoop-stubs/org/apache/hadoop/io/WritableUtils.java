// VENDORED COMPILE-TIME STUB — see Configuration.java for the rules.
// The VInt/VLong codec is byte-exact with Hadoop's zero-compressed
// format (the same contract as uda_tpu/utils/vint.py and
// uda_tpu/native/vlong.h, reference src/CommUtils/IOUtility.cc:167-397).
package org.apache.hadoop.io;

import java.io.DataInput;
import java.io.DataOutput;
import java.io.IOException;

public final class WritableUtils {

    private WritableUtils() {
    }

    public static long readVLong(DataInput in) throws IOException {
        byte first = in.readByte();
        int len = decodeVIntSize(first);
        if (len == 1) {
            return first;
        }
        long v = 0;
        for (int i = 0; i < len - 1; i++) {
            v = (v << 8) | (in.readByte() & 0xff);
        }
        return isNegativeVInt(first) ? ~v : v;
    }

    public static int readVInt(DataInput in) throws IOException {
        long v = readVLong(in);
        if (v < Integer.MIN_VALUE || v > Integer.MAX_VALUE) {
            throw new IOException("VInt out of int range: " + v);
        }
        return (int) v;
    }

    public static int decodeVIntSize(byte value) {
        if (value >= -112) {
            return 1;
        }
        return value >= -120 ? -111 - value : -119 - value;
    }

    public static boolean isNegativeVInt(byte value) {
        return value < -120 || (value >= -112 && value < 0);
    }

    public static void writeVLong(DataOutput out, long v) throws IOException {
        if (v >= -112 && v <= 127) {
            out.writeByte((byte) v);
            return;
        }
        int tag = -112;
        long u = v;
        if (v < 0) {
            u = ~u;
            tag = -120;
        }
        int body = 0;
        for (long t = u; t != 0; t >>>= 8) {
            body++;
        }
        out.writeByte((byte) (tag - body));
        for (int i = body - 1; i >= 0; i--) {
            out.writeByte((byte) (u >>> (8 * i)));
        }
    }

    public static void writeVInt(DataOutput out, int v) throws IOException {
        writeVLong(out, v);
    }
}
