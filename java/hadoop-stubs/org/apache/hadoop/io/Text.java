// VENDORED COMPILE-TIME STUB — key-class marker so
// JobConf.getOutputKeyClass() resolves; see Configuration.java.
package org.apache.hadoop.io;

public class Text {
}
