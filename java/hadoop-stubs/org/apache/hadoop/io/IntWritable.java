// VENDORED COMPILE-TIME STUB — key-class marker; see Configuration.java.
package org.apache.hadoop.io;

public class IntWritable {

    private int value;

    public IntWritable() {
    }

    public IntWritable(int value) {
        this.value = value;
    }

    public int get() {
        return value;
    }

    public void set(int value) {
        this.value = value;
    }
}
