// Marker class for the framework's RawBytes key type: jobs whose map
// output keys are unframed byte strings (TeraSort-style fixed-width
// keys) set mapreduce.job.output.key.class = uda.tpu.RawBytes and the
// engine maps the name to its raw-memcmp comparator
// (uda_tpu/utils/comparators.py registry key "uda.tpu.RawBytes").
// Part of the deployable plugin jar, not a Hadoop stub.
package uda.tpu;

public class RawBytes {
}
