// The plugin layer's unchecked failure type (reference
// plugins/shared/com/mellanox/hadoop/mapred/UdaRuntimeException.java;
// Python analogue: uda_tpu/utils/errors.py UdaError). Thrown where the
// reference threw it: fallback-impossible states, obsolete-after-success
// map attempts, reset-after-success event updates.
package com.mellanox.hadoop.mapred;

public class UdaRuntimeException extends RuntimeException {

    public UdaRuntimeException(String message) {
        super(message);
    }

    public UdaRuntimeException(String message, Throwable cause) {
        super(message, cause);
    }
}
