// UdaBridgeDriver — a JVM process completing a merge through the
// native uda_tpu bridge (the proof the reference's L5 Java layer has a
// working seat on this framework: the consumer flow of
// UdaShuffleConsumerPluginShared.java init -> INIT/FETCH/FINAL ->
// dataFromUda blocks -> fetchOverMessage).
//
// Usage:
//   java --enable-native-access=ALL-UNNAMED \
//        com.mellanox.hadoop.mapred.UdaBridgeDriver \
//        <libuda_tpu_bridge.so> <mof_root> <job_id> <num_maps> <out_file>
//
// The MOF tree under <mof_root> is prepared by the caller (the gated
// pytest uses the Python MOFWriter); the driver drives the command
// protocol, collects the merged dataFromUda stream, and writes it to
// <out_file> for the caller to validate. Exit code 0 = merge completed
// without a failure_in_uda.

package com.mellanox.hadoop.mapred;

import java.io.ByteArrayOutputStream;
import java.io.IOException;
import java.nio.file.Files;
import java.nio.file.Paths;
import java.util.concurrent.CountDownLatch;
import java.util.concurrent.TimeUnit;

public final class UdaBridgeDriver implements UdaBridge.Callable {

    private final ByteArrayOutputStream blocks = new ByteArrayOutputStream();
    private final CountDownLatch done = new CountDownLatch(1);
    private volatile String failure = null;

    @Override
    public void fetchOverMessage() {
        done.countDown();
    }

    @Override
    public void dataFromUda(byte[] data) {
        try {
            blocks.write(data);
        } catch (IOException e) {
            failure = "block write failed: " + e;
            done.countDown();
        }
    }

    @Override
    public void logToJava(int level, String message) {
        if (level <= 2) { // lsERROR and up
            System.err.println("[uda_tpu] " + message);
        }
    }

    @Override
    public void failureInUda(String what) {
        failure = what;
        done.countDown();
    }

    public static void main(String[] args) throws Throwable {
        if (args.length != 5) {
            System.err.println("usage: UdaBridgeDriver <lib> <root> <job> "
                    + "<num_maps> <out>");
            System.exit(2);
        }
        String lib = args[0], root = args[1], job = args[2], out = args[4];
        int numMaps = Integer.parseInt(args[3]);

        UdaBridgeDriver driver = new UdaBridgeDriver();
        UdaBridge bridge = new UdaBridge(lib, driver);
        bridge.start(true, new String[] {"-w", "8"});
        // short-form INIT: job, reduce_id, num_maps, key_class, dirs
        bridge.doCommand(UdaCmd.formCmd(UdaCmd.INIT_COMMAND,
                java.util.List.of(job, "0", String.valueOf(numMaps),
                        "uda.tpu.RawBytes", root)));
        for (int m = 0; m < numMaps; m++) {
            String attempt = String.format("attempt_%s_m_%06d_0", job, m);
            bridge.doCommand(UdaCmd.formCmd(UdaCmd.FETCH_COMMAND,
                    java.util.List.of("localhost", job, attempt, "0")));
        }
        bridge.doCommand(UdaCmd.formCmd(UdaCmd.FINAL_MERGE_COMMAND,
                java.util.List.of()));
        if (!driver.done.await(120, TimeUnit.SECONDS)) {
            System.err.println("merge timed out");
            System.exit(3);
        }
        bridge.reduceExit();
        if (driver.failure != null) {
            System.err.println("failure_in_uda: " + driver.failure);
            System.exit(4);
        }
        Files.write(Paths.get(out), driver.blocks.toByteArray());
        System.out.println("JVM-MERGE-OK " + driver.blocks.size()
                + " bytes");
    }
}
