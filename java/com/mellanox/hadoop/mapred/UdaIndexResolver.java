// The spill-index resolution core: (job, map, reduce) -> IndexRecord,
// served out of a bounded LRU cache over Hadoop spill-index files.
//
// This is the reference's IndexCacheBridge + UdaPluginSH.getPathIndex
// pair (plugins/shared/org/apache/hadoop/mapred/IndexCacheBridge.java,
// plugins/mlx-2.x/.../UdaPluginSH.java:107-144) as one reusable class:
// UdaPluginSH composes it for the NodeManager service, and a consumer
// embedding can register it directly as the bridge's PathResolver
// (conf key uda.tpu.path.resolver.class) to exercise the getPathUda
// round trip in-process. The index file format is the Hadoop one —
// 24-byte (start, raw, part) big-endian triples, the same bytes
// uda_tpu/mofserver/index.py reads and writes.
package com.mellanox.hadoop.mapred;

import java.io.DataInputStream;
import java.io.File;
import java.io.FileInputStream;
import java.io.IOException;
import java.util.LinkedHashMap;
import java.util.Map;
import java.util.concurrent.ConcurrentHashMap;
import java.util.logging.Logger;

import org.apache.hadoop.mapred.JobConf;
import org.apache.hadoop.mapred.JobID;

public class UdaIndexResolver implements UdaBridge.PathResolver {

    static final Logger LOG =
            Logger.getLogger(UdaIndexResolver.class.getName());

    private static final int INDEX_CACHE_ENTRIES = 1024;

    /** One cached map output: its MOF path + per-reduce index triples
     *  (caching the path too keeps cache hits free of per-root file
     *  stats). */
    private static final class Entry {
        final String mofPath;
        final long[][] triples;

        Entry(String mofPath, long[][] triples) {
            this.mofPath = mofPath;
            this.triples = triples;
        }
    }

    private final JobConf jobConf;
    private final Map<String, String> userByJob =
            new ConcurrentHashMap<>();
    // (job, map) -> cached output; LRU-bounded like the reference's
    // mapreduce.tasktracker.indexcache.mb budget
    private final Map<String, Entry> indexCache =
            java.util.Collections.synchronizedMap(
                    new LinkedHashMap<>(64, 0.75f, true) {
                        @Override
                        protected boolean removeEldestEntry(
                                Map.Entry<String, Entry> eldest) {
                            return size() > INDEX_CACHE_ENTRIES;
                        }
                    });

    public UdaIndexResolver(JobConf jobConf) {
        this.jobConf = jobConf;
    }

    public void addJob(String user, JobID jobId) {
        userByJob.put(jobId.toString(), user);
    }

    public void removeJob(JobID jobId) {
        userByJob.remove(jobId.toString());
        synchronized (indexCache) {
            indexCache.keySet().removeIf(
                    k -> k.startsWith(jobId.toString() + "/"));
        }
    }

    /** Roots to search: uda.tpu.index.local.dirs when set (a supplier
     *  embedded in a consumer process serves from dirs the reduce task
     *  does not list as its own), else the job's local dirs. */
    private String[] roots() {
        String[] own = jobConf.getTrimmedStrings("uda.tpu.index.local.dirs");
        return own.length > 0 ? own : jobConf.getLocalDirs();
    }

    /** MOF directory of one map output: the YARN
     *  usercache/<user>/appcache/<app>/output/<map> layout when the job
     *  has a registered user (UdaPluginSH.java:107-137), else the flat
     *  <root>/<job>/<map> layout of uda_tpu's DirIndexResolver. */
    private File mapDir(String root, String jobIdStr, String mapId) {
        String user = userByJob.get(jobIdStr);
        if (user != null) {
            JobID jobId = JobID.forName(jobIdStr);
            String app = "application_" + jobId.getJtIdentifier() + "_"
                    + String.format("%04d", jobId.getId());
            return new File(root, "usercache/" + user + "/appcache/" + app
                    + "/output/" + mapId);
        }
        return new File(new File(root, jobIdStr), mapId);
    }

    @Override
    public UdaBridge.IndexRecord getPathIndex(String jobId, String mapId,
                                              int reduce) {
        String cacheKey = jobId + "/" + mapId;
        Entry entry = indexCache.get(cacheKey);
        if (entry == null) {
            for (String root : roots()) {
                File dir = mapDir(root.trim(), jobId, mapId);
                File mof = new File(dir, "file.out");
                if (mof.isFile()) {
                    try {
                        entry = new Entry(mof.getPath(), readIndexFile(
                                new File(dir, "file.out.index")));
                    } catch (IOException e) {
                        LOG.severe("got an exception while retrieving the "
                                + "index info: " + e);
                        return null;
                    }
                    indexCache.put(cacheKey, entry);
                    break;
                }
            }
        }
        if (entry == null) {
            LOG.severe("no MOF for " + jobId + "/" + mapId
                    + " under local dirs");
            return null;
        }
        if (reduce < 0 || reduce >= entry.triples.length) {
            LOG.severe("reduce " + reduce + " out of range for " + mapId
                    + " (" + entry.triples.length + " partitions)");
            return null;
        }
        long[] t = entry.triples[reduce];
        return new UdaBridge.IndexRecord(entry.mofPath, t[0], t[1], t[2]);
    }

    /** Hadoop spill index: (start, raw, part) 8-byte BE triples
     *  (uda_tpu/mofserver/index.py read_index_file twin). */
    static long[][] readIndexFile(File index) throws IOException {
        long size = index.length();
        if (size % 24 != 0) {
            throw new IOException("index file " + index + " length " + size
                    + " not a multiple of 24");
        }
        long[][] out = new long[(int) (size / 24)][3];
        try (DataInputStream in = new DataInputStream(
                new FileInputStream(index))) {
            for (long[] triple : out) {
                triple[0] = in.readLong();
                triple[1] = in.readLong();
                triple[2] = in.readLong();
                if (triple[0] < 0 || triple[1] < 0 || triple[2] < 0) {
                    throw new IOException(
                            "negative field in index record of " + index);
                }
            }
        }
        return out;
    }
}
