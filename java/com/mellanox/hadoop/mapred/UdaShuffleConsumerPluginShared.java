// The version-independent consumer core: fetch orchestration, the
// map-completion-events poller, and the fallback-to-vanilla machinery.
//
// Re-creation of the reference's UdaShuffleConsumerPluginShared
// (plugins/shared/com/mellanox/hadoop/mapred/
// UdaShuffleConsumerPluginShared.java):
//
// - init constructs the UdaPluginRT channel; any throwable during init
//   triggers fallback (:180-202);
// - doFallbackInit: developer mode (mapred.rdma.developer.mode) fails
//   loudly instead of falling back (:205-232 — the reference called
//   System.exit(1); an embedded library must not kill its JVM, so this
//   throws UdaRuntimeException instead); otherwise the vanilla plugin
//   class is loaded reflectively and initialized with the same context;
// - fetchOutputs blocks on the fetch lock until the engine's
//   fetchOverMessage (or a failure) wakes it (:249-298);
// - createKVIterator returns the J2CQueue on success, or replays
//   fetchOutputs on the fallback plugin (:320-344);
// - GetMapEventsThread polls the umbilical at 1 Hz, dedupes attempts by
//   TaskID, fetches SUCCEEDED maps, treats obsolete-after-success and
//   reset-after-success as fallback triggers (:434-602). The same
//   dedupe/obsolescence contract is enforced engine-side
//   (uda_tpu/bridge/bridge.py _fetch_attempt) — defense in depth.
package com.mellanox.hadoop.mapred;

import java.io.IOException;
import java.net.URI;
import java.util.HashMap;
import java.util.HashSet;
import java.util.Map;
import java.util.Set;
import java.util.logging.Logger;

import org.apache.hadoop.mapred.JobConf;
import org.apache.hadoop.mapred.MapTaskCompletionEventsUpdate;
import org.apache.hadoop.mapred.RawKeyValueIterator;
import org.apache.hadoop.mapred.Reporter;
import org.apache.hadoop.mapred.ShuffleConsumerPlugin;
import org.apache.hadoop.mapred.TaskAttemptID;
import org.apache.hadoop.mapred.TaskCompletionEvent;
import org.apache.hadoop.mapred.TaskID;
import org.apache.hadoop.mapred.TaskUmbilicalProtocol;

public class UdaShuffleConsumerPluginShared<K, V> {

    static final Logger LOG = Logger.getLogger(
            UdaShuffleConsumerPluginShared.class.getName());

    private static final long EVENT_POLL_MS = 1000;
    private static final int MAX_EVENTS_TO_FETCH = 10000;

    TaskAttemptID reduceId;
    JobConf jobConf;
    Reporter reporter;
    TaskUmbilicalProtocol umbilical;
    ShuffleConsumerPlugin.Context<K, V> context;
    UdaPluginRT<K, V> rdmaChannel;
    ShuffleConsumerPlugin<K, V> fallbackPlugin;

    private final Object fetchLock = new Object();
    private volatile boolean fetchCompleted;
    private volatile boolean fetchOutputsCompleted;
    private volatile boolean fallbackFetchOutputsDone;
    private volatile boolean exitGetMapEvents;
    // a failure for which fallback was impossible (developer mode or
    // fallback-init failure): stored so the waiter re-raises it LOUDLY
    // instead of hanging on the fetch lock
    private volatile Throwable udaFailure;

    void notifyFetchCompleted() {
        synchronized (fetchLock) {
            fetchCompleted = true;
            fetchLock.notifyAll();
        }
    }

    /** Usually called from an engine thread (:161-177). NEVER throws:
     *  a failure here must wake the fetch waiter, not kill the calling
     *  thread (or the JVM, when the caller is an FFM upcall stub). */
    void failureInUda(Throwable t) {
        try {
            doFallbackInit(t);
        } catch (Throwable t2) {
            udaFailure = new UdaRuntimeException(
                    "Failure in UDA and failure when trying to fallback "
                    + "to vanilla", t2);
        } finally {
            synchronized (fetchLock) {
                fetchLock.notifyAll();
            }
            if (rdmaChannel != null) {
                rdmaChannel.failQueue(udaFailure != null ? udaFailure : t);
            }
        }
    }

    public void init(ShuffleConsumerPlugin.Context<K, V> context) {
        try {
            LOG.info("init - Using UdaShuffleConsumerPlugin");
            this.context = context;
            this.reduceId = context.getReduceId();
            this.jobConf = context.getJobConf();
            this.reporter = context.getReporter();
            this.umbilical = context.getUmbilical();
            this.rdmaChannel = new UdaPluginRT<>(this, reduceId, jobConf,
                    reporter, jobConf.getNumMapTasks());
        } catch (Throwable t) {
            try {
                doFallbackInit(t);
            } catch (IOException e) {
                throw new UdaRuntimeException("fallback init failed", e);
            }
        }
    }

    synchronized void doFallbackInit(Throwable t) throws IOException {
        if (fallbackPlugin != null) {
            return;  // already done
        }
        exitGetMapEvents = true;  // sanity
        String devModeProperty = "mapred.rdma.developer.mode";
        if (jobConf.getBoolean(devModeProperty, false)) {
            // the reference aborted the process here (:213-217); an
            // embedded library throws instead and lets the task fail
            throw new UdaRuntimeException("Got UDA fatal error and cannot "
                    + "fallback to vanilla under " + devModeProperty, t);
        }
        if (t != null) {
            LOG.severe("Critical failure in UdaPlugin - switching to the "
                    + "vanilla fallbackPlugin: " + t);
        }
        String vanilla = jobConf.get(
                "mapred.uda.fallback.plugin.class",
                "org.apache.hadoop.mapreduce.task.reduce.Shuffle");
        try {
            @SuppressWarnings("unchecked")
            ShuffleConsumerPlugin<K, V> plugin =
                    (ShuffleConsumerPlugin<K, V>) Class.forName(vanilla)
                            .getDeclaredConstructor().newInstance();
            plugin.init(context);
            fallbackPlugin = plugin;
            LOG.info("Successfully switched to the fallbackPlugin "
                    + vanilla);
        } catch (ReflectiveOperationException e) {
            throw new UdaRuntimeException("Failed to initialize UDA "
                    + "shuffle and failed to fallback to vanilla ("
                    + vanilla + ")", e);
        }
    }

    private boolean fetchOutputsInternal() throws IOException {
        GetMapEventsThread events = new GetMapEventsThread();
        events.start();
        LOG.info("fetchOutputs - Using UdaShuffleConsumerPlugin");
        synchronized (fetchLock) {
            while (!fetchCompleted && fallbackPlugin == null
                    && udaFailure == null) {
                try {
                    fetchLock.wait();
                } catch (InterruptedException e) {
                    Thread.currentThread().interrupt();
                    throw new IOException("interrupted in fetchOutputs");
                }
            }
        }
        exitGetMapEvents = true;
        if (udaFailure != null) {
            // developer mode / fallback-impossible: fail the task loudly
            throw new UdaRuntimeException("UDA failed with no fallback",
                    udaFailure);
        }
        if (fallbackPlugin != null) {
            throw new UdaRuntimeException(
                    "another thread has indicated Uda failure");
        }
        try {
            events.join();
        } catch (InterruptedException e) {
            Thread.currentThread().interrupt();
        }
        fetchOutputsCompleted = true;
        return true;
    }

    public boolean fetchOutputs() throws IOException {
        try {
            if (fallbackPlugin == null) {
                return fetchOutputsInternal();
            }
        } catch (Throwable t) {
            doFallbackInit(t);
        }
        LOG.info("fetchOutputs: Using fallbackPlugin");
        return doFallbackFetchOutputs();
    }

    private synchronized boolean doFallbackFetchOutputs()
            throws IOException {
        if (fallbackFetchOutputsDone) {
            return true;
        }
        doFallbackInit(null);  // sanity
        // the hadoop-2 plugin SPI folds fetch into run(): the actual
        // replay is fallbackPlugin.run() in createKVIterator; this stage
        // only records that the fallback path is armed
        fallbackFetchOutputsDone = true;
        return true;
    }

    public RawKeyValueIterator createKVIterator()
            throws IOException, InterruptedException {
        try {
            if (fetchOutputsCompleted) {
                LOG.info("createKVIterator - Using "
                        + "UdaShuffleConsumerPlugin");
                return rdmaChannel.createKVIteratorRdma();
            }
        } catch (Throwable t) {
            doFallbackInit(t);
        }
        if (!fallbackFetchOutputsDone) {
            doFallbackFetchOutputs();
        }
        LOG.info("createKVIterator: Using fallbackPlugin");
        return fallbackPlugin.run();
    }

    public void close() {
        if (fallbackPlugin == null) {
            LOG.info("close - Using UdaShuffleConsumerPlugin");
            rdmaChannel.close();
            return;
        }
        LOG.info("close: Using fallbackPlugin");
        fallbackPlugin.close();
        if (rdmaChannel != null) {
            // close the engine side too, bounded like the reference's
            // UdaCloserThread join(1000) (:346-391)
            Thread closer = new Thread(rdmaChannel::close,
                    "UdaCloserThread");
            closer.setDaemon(true);
            closer.start();
            try {
                closer.join(1000);
            } catch (InterruptedException e) {
                Thread.currentThread().interrupt();
            }
        }
    }

    /** The 1 Hz map-completion poller (:434-602). */
    private final class GetMapEventsThread extends Thread {

        private int fromEventId = 0;
        private final Map<TaskID, TaskAttemptID> succeededTasks =
                new HashMap<>();
        private final Set<TaskAttemptID> succeededAttempts =
                new HashSet<>();
        private int mapsFetched = 0;

        GetMapEventsThread() {
            setName("Thread for polling Map Completion Events");
            setDaemon(true);
        }

        @Override
        public void run() {
            LOG.info(reduceId + " thread started: " + getName());
            do {
                try {
                    getMapCompletionEvents();
                    Thread.sleep(EVENT_POLL_MS);
                } catch (InterruptedException e) {
                    LOG.warning(reduceId + " GetMapEventsThread returning "
                            + "after an interrupted exception");
                    return;
                } catch (Throwable t) {
                    LOG.severe("error in GetMapEventsThread: " + t);
                    failureInUda(t);
                    break;
                }
            } while (!exitGetMapEvents);
            LOG.info("GetMapEventsThread exiting");
        }

        private void getMapCompletionEvents() throws IOException {
            MapTaskCompletionEventsUpdate update =
                    umbilical.getMapCompletionEvents(reduceId.getJobID(),
                            fromEventId, MAX_EVENTS_TO_FETCH, reduceId);
            TaskCompletionEvent[] events =
                    update.getMapTaskCompletionEvents();
            if (update.shouldReset()) {
                fromEventId = 0;
                if (succeededTasks.isEmpty()) {
                    LOG.info("got reset update before any succeeded map - "
                            + "this is OK");
                } else {
                    throw new UdaRuntimeException("got reset update after "
                            + succeededTasks.size() + " succeeded maps");
                }
            }
            fromEventId += events.length;
            for (TaskCompletionEvent event : events) {
                switch (event.getTaskStatus()) {
                    case SUCCEEDED: {
                        TaskAttemptID attempt = event.getTaskAttemptId();
                        succeededAttempts.add(attempt);
                        TaskID task = attempt.getTaskID();
                        if (succeededTasks.containsKey(task)) {
                            LOG.info("Ignoring succeeded attempt "
                                    + attempt + ": task already succeeded "
                                    + "via " + succeededTasks.get(task));
                            break;
                        }
                        succeededTasks.put(task, attempt);
                        String host = URI.create(
                                event.getTaskTrackerHttp()).getHost();
                        rdmaChannel.sendFetchReq(host == null ? "localhost"
                                : host, attempt.getJobID().toString(),
                                attempt.toString());
                        if (++mapsFetched >= jobConf.getNumMapTasks()) {
                            // all maps announced: start the final merge
                            // (the reference's C++ tracked this count
                            // engine-side)
                            rdmaChannel.startFinalMerge();
                        }
                        break;
                    }
                    case FAILED:
                    case KILLED:
                    case OBSOLETE: {
                        TaskAttemptID attempt = event.getTaskAttemptId();
                        if (succeededAttempts.contains(attempt)) {
                            throw new UdaRuntimeException(
                                    "encountered obsolete map attempt "
                                    + attempt + " (status "
                                    + event.getTaskStatus() + ") after it "
                                    + "was already successful");
                        }
                        LOG.info("Ignoring failed attempt " + attempt
                                + " with status " + event.getTaskStatus());
                        break;
                    }
                    case TIPFAILED:
                        LOG.info("Ignoring output of failed map TIP: "
                                + event.getTaskAttemptId());
                        break;
                    default:
                        break;
                }
            }
        }
    }
}
