// UdaPluginRT — the reduce-task side of the plugin layer: owns the
// bridge lifecycle, the shuffle-memory budget, the INIT construction,
// the KVBuf staging ring, and the J2CQueue RawKeyValueIterator the
// reduce consumes.
//
// Re-creation of the reference's UdaPluginRT (plugins/shared/com/
// mellanox/hadoop/mapred/UdaPlugin.java:146-556) against the uda_tpu
// bridge:
//
// - shuffle-memory budget: mapred.rdma.shuffle.total.size when set,
//   else maxHeap * mapred.job.shuffle.input.buffer.percent (default
//   0.7, out-of-range values reset to default) — UdaPlugin.java:209-244;
// - INIT construction: the 10-param layout + checked local dirs that
//   uda_tpu/bridge/bridge.py:263-316 parses (num_maps, job, reduce,
//   lpq_size, buf(B), min_buf(B), key class, codec, codec block size,
//   shuffle memory, num_dirs, dirs...) — UdaPlugin.java:266-316;
// - KVBuf ring: kv_buf_num staging buffers cycling between
//   RECV_READY/REDC_READY under per-buffer monitors — :164-179,
//   :368-402;
// - J2CQueue implements RawKeyValueIterator: walks the VInt-framed
//   record stream out of the ring — :435-555. One deliberate redesign:
//   uda_tpu's emitter cuts blocks at exactly the block size, so records
//   MAY span blocks; J2CQueue carries the partial-record tail into the
//   next buffer (the join the reference ran native-side,
//   src/Merger/StreamRW.cc:542-590);
// - 1 Hz log-level re-sync into the native side — UdaPlugin.java:99-143
//   (java.util.logging here; the JDK has no commons-logging).
//
// fetchOverMessage: the engine reports fetch progress per 20 segments
// plus once at fetch completion (bridge.py INIT wiring of the
// MergeManager progress hook), and the count-against-numMaps rule below
// decides fetch-phase completion — the reference's exact contract.
package com.mellanox.hadoop.mapred;

import java.io.EOFException;
import java.io.IOException;
import java.util.ArrayList;
import java.util.List;
import java.util.Timer;
import java.util.TimerTask;
import java.util.logging.Level;
import java.util.logging.Logger;

import org.apache.hadoop.io.DataInputBuffer;
import org.apache.hadoop.io.WritableUtils;
import org.apache.hadoop.mapred.JobConf;
import org.apache.hadoop.mapred.RawKeyValueIterator;
import org.apache.hadoop.mapred.Reporter;
import org.apache.hadoop.mapred.TaskAttemptID;
import org.apache.hadoop.util.Progress;

public class UdaPluginRT<K, V> implements UdaBridge.Callable {

    static final Logger LOG =
            Logger.getLogger(UdaPluginRT.class.getName());

    private static final float DEFAULT_SHUFFLE_INPUT_PERCENT = 0.7f;
    static final int KV_BUF_NUM = 2;            // reference kv_buf_num
    static final int KV_BUF_SIZE = 1 << 20;     // reference 1 MB staging

    private final UdaShuffleConsumerPluginShared<K, V> udaShuffleConsumer;
    private final TaskAttemptID reduceId;
    private final JobConf jobConf;
    private final Reporter reporter;
    private final int numMaps;
    private final UdaBridge bridge;
    private final Progress progress = new Progress();
    private final KVBuf[] kvBufs = new KVBuf[KV_BUF_NUM];
    private final J2CQueue j2cQueue = new J2CQueue();
    private final Timer logLevelTimer = new Timer("uda-log-level", true);
    private int curKvIdx = 0;   // producer cursor over the ring
    private int lastLogLevel = -1;
    // closing: producers drop data instead of blocking on the ring, so
    // reduceExit's merge-thread join cannot deadlock on an abandoned
    // J2CQueue (abnormal close with both buffers REDC_READY)
    private volatile boolean shutdown = false;
    // engine failure AFTER the fetch phase: the J2CQueue consumer may
    // be blocked on the ring with no more blocks ever coming — it must
    // wake and fail the reduce instead of hanging to the task timeout
    private volatile Throwable queueFailure;

    public UdaPluginRT(UdaShuffleConsumerPluginShared<K, V> consumer,
                       TaskAttemptID reduceId, JobConf jobConf,
                       Reporter reporter, int numMaps) throws IOException {
        this.udaShuffleConsumer = consumer;
        this.reduceId = reduceId;
        this.jobConf = jobConf;
        this.reporter = reporter;
        this.numMaps = numMaps;
        for (int i = 0; i < KV_BUF_NUM; i++) {
            kvBufs[i] = new KVBuf(KV_BUF_SIZE);
        }

        long maxRdmaBufferKb = jobConf.getLong("mapred.rdma.buf.size", 1024);
        long minRdmaBufferKb =
                jobConf.getLong("mapred.rdma.buf.size.min", 16);
        long shuffleMemory = shuffleMemoryBudget();

        if (jobConf.getSpeculativeExecution()) {
            LOG.info("UDA has limited support for map task speculative "
                    + "execution");
        }
        LOG.info("UDA: fetching " + numMaps + " segments; shuffle memory "
                + (shuffleMemory >> 20) + " MB, buf " + maxRdmaBufferKb
                + " KB (min " + minRdmaBufferKb + " KB)");

        String lib = jobConf.get("uda.tpu.bridge.library",
                "libuda_tpu_bridge.so");
        try {
            // when INIT announces no usable local dirs, the engine
            // resolves MOF paths by up-call; a resolver class here
            // (e.g. UdaIndexResolver) serves that round trip in-process
            UdaBridge.PathResolver resolver = null;
            String resolverClass =
                    jobConf.get("uda.tpu.path.resolver.class", null);
            if (resolverClass != null) {
                resolver = (UdaBridge.PathResolver) Class
                        .forName(resolverClass)
                        .getConstructor(JobConf.class)
                        .newInstance(jobConf);
            }
            bridge = new UdaBridge(lib, this, resolver,
                    new JobConfSource());
            bridge.start(true, buildCmdParams());
        } catch (Throwable t) {
            throw new IOException("failed to launch the uda_tpu bridge", t);
        }
        syncLogLevel();
        logLevelTimer.schedule(new TimerTask() {
            @Override
            public void run() {
                syncLogLevel();
            }
        }, 1000, 1000);

        List<String> p = new ArrayList<>();
        p.add(Integer.toString(numMaps));
        p.add(reduceId.getJobID().toString());
        p.add(Integer.toString(reduceId.getTaskID().getId()));
        p.add(jobConf.get("mapred.netmerger.hybrid.lpq.size", "0"));
        p.add(Long.toString(maxRdmaBufferKb * 1024));
        p.add(Long.toString(minRdmaBufferKb * 1024));
        p.add(jobConf.getOutputKeyClass().getName());
        String codec = null;
        if (jobConf.getCompressMapOutput()) {
            codec = jobConf.get("mapred.map.output.compression.codec", null);
        }
        p.add(codec == null ? "0" : codec);
        String blockSize = Integer.toString(256 * 1024);
        if (codec != null) {
            if (codec.contains("lzo.LzoCodec")) {
                blockSize = jobConf.get("io.compression.codec.lzo.buffersize",
                        blockSize);
            } else if (codec.contains("SnappyCodec")) {
                blockSize = jobConf.get(
                        "io.compression.codec.snappy.buffersize", blockSize);
            }
        }
        p.add(blockSize);
        p.add(Long.toString(shuffleMemory));
        List<String> dirs = usableLocalDirs();
        p.add(Integer.toString(dirs.size()));
        p.addAll(dirs);

        doCommand(UdaCmd.formCmd(UdaCmd.INIT_COMMAND, p));
        progress.set(0.5f);
    }

    /** Budget rule of UdaPlugin.java:209-244. */
    private long shuffleMemoryBudget() {
        long total = jobConf.getLong("mapred.rdma.shuffle.total.size", 0);
        if (total < 0) {
            LOG.warning("Illegal parameter value: "
                    + "mapred.rdma.shuffle.total.size=" + total);
        }
        if (total > 0) {
            LOG.info("Using mapred.rdma.shuffle.total.size to limit UDA "
                    + "shuffle memory");
            return total;
        }
        long maxHeap = Runtime.getRuntime().maxMemory();
        float percent = jobConf.getFloat(
                "mapred.job.shuffle.input.buffer.percent",
                DEFAULT_SHUFFLE_INPUT_PERCENT);
        if (percent < 0 || percent > 1) {
            LOG.warning("mapred.job.shuffle.input.buffer.percent out of "
                    + "range - using default "
                    + DEFAULT_SHUFFLE_INPUT_PERCENT);
            percent = DEFAULT_SHUFFLE_INPUT_PERCENT;
        }
        LOG.info("Using JAVA Xmx with "
                + "mapred.job.shuffle.input.buffer.percent to limit UDA "
                + "shuffle memory");
        return (long) (maxHeap * percent);
    }

    /** Local dirs that exist and are writable (the DiskChecker pass,
     *  UdaPlugin.java:296-311). */
    private List<String> usableLocalDirs() {
        List<String> ok = new ArrayList<>();
        for (String d : jobConf.getLocalDirs()) {
            java.io.File f = new java.io.File(d.trim());
            if ((f.isDirectory() && f.canWrite()) || f.mkdirs()) {
                ok.add(d.trim());
            }
        }
        return ok;
    }

    /** argv of the C++ launch (buildCmdParams, UdaPlugin.java:181-201).
     *  Short opts parsed by uda_tpu/utils/config.py. */
    private String[] buildCmdParams() {
        return new String[] {
            "-w", jobConf.get("mapred.rdma.wqe.per.conn", "256"),
            "-r", jobConf.get("mapred.rdma.cma.port", "9011"),
            "-a", jobConf.get("mapred.netmerger.merge.approach", "1"),
            "-m", "1",
            "-s", jobConf.get("mapred.rdma.buf.size", "1024"),
        };
    }

    /** Count enabled levels like the reference's calcAndCompareLogLevel
     *  (UdaPlugin.java:80-91): fatal..trace -> 1..6. */
    private static int currentLogLevel() {
        Logger log = LOG;
        int level = 0;
        Level[] ladder = {Level.SEVERE, Level.SEVERE, Level.WARNING,
                Level.INFO, Level.FINE, Level.FINEST};
        for (Level l : ladder) {
            if (log.isLoggable(l)) {
                level++;
            }
        }
        return level;
    }

    private synchronized void syncLogLevel() {
        int level = currentLogLevel();
        if (level == lastLogLevel) {
            return;
        }
        lastLogLevel = level;
        try {
            bridge.setLogLevel(level);
        } catch (Throwable t) {
            LOG.warning("setLogLevel failed: " + t);
        }
    }

    private void doCommand(String msg) throws IOException {
        try {
            bridge.doCommand(msg);
        } catch (Throwable t) {
            throw new IOException("bridge command failed: " + msg, t);
        }
    }

    /** host:jobid:attemptid:partition (sendFetchReq,
     *  UdaPlugin.java:322-334). */
    public void sendFetchReq(String host, String jobId, String attemptId)
            throws IOException {
        List<String> p = new ArrayList<>();
        p.add(host);
        p.add(jobId);
        p.add(attemptId);
        p.add(Integer.toString(reduceId.getTaskID().getId()));
        doCommand(UdaCmd.formCmd(UdaCmd.FETCH_COMMAND, p));
    }

    /** Start the final merge (FINAL_MERGE_COMMAND; the reference issued
     *  it from the C++ side's fetch bookkeeping, here the shared plugin
     *  issues it when all maps are announced). */
    public void startFinalMerge() throws IOException {
        doCommand(UdaCmd.formCmd(UdaCmd.FINAL_MERGE_COMMAND,
                new ArrayList<>()));
    }

    public RawKeyValueIterator createKVIteratorRdma() {
        j2cQueue.initialize();
        return j2cQueue;
    }

    public void close() {
        logLevelTimer.cancel();
        // release the ring BEFORE reduceExit: reduceExit joins the merge
        // thread, which may be blocked in dataFromUda waiting for a slot
        // the (possibly abandoned) J2CQueue will never free
        shutdown = true;
        for (KVBuf buf : kvBufs) {
            synchronized (buf) {
                buf.notifyAll();
            }
        }
        try {
            bridge.reduceExit();
        } catch (Throwable t) {
            LOG.warning("reduceExit failed: " + t);
        }
        j2cQueue.close();
    }

    // ---- callbacks from the native side --------------------------------

    static final int REPORT_COUNT = 20;  // reference mReportCount
    private int mapsCount = 0;

    /** One up-call per REPORT_COUNT fetched segments (+ one at fetch
     *  completion); counting against numMaps decides when the fetch
     *  phase is done (reference UdaPlugin.java:351-364). The merge
     *  STREAM's end is signaled in-band by the IFile EOF marker the
     *  J2CQueue consumes. */
    @Override
    public synchronized void fetchOverMessage() {
        // synchronized: the engine fires this from fetch completion
        // threads; a lost mapsCount update would hang fetchOutputs
        mapsCount += REPORT_COUNT;
        if (mapsCount > numMaps) {
            mapsCount = numMaps;
        }
        reporter.progress();
        LOG.info("fetchOverMessage: mapsCount=" + mapsCount + " numMaps="
                + numMaps);
        if (mapsCount >= numMaps) {
            udaShuffleConsumer.notifyFetchCompleted();
        }
    }

    @Override
    public void dataFromUda(byte[] data) {
        KVBuf buf = kvBufs[curKvIdx];
        synchronized (buf) {
            while (buf.status != KVBuf.RECV_READY && !shutdown) {
                try {
                    buf.wait();
                } catch (InterruptedException e) {
                    Thread.currentThread().interrupt();
                    return;
                }
            }
            if (shutdown) {
                return;  // closing: drop the block, unblock the engine
            }
            if (data.length > buf.bytes.length) {
                // emitter blocks are bounded by the INIT buffer size;
                // grow defensively rather than corrupt the ring
                buf.bytes = new byte[data.length];
            }
            System.arraycopy(data, 0, buf.bytes, 0, data.length);
            buf.actLen = data.length;
            buf.status = KVBuf.REDC_READY;
            curKvIdx = (curKvIdx + 1) % KV_BUF_NUM;
            buf.notifyAll();
        }
    }

    @Override
    public void logToJava(int level, String message) {
        // bridge levels: 1 fatal, 2 error, 3 warn, 4 info, 5 debug, 6 trace
        Level l = level <= 2 ? Level.SEVERE
                : level == 3 ? Level.WARNING
                : level == 4 ? Level.INFO : Level.FINE;
        LOG.log(l, "[uda_tpu] " + message);
    }

    @Override
    public void failureInUda(String what) {
        udaShuffleConsumer.failureInUda(
                new UdaRuntimeException("UDA failure in an engine thread: "
                        + what));
    }

    /** Wake a consumer blocked on the ring with a terminal failure
     *  (no more blocks are coming). */
    void failQueue(Throwable t) {
        queueFailure = t;
        for (KVBuf buf : kvBufs) {
            synchronized (buf) {
                buf.notifyAll();
            }
        }
    }

    Progress getProgress() {
        return progress;
    }

    /** One staging buffer of the ring (reference KVBuf,
     *  UdaPlugin.java:421-433). */
    private static final class KVBuf {
        static final int RECV_READY = 1;
        static final int REDC_READY = 2;

        byte[] bytes;
        int actLen;
        int status = RECV_READY;

        KVBuf(int size) {
            bytes = new byte[size];
        }
    }

    /** The RawKeyValueIterator the reduce drains (reference J2CQueue,
     *  UdaPlugin.java:435-555) with cross-buffer record joining. */
    private final class J2CQueue implements RawKeyValueIterator {

        private final DataInputBuffer key = new DataInputBuffer();
        private final DataInputBuffer val = new DataInputBuffer();
        private final DataInputBuffer cur = new DataInputBuffer();
        private byte[] carry = new byte[0];  // partial record tail
        private int consumerIdx = -1;
        private boolean sawEof = false;
        private boolean closed = false;
        private int timeCount = 0;

        void initialize() {
            timeCount = 0;
        }

        /** Release the drained buffer and block for the next one;
         *  prepends the carry tail so split records re-join. */
        private void moveToNextKv() throws IOException {
            int remaining = cur.getLength() - cur.getPosition();
            if (remaining > 0) {
                byte[] tail = new byte[remaining];
                System.arraycopy(cur.getData(), cur.getPosition(), tail, 0,
                        remaining);
                carry = tail;
            }
            if (consumerIdx >= 0) {
                KVBuf finished = kvBufs[consumerIdx];
                synchronized (finished) {
                    finished.status = KVBuf.RECV_READY;
                    finished.notifyAll();
                }
            }
            consumerIdx = (consumerIdx + 1) % KV_BUF_NUM;
            KVBuf next = kvBufs[consumerIdx];
            synchronized (next) {
                while (next.status != KVBuf.REDC_READY && !closed
                        && queueFailure == null) {
                    try {
                        next.wait();
                    } catch (InterruptedException e) {
                        Thread.currentThread().interrupt();
                        throw new IOException("interrupted waiting for "
                                + "merge data");
                    }
                }
                if (next.status != KVBuf.REDC_READY) {
                    if (queueFailure != null) {
                        throw new IOException(
                                "engine failed mid-stream", queueFailure);
                    }
                    throw new EOFException("queue closed mid-stream");
                }
                if (carry.length == 0) {
                    cur.reset(next.bytes, 0, next.actLen);
                } else {
                    byte[] joined = new byte[carry.length + next.actLen];
                    System.arraycopy(carry, 0, joined, 0, carry.length);
                    System.arraycopy(next.bytes, 0, joined, carry.length,
                            next.actLen);
                    carry = new byte[0];
                    cur.reset(joined, 0, joined.length);
                }
            }
        }

        @Override
        public DataInputBuffer getKey() {
            return key;
        }

        @Override
        public DataInputBuffer getValue() {
            return val;
        }

        @Override
        public boolean next() throws IOException {
            if (sawEof) {
                return false;
            }
            if (timeCount > 1000) {
                reporter.progress();
                timeCount = 0;
            }
            timeCount++;
            for (;;) {
                int mark = cur.getPosition();
                try {
                    int keyLen = WritableUtils.readVInt(cur);
                    int valLen = WritableUtils.readVInt(cur);
                    if (keyLen == -1 && valLen == -1) {
                        sawEof = true;    // the (-1,-1) stream marker
                        return false;
                    }
                    if (keyLen < 0 || valLen < 0) {
                        throw new IOException("corrupt record framing: ("
                                + keyLen + ", " + valLen + ")");
                    }
                    if (cur.getPosition() + keyLen + valLen
                            > cur.getLength()) {
                        cur.reset(cur.getData(), mark,
                                cur.getLength() - mark);
                        moveToNextKv();  // record spans buffers: join
                        continue;
                    }
                    key.reset(cur.getData(), cur.getPosition(), keyLen);
                    cur.skipBytes(keyLen);
                    val.reset(cur.getData(), cur.getPosition(), valLen);
                    cur.skipBytes(valLen);
                    return true;
                } catch (EOFException e) {
                    // framing split across the buffer boundary
                    cur.reset(cur.getData(), mark, cur.getLength() - mark);
                    moveToNextKv();
                }
            }
        }

        @Override
        public void close() {
            closed = true;
            for (KVBuf buf : kvBufs) {
                synchronized (buf) {
                    buf.notifyAll();
                }
            }
        }

        @Override
        public Progress getProgress() {
            return progress;
        }
    }

    /** Pull-based conf for the bridge's get_conf_data up-call. */
    private final class JobConfSource implements UdaBridge.ConfSource {
        @Override
        public String get(String name, String defaultValue) {
            return jobConf.get(name, defaultValue);
        }
    }
}
