// UdaShuffleHandler — the NodeManager auxiliary service the provider
// side registers as (yarn.nodemanager.aux-services = uda_shuffle,
// yarn.nodemanager.aux-services.uda_shuffle.class = this class).
//
// Mirrors the reference's UdaShuffleHandler (plugins/mlx-2.x/com/
// mellanox/hadoop/mapred/UdaShuffleHandler.java:59-151): service
// lifecycle owns the UdaPluginSH channel; per-application init/stop
// keeps the job -> user registry getPathIndex resolves through.
package com.mellanox.hadoop.mapred;

import java.io.IOException;
import java.nio.ByteBuffer;
import java.util.logging.Logger;

import org.apache.hadoop.conf.Configuration;
import org.apache.hadoop.mapred.JobID;
import org.apache.hadoop.yarn.api.records.ApplicationId;
import org.apache.hadoop.yarn.server.api.ApplicationInitializationContext;
import org.apache.hadoop.yarn.server.api.ApplicationTerminationContext;
import org.apache.hadoop.yarn.server.api.AuxiliaryService;

public class UdaShuffleHandler extends AuxiliaryService {

    private static final Logger LOG =
            Logger.getLogger(UdaShuffleHandler.class.getName());

    public static final String MAPREDUCE_RDMA_SHUFFLE_SERVICEID =
            "uda.shuffle";

    private Configuration config;
    private UdaPluginSH rdmaChannel;

    public UdaShuffleHandler() {
        super("uda_shuffle");
    }

    @Override
    public synchronized void init(Configuration conf) {
        LOG.info("init of UdaShuffleHandler");
        this.config = conf;
        super.init(new Configuration(conf));
    }

    @Override
    public synchronized void start() {
        LOG.info("start of UdaShuffleHandler");
        try {
            rdmaChannel = new UdaPluginSH(config);
        } catch (IOException e) {
            throw new UdaRuntimeException(
                    "failed to start the UDA supplier channel", e);
        }
        super.start();
    }

    @Override
    public synchronized void stop() {
        LOG.info("stop of UdaShuffleHandler");
        if (rdmaChannel != null) {
            rdmaChannel.close();
        }
        super.stop();
    }

    @Override
    public void initializeApplication(
            ApplicationInitializationContext context) {
        ApplicationId appId = context.getApplicationId();
        JobID jobId = new JobID(
                Long.toString(appId.getClusterTimestamp()), appId.getId());
        rdmaChannel.addJob(context.getUser(), jobId);
    }

    @Override
    public void stopApplication(ApplicationTerminationContext context) {
        ApplicationId appId = context.getApplicationId();
        JobID jobId = new JobID(
                Long.toString(appId.getClusterTimestamp()), appId.getId());
        rdmaChannel.removeJob(jobId);
    }

    @Override
    public synchronized ByteBuffer getMetaData() {
        // empty, not null (YARN-1256)
        return ByteBuffer.allocate(0);
    }
}
