// UdaPluginSH — the supplier (NodeManager) side of the plugin layer:
// launches the MOFSupplier role of the bridge and serves getPathIndex
// up-calls through the UdaIndexResolver cache.
//
// Re-creation of the reference's UdaPluginSH (plugins/mlx-2.x/com/
// mellanox/hadoop/mapred/UdaPluginSH.java:67-146): job -> user
// registration (addJob/removeJob) and the getPathIndex resolution the
// bridge's get_path_uda up-call lands on, closing the IndexCache round
// trip the reference ran through JNI (UdaBridge.cc:352-438 ->
// UdaPluginSH.java:107-144).
package com.mellanox.hadoop.mapred;

import java.io.IOException;
import java.util.logging.Logger;

import org.apache.hadoop.conf.Configuration;
import org.apache.hadoop.mapred.JobConf;
import org.apache.hadoop.mapred.JobID;

public class UdaPluginSH implements UdaBridge.Callable {

    static final Logger LOG =
            Logger.getLogger(UdaPluginSH.class.getName());

    private final JobConf jobConf;
    private final UdaIndexResolver resolver;
    private final UdaBridge bridge;

    public UdaPluginSH(Configuration conf) throws IOException {
        this.jobConf = new JobConf(conf);
        this.resolver = new UdaIndexResolver(jobConf);
        LOG.info("initApp of UdaPluginSH");
        String lib = jobConf.get("uda.tpu.bridge.library",
                "libuda_tpu_bridge.so");
        try {
            bridge = new UdaBridge(lib, this, resolver, (name, dflt) ->
                    jobConf.get(name, dflt));
            bridge.start(false, buildCmdParams());
        } catch (Throwable t) {
            throw new IOException("failed to launch the uda_tpu supplier "
                    + "bridge", t);
        }
    }

    private String[] buildCmdParams() {
        return new String[] {
            "-w", jobConf.get("mapred.rdma.wqe.per.conn", "256"),
            "-r", jobConf.get("mapred.rdma.cma.port", "9011"),
            "-s", jobConf.get("mapred.rdma.buf.size", "1024"),
        };
    }

    public void addJob(String user, JobID jobId) {
        resolver.addJob(user, jobId);
    }

    public void removeJob(JobID jobId) {
        resolver.removeJob(jobId);
        try {
            // engine-side cache hygiene: JOB_OVER invalidates the
            // supplier's cached index records for the job (the
            // reference's mof_downcall JOB_OVER path)
            bridge.doCommand(UdaCmd.formCmd(UdaCmd.JOB_OVER_COMMAND,
                    java.util.List.of(jobId.toString())));
        } catch (Throwable t) {
            LOG.warning("JOB_OVER for " + jobId + " failed: " + t);
        }
    }

    public void close() {
        try {
            bridge.reduceExit();  // EXIT teardown for the supplier role
        } catch (Throwable t) {
            LOG.warning("supplier close failed: " + t);
        }
    }

    // ---- Callable (supplier side only logs/fails) -----------------------

    @Override
    public void fetchOverMessage() {
    }

    @Override
    public void dataFromUda(byte[] data) {
    }

    @Override
    public void logToJava(int level, String message) {
        if (level <= 2) {
            LOG.severe("[uda_tpu] " + message);
        } else if (level == 3) {
            LOG.warning("[uda_tpu] " + message);
        } else {
            LOG.info("[uda_tpu] " + message);
        }
    }

    @Override
    public void failureInUda(String what) {
        LOG.severe("UDA supplier failure: " + what);
    }
}
