// UdaBridge.java — the JVM binding of the uda_tpu native bridge.
//
// Mirrors the reference's plugins/shared/com/mellanox/hadoop/mapred/
// UdaBridge.java (the 4 native down-calls, UdaBridge.java:49-81, and
// the static up-call receivers :85-145), but binds libuda_tpu_bridge.so
// through the JDK's java.lang.foreign (FFM) API instead of JNI — no
// extra jar, no jni.h: the shim exposes a plain C ABI
// (uda_bridge_start / uda_bridge_do_command / uda_bridge_reduce_exit /
// uda_bridge_set_log_level + an uda_callbacks_t function-pointer table,
// uda_tpu/native/bridge_shim.cc) designed for exactly this kind of
// foreign-function embedding.
//
// Requires JDK 22+ (final FFM API). Run with
//   --enable-native-access=ALL-UNNAMED
// so the upcall stubs are permitted.

package com.mellanox.hadoop.mapred;

import java.lang.foreign.Arena;
import java.lang.foreign.FunctionDescriptor;
import java.lang.foreign.Linker;
import java.lang.foreign.MemorySegment;
import java.lang.foreign.SymbolLookup;
import java.lang.invoke.MethodHandle;
import java.lang.invoke.MethodHandles;
import java.lang.invoke.MethodType;

import static java.lang.foreign.ValueLayout.ADDRESS;
import static java.lang.foreign.ValueLayout.JAVA_BYTE;
import static java.lang.foreign.ValueLayout.JAVA_INT;
import static java.lang.foreign.ValueLayout.JAVA_LONG;

public final class UdaBridge {

    /** Up-call surface, the UdaCallable of the reference (the subset a
     *  consumer plugin needs; index/conf resolution is the separate
     *  PathResolver/ConfSource surface below). */
    public interface Callable {
        void fetchOverMessage();

        void dataFromUda(byte[] data);

        void logToJava(int level, String message);

        void failureInUda(String what);
    }

    /** One reduce partition of one map output — the Java view of the
     *  shim's uda_index_record_t (bridge_shim.cc:41-46; reference
     *  index_record_t, src/MOFServer/IndexInfo.h:98-104). */
    public static final class IndexRecord {
        public final String path;
        public final long startOffset;
        public final long rawLength;
        public final long partLength;

        public IndexRecord(String path, long startOffset, long rawLength,
                           long partLength) {
            this.path = path;
            this.startOffset = startOffset;
            this.rawLength = rawLength;
            this.partLength = partLength;
        }
    }

    /** Supplier-side index resolution (the getPathUda up-call target,
     *  reference UdaBridge.cc:352-438 -> UdaPluginSH.getPathIndex,
     *  UdaPluginSH.java:107-144). Return null on failure. */
    public interface PathResolver {
        IndexRecord getPathIndex(String jobId, String mapId, int reduce);
    }

    /** Pull-based conf channel (the getConfData up-call, reference
     *  UdaBridge.cc:441-471 -> UdaPluginRT.getDataFromConf). */
    public interface ConfSource {
        String get(String name, String defaultValue);
    }

    private static final Linker LINKER = Linker.nativeLinker();
    private static final Arena ARENA = Arena.ofShared();

    private final MethodHandle hStart;
    private final MethodHandle hDoCommand;
    private final MethodHandle hReduceExit;
    private final MethodHandle hSetLogLevel;
    private final MethodHandle hFailed;
    private final MemorySegment callbacks; // uda_callbacks_t
    private final Callable callable;
    // One live bridge per process (the shim keeps process-global state,
    // like the reference's single reduce task per NetMerger process,
    // reducer.h:137); the up-call receivers bind at start(), not at
    // construction, so building a second instance cannot steal a live
    // bridge's callbacks.
    private static volatile Callable target;
    private static volatile PathResolver pathResolver;
    private static volatile ConfSource confSource;
    private final PathResolver resolver;
    private final ConfSource conf;

    public UdaBridge(String libraryPath, Callable callable)
            throws Throwable {
        this(libraryPath, callable, null, null);
    }

    /** Full surface: a consumer embedding passes a Callable; a supplier
     *  embedding additionally registers the PathResolver (and either
     *  may expose pull-based conf). */
    public UdaBridge(String libraryPath, Callable callable,
                     PathResolver resolver, ConfSource conf)
            throws Throwable {
        this.callable = callable;
        this.resolver = resolver;
        this.conf = conf;
        SymbolLookup lib = SymbolLookup.libraryLookup(libraryPath, ARENA);
        hStart = LINKER.downcallHandle(
                lib.find("uda_bridge_start").orElseThrow(),
                FunctionDescriptor.of(JAVA_INT, JAVA_INT, JAVA_INT,
                        ADDRESS, ADDRESS));
        hDoCommand = LINKER.downcallHandle(
                lib.find("uda_bridge_do_command").orElseThrow(),
                FunctionDescriptor.of(JAVA_INT, ADDRESS));
        hReduceExit = LINKER.downcallHandle(
                lib.find("uda_bridge_reduce_exit").orElseThrow(),
                FunctionDescriptor.of(JAVA_INT));
        hSetLogLevel = LINKER.downcallHandle(
                lib.find("uda_bridge_set_log_level").orElseThrow(),
                FunctionDescriptor.of(JAVA_INT, JAVA_INT));
        hFailed = LINKER.downcallHandle(
                lib.find("uda_bridge_failed").orElseThrow(),
                FunctionDescriptor.of(JAVA_INT));
        callbacks = buildCallbacks();
    }

    // ---- static up-call receivers (the reference's static methods,
    // UdaBridge.java:85-145) -------------------------------------------

    // Every receiver swallows Throwable: an exception unwinding into a
    // Linker.upcallStub terminates the whole JVM (FFM semantics) — the
    // embedder surfaces failures through its own channels instead.
    private static void cbFetchOver(MemorySegment ctx) {
        try {
            Callable t = target;
            if (t != null) t.fetchOverMessage();
        } catch (Throwable t2) {
            System.err.println("[UdaBridge] fetchOverMessage threw: " + t2);
        }
    }

    private static void cbDataFromUda(MemorySegment ctx, MemorySegment data,
                                      long len) {
        try {
            Callable t = target;
            if (t == null) return;
            byte[] out = new byte[(int) len];
            MemorySegment.copy(data.reinterpret(len), JAVA_BYTE, 0, out, 0,
                    (int) len);
            t.dataFromUda(out);
        } catch (Throwable t2) {
            // a dropped block means the stream is unrecoverable: route
            // into the failure path so consumers wake and fail over
            // instead of waiting forever for the missing bytes
            System.err.println("[UdaBridge] dataFromUda threw: " + t2);
            try {
                Callable t = target;
                if (t != null) {
                    t.failureInUda("dataFromUda delivery failed: " + t2);
                }
            } catch (Throwable t3) {
                System.err.println("[UdaBridge] failure relay threw: "
                        + t3);
            }
        }
    }

    // uda_index_record_t layout (bridge_shim.cc:41-46):
    // char path[4096]; long long start_offset, raw_length, part_length
    private static final long REC_PATH_CAP = 4096;
    private static final long REC_SIZE = 4096 + 3 * 8;

    private static int cbGetPath(MemorySegment ctx, MemorySegment job,
                                 MemorySegment map, int reduce,
                                 MemorySegment rec) {
        PathResolver r = pathResolver;
        if (r == null) return 1;
        try {
            IndexRecord ir = r.getPathIndex(
                    job.reinterpret(1 << 16).getString(0),
                    map.reinterpret(1 << 16).getString(0), reduce);
            if (ir == null) return 1;
            byte[] path = ir.path.getBytes(
                    java.nio.charset.StandardCharsets.UTF_8);
            if (path.length >= REC_PATH_CAP) return 1;
            MemorySegment out = rec.reinterpret(REC_SIZE);
            MemorySegment.copy(path, 0, out, JAVA_BYTE, 0, path.length);
            out.set(JAVA_BYTE, path.length, (byte) 0);
            out.set(JAVA_LONG, 4096, ir.startOffset);
            out.set(JAVA_LONG, 4104, ir.rawLength);
            out.set(JAVA_LONG, 4112, ir.partLength);
            return 0;
        } catch (Throwable t) {
            // never let an exception unwind into native
            return 1;
        }
    }

    private static void cbGetConf(MemorySegment ctx, MemorySegment name,
                                  MemorySegment dflt, MemorySegment out,
                                  int cap) {
        String value = null;
        try {
            String dfltStr = dflt.reinterpret(1 << 16).getString(0);
            ConfSource c = confSource;
            value = c == null ? dfltStr
                    : c.get(name.reinterpret(1 << 16).getString(0), dfltStr);
            if (value == null) value = dfltStr;
        } catch (Throwable t) {
            value = "";
        }
        if (cap <= 0) return;
        byte[] bytes = value.getBytes(
                java.nio.charset.StandardCharsets.UTF_8);
        int n = Math.min(bytes.length, cap - 1);
        MemorySegment o = out.reinterpret(cap);
        MemorySegment.copy(bytes, 0, o, JAVA_BYTE, 0, n);
        o.set(JAVA_BYTE, n, (byte) 0);
    }

    private static void cbLogTo(MemorySegment ctx, int level,
                                MemorySegment msg) {
        try {
            Callable t = target;
            if (t != null) t.logToJava(level,
                    msg.reinterpret(1 << 16).getString(0));
        } catch (Throwable t2) {
            System.err.println("[UdaBridge] logToJava threw: " + t2);
        }
    }

    private static void cbFailure(MemorySegment ctx, MemorySegment what) {
        try {
            Callable t = target;
            if (t != null) t.failureInUda(
                    what.reinterpret(1 << 16).getString(0));
        } catch (Throwable t2) {
            System.err.println("[UdaBridge] failureInUda threw: " + t2);
        }
    }

    private MemorySegment buildCallbacks() throws Throwable {
        MethodHandles.Lookup l = MethodHandles.lookup();
        MemorySegment fetchOver = LINKER.upcallStub(
                l.findStatic(UdaBridge.class, "cbFetchOver",
                        MethodType.methodType(void.class,
                                MemorySegment.class)),
                FunctionDescriptor.ofVoid(ADDRESS), ARENA);
        MemorySegment dataFrom = LINKER.upcallStub(
                l.findStatic(UdaBridge.class, "cbDataFromUda",
                        MethodType.methodType(void.class,
                                MemorySegment.class, MemorySegment.class,
                                long.class)),
                FunctionDescriptor.ofVoid(ADDRESS, ADDRESS, JAVA_LONG),
                ARENA);
        MemorySegment logTo = LINKER.upcallStub(
                l.findStatic(UdaBridge.class, "cbLogTo",
                        MethodType.methodType(void.class,
                                MemorySegment.class, int.class,
                                MemorySegment.class)),
                FunctionDescriptor.ofVoid(ADDRESS, JAVA_INT, ADDRESS),
                ARENA);
        MemorySegment failure = LINKER.upcallStub(
                l.findStatic(UdaBridge.class, "cbFailure",
                        MethodType.methodType(void.class,
                                MemorySegment.class, MemorySegment.class)),
                FunctionDescriptor.ofVoid(ADDRESS, ADDRESS), ARENA);
        MemorySegment getPath = LINKER.upcallStub(
                l.findStatic(UdaBridge.class, "cbGetPath",
                        MethodType.methodType(int.class,
                                MemorySegment.class, MemorySegment.class,
                                MemorySegment.class, int.class,
                                MemorySegment.class)),
                FunctionDescriptor.of(JAVA_INT, ADDRESS, ADDRESS, ADDRESS,
                        JAVA_INT, ADDRESS), ARENA);
        MemorySegment getConf = LINKER.upcallStub(
                l.findStatic(UdaBridge.class, "cbGetConf",
                        MethodType.methodType(void.class,
                                MemorySegment.class, MemorySegment.class,
                                MemorySegment.class, MemorySegment.class,
                                int.class)),
                FunctionDescriptor.ofVoid(ADDRESS, ADDRESS, ADDRESS,
                        ADDRESS, JAVA_INT), ARENA);
        // uda_callbacks_t: {ctx, fetch_over_message, data_from_uda,
        //                   get_path_uda, get_conf_data, log_to,
        //                   failure_in_uda} — 7 pointers
        MemorySegment cbs = ARENA.allocate(7 * 8L, 8);
        cbs.set(ADDRESS, 0, MemorySegment.NULL);        // ctx
        cbs.set(ADDRESS, 8, fetchOver);
        cbs.set(ADDRESS, 16, dataFrom);
        cbs.set(ADDRESS, 24, getPath);   // -> PathResolver (or rc=1)
        cbs.set(ADDRESS, 32, getConf);   // -> ConfSource (or default)
        cbs.set(ADDRESS, 40, logTo);
        cbs.set(ADDRESS, 48, failure);
        return cbs;
    }

    // ---- down-calls (startNative / doCommandNative /
    // reduceExitMsgNative / setLogLevelNative) --------------------------

    public void start(boolean isNetMerger, String[] argv) throws Throwable {
        // the live bridge's receivers (see field note)
        target = callable;
        pathResolver = resolver;
        confSource = conf;
        // per-call natives live in a confined arena: freed on return
        // (the shim copies argv into Python strings during the call)
        try (Arena a = Arena.ofConfined()) {
            MemorySegment argvSeg = a.allocate((long) Math.max(
                    argv.length, 1) * 8, 8);
            for (int i = 0; i < argv.length; i++) {
                argvSeg.set(ADDRESS, (long) i * 8, a.allocateFrom(argv[i]));
            }
            int rc = (int) hStart.invokeExact(isNetMerger ? 1 : 0,
                    argv.length, argvSeg, callbacks);
            if (rc != 0) throw new RuntimeException(
                    "uda_bridge_start rc=" + rc);
        }
    }

    public void doCommand(String cmd) throws Throwable {
        try (Arena a = Arena.ofConfined()) {
            int rc = (int) hDoCommand.invokeExact(
                    (MemorySegment) a.allocateFrom(cmd));
            if (rc != 0) throw new RuntimeException(
                    "uda_bridge_do_command rc=" + rc + " cmd=" + cmd);
        }
    }

    public void reduceExit() throws Throwable {
        int rc = (int) hReduceExit.invokeExact();
        if (rc != 0) throw new RuntimeException("uda_bridge_reduce_exit rc="
                + rc);
    }

    public void setLogLevel(int level) throws Throwable {
        int rc = (int) hSetLogLevel.invokeExact(level);
        if (rc != 0) throw new RuntimeException(
                "uda_bridge_set_log_level rc=" + rc);
    }

    public boolean failed() throws Throwable {
        return (int) hFailed.invokeExact() != 0;
    }
}
