// UdaJobDriver — a JVM process driving the FULL Hadoop plugin stack
// end-to-end: UdaShuffleConsumerPlugin.init(Context) constructs
// UdaPluginRT (shuffle-memory budget + INIT over the bridge), a fake
// umbilical feeds map-completion events to the GetMapEventsThread
// (dedupe + fetch + final merge), run() returns the J2CQueue
// RawKeyValueIterator, and the driver drains it through the KVBuf ring
// — the whole consumer path a real ReduceTask would execute, plus the
// supplier-side getPathUda round trip when the resolver mode is on.
//
// Usage:
//   java --enable-native-access=ALL-UNNAMED \
//        com.mellanox.hadoop.mapred.UdaJobDriver \
//        <libuda_tpu_bridge.so> <mof_root> <job_id> <num_maps> <out> \
//        <mode: dirs | upcall>
//
// mode=dirs:   INIT carries the MOF root as a local dir (engine-side
//              DirIndexResolver).
// mode=upcall: INIT carries NO dirs; the engine resolves every map
//              output through the get_path_uda up-call into
//              UdaIndexResolver (the reference's IndexCache round trip,
//              UdaBridge.cc:352-438 -> UdaPluginSH.java:107-144).
//
// The merged records are re-framed (VInt klen, VInt vlen, key, value +
// EOF marker) into <out> for the Python caller to validate byte-level.
package com.mellanox.hadoop.mapred;

import java.io.DataOutputStream;
import java.io.FileOutputStream;
import java.io.IOException;
import java.util.ArrayList;
import java.util.List;

import org.apache.hadoop.io.DataInputBuffer;
import org.apache.hadoop.io.WritableUtils;
import org.apache.hadoop.mapred.JobID;
import org.apache.hadoop.mapred.MapTaskCompletionEventsUpdate;
import org.apache.hadoop.mapred.RawKeyValueIterator;
import org.apache.hadoop.mapred.Reporter;
import org.apache.hadoop.mapred.ShuffleConsumerPlugin;
import org.apache.hadoop.mapred.TaskAttemptID;
import org.apache.hadoop.mapred.TaskCompletionEvent;
import org.apache.hadoop.mapred.TaskUmbilicalProtocol;
import org.apache.hadoop.mapred.JobConf;

public final class UdaJobDriver {

    /** Serves SUCCEEDED events in two batches (exercising incremental
     *  fromEventId) and prepends a duplicate attempt of map 0 (the
     *  dedupe path, UdaShuffleConsumerPluginShared.java:546-566). */
    private static final class FakeUmbilical
            implements TaskUmbilicalProtocol {

        private final List<TaskCompletionEvent> events = new ArrayList<>();

        FakeUmbilical(String job, int numMaps) {
            for (int m = 0; m < numMaps; m++) {
                String attempt = String.format("attempt_%s_m_%06d_0",
                        job.substring("job_".length()), m);
                events.add(new TaskCompletionEvent(
                        TaskCompletionEvent.Status.SUCCEEDED,
                        TaskAttemptID.forName(attempt),
                        "http://localhost:8080"));
                if (m == 0) {
                    // a second attempt of the same task: must be ignored
                    events.add(new TaskCompletionEvent(
                            TaskCompletionEvent.Status.SUCCEEDED,
                            TaskAttemptID.forName(String.format(
                                    "attempt_%s_m_%06d_1",
                                    job.substring("job_".length()), m)),
                            "http://localhost:8080"));
                }
            }
        }

        @Override
        public MapTaskCompletionEventsUpdate getMapCompletionEvents(
                JobID jobId, int fromEventId, int maxLocs,
                TaskAttemptID reduceId) {
            int half = Math.max(1, events.size() / 2);
            int upto = fromEventId == 0 ? half : events.size();
            if (fromEventId >= events.size()) {
                return new MapTaskCompletionEventsUpdate(
                        new TaskCompletionEvent[0], false);
            }
            List<TaskCompletionEvent> batch =
                    events.subList(fromEventId, upto);
            return new MapTaskCompletionEventsUpdate(
                    batch.toArray(new TaskCompletionEvent[0]), false);
        }
    }

    public static void main(String[] args) throws Exception {
        if (args.length != 6) {
            System.err.println("usage: UdaJobDriver <lib> <root> <job> "
                    + "<num_maps> <out> <dirs|upcall>");
            System.exit(2);
        }
        String lib = args[0], root = args[1], job = args[2], out = args[4];
        int numMaps = Integer.parseInt(args[3]);
        boolean upcall = args[5].equals("upcall");

        JobConf conf = new JobConf();
        conf.set("uda.tpu.bridge.library", lib);
        conf.set("mapreduce.job.maps", Integer.toString(numMaps));
        conf.set("mapreduce.job.output.key.class", "uda.tpu.RawBytes");
        if (upcall) {
            // no local dirs in INIT -> the engine resolves through the
            // get_path_uda up-call into UdaIndexResolver
            conf.set("uda.tpu.path.resolver.class",
                    "com.mellanox.hadoop.mapred.UdaIndexResolver");
            conf.set("uda.tpu.index.local.dirs", root);
        } else {
            conf.set("mapred.local.dir", root);
        }

        String jt = job.substring("job_".length(),
                job.lastIndexOf('_'));
        String jobNum = job.substring(job.lastIndexOf('_') + 1);
        TaskAttemptID reduceId = TaskAttemptID.forName(
                "attempt_" + jt + "_" + jobNum + "_r_000000_0");
        Reporter reporter = new Reporter() {
            @Override
            public void progress() {
            }

            @Override
            public void setStatus(String status) {
            }
        };

        UdaShuffleConsumerPlugin<byte[], byte[]> plugin =
                new UdaShuffleConsumerPlugin<>();
        plugin.init(new ShuffleConsumerPlugin.Context<>(reduceId, conf,
                reporter, new FakeUmbilical(job, numMaps)));
        RawKeyValueIterator it = plugin.run();

        int records = 0;
        try (DataOutputStream o = new DataOutputStream(
                new FileOutputStream(out))) {
            while (it.next()) {
                DataInputBuffer k = it.getKey();
                DataInputBuffer v = it.getValue();
                int klen = k.getLength() - k.getPosition();
                int vlen = v.getLength() - v.getPosition();
                WritableUtils.writeVInt(o, klen);
                WritableUtils.writeVInt(o, vlen);
                o.write(k.getData(), k.getPosition(), klen);
                o.write(v.getData(), v.getPosition(), vlen);
                records++;
            }
            o.writeByte(0xFF);  // EOF marker: VInt(-1) VInt(-1)
            o.writeByte(0xFF);
        }
        plugin.close();
        System.out.println("JVM-PLUGIN-OK " + records + " records mode="
                + args[5]);
    }

    private UdaJobDriver() {
    }
}
