// The class Hadoop actually loads: set
//   mapreduce.job.reduce.shuffle.consumer.plugin.class =
//       com.mellanox.hadoop.mapred.UdaShuffleConsumerPlugin
// and the ReduceTask drives init -> run -> close through the
// hadoop-2.x ShuffleConsumerPlugin SPI.
//
// Mirrors the reference's per-version UdaShuffleConsumerPlugin
// (plugins/mlx-2.x/com/mellanox/hadoop/mapred/
// UdaShuffleConsumerPlugin.java:30-84): a thin SPI adapter over the
// shared core — init delegates, run = fetchOutputs + createKVIterator,
// close delegates.
package com.mellanox.hadoop.mapred;

import java.io.IOException;

import org.apache.hadoop.mapred.RawKeyValueIterator;
import org.apache.hadoop.mapred.ShuffleConsumerPlugin;

public class UdaShuffleConsumerPlugin<K, V>
        implements ShuffleConsumerPlugin<K, V> {

    private final UdaShuffleConsumerPluginShared<K, V> udaPlugin =
            new UdaShuffleConsumerPluginShared<>();

    @Override
    public void init(ShuffleConsumerPlugin.Context<K, V> context) {
        udaPlugin.init(context);
    }

    @Override
    public RawKeyValueIterator run() throws IOException,
            InterruptedException {
        if (udaPlugin.fetchOutputs()) {
            return udaPlugin.createKVIterator();
        }
        throw new IOException(
                "critical failure in udaPlugin.fetchOutputs()");
    }

    @Override
    public void close() {
        udaPlugin.close();
    }
}
