// The count:header:params command protocol (reference UdaCmd,
// plugins/shared/.../UdaPlugin.java:562-587; Python twin:
// uda_tpu/bridge/protocol.py — the enum values must stay identical).
package com.mellanox.hadoop.mapred;

import java.util.List;

final class UdaCmd {

    static final int EXIT_COMMAND = 0;
    static final int NEW_MAP_COMMAND = 1;
    static final int FINAL_MERGE_COMMAND = 2;
    static final int RESULT_COMMAND = 3;
    static final int FETCH_COMMAND = 4;
    static final int FETCH_OVER_COMMAND = 5;
    static final int JOB_OVER_COMMAND = 6;
    static final int INIT_COMMAND = 7;
    static final int MORE_COMMAND = 8;
    static final int NETLEV_REDUCE_LAUNCHED = 9;
    private static final char SEPARATOR = ':';

    private UdaCmd() {
    }

    /** num_params:cmd:param1:param2... */
    static String formCmd(int cmd, List<String> params) {
        StringBuilder sb = new StringBuilder();
        sb.append(params.size()).append(SEPARATOR).append(cmd);
        for (String p : params) {
            sb.append(SEPARATOR).append(p);
        }
        return sb.toString();
    }
}
