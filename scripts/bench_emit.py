"""Microbench: native bulk framing vs the per-record Python writer.

The emit/spill hot path (reference src/Merger/StreamRW.cc:151-225
``write_kv_to_stream``, a C++ loop) must not degrade to per-record
Python at TeraSort scale. Measures both FramedEmitter paths over the
same sorted batch and prints the speedup.

Run: python scripts/bench_emit.py [num_records]
"""

from __future__ import annotations

import sys
import time

import numpy as np


def main() -> None:
    sys.path.insert(0, ".")
    from uda_tpu import native
    from uda_tpu.merger.emitter import FramedEmitter
    from uda_tpu.utils.ifile import crack, write_records

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
    rng = np.random.default_rng(0)
    print(f"# building {n} records (10B keys / 90B values)...",
          file=sys.stderr)
    keys = rng.bytes(10 * n)
    vals = rng.bytes(90 * n)
    recs = [(keys[i * 10:(i + 1) * 10], vals[i * 90:(i + 1) * 90])
            for i in range(n)]
    batch = crack(write_records(recs))
    block = 1 << 20
    sink = {"bytes": 0}

    def consumer(view) -> None:
        sink["bytes"] += len(view)

    def timed(fn) -> float:
        best = float("inf")
        for _ in range(3):
            sink["bytes"] = 0
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    em = FramedEmitter(block)
    t_py = timed(lambda: em.emit(iter(recs), consumer))
    py_gbps = sink["bytes"] / t_py / 1e9
    if not native.build():
        print(f"python emit: {py_gbps:.2f} GB/s (native library not "
              "built; no comparison)")
        return
    t_nat = timed(lambda: em.emit_batch(batch, consumer))
    nat_gbps = sink["bytes"] / t_nat / 1e9
    print(f"python per-record emit: {t_py:.3f}s ({py_gbps:.2f} GB/s)")
    print(f"native bulk emit:       {t_nat:.3f}s ({nat_gbps:.2f} GB/s)")
    print(f"speedup: {t_py / t_nat:.1f}x")


if __name__ == "__main__":
    main()
