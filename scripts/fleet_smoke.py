#!/usr/bin/env python
"""Loopback smoke of the fleet observability plane (scripts/build/
ci.sh gate): ONE tenanted, observability-armed daemon on 127.0.0.1,
8 tenant driver processes (scripts/tenant_bench.py --driver) hammering
it with equal weights, and scripts/udafleet.py --once --json polled
against it — first mid-run (the live view must carry the CAP_OBS
sections while queues are formed), then post-run for the WDRR
fairness audit: every tenant's fleet share of scheduled bytes must
land within FAIR_TOL of its weight-proportional entitlement (equal
weights -> 1/8 each). Exit code != 0 on any gate failure.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tests.helpers import make_mof_tree  # noqa: E402
from uda_tpu.mofserver import DataEngine, DirIndexResolver  # noqa: E402
from uda_tpu.net import ShuffleServer  # noqa: E402
from uda_tpu.utils.config import Config  # noqa: E402

TENANTS = 8
FAIR_TOL = 0.02  # |share - entitlement|, absolute (the 2% acceptance)
NUM_MAPS = 1
RECORDS = 100
VAL_BYTES = 500
CHUNK = 4 << 20
DEPTH = 12
WARMUP_S = 0.5
WINDOW_S = 2.0


def tenant_name(i: int) -> str:
    return f"tenant{i:02d}"


def job_name(i: int) -> str:
    return f"jobFleet{i:02d}"


def udafleet_once(port: int) -> dict:
    """The literal ci gate: one scripts/udafleet.py --once --json run
    against the live daemon, parsed."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/udafleet.py"),
         f"127.0.0.1:{port}", "--once", "--json", "--window", "30",
         "--timeout", "10"],
        capture_output=True, text=True, timeout=60)
    if out.returncode != 0:
        print(f"FLEET SMOKE FAIL: udafleet exited {out.returncode}: "
              f"{out.stderr.strip()}")
        sys.exit(1)
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="uda_fleet_smoke_")
    for i in range(TENANTS):
        make_mof_tree(tmp, job_name(i), num_maps=NUM_MAPS,
                      num_reducers=1, records_per_map=RECORDS,
                      val_bytes=VAL_BYTES, seed=300 + i)
    engine = DataEngine(DirIndexResolver(tmp), Config())
    # the tenant_bench contention shape (small shared pool, byte-path
    # serves, small socket buffers) so WDRR queues actually form, PLUS
    # the observability plane armed: rollup ring on a fast interval so
    # the SLI book sees several intervals inside the driver window
    server = ShuffleServer(
        engine, Config({"uda.tpu.tenant.enable": True,
                        "uda.tpu.stats.enable": True,
                        "uda.tpu.ts.interval.s": 0.2,
                        "uda.tpu.net.zerocopy": False,
                        "uda.tpu.net.sockbuf.kb": 64,
                        "uda.tpu.tenant.wqe.total": TENANTS // 2}),
        host="127.0.0.1", port=0).start()
    rc = 0
    procs = []
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        for i in range(TENANTS):
            procs.append(subprocess.Popen(
                [sys.executable,
                 os.path.join(REPO, "scripts/tenant_bench.py"),
                 "--driver", "--port", str(server.port),
                 "--tenant", tenant_name(i), "--job", job_name(i),
                 "--maps", str(NUM_MAPS), "--chunk", str(CHUNK),
                 "--depth", str(DEPTH), "--weight", "1",
                 "--warmup", str(WARMUP_S), "--window", str(WINDOW_S)],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=env))
        # mid-run poll: the live fleet view, queues formed
        time.sleep(WARMUP_S + WINDOW_S * 0.5)
        live = udafleet_once(server.port)
        spec = f"127.0.0.1:{server.port}"
        if live["daemons"].get(spec) != "ok":
            print(f"FLEET SMOKE FAIL: daemon status "
                  f"{live['daemons'].get(spec)!r}, want 'ok'")
            return 1
        if not isinstance(live.get("anomalies"), list):
            print("FLEET SMOKE FAIL: no anomalies section")
            return 1
        for p in procs:
            p.wait(timeout=WARMUP_S + WINDOW_S + 60)
        # post-run poll: lifetime scheduled bytes are final — the
        # fairness audit the SLI book exists to answer
        final = udafleet_once(server.port)
        tenants = final.get("tenants", {})
        if len(tenants) < TENANTS:
            print(f"FLEET SMOKE FAIL: fleet view shows "
                  f"{len(tenants)}/{TENANTS} tenants: {sorted(tenants)}")
            return 1
        entitled = 1.0 / TENANTS
        worst = (None, 0.0)
        for t, agg in sorted(tenants.items()):
            share = agg.get("fleet_share")
            if share is None:
                print(f"FLEET SMOKE FAIL: tenant {t} has no fleet share")
                return 1
            dev = abs(share - entitled)
            if dev > worst[1]:
                worst = (t, dev)
            if dev > FAIR_TOL:
                print(f"FLEET SMOKE FAIL: tenant {t} share "
                      f"{share:.4f} deviates {dev:.4f} from the "
                      f"equal-weight entitlement {entitled:.4f} "
                      f"(tol {FAIR_TOL})")
                rc = 1
        if rc == 0:
            print(f"FLEET SMOKE OK: {TENANTS} tenants, worst share "
                  f"deviation {worst[1]:.4f} ({worst[0]}) within "
                  f"{FAIR_TOL} of entitlement; daemon ok, "
                  f"{len(final['anomalies'])} active anomalies")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
        engine.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
