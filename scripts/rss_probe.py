#!/usr/bin/env python
"""Peak-RSS probe for the online merge: multi-GB shuffle, O(window) host?

Drives MergeManager over a SYNTHETIC transport that manufactures each
fetch chunk on the fly (deterministic per (map, offset)), so the input
shuffle never exists in host memory or on disk — whatever RSS the
process reaches is the merge engine's own footprint. This is the
evidence harness for the bounded-memory claim of
``uda.tpu.online.streaming`` (the reference's staging-loop memory model,
reference src/Merger/StreamRW.cc:151-225, MergeManager.cc:155-182): the
streaming path must hold O(fetch window), not O(shuffle).

Prints one JSON line:
  {"mode": ..., "shuffle_bytes": N, "peak_rss_bytes": N, "wall_s": ...}

Run it in a fresh subprocess per mode (RU_MAXRSS is a process high-water
mark); ``--compare`` forks one child per mode and asserts the bound.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _force_cpu() -> None:
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")


class SyntheticClient:
    """InputClient manufacturing sorted IFile partitions chunk by chunk.

    Each map's partition is ``records`` fixed-size records (key_bytes
    key, val_bytes value) with keys drawn from a per-map seeded
    Philox stream and PRE-SORTED — generated lazily per chunk request,
    cached only for the duration of that map's fetch."""

    def __init__(self, records: int, key_bytes: int, val_bytes: int,
                 cache_slots: int = 12):
        self.records = records
        self.key_bytes = key_bytes
        self.val_bytes = val_bytes
        self.cache_slots = cache_slots  # ~fetch window; keep the probe's
        self._cache: dict[str, bytes] = {}  # own memory out of the result

    def _partition(self, map_id: str) -> bytes:
        # one map's framed partition; cached so the 2-3 chunk fetches of
        # the same map don't regenerate it, evicted when another map is
        # requested (fetch windows interleave, so keep a small LRU)
        data = self._cache.get(map_id)
        if data is None:
            import numpy as np

            from uda_tpu.utils.ifile import RecordBatch
            from uda_tpu import native

            seed = abs(hash(map_id)) % (2**31)
            rng = np.random.default_rng(seed)
            keys = rng.integers(0, 256, (self.records, self.key_bytes),
                                dtype=np.uint8)
            keys = keys[np.lexsort(
                tuple(keys[:, c] for c in range(self.key_bytes - 1, -1, -1)))]
            vals = rng.integers(0, 256, (self.records, self.val_bytes),
                                dtype=np.uint8)
            buf = np.concatenate(
                [keys.reshape(-1), vals.reshape(-1)]).astype(np.uint8)
            n = self.records
            batch = RecordBatch(
                buf,
                np.arange(n, dtype=np.int64) * self.key_bytes,
                np.full(n, self.key_bytes, np.int64),
                n * self.key_bytes + np.arange(n, dtype=np.int64)
                * self.val_bytes,
                np.full(n, self.val_bytes, np.int64))
            data = b"".join(native.iter_framed_chunks(batch, write_eof=True))
            if len(self._cache) >= self.cache_slots:
                self._cache.pop(next(iter(self._cache)))
            self._cache[map_id] = data
        return data

    def start_fetch(self, req, on_complete) -> None:
        from uda_tpu.mofserver.data_engine import FetchResult

        data = self._partition(req.map_id)
        chunk = data[req.offset:req.offset + req.chunk_size]
        last = req.offset + len(chunk) >= len(data)
        if last:
            self._cache.pop(req.map_id, None)
        on_complete(FetchResult(chunk, len(data), len(data), req.offset,
                                "synthetic", last))

    def stop(self) -> None:
        self._cache.clear()


def run_probe(mode: str, maps: int, records: int, key_bytes: int,
              val_bytes: int) -> dict:
    _force_cpu()
    from uda_tpu.merger.merge_manager import MergeManager
    from uda_tpu.utils.comparators import get_key_type
    from uda_tpu.utils.config import Config

    cfg = Config({
        "uda.tpu.online.streaming": mode == "streaming",
        "mapred.netmerger.merge.approach": 2 if mode == "hybrid" else 1,
        "mapred.rdma.wqe.per.conn": 4,
    })
    client = SyntheticClient(records, key_bytes, val_bytes)
    mm = MergeManager(client, get_key_type("uda.tpu.RawBytes"), cfg)
    emitted = 0
    last_tail = b""

    def consumer(mv) -> None:
        nonlocal emitted, last_tail
        emitted += len(mv)
        last_tail = bytes(mv[-2:])

    t0 = time.monotonic()
    total = mm.run("rssjob", [f"m{i:05d}" for i in range(maps)], 0, consumer)
    wall = time.monotonic() - t0
    assert total == emitted and last_tail == b"\xff\xff", \
        (total, emitted, last_tail)
    shuffle = maps * records * (key_bytes + val_bytes)
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    # on the CPU probe the forest key rows live in HOST rss (the "host"
    # merge engine); on TPU they are HBM-resident — report the surrogate
    # so the host-side bound is judged on record bytes, as deployed
    kw = 16 // 4  # default uda.tpu.key.width
    rows_surrogate = maps * records * (kw + 3) * 4
    return {"mode": mode, "maps": maps, "records_per_map": records,
            "shuffle_bytes": shuffle, "emitted_bytes": emitted,
            "peak_rss_bytes": peak,
            "device_rows_surrogate_bytes": rows_surrogate,
            "wall_s": round(wall, 2)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["streaming", "inmem", "hybrid"],
                    default="streaming")
    ap.add_argument("--maps", type=int, default=80)
    ap.add_argument("--records", type=int, default=50_000,
                    help="records per map")
    ap.add_argument("--key-bytes", type=int, default=10)
    ap.add_argument("--val-bytes", type=int, default=1014,
                    help="default sizes a 4 GB shuffle whose device-row "
                         "surrogate is <2%% of it (see run_probe note)")
    ap.add_argument("--compare", action="store_true",
                    help="fork a child per mode; assert streaming stays "
                         "bounded while inmem scales with the shuffle")
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    if not args.compare:
        print(json.dumps(run_probe(args.mode, args.maps, args.records,
                                   args.key_bytes, args.val_bytes)))
        return 0

    results = {}
    for mode in ("streaming", "inmem"):
        cmd = [sys.executable, os.path.abspath(__file__), "--mode", mode,
               "--maps", str(args.maps), "--records", str(args.records),
               "--key-bytes", str(args.key_bytes),
               "--val-bytes", str(args.val_bytes)]
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=3600)
        if out.returncode != 0:
            print(out.stdout + out.stderr, file=sys.stderr)
            return 1
        results[mode] = json.loads(out.stdout.strip().splitlines()[-1])
    shuffle = results["streaming"]["shuffle_bytes"]
    verdict = {
        "shuffle_bytes": shuffle,
        "streaming_peak": results["streaming"]["peak_rss_bytes"],
        "inmem_peak": results["inmem"]["peak_rss_bytes"],
        "streaming_frac": round(
            results["streaming"]["peak_rss_bytes"] / shuffle, 3),
        "inmem_frac": round(
            results["inmem"]["peak_rss_bytes"] / shuffle, 3),
        "wall_streaming_s": results["streaming"]["wall_s"],
        "wall_inmem_s": results["inmem"]["wall_s"],
        # the claim: streaming holds O(window) of record bytes, far
        # below the shuffle (quarter-shuffle bound at the 4 GB default)
        "bounded": results["streaming"]["peak_rss_bytes"] < shuffle // 4,
    }
    print(json.dumps(verdict))
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"results": results, "verdict": verdict}, f, indent=1)
    return 0 if verdict["bounded"] else 2


if __name__ == "__main__":
    sys.exit(main())
