#!/usr/bin/env python
"""Metrics-name lint: every ``metrics.add/gauge/gauge_add/observe`` call
site in ``uda_tpu/`` must name a metric that

1. matches the dotted ``domain.metric`` namespace regex
   (``uda_tpu.utils.metrics.NAME_RE``), and
2. is listed in the registry table ``METRICS_REGISTRY`` (or, for
   f-string names, starts with a ``REGISTRY_PREFIXES`` prefix).

Run directly (exit 1 on violations) or through the tier-1 suite
(``tests/test_metrics.py::test_metrics_names_lint``). The point is that
a metric cannot be added ad hoc: the registry doubles as the documented
schema of the JSON-lines stats stream, so a name that never made it
into the table never made it into the docs either.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# first argument of a metrics call: a plain or f- string literal, or
# anything else (flagged: names must be statically auditable)
_CALL = re.compile(
    r"metrics\.(?:add|gauge|gauge_add|observe)\(\s*"
    r"(?:(f?)([\"'])([^\"']*)\2|([A-Za-z_][\w.\[\]]*))")


def _metrics_defs():
    sys.path.insert(0, REPO)
    from uda_tpu.utils.metrics import (METRICS_REGISTRY, NAME_RE,
                                       REGISTRY_PREFIXES)
    return METRICS_REGISTRY, REGISTRY_PREFIXES, re.compile(NAME_RE + r"\Z")


def check(root: str = None) -> List[Tuple[str, int, str, str]]:
    """Returns violations as (file, line, name, reason) tuples."""
    registry, prefixes, name_re = _metrics_defs()
    root = root or os.path.join(REPO, "uda_tpu")
    bad: List[Tuple[str, int, str, str]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                text = f.read()
            rel = os.path.relpath(path, REPO)
            for m in _CALL.finditer(text):
                line = text.count("\n", 0, m.start()) + 1
                fstr, name, expr = m.group(1), m.group(3), m.group(4)
                if expr is not None:
                    bad.append((rel, line, expr,
                                "metric name must be a string literal"))
                    continue
                if fstr:
                    prefix = name.split("{", 1)[0]
                    if not any(prefix.startswith(p) for p in prefixes):
                        bad.append((rel, line, name,
                                    f"f-string prefix {prefix!r} not in "
                                    f"REGISTRY_PREFIXES {prefixes}"))
                    continue
                if not name_re.match(name):
                    bad.append((rel, line, name,
                                "not dotted domain.metric namespace"))
                elif name not in registry:
                    bad.append((rel, line, name,
                                "not listed in METRICS_REGISTRY"))
    return bad


def main() -> int:
    bad = check()
    for rel, line, name, reason in bad:
        print(f"{rel}:{line}: metric {name!r}: {reason}", file=sys.stderr)
    if bad:
        print(f"{len(bad)} metric-name violation(s); register names in "
              f"uda_tpu/utils/metrics.py METRICS_REGISTRY",
              file=sys.stderr)
        return 1
    print("metrics names OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
