#!/usr/bin/env python
"""Metrics-name lint: every ``metrics.add/gauge/gauge_add/observe`` call
site in ``uda_tpu/`` must name a metric that

1. matches the dotted ``domain.metric`` namespace regex
   (``uda_tpu.utils.metrics.NAME_RE``), and
2. is listed in the registry table ``METRICS_REGISTRY`` (or, for
   f-string names, starts with a ``REGISTRY_PREFIXES`` prefix).

Since PR 5 this is a thin wrapper over the udalint **UDA002** AST rule
(``uda_tpu.analysis.rules.MetricsNameRule``) — the old regex engine
missed multiline call sites and aliased receivers (``from ... import
metrics as m``); the AST pass sees both. Same CLI and exit-code
contract as before: run directly (exit 1 on violations) or through the
tier-1 suite (``tests/test_metrics.py::test_metrics_names_lint``). The
point is unchanged: a metric cannot be added ad hoc — the registry
doubles as the documented schema of the JSON-lines stats stream, so a
name that never made it into the table never made it into the docs
either.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def check(root: str = None) -> List[Tuple[str, int, str, str]]:
    """Returns violations as (file, line, name, reason) tuples."""
    sys.path.insert(0, REPO)
    from uda_tpu.analysis.core import Engine
    from uda_tpu.analysis.rules import MetricsNameRule

    root = root or os.path.join(REPO, "uda_tpu")
    findings = Engine([MetricsNameRule()], root=REPO).lint_paths([root])
    return [(f.file, f.line, (f.data or {}).get("name", ""),
             (f.data or {}).get("reason", f.message))
            for f in findings]


def main() -> int:
    bad = check()
    for rel, line, name, reason in bad:
        print(f"{rel}:{line}: metric {name!r}: {reason}", file=sys.stderr)
    if bad:
        print(f"{len(bad)} metric-name violation(s); register names in "
              f"uda_tpu/utils/metrics.py METRICS_REGISTRY",
              file=sys.stderr)
        return 1
    print("metrics names OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
