#!/usr/bin/env python
"""Fused staging-pipeline A/B: pipelined vs serial stage path.

ISSUE 9's tentpole gate. The device engine hit 3.1 GB/s single-chip
(BENCH_HW_r05.json) while serial staging fed it at 42-72 MB/s
(STAGING_BENCH_r05.json) — the device was starved, not slow. The fix is
the bounded stage pool + merge consumer in uda_tpu.merger.overlap
(uda.tpu.stage.pipeline). This bench proves both halves of the claim on
CPU, where correctness is provable without a pool window:

- **correctness gate** (always, and all of ``--quick``): the pipelined
  staging path is BYTE-IDENTICAL to the serial path across
  sorted/shuffled input, the in-memory and spooled (streaming) modes,
  and a compressed end-to-end MergeManager run;
- **throughput A/B** (full mode): staged MB/s of the pipelined pool vs
  the serial ``stage_sorted_x1`` baseline on the 64x64 MB deployment
  shape — gate: pipelined >= 1.5x serial, spool variants must not
  regress (>= 0.95x) — plus ``merge.wait_ms`` p95 (how long the merge
  waited for each run to become mergeable) for both paths in the same
  run: the pipeline must DROP it.

Hardware re-probe of the device-side levers (keys8f / lanes2 /
cc-ladder / two-phase) is staged separately in scripts/tpu_return.py —
pending pool recovery, not claimed here.

Usage: python scripts/bench_pipeline.py [--segs 64] [--seg-mb 64]
       [--quick] [--out BENCH_PIPELINE.json]
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def _force_cpu() -> None:
    # staging is HOST work; the bench is valid on any backend. Force CPU
    # so a wedged TPU pool can't hang the run.
    import jax

    jax.config.update("jax_platforms", "cpu")


def _stage_once(batches, pipeline: bool, stagers: int, spool: bool,
                tmp: str) -> dict:
    """Stage every batch through one OverlappedMerger config; returns
    wall seconds + merge.wait_ms p95 (stats enabled per run)."""
    from uda_tpu.merger.overlap import OverlappedMerger
    from uda_tpu.merger.streaming import RunStore
    from uda_tpu.utils.comparators import get_key_type
    from uda_tpu.utils.metrics import metrics

    kt = get_key_type("uda.tpu.RawBytes")
    metrics.reset()
    metrics.enable_stats()
    store = RunStore([tmp], tag="pipebench") if spool else None
    om = OverlappedMerger(kt, 16, engine="host", run_store=store,
                          stagers=stagers, pipeline=pipeline)
    t0 = time.monotonic()
    for i, b in enumerate(batches):
        om.feed(i, b)
    om._drain()  # raises any staging error
    wall = time.monotonic() - t0
    p95 = metrics.percentile("merge.wait_ms", 95)
    if store is not None:
        assert store.total_records == sum(b.num_records for b in batches)
        store.cleanup()
    metrics.reset()
    return {"wall_s": wall, "wait_p95_ms": p95}


def _finish_bytes(batches, pipeline: bool, spool: bool, tmp: str) -> bytes:
    """Full staged merge -> emitted IFile bytes for identity checks."""
    from uda_tpu.merger.emitter import FramedEmitter
    from uda_tpu.merger.overlap import OverlappedMerger
    from uda_tpu.merger.streaming import RunStore
    from uda_tpu.utils.comparators import get_key_type

    kt = get_key_type("uda.tpu.RawBytes")
    store = RunStore([tmp], tag="pipeident") if spool else None
    om = OverlappedMerger(kt, 16, engine="host", run_store=store,
                          stagers=2 if pipeline else 1, pipeline=pipeline,
                          inflight_bytes=64 << 20)
    out = io.BytesIO()
    for i, b in enumerate(batches):
        om.feed(i, b)
    emitter = FramedEmitter(1 << 16)
    total = sum(b.num_records for b in batches)
    if spool:
        om.finish_streaming(emitter, lambda blk: out.write(bytes(blk)),
                            expected_records=total)
    else:
        om.emit_stream(batches, emitter,
                       lambda blk: out.write(bytes(blk)))
    return out.getvalue()


def _compressed_run_bytes(tmp: str, pipeline: bool) -> bytes:
    """Compressed end-to-end MergeManager run (zlib): fetch ->
    decompress -> pipelined/serial stage -> merge -> emit."""
    import numpy as np

    from uda_tpu.compress import DecompressingClient, get_codec
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.mofserver.writer import MOFWriter
    from uda_tpu.utils.config import Config

    root = os.path.join(tmp, f"cmof_{int(pipeline)}")
    codec = get_codec("zlib")
    rng = np.random.default_rng(7)
    job = "pipebenchC"
    writer = MOFWriter(root, job, codec=codec)
    for m in range(4):
        recs = sorted((rng.bytes(10), rng.bytes(40)) for _ in range(300))
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])
    cfg = Config({"uda.tpu.stage.pipeline": pipeline,
                  "mapred.rdma.buf.size": 8})
    engine = DataEngine(DirIndexResolver(root), cfg)
    try:
        mm = MergeManager(DecompressingClient(LocalFetchClient(engine),
                                              codec),
                          "uda.tpu.RawBytes", cfg)
        blocks: list[bytes] = []
        mm.run(job, writer.map_ids, 0, lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    return b"".join(blocks)


def _time_accounting_point(tmp: str) -> dict:
    """One pipelined MergeManager run with spans on -> the critpath
    ``time_accounting`` block (uda_tpu.utils.critpath). This is the
    time-accounting point perfwatch ingests next to the throughput
    numbers: bucket shares trend across rounds, and the buckets-sum-
    to-wall invariant is checked right here (exit gate in _run)."""
    from uda_tpu.utils.critpath import time_accounting_block
    from uda_tpu.utils.metrics import metrics

    metrics.reset()
    metrics.enable_stats()
    try:
        _compressed_run_bytes(os.path.join(tmp, "timeacct"), True)
        block = time_accounting_block()
    finally:
        metrics.reset()
    return block or {}


def identity_gate(tmp: str) -> dict:
    """Byte-identity of pipelined vs serial staging across input order,
    spool mode and compression — the CI correctness gate."""
    from scripts.bench_staging import make_segments

    checks = {}
    for sorted_input in (True, False):
        batches = make_segments(4, 1 << 20, sorted_input)
        tag = "sorted" if sorted_input else "shuffled"
        for spool in (False, True):
            a = _finish_bytes(batches, False, spool, tmp)
            b = _finish_bytes(batches, True, spool, tmp)
            key = f"{tag}{'_spool' if spool else ''}"
            checks[key] = (a == b and len(a) > 0)
    a = _compressed_run_bytes(tmp, False)
    b = _compressed_run_bytes(tmp, True)
    checks["compressed_e2e"] = (a == b and len(a) > 0)
    checks["all_identical"] = all(checks.values())
    return checks


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--segs", type=int, default=64)
    ap.add_argument("--seg-mb", type=int, default=64)
    ap.add_argument("--quick", action="store_true",
                    help="correctness gate + a small A/B (CI mode: "
                    "identity gated, throughput reported not gated)")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    _force_cpu()
    tmp = tempfile.mkdtemp(prefix="uda_pipebench_")
    try:
        return _run(args, tmp)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


def _run(args, tmp: str) -> int:
    from scripts.bench_staging import make_segments

    result: dict = {"identity": identity_gate(tmp)}
    if not result["identity"]["all_identical"]:
        print(json.dumps(result))
        print("FAIL: pipelined staging is not byte-identical to serial",
              file=sys.stderr)
        return 3

    # the where-time-goes point: buckets must partition the task wall
    # (critical + idle == wall by construction; gate at 5% for the
    # acceptance record). A missing block (span layer broken) fails —
    # this bench is the time-accounting plane's own canary.
    ta = _time_accounting_point(tmp)
    result["time_accounting"] = ta
    ta_sum = (sum(b["critical_s"] for b in ta.get("buckets", {}).values())
              + ta.get("idle_s", 0.0))
    result["time_accounting_sums_to_wall"] = bool(
        ta.get("wall_s") and abs(ta_sum - ta["wall_s"])
        <= 0.05 * ta["wall_s"])
    if not result["time_accounting_sums_to_wall"]:
        print(json.dumps(result))
        print("FAIL: time_accounting buckets do not sum to task wall",
              file=sys.stderr)
        return 3

    segs = 6 if args.quick else args.segs
    seg_mb = 4 if args.quick else args.seg_mb
    seg_bytes = seg_mb << 20
    total_mb = segs * seg_mb
    result.update({"segs": segs, "seg_mb": seg_mb, "total_mb": total_mb,
                   "nproc": os.cpu_count(), "quick": bool(args.quick)})

    # A/B matrix: serial x1 is THE baseline (stage_sorted_x1 of
    # STAGING_BENCH_r05); pipelined = stage pool (auto width) + merge
    # consumer. Fresh batches per sortedness so page-cache state is
    # comparable between the two paths.
    for sorted_input in (True, False):
        batches = make_segments(segs, seg_bytes, sorted_input)
        tag = "sorted" if sorted_input else "shuffled"
        for spool in ((False, True) if sorted_input else (False,)):
            sp = "_spool" if spool else ""
            for name, pipeline, stagers in (("serial_x1", False, 1),
                                            ("pipelined", True, 0)):
                r = _stage_once(batches, pipeline, stagers, spool, tmp)
                key = f"{tag}{sp}_{name}"
                result[key + "_s"] = round(r["wall_s"], 2)
                result[key + "_MBps"] = round(total_mb / r["wall_s"], 1)
                if r["wait_p95_ms"] is not None:
                    result[key + "_wait_p95_ms"] = round(r["wait_p95_ms"], 1)
        del batches

    def ratio(num_key: str, den_key: str) -> float:
        return round(result[num_key] / max(result[den_key], 1e-9), 2)

    result["speedup_sorted"] = ratio("sorted_pipelined_MBps",
                                     "sorted_serial_x1_MBps")
    result["speedup_sorted_spool"] = ratio("sorted_spool_pipelined_MBps",
                                           "sorted_spool_serial_x1_MBps")
    result["speedup_shuffled"] = ratio("shuffled_pipelined_MBps",
                                       "shuffled_serial_x1_MBps")
    wait_s = result.get("sorted_serial_x1_wait_p95_ms")
    wait_p = result.get("sorted_pipelined_wait_p95_ms")
    result["wait_p95_drops"] = (wait_s is not None and wait_p is not None
                                and wait_p < wait_s)
    # gates: identity always; throughput only in full mode (a noisy
    # shared host must not flake CI — full runs ride BENCH artifacts)
    result["speedup_ok"] = result["speedup_sorted"] >= 1.5
    result["spool_ok"] = result["speedup_sorted_spool"] >= 0.95
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    if args.quick:
        return 0
    return 0 if (result["speedup_ok"] and result["spool_ok"]) else 2


if __name__ == "__main__":
    sys.exit(main())
