#!/usr/bin/env python
"""Loopback smoke of the network shuffle data plane (scripts/build/
ci.sh gate): a ShuffleServer over a synthetic MOF tree on 127.0.0.1,
two concurrent reduce clients running full MergeManager shuffles
through RemoteFetchClient (via HostRoutingClient's default socket
factory), output checked byte-identical against the in-process
LocalFetchClient path. Exit code != 0 on any mismatch or wedge.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tests.helpers import make_mof_tree, map_ids  # noqa: E402
from uda_tpu.merger import (HostRoutingClient, LocalFetchClient,  # noqa: E402
                            MergeManager)
from uda_tpu.mofserver import DataEngine, DirIndexResolver  # noqa: E402
from uda_tpu.net import ShuffleServer  # noqa: E402
from uda_tpu.utils.config import Config  # noqa: E402
from uda_tpu.utils.metrics import metrics  # noqa: E402

JOB = "jobSmoke"
NUM_MAPS = 6
NUM_REDUCERS = 2


def run_reduce(port: int, reduce_id: int, out: dict) -> None:
    router = HostRoutingClient(config=Config())
    mm = MergeManager(router, "uda.tpu.RawBytes", Config())
    blocks: list[bytes] = []
    maps = [(f"127.0.0.1:{port}", m) for m in map_ids(JOB, NUM_MAPS)]
    try:
        mm.run(JOB, maps, reduce_id, lambda b: blocks.append(bytes(b)))
        out[reduce_id] = b"".join(blocks)
    finally:
        router.stop()


def main() -> int:
    # optional span export (--spans <path>): record the whole smoke as
    # a span tree and write the per-process JSONL file that
    # scripts/trace_merge.py stitches — the ci.sh trace gate. The
    # wire's trace context makes the in-process server's net.serve /
    # engine.pread spans children of each reducer's fetch spans.
    spans_out = None
    argv = sys.argv[1:]
    if "--spans" in argv:
        spans_out = argv[argv.index("--spans") + 1]
        metrics.enable_spans()
    tmp = tempfile.mkdtemp(prefix="uda_net_smoke_")
    make_mof_tree(tmp, JOB, NUM_MAPS, NUM_REDUCERS, records_per_map=200,
                  seed=42)
    engine = DataEngine(DirIndexResolver(tmp), Config())
    server = ShuffleServer(engine, Config(), host="127.0.0.1", port=0)
    server.start()
    try:
        out: dict = {}
        threads = [threading.Thread(target=run_reduce,
                                    args=(server.port, r, out))
                   for r in range(NUM_REDUCERS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            if t.is_alive():
                print("NET SMOKE FAIL: reduce client wedged", flush=True)
                return 1
        for r in range(NUM_REDUCERS):
            if r not in out:
                print(f"NET SMOKE FAIL: reducer {r} produced no output")
                return 1
            mm = MergeManager(LocalFetchClient(engine),
                              "uda.tpu.RawBytes", Config())
            blocks: list[bytes] = []
            mm.run(JOB, map_ids(JOB, NUM_MAPS), r,
                   lambda b: blocks.append(bytes(b)))
            if out[r] != b"".join(blocks):
                print(f"NET SMOKE FAIL: reducer {r} output differs from "
                      f"the LocalFetchClient path")
                return 1
        # the introspection plane: one MSG_STATS poll against the live
        # server must return counters + the resledger block (the
        # udatop scrape surface)
        from uda_tpu.net.client import fetch_remote_stats
        snap = fetch_remote_stats("127.0.0.1", server.port)
        if "counters" not in snap or "resledger" not in snap \
                or "net.server" not in snap.get("providers", {}):
            print(f"NET SMOKE FAIL: MSG_STATS snapshot incomplete: "
                  f"{sorted(snap)}")
            return 1
    finally:
        server.stop()
        engine.stop()
    if spans_out is not None:
        n = metrics.export_spans_jsonl(spans_out)
        print(f"NET SMOKE: {n} spans -> {spans_out}")
    print(f"NET SMOKE OK: {NUM_REDUCERS} concurrent reduce clients, "
          f"{int(metrics.get('net.requests'))} requests, "
          f"{int(metrics.get('net.bytes.out', role='server'))} B served, "
          f"byte-identical to the local path")
    return 0


if __name__ == "__main__":
    sys.exit(main())
