#!/usr/bin/env python
"""tune_probe: seeded fly-off probes that populate the online tuning
cache (uda_tpu/utils/tuncache.py).

The generalization of the repo's hand-deployed sweep winners
(``UDA_TPU_SORT_PATH``/``UDA_TPU_CHUNK_COLS``; ROADMAP item 5): instead
of a human reading BENCH_*.json and exporting env vars, this probe
measures on THIS host and persists per-(key-shape, platform, backend)
winners that ``ops.sort.route_engine`` and the batched host-I/O plane
consult at routing time. Env-var winners still override the cache —
precedence is env > cache > built-in, tested in
tests/test_tuncache.py.

Domains probed (``--domain`` selects one, default both):

- ``sort.engine``: a bench_step fly-off over the pure-XLA engine set
  (plus the Pallas lanes engines on a TPU backend) at two row-bucket
  shapes, one winner per (backend, rows-bucket, lanes-capability) key.
- ``io.read``: a submit_batch burst A/B over coalesce-gap settings on
  a synthetic MOF (the io_bench hot-burst shape, in-process), one
  winner per platform: {batch, gap_kb, batch_max, backend}.

Re-probe rung: ``--reprobe-age S`` skips entries younger than S
seconds (the background-freshness contract: a cron/idle-time
invocation re-measures only what drifted stale; ``uda.tpu.tune.
reprobe.s`` is the in-process analogue via tuncache.ensure_fresh).
``--force`` re-measures everything. Probes count ``tune.probes`` —
the lifecycle test's "probe counter zero on the second run" gate rides
exactly this skip.

Usage::

    UDA_TPU_TUNE_CACHE=/path/tune.json python scripts/tune_probe.py --quick
    python scripts/tune_probe.py --cache /path/tune.json --domain io.read
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

JOB = "jobTuneProbe"
MAP = "attempt_jobTuneProbe_m_000000_0"


def _fresh(cache, domain: str, key: str, reprobe_age: float,
           force: bool) -> bool:
    """True when the entry is fresh enough to SKIP re-probing."""
    if force:
        return False
    age = cache.age_s(domain, key)
    if age is None:
        return False
    if reprobe_age <= 0:
        return True  # a winner exists and no staleness horizon: keep it
    return age <= reprobe_age


def probe_sort_engine(cache, quick: bool, reprobe_age: float,
                      force: bool, seed: int) -> list:
    """Fly-off per (backend, rows-bucket, lanes-capability): time each
    candidate engine with bench_step (sortedness + checksum asserted —
    a broken engine can never be crowned) and persist the winner."""
    import jax
    import numpy as np

    from uda_tpu.models import terasort
    from uda_tpu.ops import sort as sort_ops
    from uda_tpu.utils.metrics import metrics
    from uda_tpu.utils.tuncache import rows_bucket

    backend = jax.default_backend()
    sizes = (1 << 14,) if quick else (1 << 16, 1 << 20)
    out = []
    for n in sizes:
        for lanes_ok in (False, True):
            key = f"{backend}|rows{rows_bucket(n)}|lanes{int(lanes_ok)}"
            if _fresh(cache, "sort.engine", key, reprobe_age, force):
                out.append((key, "fresh", None))
                continue
            metrics.add("tune.probes", domain="sort.engine")
            candidates = ["carry", "gather", "gather2", "carrychunk"]
            if lanes_ok and backend == "tpu":
                # interpret-mode lanes on CPU are pathologically slow
                # and would never win honestly — probe them only where
                # they compile for real
                candidates += list(sort_ops.LANES_ENGINES)
            best = None
            times = {}
            for path in candidates:
                try:
                    def one(s):
                        t0 = time.perf_counter()
                        viol, ck_in, ck_out = terasort.bench_step(
                            jax.random.key(s), n, 1, path=path,
                            tile=min(1024, n))
                        assert int(viol) == 0
                        assert np.uint32(ck_in) == np.uint32(ck_out)
                        return time.perf_counter() - t0

                    one(seed)  # warmup/compile
                    dt = min(one(seed + 1), one(seed + 2))
                    times[path] = round(dt, 5)
                    if best is None or dt < best[1]:
                        best = (path, dt)
                except Exception as e:  # noqa: BLE001 - one engine's
                    # failure (unsupported shape/backend) must not
                    # kill the fly-off; it just cannot win
                    times[path] = f"error: {type(e).__name__}"
            if best is None:
                out.append((key, "no-winner", None))
                continue
            gbps = n * terasort.RECORD_BYTES / 1e9 / best[1]
            cache.record("sort.engine", key,
                         {"engine": best[0], "times_s": times},
                         metric=round(gbps, 4), probe="tune_probe")
            out.append((key, "probed", best[0]))
    return out


def probe_io_read(cache, quick: bool, reprobe_age: float, force: bool,
                  seed: int) -> list:
    """Burst A/B over the batched read plane's parameters on a
    synthetic MOF: batch off vs on at each coalesce-gap rung, winner =
    the fastest configuration whose bytes matched the oracle."""
    from uda_tpu.mofserver.data_engine import DataEngine, ShuffleRequest
    from uda_tpu.mofserver.index import IndexRecord
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.metrics import metrics

    key = sys.platform
    if _fresh(cache, "io.read", key, reprobe_age, force):
        return [(key, "fresh", None)]
    metrics.add("tune.probes", domain="io.read")

    class _Resolver:
        def __init__(self, path, n):
            self._rec = IndexRecord(start_offset=0, raw_length=n,
                                    part_length=n, path=path)

        def resolve(self, job_id, map_id, reduce_id):
            return self._rec

    import random

    total = (8 << 20) if quick else (64 << 20)
    chunk = 64 << 10
    burst = 64 if quick else 256
    tmp = tempfile.mkdtemp(prefix="uda_tune_probe_")
    path = os.path.join(tmp, "probe.mof")
    block = os.urandom(1 << 20)
    with open(path, "wb") as f:
        left = total
        while left > 0:
            f.write(block[:min(left, len(block))])
            left -= len(block)

    def burst_offsets():
        # the hot-burst shape: mostly-sequential chunks with jitter.
        # The rng is REBUILT per call so every configuration and every
        # repetition fetches the same ranges in the same order — a
        # shared advancing rng would hand each A/B arm a different
        # arrival order and bias which winner gets crowned
        offs = [(i * chunk) % (total - chunk) for i in range(burst)]
        random.Random(seed).shuffle(offs)
        return offs

    def run(cfg_over: dict, batched: bool) -> float:
        engine = DataEngine(_Resolver(path, total),
                            Config(dict(cfg_over)))
        offs = burst_offsets()
        reqs = [ShuffleRequest(JOB, MAP, 0, off, chunk) for off in offs]
        t0 = time.perf_counter()
        if batched:
            futs = engine.submit_batch(reqs)
        else:
            futs = [engine.submit(r) for r in reqs]
        with open(path, "rb") as oracle_f:
            for req, fut in zip(reqs, futs):
                res = fut.result(timeout=60.0)
                oracle_f.seek(req.offset)
                want = oracle_f.read(min(chunk, total - req.offset))
                assert bytes(res.data) == want, "probe identity broke"
        dt = time.perf_counter() - t0
        engine.stop()
        return dt

    reps = 2 if quick else 3
    results = {}
    results["off"] = min(run({}, batched=False) for _ in range(reps))
    gaps = (0, 64, 256)
    best = ("off", results["off"], {})
    for gap in gaps:
        name = f"gap{gap}"
        results[name] = min(
            run({"uda.tpu.read.coalesce.gap.kb": gap}, batched=True)
            for _ in range(reps))
        if results[name] < best[1]:
            best = (name, results[name],
                    {"batch": "on", "gap_kb": gap, "batch_max": 256})
    probe_engine = DataEngine(_Resolver(path, total), Config())
    winner = dict(best[2] or {"batch": "off"})
    winner["backend"] = probe_engine.io_backend
    probe_engine.stop()
    mbps = burst * chunk / (1 << 20) / best[1]
    cache.record("io.read", key, winner, metric=round(mbps, 2),
                 probe="tune_probe")
    try:
        os.remove(path)
        os.rmdir(tmp)
    except OSError:
        pass
    return [(key, "probed",
             f"{winner} ({ {k: round(v, 4) for k, v in results.items()} })")]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cache", default="",
                    help="tuning-cache path (default: UDA_TPU_TUNE_CACHE"
                         " env, required one way or the other)")
    ap.add_argument("--domain", choices=["sort.engine", "io.read"],
                    help="probe one domain only (default: both)")
    ap.add_argument("--quick", action="store_true",
                    help="small shapes (CI / test sizes)")
    ap.add_argument("--force", action="store_true",
                    help="re-measure even fresh entries")
    ap.add_argument("--reprobe-age", type=float, default=0.0,
                    help="re-measure entries older than this many "
                         "seconds (0 = existing winners are kept; "
                         "this is the background re-probe rung)")
    ap.add_argument("--seed", type=int, default=13)
    ap.add_argument("--list", action="store_true",
                    help="print the cache entries and exit")
    args = ap.parse_args()

    from uda_tpu.utils.metrics import metrics
    from uda_tpu.utils.tuncache import TuneCache, cache_path_from_env

    path = args.cache or cache_path_from_env()
    if not path:
        print("tune_probe: no cache path (--cache or UDA_TPU_TUNE_CACHE)",
              file=sys.stderr)
        return 2
    cache = TuneCache(path)
    if args.list:
        for k, v in sorted(cache.entries().items()):
            print(f"{k}: {v.get('winner')} (metric {v.get('metric')})")
        return 0
    reports = []
    if args.domain in (None, "io.read"):
        reports += probe_io_read(cache, args.quick, args.reprobe_age,
                                 args.force, args.seed)
    if args.domain in (None, "sort.engine"):
        reports += probe_sort_engine(cache, args.quick,
                                     args.reprobe_age, args.force,
                                     args.seed)
    probes = int(metrics.get("tune.probes"))
    for key, status, winner in reports:
        line = f"tune_probe: {key}: {status}"
        if winner is not None:
            line += f" -> {winner}"
        print(line)
    print(f"tune_probe: {probes} probe(s) run, cache at {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
