#!/usr/bin/env python
"""udalint CLI: the shuffle stack's AST invariant linter.

Runs the uda_tpu.analysis rule suite — the syntactic tier (UDA001-
UDA008) and the udaflow CFG/dataflow tier (UDA101-UDA103), see
``--list-rules`` — over the given files/directories and prints findings
as ``file:line:col: RULE message [fix: hint]``. Exit 1 when any
non-suppressed finding exists, 0 on a clean tree.

Usage::

    python scripts/udalint.py [paths ...]       # default: uda_tpu scripts
    python scripts/udalint.py --list-rules
    python scripts/udalint.py --rule UDA004 uda_tpu/net
    python scripts/udalint.py --json uda_tpu    # machine-readable

``--json`` prints one JSON object to stdout — ``{"files": N,
"findings": [{file, line, col, rule, message, hint, data}, ...]}`` —
so the CI and chaos gates consume findings structurally instead of
grepping human output (the check_metrics_names.py wrapper contract).
Exit codes are identical to the human mode.

Suppression: append ``# udalint: disable=<RULE>[,<RULE>...]`` (or
``disable=all``) to the offending line. ``scripts/build/ci.sh`` runs
this gate before the test tiers; ``tests/test_udalint.py`` keeps the
whole tree clean in tier-1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="udalint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint "
                         "(default: uda_tpu scripts)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule inventory and exit")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="ID", help="run only these rule ids "
                                       "(repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable findings on stdout "
                         "(file/line/col/rule/message/hint/data)")
    args = ap.parse_args(argv)

    from uda_tpu.analysis.core import Engine, iter_py_files
    from uda_tpu.analysis.rules import ALL_RULES

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.rule_id}  {cls.description}")
        return 0

    wanted = {r.upper() for r in args.rule} if args.rule else None
    rules = [cls() for cls in ALL_RULES
             if wanted is None or cls.rule_id in wanted]
    if wanted and not rules:
        print(f"udalint: no such rule(s): {', '.join(sorted(wanted))}",
              file=sys.stderr)
        return 2

    paths = [os.path.join(REPO, p) if not os.path.isabs(p) else p
             for p in (args.paths or ["uda_tpu", "scripts"])]
    for p in paths:
        if not os.path.exists(p):
            print(f"udalint: no such path: {p}", file=sys.stderr)
            return 2

    engine = Engine(rules, root=REPO)
    findings = engine.lint_paths(paths)
    nfiles = len(iter_py_files(paths))
    if args.json:
        print(json.dumps(
            {"files": nfiles, "rules": [r.rule_id for r in rules],
             "findings": [{"file": f.file, "line": f.line, "col": f.col,
                           "rule": f.rule, "message": f.message,
                           "hint": f.hint, "data": f.data}
                          for f in findings]},
            indent=1, sort_keys=True))
        return 1 if findings else 0
    for f in findings:
        print(f.render(), file=sys.stderr)
    if findings:
        print(f"udalint: {len(findings)} finding(s) in {nfiles} file(s)",
              file=sys.stderr)
        return 1
    print(f"udalint: {nfiles} file(s) clean "
          f"({len(rules)} rule(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
