#!/usr/bin/env python
"""udalint CLI: the shuffle stack's AST invariant linter.

Runs the uda_tpu.analysis rule suite — the syntactic tier (UDA001-
UDA008), the udaflow CFG/dataflow tier (UDA101-UDA103) and the udarace
lockset tier (UDA201-UDA204), see ``--list-rules`` — over the given
files/directories and prints findings as ``file:line:col: RULE message
[fix: hint]``. Exit 1 when any non-suppressed finding exists, 0 on a
clean tree.

Usage::

    python scripts/udalint.py [paths ...]       # default: uda_tpu scripts
    python scripts/udalint.py --list-rules
    python scripts/udalint.py --rule UDA004 uda_tpu/net
    python scripts/udalint.py --json uda_tpu    # machine-readable
    python scripts/udalint.py --changed         # git-diff files only
    python scripts/udalint.py --cache           # content-hash cache

``--json`` prints one JSON object to stdout — ``{"files": N,
"findings": [{file, line, col, rule, message, hint, data}, ...]}`` —
so the CI and chaos gates consume findings structurally instead of
grepping human output (the check_metrics_names.py wrapper contract).
Exit codes are identical to the human mode.

``--changed`` lints only the files ``git diff --name-only HEAD`` (plus
untracked files) reports, running the per-file rules only — tree-wide
rules (lock order, lockset inference, wire exhaustiveness) need the
whole tree and are skipped with a printed note. Same exit contract.

``--cache`` keeps a findings cache at ``.udalint_cache.json`` keyed on
content hashes (and on the analysis package's own sources, so editing
a rule invalidates everything). A full-tree re-run over an unchanged
tree — e.g. ci.sh's human-then-JSON double invocation — re-parses
nothing; per-file entries also let partially-changed runs skip the
per-file rule work for untouched files.

Suppression: append ``# udalint: disable=<RULE>[,<RULE>...]`` (or
``disable=all``) to the offending line; lockset waivers use
``# udarace: lockfree=<attr>[,<attr>] - <why>``. ``scripts/build/ci.sh``
runs this gate before the test tiers; ``tests/test_udalint.py`` keeps
the whole tree clean in tier-1.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CACHE_PATH = os.path.join(REPO, ".udalint_cache.json")
# bump when the cache schema (not the rules — those self-invalidate
# through the analysis-source hash) changes shape
CACHE_SCHEMA = 1

_F_FIELDS = ("file", "line", "col", "rule", "message", "hint", "data")


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _file_sha(path: str) -> str:
    with open(path, "rb") as f:
        return _sha(f.read())


def _ruleset_key(rules) -> str:
    """Cache key covering WHICH rules run and WHAT they mean: the rule
    ids plus a hash of every source file in uda_tpu/analysis — editing
    any rule, the engine or the thread-root registry invalidates the
    whole cache (stale findings are worse than a cold run)."""
    h = hashlib.sha256()
    h.update(",".join(sorted(r.rule_id for r in rules)).encode())
    adir = os.path.join(REPO, "uda_tpu", "analysis")
    for dirpath, dirnames, filenames in os.walk(adir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                h.update(fn.encode())
                with open(os.path.join(dirpath, fn), "rb") as f:
                    h.update(f.read())
    return h.hexdigest()


def _load_cache(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            cache = json.load(f)
        if cache.get("schema") == CACHE_SCHEMA:
            return cache
    except (OSError, ValueError):
        pass
    return {"schema": CACHE_SCHEMA, "per_file": {}, "tree": {}}


def _save_cache(path: str, cache: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(cache, f)
        os.replace(tmp, path)
    except OSError as e:
        print(f"udalint: cannot write cache {path}: {e}",
              file=sys.stderr)


def _ser(findings) -> list:
    return [[getattr(f, k) for k in _F_FIELDS] for f in findings]


def _deser(rows) -> list:
    from uda_tpu.analysis.core import Finding
    return [Finding(*row) for row in rows]


def _changed_files() -> list:
    """Repo-relative .py files git considers changed (vs HEAD) or
    untracked; missing git degrades to the full default paths."""
    out = []
    for cmd in (["git", "diff", "--name-only", "HEAD", "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            r = subprocess.run(cmd, cwd=REPO, capture_output=True,
                               text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return []
        if r.returncode != 0:
            return []
        out.extend(line.strip() for line in r.stdout.splitlines())
    seen = set()
    files = []
    for rel in out:
        if (rel.endswith(".py") and rel not in seen
                and os.path.exists(os.path.join(REPO, rel))):
            seen.add(rel)
            files.append(os.path.join(REPO, rel))
    return sorted(files)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="udalint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint "
                         "(default: uda_tpu scripts)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule inventory and exit")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="ID", help="run only these rule ids "
                                       "(repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable findings on stdout "
                         "(file/line/col/rule/message/hint/data)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only git-changed/untracked .py files "
                         "(per-file rules only; tree-wide rules need "
                         "the whole tree and are skipped)")
    ap.add_argument("--cache", action="store_true",
                    help=f"use the content-hash findings cache "
                         f"({os.path.relpath(CACHE_PATH, REPO)})")
    args = ap.parse_args(argv)

    from uda_tpu.analysis.core import Engine, Rule, iter_py_files
    from uda_tpu.analysis.rules import ALL_RULES

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.rule_id}  {cls.description}")
        return 0

    wanted = {r.upper() for r in args.rule} if args.rule else None
    rule_classes = [cls for cls in ALL_RULES
                    if wanted is None or cls.rule_id in wanted]
    if wanted and not rule_classes:
        print(f"udalint: no such rule(s): {', '.join(sorted(wanted))}",
              file=sys.stderr)
        return 2

    if args.changed:
        # incremental mode: only per-file rules are sound on a partial
        # file set (a tree-wide rule fed 3 files would "prove" absence
        # of things that exist in the other 100)
        tree_ids = [cls.rule_id for cls in rule_classes
                    if cls.finalize is not Rule.finalize]
        rule_classes = [cls for cls in rule_classes
                        if cls.finalize is Rule.finalize]
        files = _changed_files()
        if tree_ids:
            print(f"udalint: --changed: tree-wide rule(s) skipped: "
                  f"{', '.join(tree_ids)} (run without --changed for "
                  f"the full gate)", file=sys.stderr)
        if not files:
            print("udalint: --changed: no changed .py files")
            return 0
        rules = [cls() for cls in rule_classes]
        engine = Engine(rules, root=REPO)
        findings = engine.lint_paths(files)
        return _emit(args, findings, len(files), rules)

    rules = [cls() for cls in rule_classes]
    paths = [os.path.join(REPO, p) if not os.path.isabs(p) else p
             for p in (args.paths or ["uda_tpu", "scripts"])]
    for p in paths:
        if not os.path.exists(p):
            print(f"udalint: no such path: {p}", file=sys.stderr)
            return 2

    if not args.cache:
        engine = Engine(rules, root=REPO)
        findings = engine.lint_paths(paths)
        return _emit(args, findings, len(iter_py_files(paths)), rules)

    # -- cached run ----------------------------------------------------------
    files = iter_py_files(paths)
    shas = {os.path.relpath(p, REPO): _file_sha(p) for p in files}
    rkey = _ruleset_key(rules)
    fingerprint = _sha(json.dumps(
        [rkey, sorted(shas.items())]).encode())
    cache = _load_cache(CACHE_PATH)
    tree = cache.get("tree", {})
    if tree.get("fingerprint") == fingerprint:
        # unchanged tree + unchanged rules: the whole run is cached —
        # nothing is parsed (the ci.sh human-then-JSON double pass)
        return _emit(args, _deser(tree.get("findings", [])),
                     len(files), rules)

    per_file_rules = [r for r in rules
                      if type(r).finalize is Rule.finalize]
    tree_rules = [r for r in rules
                  if type(r).finalize is not Rule.finalize]
    pf_ids = {r.rule_id for r in per_file_rules}
    pf_engine = Engine(per_file_rules, root=REPO)
    tree_engine = Engine(tree_rules, root=REPO)
    per_cache = cache.get("per_file", {})
    new_per: dict = {}
    findings = []
    for path in files:
        rel = os.path.relpath(path, REPO)
        ent = per_cache.get(rel)
        if ent and ent.get("sha") == shas[rel] \
                and ent.get("rkey") == rkey:
            pf_findings = _deser(ent["findings"])
        else:
            pf_findings = [f for f in pf_engine.lint_file(path)
                           if f.rule in pf_ids or f.rule == "UDA000"]
        new_per[rel] = {"sha": shas[rel], "rkey": rkey,
                        "findings": _ser(pf_findings)}
        findings.extend(pf_findings)
        # tree-wide rules always see every file (their verdicts are
        # global); this is the parse the fingerprint hit avoids
        if tree_rules:
            findings.extend(tree_engine.lint_file(path))
    findings.extend(tree_engine.finish())
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    cache["per_file"] = new_per
    cache["tree"] = {"fingerprint": fingerprint,
                     "findings": _ser(findings)}
    _save_cache(CACHE_PATH, cache)
    return _emit(args, findings, len(files), rules)


def _emit(args, findings, nfiles: int, rules) -> int:
    if args.json:
        print(json.dumps(
            {"files": nfiles, "rules": [r.rule_id for r in rules],
             "findings": [{"file": f.file, "line": f.line, "col": f.col,
                           "rule": f.rule, "message": f.message,
                           "hint": f.hint, "data": f.data}
                          for f in findings]},
            indent=1, sort_keys=True))
        return 1 if findings else 0
    for f in findings:
        print(f.render(), file=sys.stderr)
    if findings:
        print(f"udalint: {len(findings)} finding(s) in {nfiles} file(s)",
              file=sys.stderr)
        return 1
    print(f"udalint: {nfiles} file(s) clean "
          f"({len(rules)} rule(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
