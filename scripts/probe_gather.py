"""Mosaic lowering probe: in-kernel dynamic LANE gather.

Feasibility check for a two-phase merge kernel (run the bitonic network
on the 4 key rows only, then apply the resulting permutation to the
payload rows with ONE in-VMEM lane gather instead of carrying 32 rows
through every compare-exchange stage). Worth ~2-3x on the merge cascade
IF Mosaic can lower a dynamic lane-axis gather at useful speed.

Prints which formulations compile + run correctly on the ambient
backend, and a rough per-call timing.
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

ROWS, N = 32, 2048


def kern_take(idx_ref, x_ref, o_ref, *, rows, n):
    o_ref[...] = jnp.take(x_ref[...], idx_ref[0], axis=1)


def kern_take_along(idx_ref, x_ref, o_ref, *, rows, n):
    idx = jnp.broadcast_to(idx_ref[0][None, :], (rows, n))
    o_ref[...] = jnp.take_along_axis(x_ref[...], idx, axis=1)


def kern_take_along_i32(idx_ref, x_ref, o_ref, *, rows, n):
    # same, through an int32 view: Mosaic's gather support is
    # dtype-sensitive (the uint32 onehot path already failed on a cast)
    idx = jnp.broadcast_to(idx_ref[0][None, :], (rows, n))
    xi = x_ref[...].astype(jnp.int32)
    o_ref[...] = jnp.take_along_axis(xi, idx, axis=1).astype(jnp.uint32)


def kern_onehot_matmul(idx_ref, x_ref, o_ref, *, rows, n):
    # permutation as one-hot matmul on the MXU: out = x @ P where
    # P[s, d] = 1 iff idx[d] == s  (uint32 payload split into 2 bf16-safe
    # halves would be needed for exactness; here int32 accumulate)
    idx = idx_ref[0]
    src = lax.broadcasted_iota(jnp.int32, (n, n), 0)
    onehot = (src == idx[None, :]).astype(jnp.float32)
    o_ref[...] = jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), onehot,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(jnp.uint32)


def run(name, kern, rows=ROWS, n=N):
    x = jnp.asarray(
        np.random.default_rng(0).integers(0, 1 << 31, (rows, n)),
        jnp.uint32)
    perm = np.random.default_rng(1).permutation(n).astype(np.int32)
    idx = jnp.asarray(perm)[None, :]
    try:
        f = pl.pallas_call(
            partial(kern, rows=rows, n=n),
            in_specs=[pl.BlockSpec((1, n), lambda: (0, 0)),
                      pl.BlockSpec((rows, n), lambda: (0, 0))],
            out_specs=pl.BlockSpec((rows, n), lambda: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, n), jnp.uint32),
        )
        out = np.asarray(f(idx, x))
        want = np.asarray(x)[:, perm]
        ok = np.array_equal(out, want)
        # rough timing: 50 calls under one jit
        @jax.jit
        def many(idx, x):
            def body(i, acc):
                return f(idx, acc)
            return lax.fori_loop(0, 50, body, x)

        r = many(idx, x)
        int(r[0, 0])
        t0 = time.perf_counter()
        r = many(idx, x)
        int(r[0, 0])
        dt = (time.perf_counter() - t0) / 50
        print(f"{name}: compiles, correct={ok}, ~{dt*1e6:.0f} us/call "
              f"({rows*n*4/dt/1e9:.1f} GB/s)", flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"{name}: FAILED {type(e).__name__}: {str(e)[:160]}",
              flush=True)


if __name__ == "__main__":
    print("backend:", jax.devices()[0].platform, flush=True)
    for name, kern, kw in [
            ("jnp.take(axis=1)", kern_take, {}),
            ("take_along_axis", kern_take_along, {}),
            ("take_along_axis_i32", kern_take_along_i32, {}),
            # shape sensitivity: one sublane tile / short lane count
            ("take_along[8,2048]", kern_take_along, dict(rows=8)),
            ("take_along[8,512]", kern_take_along, dict(rows=8, n=512)),
            ("take_along_i32[8,512]", kern_take_along_i32,
             dict(rows=8, n=512)),
            ("onehot_matmul", kern_onehot_matmul, {}),
    ]:
        run(name, kern, **kw)
