"""Overlap-forest vs post-hoc global sort: the network-levitated
property's perf datum (VERDICT r3 weak #5 / task #7).

The reference's headline property is that merging overlaps fetching, so
the post-last-fetch latency is small (reference MergeManager.cc:47-182).
This bench stages k pre-sorted segments into the OverlappedMerger run
forest exactly as fetch completions would, then measures:

- ``batch_sort_s``     — the post-hoc global device sort of everything
                         (merge_batches), the no-overlap strawman;
- ``overlap_total_s``  — feed()+finish() wall-clock (all merge work);
- ``overlap_finish_s`` — finish() alone after the forest has drained
                         every staged segment: the latency the reduce
                         actually waits after the LAST fetch lands —
                         the number the reference's design minimizes.

Runs on whatever backend is present (Pallas merge-path kernel on TPU;
on CPU the host engine, or UDA_TPU_OVERLAP_ENGINE=pallas for
interpret-mode smoke). One JSON line at the end for the notes table.

Usage: python scripts/bench_overlap.py
Env: UDA_TPU_OVERLAP_LOG2 (total records, default 22: ~0.4 GB),
     UDA_TPU_OVERLAP_SEGS (segment count, default 64),
     UDA_TPU_OVERLAP_ENGINE (auto|host|pallas)
"""

from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

from uda_tpu.utils import compile_cache  # noqa: E402

compile_cache.apply_platform_env()
compile_cache.enable()

import numpy as np  # noqa: E402


def make_segments(total: int, k: int, key_bytes=10, val_bytes=90, seed=0):
    """k segments of sorted TeraSort-shaped records as RecordBatches
    (vectorized: both lengths < 128 so the IFile framing is two 1-byte
    VInts, built as numpy columns)."""
    from uda_tpu.utils.ifile import EOF_MARKER, crack

    rng = np.random.default_rng(seed)
    per = total // k
    batches = []
    for _ in range(k):
        keys = np.frombuffer(rng.bytes(per * key_bytes), np.uint8
                             ).reshape(per, key_bytes)
        order = np.argsort(
            keys.view(np.dtype((np.void, key_bytes))).ravel())
        frame = np.empty((per, 2 + key_bytes + val_bytes), np.uint8)
        frame[:, 0] = key_bytes
        frame[:, 1] = val_bytes
        frame[:, 2:2 + key_bytes] = keys[order]
        frame[:, 2 + key_bytes:] = ord("v")
        batches.append(crack(frame.tobytes() + EOF_MARKER))
    return batches


class _SyncPoint:
    """A queue barrier: fed to the OverlappedMerger like a segment, its
    record_batch() runs on the merge thread AFTER every previously fed
    segment's stage+carry-merges completed (the queue is FIFO and
    single-threaded), sets the event, and contributes zero records."""

    def __init__(self):
        import threading

        self.reached = threading.Event()

    def record_batch(self):
        from uda_tpu.utils.ifile import EOF_MARKER, crack

        self.reached.set()
        return crack(EOF_MARKER)


def main() -> int:
    import jax

    from uda_tpu.merger.overlap import OverlappedMerger
    from uda_tpu.ops import merge as merge_ops
    from uda_tpu.utils.comparators import get_key_type
    from uda_tpu.utils.config import Config

    log2 = int(os.environ.get("UDA_TPU_OVERLAP_LOG2", 22))
    k = int(os.environ.get("UDA_TPU_OVERLAP_SEGS", 64))
    engine = os.environ.get("UDA_TPU_OVERLAP_ENGINE", "auto")
    total = 1 << log2
    kt = get_key_type("uda.tpu.RawBytes")
    width = Config().get("uda.tpu.key.width")
    backend = jax.default_backend()
    print(f"overlap bench: 2^{log2} records in {k} segments, "
          f"engine={engine} backend={backend}", flush=True)
    batches = make_segments(total, k)

    # ---- post-hoc global sort: warm at the FULL shape (the device
    # sort executable is shape-specialized), then time ----
    want = merge_ops.merge_batches(batches, kt, width)
    t0 = time.perf_counter()
    want = merge_ops.merge_batches(batches, kt, width)
    batch_sort_s = time.perf_counter() - t0
    print(f"batch global sort: {batch_sort_s:.3f}s", flush=True)

    # ---- overlap forest ----
    om = OverlappedMerger(kt, width, engine=engine)
    t0 = time.perf_counter()
    for i, b in enumerate(batches):
        om.feed(i, b)
    # deterministic drain barrier: the sync point's record_batch runs
    # after every staged segment's merge cascade completed
    sync = _SyncPoint()
    om.feed(len(batches), sync)
    sync.reached.wait()
    drained_at = time.perf_counter()
    got = om.finish(batches)
    t_end = time.perf_counter()
    overlap_total_s = t_end - t0
    overlap_finish_s = t_end - drained_at

    assert got.num_records == want.num_records
    assert bytes(got.key(0)) == bytes(want.key(0))
    assert bytes(got.key(got.num_records - 1)) == \
        bytes(want.key(want.num_records - 1))
    print(f"overlap total: {overlap_total_s:.3f}s  "
          f"finish-after-last-fetch: {overlap_finish_s:.3f}s  "
          f"(stats {om.stats})", flush=True)
    print(json.dumps({
        "bench": "overlap_vs_batch", "backend": backend,
        "records": total, "segments": k, "engine": om.engine,
        "batch_sort_s": round(batch_sort_s, 4),
        "overlap_total_s": round(overlap_total_s, 4),
        "overlap_finish_s": round(overlap_finish_s, 4),
        "finish_vs_batch": round(batch_sort_s / max(overlap_finish_s,
                                                    1e-9), 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
