#!/usr/bin/env python
"""CPU-only loopback benchmark of the network shuffle data plane.

The net plane's perf trajectory without the (frequently unreachable)
accelerator pool: a ShuffleServer over a synthetic MOF on 127.0.0.1,
measured three ways on the event-loop core (the ONLY core since the
legacy threaded baseline was deleted — its last measured point is
``BENCH_NET_r06.json``: 944 vs 323 MB/s single-stream, 2.92x):

1. **single-stream throughput** — one client, windowed pipelined chunk
   fetches of one large partition (the Segment steady-state shape);
   the headline number the zero-copy serve path must move;
2. **p99 frame latency** — sequential small (4 KB) request->response
   round trips; the TCP_NODELAY/sockbuf regression guard;
3. **256-connection fan-in** — 256 concurrent fetch clients against
   one server; must complete with zero errors and zero stall, the
   "dead at 10k" scale direction.

Emits a comparable JSON block (default ``BENCH_NET_r07.json``) with
throughput, latency percentiles, the zero-copy counters (sendfile
bytes, fd/byte-path serve split) and the process-wide traced
allocation peak (tracemalloc) — the flat-per-chunk-alloc evidence.

Exit code != 0 on any fan-in error/stall or a single-stream failure
(the ci.sh --quick gate); throughput itself is reported, not gated,
so a noisy shared host cannot flake CI.

Usage: scripts/net_bench.py [--quick] [--out PATH] [--sockbuf-kb N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import tracemalloc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from uda_tpu.mofserver import DataEngine, ShuffleRequest  # noqa: E402
from uda_tpu.mofserver.index import IndexRecord  # noqa: E402
from uda_tpu.net import ShuffleServer  # noqa: E402
from uda_tpu.net.client import RemoteFetchClient  # noqa: E402
from uda_tpu.utils.config import Config  # noqa: E402
from uda_tpu.utils.metrics import metrics  # noqa: E402

JOB = "jobNetBench"
MAP = "attempt_jobNetBench_m_000000_0"


class _SyntheticResolver:
    """Every (job, map, reduce) resolves to one big pre-written file —
    the bench measures the wire, not index parsing."""

    def __init__(self, path: str, nbytes: int):
        self._rec = IndexRecord(start_offset=0, raw_length=nbytes,
                                part_length=nbytes, path=path)

    def resolve(self, job_id: str, map_id: str, reduce_id: int):
        return self._rec


def _make_data_file(tmp: str, nbytes: int) -> str:
    path = os.path.join(tmp, "bench.mof")
    block = os.urandom(1 << 20)
    with open(path, "wb") as f:
        left = nbytes
        while left > 0:
            f.write(block[:min(left, len(block))])
            left -= len(block)
    return path


def _cfg(sockbuf_kb: int) -> Config:
    return Config({"uda.tpu.net.sockbuf.kb": sockbuf_kb})


def run_single_stream(path: str, total: int, chunk: int,
                      window: int, sockbuf_kb: int) -> dict:
    """Windowed pipelined fetches of one `total`-byte partition."""
    metrics.reset()
    cfg = _cfg(sockbuf_kb)
    engine = DataEngine(_SyntheticResolver(path, total), Config())
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0).start()
    client = RemoteFetchClient("127.0.0.1", server.port, cfg)
    lock = threading.RLock()
    done = threading.Event()
    state = {"next": 0, "inflight": 0, "got": 0, "err": None}

    def issue_locked() -> None:
        while state["inflight"] < window and state["next"] < total:
            off = state["next"]
            state["next"] = min(off + chunk, total)
            state["inflight"] += 1
            client.start_fetch(ShuffleRequest(JOB, MAP, 0, off, chunk),
                               on_complete)

    def on_complete(res) -> None:
        with lock:
            state["inflight"] -= 1
            if isinstance(res, Exception):
                state["err"] = res
                done.set()
                return
            state["got"] += len(res.data)
            if state["got"] >= total:
                done.set()
                return
            issue_locked()

    tracemalloc.start()
    t0 = time.perf_counter()
    with lock:
        issue_locked()
    ok = done.wait(timeout=600.0)
    secs = time.perf_counter() - t0
    peak = tracemalloc.get_traced_memory()[1]
    tracemalloc.stop()
    client.stop()
    server.stop()
    engine.stop()
    if not ok or state["err"] is not None:
        raise RuntimeError(f"single-stream failed: "
                           f"{state['err'] or 'stalled'}")
    return {"bytes": state["got"], "seconds": round(secs, 4),
            "mb_per_s": round(state["got"] / (1 << 20) / secs, 1),
            "chunk_kb": chunk // 1024, "window": window,
            "sendfile_bytes": int(metrics.get("net.sendfile.bytes")),
            "mmap_bytes": int(metrics.get("net.mmap.bytes")),
            "serve_fd": int(metrics.get("net.serve.fd")),
            "serve_copy": int(metrics.get("net.serve.copy")),
            "traced_peak_mb": round(peak / (1 << 20), 1)}


def run_latency(path: str, total: int, samples: int,
                sockbuf_kb: int) -> dict:
    """Sequential 4 KB round trips -> p50/p99 frame latency."""
    metrics.reset()
    cfg = _cfg(sockbuf_kb)
    engine = DataEngine(_SyntheticResolver(path, total), Config())
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0).start()
    client = RemoteFetchClient("127.0.0.1", server.port, cfg)
    lats: list = []
    try:
        for i in range(samples):
            off = (i * 4096) % (total - 4096)
            box, got = [], threading.Event()
            t0 = time.perf_counter()
            client.start_fetch(ShuffleRequest(JOB, MAP, 0, off, 4096),
                               lambda r: (box.append(r), got.set()))
            if not got.wait(timeout=30.0):
                raise RuntimeError(f"latency fetch {i} stalled")
            if isinstance(box[0], Exception):
                raise RuntimeError(f"latency fetch {i} failed: "
                                   f"{box[0]}")
            lats.append((time.perf_counter() - t0) * 1e3)
    finally:
        client.stop()
        server.stop()
        engine.stop()
    lats.sort()
    return {"samples": samples,
            "p50_ms": round(lats[len(lats) // 2], 3),
            "p99_ms": round(lats[min(len(lats) - 1,
                                     int(len(lats) * 0.99))], 3)}


def run_fanin(path: str, total: int, connections: int, chunks: int,
              chunk: int, sockbuf_kb: int) -> dict:
    """N concurrent clients, each chaining `chunks` fetches — the
    fan-in scale test."""
    metrics.reset()
    cfg = _cfg(sockbuf_kb)
    engine = DataEngine(_SyntheticResolver(path, total), Config())
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0).start()
    clients = [RemoteFetchClient("127.0.0.1", server.port, cfg)
               for _ in range(connections)]
    lock = threading.Lock()
    done = threading.Event()
    state = {"finished": 0, "bytes": 0, "errors": 0}

    def chain(ci: int, left: int) -> None:
        off = ((ci * 7919) + (chunks - left) * chunk) % max(total - chunk, 1)

        def on_complete(res, ci=ci, left=left) -> None:
            with lock:
                if isinstance(res, Exception):
                    state["errors"] += 1
                    state["finished"] += 1
                    if state["finished"] == connections:
                        done.set()
                    return
                state["bytes"] += len(res.data)
            if left > 1:
                chain(ci, left - 1)
            else:
                with lock:
                    state["finished"] += 1
                    if state["finished"] == connections:
                        done.set()

        clients[ci].start_fetch(
            ShuffleRequest(JOB, MAP, 0, off, chunk), on_complete)

    t0 = time.perf_counter()
    for ci in range(connections):
        chain(ci, chunks)
    ok = done.wait(timeout=600.0)
    secs = time.perf_counter() - t0
    for c in clients:
        c.stop()
    server.stop()
    engine.stop()
    return {"core": "evloop", "connections": connections,
            "chunks_per_conn": chunks, "chunk_kb": chunk // 1024,
            "completed": state["finished"], "errors": state["errors"],
            "stalled": not ok, "bytes": state["bytes"],
            "seconds": round(secs, 4),
            "agg_mb_per_s": round(state["bytes"] / (1 << 20)
                                  / max(secs, 1e-9), 1)}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="small sizes for the ci.sh gate")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_NET_r07.json"))
    ap.add_argument("--sockbuf-kb", type=int, default=4096,
                    help="uda.tpu.net.sockbuf.kb for every socket")
    ap.add_argument("--reps", type=int, default=3,
                    help="single-stream repetitions; the best "
                         "is reported (noisy-host discipline: the "
                         "minimum-interference run is the one that "
                         "measures the core, not the neighbors)")
    args = ap.parse_args()

    if args.quick:
        stream_mb, chunk_kb, window = 32, 1024, 6
        lat_samples, fanin_chunks, fanin_kb = 150, 2, 64
        args.reps = min(args.reps, 2)
    else:
        stream_mb, chunk_kb, window = 128, 4096, 6
        lat_samples, fanin_chunks, fanin_kb = 1000, 16, 64
    total = stream_mb << 20

    tmp = tempfile.mkdtemp(prefix="uda_net_bench_")
    path = _make_data_file(tmp, total)
    out: dict = {"bench": "net_loopback", "round": "r07",
                 "quick": args.quick,
                 "sockbuf_kb": args.sockbuf_kb,
                 # the deleted threaded core's last measured point, for
                 # trajectory comparisons (BENCH_NET_r06.json)
                 "threaded_baseline_r06_mb_per_s": 323,
                 "single_stream": {}, "frame_latency": {}}

    rc = 0
    runs = [run_single_stream(path, total, chunk_kb << 10,
                              window, args.sockbuf_kb)
            for _ in range(max(1, args.reps))]
    s = max(runs, key=lambda r: r["mb_per_s"])
    s["reps_mb_per_s"] = [r["mb_per_s"] for r in runs]
    out["single_stream"]["evloop"] = s
    print(f"single-stream: {s['mb_per_s']} MB/s best of "
          f"{s['reps_mb_per_s']} "
          f"({s['bytes'] >> 20} MB; sendfile "
          f"{s['sendfile_bytes'] >> 20} MB, mmap "
          f"{s['mmap_bytes'] >> 20} MB, traced peak "
          f"{s['traced_peak_mb']} MB)")
    lt = run_latency(path, total, lat_samples, args.sockbuf_kb)
    out["frame_latency"]["evloop"] = lt
    print(f"frame-latency: p50 {lt['p50_ms']} ms, "
          f"p99 {lt['p99_ms']} ms over {lt['samples']} fetches")

    fan = run_fanin(path, total, 256, fanin_chunks, fanin_kb << 10,
                    args.sockbuf_kb)
    out["fanin"] = fan
    print(f"fan-in: {fan['connections']} connections x "
          f"{fan['chunks_per_conn']} chunks -> {fan['agg_mb_per_s']} "
          f"MB/s aggregate, errors={fan['errors']}, "
          f"stalled={fan['stalled']}")
    if fan["errors"] or fan["stalled"] or \
            fan["completed"] != fan["connections"]:
        print("FAIL: fan-in saw errors or a stall", file=sys.stderr)
        rc = 1

    with open(args.out, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}")
    try:
        os.remove(path)
        os.rmdir(tmp)
    except OSError:
        pass
    return rc


if __name__ == "__main__":
    sys.exit(main())
