#!/usr/bin/env python
"""perfwatch: normalize bench history into one trajectory + gate on it.

The repo's bench record is nine heterogeneous ``BENCH_*.json`` shapes
plus ``REGRESSION_*`` ladders — a perf regression is caught only if a
human rereads old JSON. This tool makes the trajectory a machine
artifact:

- **ingest**: parse every historical ``BENCH_*.json`` /
  ``REGRESSION_*.json`` (each shape has a dedicated extractor below)
  into one normalized ``PERF_TRAJECTORY.json``::

      {"schema": 1, "entries": [
        {"run": "BENCH_PIPELINE_r09", "rev": "d00dbd9",
         "workload": "pipeline", "metric": "sorted_pipelined_MBps",
         "value": 277.8, "direction": "up"}, ...]}

  ``direction``: ``up`` = higher is better, ``down`` = lower is
  better, ``info`` = recorded for trends, never gated
  (time-accounting shares). Correctness metrics (identity/status
  booleans, error counts) carry a per-entry ``tol`` of 0 — any
  worsening fails regardless of the band.

- **--check POINT.json**: normalize a fresh bench output (same
  extractors) and compare each metric against the LATEST trajectory
  entry for the same (workload, metric) under a relative tolerance
  band — ``up`` fails when ``value < base*(1-tol)``, ``down`` when
  ``value > base*(1+tol)``; a per-entry ``tol`` (the 0 on correctness
  metrics) overrides the band. Metrics with no
  baseline report ``new`` and pass. Exit 1 on any regression — the
  ci.sh gate (which passes a generous ``--tolerance`` because shared
  CI hosts gate direction-of-change, not absolute MB/s).
  ``--append`` adds the checked point to the trajectory on green.

Usage::

    python scripts/perfwatch.py ingest                      # rebuild
    python scripts/perfwatch.py --check ci/bench.json --tolerance 0.6
    python scripts/perfwatch.py --check new.json --append
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

TRAJECTORY = os.path.join(REPO, "PERF_TRAJECTORY.json")
DEFAULT_TOLERANCE = 0.25


# -- normalization ------------------------------------------------------------

def _add(entries: List[Dict], run: str, workload: str, metric: str,
         value, direction: str, tol: Optional[float] = None) -> None:
    try:
        value = float(value)
    except (TypeError, ValueError):
        return
    rec = {"run": run, "workload": workload, "metric": metric,
           "value": round(value, 6), "direction": direction}
    if tol is not None:
        rec["tol"] = tol
    entries.append(rec)


def _extract_headline(run: str, data: Dict, out: List[Dict]) -> None:
    """bench.py output (flat or BENCH_HW headline block)."""
    head = data.get("headline") if isinstance(data.get("headline"), dict) \
        else data
    if head.get("metric") and "value" in head:
        _add(out, run, "terasort_singlechip", head["metric"],
             head["value"], "up")
    for rows, block in (data.get("small_batch") or {}).items():
        if isinstance(block, dict) and "gbps" in block:
            _add(out, run, "terasort_small_batch",
                 f"gbps_rows_{rows}", block["gbps"], "up")
    for path, v in (data.get("flyoff") or {}).items():
        if isinstance(v, (int, float)):
            _add(out, run, "terasort_flyoff", f"{path}_gbps", v, "up")


def _extract_net(run: str, data: Dict, out: List[Dict]) -> None:
    w = "net_quick" if data.get("quick") else "net"
    ss = (data.get("single_stream") or {}).get("evloop") or {}
    if "mb_per_s" in ss:
        _add(out, run, w, "single_stream_mb_per_s", ss["mb_per_s"], "up")
    fan = data.get("fanin") or {}
    if "agg_mb_per_s" in fan:
        _add(out, run, w, "fanin_agg_mb_per_s", fan["agg_mb_per_s"], "up")
    if "errors" in fan:
        _add(out, run, w, "fanin_errors", fan["errors"], "down", tol=0.0)
    if "stalled" in fan:
        _add(out, run, w, "fanin_ok", 0.0 if fan["stalled"] else 1.0,
             "up", tol=0.0)
    lat = (data.get("frame_latency") or {}).get("evloop") or {}
    if "p99_ms" in lat:
        _add(out, run, w, "frame_p99_ms", lat["p99_ms"], "down")


def _extract_pipeline(run: str, data: Dict, out: List[Dict]) -> None:
    quick = bool(data.get("quick"))
    w = "pipeline_quick" if quick else "pipeline"
    ident = data.get("identity") or {}
    if "all_identical" in ident:
        _add(out, run, w, "identity_all",
             1.0 if ident["all_identical"] else 0.0, "up", tol=0.0)
    if "time_accounting_sums_to_wall" in data:
        _add(out, run, w, "timeacct_sums_to_wall",
             1.0 if data["time_accounting_sums_to_wall"] else 0.0,
             "up", tol=0.0)
    # quick-mode throughput on a shared host is noise (observed 0.7-1.8x
    # spread run to run): record it as trend data, gate only full runs —
    # direction-of-change gating, never absolute MB/s on CI iron
    for key, value in data.items():
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        if key.endswith("_MBps") or key.startswith("speedup_"):
            _add(out, run, w, key, value, "info" if quick else "up")
        elif key.endswith("_wait_p95_ms"):
            _add(out, run, w, key, value, "info" if quick else "down")
    for key in ("speedup_ok", "spool_ok", "wait_p95_drops"):
        if key in data and not quick:
            # full-mode gates only: quick throughput is host noise
            _add(out, run, w, key, 1.0 if data[key] else 0.0, "up",
                 tol=0.0)
    _extract_time_accounting(run, w, data, out)


def _extract_io(run: str, data: Dict, out: List[Dict]) -> None:
    """scripts/io_bench.py output: batched-vs-single-pread serve A/B.
    Identity is a hard gate (tol 0); quick-mode throughput/speedup are
    trend data (the pipeline-quick precedent: shared-host noise must
    not flake CI), full-mode gates direction-of-change."""
    quick = bool(data.get("quick"))
    w = "io_serve_quick" if quick else "io_serve"
    if "identity_all" in data:
        _add(out, run, w, "identity_all",
             1.0 if data["identity_all"] else 0.0, "up", tol=0.0)
    if "speedup_batched" in data:
        _add(out, run, w, "speedup_batched", data["speedup_batched"],
             "info" if quick else "up")
    for cfg, rec in (data.get("burst") or {}).items():
        if isinstance(rec, dict) and "mb_per_s" in rec:
            _add(out, run, w, f"{cfg}_mb_per_s", rec["mb_per_s"],
                 "info" if quick else "up")
        if isinstance(rec, dict) and "io_batch_reads" in rec \
                and cfg == "batch_on":
            # the O(files)-not-O(chunks) structural figure: kernel
            # reads per burst must not creep back toward chunk count.
            # Quick mode records it as trend data only — recv batching
            # (and so run count) swings with host load, and a loaded
            # CI box must not flake the gate
            _add(out, run, w, "batched_reads_per_burst",
                 rec["io_batch_reads"], "info" if quick else "down")


def _extract_tenant(run: str, data: Dict, out: List[Dict]) -> None:
    """scripts/tenant_bench.py output: many-job fairness. Identity is
    the hard gate (tol 0); the fairness/weighted ratios gate full runs
    (direction-of-change) and trend quick runs — scheduler fairness is
    remarkably stable, but CI hosts still only gate direction."""
    quick = bool(data.get("quick"))
    w = "tenant_fairness_quick" if quick else "tenant_fairness"
    ident = data.get("identity") or {}
    if "concurrent_equals_solo" in ident:
        _add(out, run, w, "identity_concurrent_equals_solo",
             1.0 if ident["concurrent_equals_solo"] else 0.0, "up",
             tol=0.0)
    eq = data.get("equal_weight") or {}
    if "fairness_ratio" in eq:
        _add(out, run, w, "fairness_ratio", eq["fairness_ratio"],
             "info" if quick else "up")
        vals = list((eq.get("goodput_mb_s") or {}).values())
        if vals:
            _add(out, run, w, "aggregate_goodput_mb_s",
                 round(sum(vals), 3), "info" if quick else "up")
    wt = data.get("weighted") or {}
    if "weighted_ratio" in wt:
        _add(out, run, w, "weighted_ratio", wt["weighted_ratio"],
             "info")  # a band, not a direction: perfwatch trends it,
        # the bench itself gates the [1.4, 3.0] band on full runs


def _extract_ckpt(run: str, data: Dict, out: List[Dict]) -> None:
    """scripts/bench_ckpt.py output (bench "ckpt_overhead", r16+):
    crash-consistent snapshot plane on vs off. The identity/resume
    booleans are hard gates (tol 0 — a resume that restarts from
    scratch or drifts a byte is a correctness break, not a trend);
    overhead_pct gates full runs direction-of-change DOWN (the armed
    plane must stay within its <=5% budget and not creep) while quick
    runs only trend it — wall-clock deltas this small flake on shared
    CI hosts."""
    quick = bool(data.get("quick"))
    w = "ckpt_overhead_quick" if quick else "ckpt_overhead"
    res = data.get("resume") or {}
    for key in ("ckpt_on_identical", "resume_identical",
                "resumed_not_restarted"):
        if key in res:
            _add(out, run, w, key, 1.0 if res[key] else 0.0, "up",
                 tol=0.0)
    if "overhead_pct" in data:
        _add(out, run, w, "overhead_pct", data["overhead_pct"],
             "info" if quick else "down")
    if "ckpt_on_MBps" in data:
        _add(out, run, w, "ckpt_on_MBps", data["ckpt_on_MBps"],
             "info" if quick else "up")
    if "snapshots" in data:
        # structural, not wall clock: snapshot count at the default
        # interval on the reference shape — creep here means the
        # rate-limiter regressed
        _add(out, run, w, "snapshots", data["snapshots"], "info")


def _extract_exchange(run: str, data: Dict, out: List[Dict]) -> None:
    """scripts/exchange_bench.py output (bench "exchange_modes", r15+):
    flat vs hierarchical vs coded accounting per mesh x workload.
    Identity/invariant booleans are hard gates (tol 0); the structural
    figures — per-round DCN message coalescing and the coded-over-
    hierarchical payload ratio — gate direction-of-change (they are
    planner ledger counts, not wall clock, so they are exact)."""
    quick = bool(data.get("quick"))
    w = "exchange_quick" if quick else "exchange"
    for runrec in data.get("runs", []):
        rep = runrec.get("report") or {}
        meshname = str(runrec.get("mesh", "")).replace(":", "").replace(
            ",", "_")
        _add(out, run, w, f"{meshname}_ok",
             1.0 if runrec.get("ok") else 0.0, "up", tol=0.0)
        for case in rep.get("cases", []):
            label = f"{meshname}_{case.get('workload')}"
            checks = case.get("checks") or {}
            _add(out, run, w, f"{label}_checks_pass",
                 1.0 if checks and all(checks.values()) else 0.0,
                 "up", tol=0.0)
            f, h = case.get("flat") or {}, case.get("hierarchical") or {}
            c = case.get("coded") or {}
            if f.get("dcn_messages_per_round_max") and h:
                _add(out, run, w, f"{label}_dcn_msgs_coalescing",
                     f["dcn_messages_per_round_max"]
                     / max(1, h.get("dcn_messages_per_round_max", 1)),
                     "up")
            if h.get("dcn_bytes") and c:
                # THE coded figure: multicast charge / uncoded payload
                _add(out, run, w, f"{label}_coded_over_hier_dcn",
                     c.get("dcn_bytes", 0) / h["dcn_bytes"], "down")
                _add(out, run, w, f"{label}_dcn_saved_bytes",
                     c.get("dcn_saved_bytes", 0), "up")


def _extract_elastic(run: str, data: Dict, out: List[Dict]) -> None:
    """scripts/bench_elastic.py output (bench "elastic", r18+): spill
    ladder + mid-job join. The identity/bounded/registered booleans
    are hard gates (tol 0 — a spilled shuffle that drifts a byte or a
    ladder that stops bounding retention is a correctness break);
    spill throughput and the join speedup gate full runs
    direction-of-change and trend quick runs (shared-host walls)."""
    quick = bool(data.get("quick"))
    w = "elastic_quick" if quick else "elastic"
    for key in ("spill_identical", "join_identical", "retained_bounded",
                "join_registered"):
        if key in data:
            _add(out, run, w, key, 1.0 if data[key] else 0.0, "up",
                 tol=0.0)
    if "spill_MBps" in data:
        _add(out, run, w, "spill_MBps", data["spill_MBps"],
             "info" if quick else "up")
    if "join_speedup" in data:
        _add(out, run, w, "join_speedup", data["join_speedup"],
             "info" if quick else "up")
    for key in ("peak_retained_mb", "spilled_mb", "spill_migrations",
                "maxrss_mb"):
        if key in data:
            # structural/trend figures: the retention peak and the
            # spilled volume on the reference shape
            _add(out, run, w, key, data[key], "info")


def _extract_push(run: str, data: Dict, out: List[Dict]) -> None:
    """scripts/bench_push.py output (bench "push_overlap", r19+):
    supplier-initiated push vs the fetch-wave pull baseline, end to
    end. The identity/engagement/zero-fallback booleans are hard gates
    (tol 0 — a pushed run that drifts a byte from the pull oracle, or
    one where the push plane silently never engaged, is a correctness
    break); the e2e speedup and the reduce-tail shrink gate full runs
    direction-of-change and trend quick runs (shared-host walls)."""
    quick = bool(data.get("quick"))
    w = "push_overlap_quick" if quick else "push_overlap"
    for key in ("identity_push_eq_pull", "push_engaged",
                "zero_fallbacks"):
        if key in data:
            _add(out, run, w, key, 1.0 if data[key] else 0.0, "up",
                 tol=0.0)
    if "speedup_e2e" in data:
        _add(out, run, w, "speedup_e2e", data["speedup_e2e"],
             "info" if quick else "up")
    if "overlap_margin_s" in data:
        _add(out, run, w, "overlap_margin_s", data["overlap_margin_s"],
             "info")
    for side in ("pull", "push"):
        rec = data.get(side) or {}
        if "MBps" in rec:
            _add(out, run, w, f"{side}_MBps", rec["MBps"],
                 "info" if quick else "up")
        if "reduce_wall_s" in rec:
            _add(out, run, w, f"{side}_reduce_wall_s",
                 rec["reduce_wall_s"], "info" if quick else "down")
    push = data.get("push") or {}
    for key in ("push_chunks", "push_adopted_mb", "push_refused"):
        if key in push:
            # structural trend figures: the plane's traffic shape
            _add(out, run, w, key, push[key], "info")


def _extract_regression(run: str, data: Dict, out: List[Dict]) -> None:
    w = f"regression_{data.get('size', 'unknown')}"
    for rec in data.get("results", []):
        if not isinstance(rec, dict) or "workload" not in rec:
            continue
        name = rec["workload"]
        if "status" in rec:
            _add(out, run, w, f"{name}_pass",
                 1.0 if rec["status"] == "PASS" else 0.0, "up", tol=0.0)
        if rec.get("wall_s"):
            _add(out, run, w, f"{name}_wall_s", rec["wall_s"], "down")
        if rec.get("max_rss_mb"):
            _add(out, run, w, f"{name}_max_rss_mb", rec["max_rss_mb"],
                 "down")


def _extract_time_accounting(run: str, workload: str, data: Dict,
                             out: List[Dict]) -> None:
    """The critpath block (utils/critpath.py): bucket shares ride the
    trajectory as trend data (``info`` — a share shift is a finding to
    read, not automatically a regression)."""
    ta = data.get("time_accounting")
    if not isinstance(ta, dict):
        return
    if "wall_s" in ta:
        _add(out, run, workload, "timeacct_wall_s", ta["wall_s"], "info")
    for bucket, rec in (ta.get("buckets") or {}).items():
        if isinstance(rec, dict) and "share" in rec:
            _add(out, run, workload, f"timeacct_{bucket}_share",
                 rec["share"], "info")


def _extract_telemetry_hists(run: str, workload: str, data: Dict,
                             out: List[Dict]) -> None:
    """The offline-percentile consumer of the exported histogram
    bucket boundaries+counts: recompute p90 — a percentile the inline
    p50/p95/p99 trio does NOT carry — from a committed telemetry block
    alone (metrics.percentile_from_summary, the exact live
    estimator), recorded as latency trend data."""
    hists = (data.get("telemetry") or {}).get("histograms") or {}
    entries = [(name, s) for name, s in hists.items()
               if isinstance(s, dict) and s.get("buckets")
               and "{" not in name]  # totals only, not labeled series
    if not entries:
        return
    from uda_tpu.utils.metrics import percentile_from_summary
    for name, s in entries:
        if name.endswith("_ms"):
            _add(out, run, workload, f"hist_{name}_p90",
                 percentile_from_summary(s, 90), "info")


def extract(run: str, data) -> List[Dict]:
    """Shape-sniffing dispatch over every historical artifact layout.
    Unknown or payload-less shapes (the early driver-wrapped bench
    failures with ``"parsed": null``) normalize to zero entries."""
    out: List[Dict] = []
    if not isinstance(data, dict):
        return out
    if "parsed" in data and "cmd" in data:  # driver wrapper
        data = data.get("parsed")
        if not isinstance(data, dict):
            return out
    if data.get("bench") == "net_loopback":
        _extract_net(run, data, out)
    elif data.get("bench") == "io_serve":
        _extract_io(run, data, out)
    elif data.get("bench") == "tenant_fairness":
        _extract_tenant(run, data, out)
    elif data.get("bench") == "exchange_modes":
        _extract_exchange(run, data, out)
    elif data.get("bench") == "ckpt_overhead":
        _extract_ckpt(run, data, out)
    elif data.get("bench") == "elastic":
        _extract_elastic(run, data, out)
    elif data.get("bench") == "push_overlap":
        _extract_push(run, data, out)
    elif "identity" in data and "speedup_sorted" in data:
        _extract_pipeline(run, data, out)
    elif isinstance(data.get("results"), list):
        _extract_regression(run, data, out)
    elif isinstance(data.get("headline"), dict) \
            or ("metric" in data and "value" in data):
        _extract_headline(run, data, out)
        _extract_time_accounting(run, "terasort_singlechip", data, out)
        _extract_telemetry_hists(run, "terasort_singlechip", data, out)
    return out


def _git_rev(args: List[str]) -> str:
    try:
        res = subprocess.run(["git"] + args, cwd=REPO, timeout=10,
                             capture_output=True, text=True, check=False)
        return res.stdout.strip() if res.returncode == 0 else ""
    except OSError:
        return ""


def normalize_file(path: str, rev: Optional[str] = None) -> List[Dict]:
    run = os.path.splitext(os.path.basename(path))[0]
    with open(path) as f:
        data = json.load(f)
    entries = extract(run, data)
    if rev is None:
        rev = _git_rev(["log", "-n1", "--format=%h", "--", path])
    for e in entries:
        e["rev"] = rev
    return entries


# -- trajectory ---------------------------------------------------------------

def load_trajectory(path: str) -> List[Dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        doc = json.load(f)
    return list(doc.get("entries", []))


def save_trajectory(path: str, entries: List[Dict]) -> None:
    with open(path, "w") as f:
        json.dump({"schema": 1, "entries": entries}, f, indent=1,
                  sort_keys=True)
        f.write("\n")


def ingest(files: List[str], out: str) -> int:
    if not files:
        files = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json"))
                       + glob.glob(os.path.join(REPO,
                                                "REGRESSION_*.json"))
                       + glob.glob(os.path.join(
                           REPO, "MULTICHIP_SCALE_*.json")))
    entries: List[Dict] = []
    skipped = []
    for path in files:
        try:
            got = normalize_file(path)
        except (OSError, ValueError) as e:
            print(f"perfwatch: {path}: unreadable ({e})", file=sys.stderr)
            skipped.append(os.path.basename(path))
            continue
        if not got:
            skipped.append(os.path.basename(path))
        entries.extend(got)
    # stable order: ingest file order (run ids are round-stamped, so
    # later files ARE later rounds); dedupe keeps the last occurrence
    seen: Dict[tuple, Dict] = {}
    for e in entries:
        seen[(e["run"], e["workload"], e["metric"])] = e
    entries = list(seen.values())
    save_trajectory(out, entries)
    print(f"perfwatch: {len(entries)} entries from "
          f"{len(files) - len(skipped)}/{len(files)} file(s) -> {out}")
    if skipped:
        # no silent caps: files that normalized to nothing are named
        print(f"perfwatch: no metrics in: {', '.join(skipped)}")
    return 0


# -- the gate -----------------------------------------------------------------

def check(point_path: str, trajectory_path: str, tolerance: float,
          append: bool) -> int:
    entries = load_trajectory(trajectory_path)
    if not entries:
        print(f"perfwatch: no trajectory at {trajectory_path} "
              f"(run `perfwatch.py ingest` first)", file=sys.stderr)
        return 2
    try:
        point = normalize_file(point_path,
                               rev=_git_rev(["rev-parse", "--short",
                                             "HEAD"]))
    except (OSError, ValueError) as e:
        print(f"perfwatch: {point_path}: {e}", file=sys.stderr)
        return 2
    if not point:
        print(f"perfwatch: {point_path} normalized to zero metrics "
              f"(unknown shape?)", file=sys.stderr)
        return 2
    latest: Dict[tuple, Dict] = {}
    for e in entries:  # file order; last occurrence = latest round
        latest[(e["workload"], e["metric"])] = e
    regressions = []
    compared = fresh = 0
    rows = []
    for e in point:
        direction = e["direction"]
        base = latest.get((e["workload"], e["metric"]))
        if base is None:
            fresh += 1
            rows.append((e, None, "new"))
            continue
        if direction == "info":
            rows.append((e, base, "info"))
            continue
        compared += 1
        tol = e.get("tol", tolerance)
        bv, nv = base["value"], e["value"]
        bad = ((direction == "up" and nv < bv * (1 - tol) and nv < bv)
               or (direction == "down" and nv > bv * (1 + tol)
                   and nv > bv))
        verdict = "REGRESSION" if bad else "ok"
        if bad:
            regressions.append((e, base))
        rows.append((e, base, verdict))
    width = max((len(f"{e['workload']}.{e['metric']}") for e, _, _ in
                 rows), default=10)
    print(f"perfwatch: {point_path} vs {trajectory_path} "
          f"(tolerance {tolerance:g})")
    for e, base, verdict in rows:
        name = f"{e['workload']}.{e['metric']}"
        if base is None:
            print(f"  {name:<{width}}  {e['value']:>12g}  "
                  f"(no baseline) {verdict}")
        else:
            delta = ((e["value"] - base["value"]) / base["value"] * 100
                     if base["value"] else 0.0)
            print(f"  {name:<{width}}  {e['value']:>12g}  vs "
                  f"{base['value']:>12g} ({base['run']})  "
                  f"{delta:+.1f}%  {verdict}")
    print(f"perfwatch: {compared} compared, {fresh} new, "
          f"{len(regressions)} regression(s)")
    if regressions:
        for e, base in regressions:
            print(f"perfwatch: REGRESSION {e['workload']}."
                  f"{e['metric']}: {e['value']:g} vs {base['value']:g} "
                  f"({base['run']}, direction {e['direction']})",
                  file=sys.stderr)
        return 1
    if append:
        merged = {(x["run"], x["workload"], x["metric"]): x
                  for x in entries}
        for e in point:
            merged[(e["run"], e["workload"], e["metric"])] = e
        save_trajectory(trajectory_path, list(merged.values()))
        print(f"perfwatch: appended {len(point)} entries "
              f"-> {trajectory_path}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("mode", nargs="?", choices=["ingest"],
                    help="'ingest': rebuild the trajectory from "
                         "historical artifacts")
    ap.add_argument("files", nargs="*",
                    help="artifact files for ingest (default: the "
                         "repo's BENCH_*.json + REGRESSION_*.json)")
    ap.add_argument("--check", metavar="POINT",
                    help="normalize POINT.json and gate it against "
                         "the trajectory (exit 1 on regression)")
    ap.add_argument("--trajectory", default=TRAJECTORY)
    ap.add_argument("--out", default=TRAJECTORY,
                    help="ingest destination")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="relative band for up/down metrics (entries "
                         "with their own tol, e.g. correctness "
                         "booleans at 0, keep it); default %(default)s")
    ap.add_argument("--append", action="store_true",
                    help="with --check: append the point to the "
                         "trajectory when green")
    args = ap.parse_args()
    if args.check:
        return check(args.check, args.trajectory, args.tolerance,
                     args.append)
    if args.mode == "ingest":
        return ingest(args.files, args.out)
    ap.print_usage()
    return 2


if __name__ == "__main__":
    sys.exit(main())
