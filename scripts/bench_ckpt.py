#!/usr/bin/env python
"""Checkpoint-overhead A/B: crash-consistent snapshots on vs off.

ISSUE 16's acceptance gate. Arming ``uda.tpu.ckpt.dir`` buys durable
resume (merger/checkpoint.py) at a cost of (a) fsync'd run spools +
``.off`` sidecars (RunStore fixed-dir mode), and (b) a manifest write
per snapshot trigger (run-spool boundary, rate-limited by
``uda.tpu.ckpt.interval.s``). This bench prices that:

- **identity + resume gate** (always, and all of ``--quick``): a
  checkpoint-armed end-to-end MergeManager run is BYTE-IDENTICAL to a
  checkpoint-off run; then a fault-killed attempt resumes
  byte-identical with ``ckpt.resumed`` counted and ZERO refetch of
  manifest-recorded runs — restart-from-scratch fails the bench;
- **overhead A/B** (full mode): the 64x64 MB pipelined spool shape of
  BENCH_PIPELINE_r09 (stage pool + run spool + streaming finish), run
  with the checkpoint plane off vs armed at the DEFAULT interval
  (30 s) — gate: overhead <= 5% wall.

Usage: python scripts/bench_ckpt.py [--segs 64] [--seg-mb 64]
       [--interval 30.0] [--quick] [--out BENCH_CKPT.json]
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def _force_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")


def _mof_tree(tmp: str, job: str, maps: int, recs_per_map: int):
    """A small deterministic MOF tree for the end-to-end gates."""
    import numpy as np

    from uda_tpu.mofserver.writer import MOFWriter

    root = os.path.join(tmp, f"mof_{job}")
    rng = np.random.default_rng(16)
    writer = MOFWriter(root, job)
    for m in range(maps):
        recs = sorted((rng.bytes(10), rng.bytes(30))
                      for _ in range(recs_per_map))
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])
    return root, writer.map_ids


def _e2e_run(root, job, mids, ckdir: str, fault: str = "",
             interval: float = 0.0):
    """One MergeManager run; returns (bytes, ckpt.resumed delta) or
    raises FallbackSignal when the injected fault kills the attempt."""
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.failpoints import failpoints
    from uda_tpu.utils.metrics import metrics

    cfg = Config({"uda.tpu.online.streaming": True,
                  "uda.tpu.ckpt.dir": ckdir,
                  "uda.tpu.ckpt.interval.s": interval,
                  "uda.tpu.fetch.retries": 0})
    engine = DataEngine(DirIndexResolver(root), cfg)
    out = io.BytesIO()
    r0 = metrics.snapshot().get("ckpt.resumed", 0)
    try:
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes",
                          cfg)
        if fault:
            with failpoints.scoped(fault):
                mm.run(job, mids, 0, lambda b: out.write(bytes(b)))
        else:
            mm.run(job, mids, 0, lambda b: out.write(bytes(b)))
    finally:
        engine.stop()
    resumed = metrics.snapshot().get("ckpt.resumed", 0) - r0
    return out.getvalue(), resumed


def resume_gate(tmp: str) -> dict:
    """Identity + crash/resume correctness — the CI gate."""
    from uda_tpu.utils.errors import FallbackSignal

    job = "ckbench"
    root, mids = _mof_tree(tmp, job, 6, 2000)
    ref, _ = _e2e_run(root, job, mids, "")
    on, _ = _e2e_run(root, job, mids, os.path.join(tmp, "ck_id"))
    checks = {"ckpt_on_identical": (on == ref and len(ref) > 0)}
    ckdir = os.path.join(tmp, "ck_res")
    try:
        _e2e_run(root, job, mids, ckdir,
                 fault="segment.fetch=error:match:m_000004")
        checks["fault_killed_attempt"] = False
    except FallbackSignal:
        checks["fault_killed_attempt"] = True
    res, resumed = _e2e_run(root, job, mids, ckdir)
    checks["resume_identical"] = (res == ref)
    checks["resumed_not_restarted"] = (resumed >= 1)
    checks["all_ok"] = all(checks.values())
    return checks


def _spool_once(batches, tmp: str, ckpt_on: bool,
                interval: float) -> dict:
    """The BENCH_PIPELINE_r09 pipelined spool shape (feed -> stage pool
    -> run spool -> streaming k-way finish), with the checkpoint plane
    off or armed. Wall covers feed through emitted bytes — the whole
    reduce-side pipeline the overhead gate prices."""
    from uda_tpu.merger.checkpoint import RUN_EOF_LEN, TaskCheckpoint
    from uda_tpu.merger.emitter import FramedEmitter
    from uda_tpu.merger.overlap import OverlappedMerger
    from uda_tpu.merger.streaming import RunStore
    from uda_tpu.utils.comparators import get_key_type
    from uda_tpu.utils.metrics import metrics

    kt = get_key_type("uda.tpu.RawBytes")
    metrics.reset()
    ck = None
    if ckpt_on:
        ck = TaskCheckpoint(os.path.join(tmp, "ck_ab"), "ckbenchAB", 0,
                            interval_s=interval)
        store = RunStore(tag="ckbenchAB.r0", fixed_dir=ck.runs_dir)

        def collect():
            runs = {str(i): {"records": n, "bytes": b,
                             "length": b + RUN_EOF_LEN, "crc": c}
                    for i, (n, b, c) in store.manifest().items()}
            return ({"maps": [], "runs": runs, "ledgers": {},
                     "journal": [], "penalty": {}, "forest": {}}, {})

        on_spool = lambda i: ck.maybe_save(collect)  # noqa: E731
    else:
        store = RunStore([tmp], tag="ckbenchAB_off")
        on_spool = None
    om = OverlappedMerger(kt, 16, engine="host", run_store=store,
                          pipeline=True, on_spool=on_spool)
    total = sum(b.num_records for b in batches)
    sink = {"n": 0}
    t0 = time.monotonic()
    for i, b in enumerate(batches):
        om.feed(i, b)
    om.finish_streaming(
        FramedEmitter(1 << 16),
        lambda blk: sink.__setitem__("n", sink["n"] + len(blk)),
        expected_records=total)
    wall = time.monotonic() - t0
    snaps = metrics.snapshot().get("ckpt.snapshots", 0)
    if ck is not None:
        ck.discard()
    else:
        store.cleanup()
    metrics.reset()
    return {"wall_s": wall, "snapshots": int(snaps),
            "out_bytes": sink["n"]}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--segs", type=int, default=64)
    ap.add_argument("--seg-mb", type=int, default=64)
    ap.add_argument("--interval", type=float, default=30.0,
                    help="snapshot interval for the armed variant "
                    "(default = the uda.tpu.ckpt.interval.s default)")
    ap.add_argument("--quick", action="store_true",
                    help="identity + resume gate plus a small A/B "
                    "(CI mode: overhead reported, not gated)")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    _force_cpu()
    tmp = tempfile.mkdtemp(prefix="uda_ckbench_")
    try:
        return _run(args, tmp)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


def _run(args, tmp: str) -> int:
    from scripts.bench_staging import make_segments

    result: dict = {"bench": "ckpt_overhead",
                    "resume": resume_gate(tmp)}
    if not result["resume"]["all_ok"]:
        print(json.dumps(result))
        print("FAIL: checkpoint identity/resume gate", file=sys.stderr)
        return 3

    segs = 6 if args.quick else args.segs
    seg_mb = 4 if args.quick else args.seg_mb
    total_mb = segs * seg_mb
    result.update({"segs": segs, "seg_mb": seg_mb, "total_mb": total_mb,
                   "interval_s": args.interval,
                   "nproc": os.cpu_count(), "quick": bool(args.quick)})
    batches = make_segments(segs, seg_mb << 20, True)
    off = _spool_once(batches, tmp, False, args.interval)
    on = _spool_once(batches, tmp, True, args.interval)
    assert on["out_bytes"] == off["out_bytes"] > 0
    result["ckpt_off_s"] = round(off["wall_s"], 2)
    result["ckpt_on_s"] = round(on["wall_s"], 2)
    result["ckpt_off_MBps"] = round(total_mb / off["wall_s"], 1)
    result["ckpt_on_MBps"] = round(total_mb / on["wall_s"], 1)
    result["snapshots"] = on["snapshots"]
    result["overhead_pct"] = round(
        100.0 * (on["wall_s"] - off["wall_s"]) / off["wall_s"], 2)
    # gate only in full mode: a noisy shared host must not flake CI
    result["overhead_ok"] = result["overhead_pct"] <= 5.0
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    if args.quick:
        return 0
    return 0 if result["overhead_ok"] else 2


if __name__ == "__main__":
    sys.exit(main())
