"""Shared helpers for the staged TPU-pool drivers (tpu_return,
sweep_carrychunk, pool_watch).

Discipline encoded here (learned from the 2026-07-30 pool wedges):
stages run strictly sequentially; a timed-out stage is killed as a
whole PROCESS GROUP (bench/regression spawn their own subprocesses —
killing only the direct child leaves a grandchild holding the pool's
single device claim, i.e. a concurrent client); stage output streams
straight to a log file (no pipes: nothing to lose on a kill, nothing
to block on).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
from uda_tpu.utils.compile_cache import PLATFORM_PRELUDE  # noqa: E402

# One tiny device op: fails fast (rc!=0 / timeout) when the pool is
# wedged, prints ALIVE when it answers.
LIVENESS = (PLATFORM_PRELUDE +
            "import jax.numpy as jnp, numpy as np; "
            "print('ALIVE', int(jnp.asarray(np.arange(8)).sum()))")


def run_stage(name: str, argv: list[str], budget_s: float,
              log_dir: str, extra_env: dict | None = None
              ) -> tuple[bool, bool]:
    """One subprocess stage -> (ok, timed_out). Output streams directly
    to <log_dir>/<name>.log (stdout+stderr interleaved; nothing is lost
    if the stage is killed). On budget overrun the stage's whole
    process group is killed so no grandchild survives to hold the
    device claim."""
    log = os.path.join(log_dir, f"{name}.log")
    t0 = time.perf_counter()
    timed_out = False
    with open(log, "w") as f:
        proc = subprocess.Popen(
            argv, cwd=REPO, stdout=f, stderr=subprocess.STDOUT,
            start_new_session=True,
            env=dict(os.environ, JAX_TRACEBACK_FILTERING="off",
                     **(extra_env or {})))
        try:
            rc = proc.wait(timeout=budget_s)
        except subprocess.TimeoutExpired:
            timed_out = True
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait()
            rc = -9
            f.write(f"\n--- TIMEOUT: killed process group after "
                    f"{budget_s:.0f}s ---\n")
    ok = rc == 0
    dt = time.perf_counter() - t0
    print(f"[{name}] {'ok' if ok else 'FAIL'} in {dt:.0f}s -> {log}",
          flush=True)
    return ok, timed_out
