#!/usr/bin/env python
"""Staging throughput vs fetch line rate (VERDICT r4 weak #6 / task #7).

The network-levitated property only holds if staging (pack [+sort]
[+spool]) keeps up with fetch arrival — otherwise the merge thread is
the new bottleneck the reference's design existed to avoid (reference
src/Merger/MergeManager.cc:47-182). This bench measures both sides on
the same machine and data shape:

- ``fetch_MBps``: DataEngine -> fetch window -> cracked segments, no
  staging consumer (the arrival line rate a reduce task actually sees
  from local MOFs; on a cluster the fabric caps this instead);
- ``stage_MBps``: OverlappedMerger._stage over pre-materialized
  segments — sorted input (the Hadoop map-side-sort contract: pack +
  monotonicity check only) and shuffled input (full lexsort), with and
  without run spooling, at 1 and N stager threads.

Verdict: ``stage_sorted_spool_MBps >= fetch_MBps`` — staging at least
matches arrival on the deployment-shaped input.

Usage: python scripts/bench_staging.py [--segs 64] [--seg-mb 64]
       [--out STAGING_BENCH_r05.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def _force_cpu_if_no_tpu() -> None:
    # staging is HOST work; the bench is valid on any backend. Force CPU
    # so a wedged TPU pool can't hang the run.
    import jax

    jax.config.update("jax_platforms", "cpu")


def make_segments(segs: int, seg_bytes: int, sorted_input: bool):
    """TeraSort-shaped segments as RecordBatches (10B key / 90B value)."""
    import numpy as np

    from uda_tpu.utils.ifile import RecordBatch

    per = seg_bytes // 100
    out = []
    for s in range(segs):
        rng = np.random.default_rng(1000 + s)
        keys = rng.integers(0, 256, (per, 10), dtype=np.uint8)
        if sorted_input:
            keys = keys[np.lexsort(
                tuple(keys[:, c] for c in range(9, -1, -1)))]
        vals = rng.integers(0, 256, (per, 90), dtype=np.uint8)
        buf = np.concatenate([keys.reshape(-1), vals.reshape(-1)])
        out.append(RecordBatch(
            buf,
            np.arange(per, dtype=np.int64) * 10,
            np.full(per, 10, np.int64),
            per * 10 + np.arange(per, dtype=np.int64) * 90,
            np.full(per, 90, np.int64)))
    return out


def bench_stage(batches, stagers: int, spool: bool, tmp: str) -> float:
    """Wall seconds to stage every batch (feed + drain)."""
    from uda_tpu.merger.overlap import OverlappedMerger
    from uda_tpu.merger.streaming import RunStore
    from uda_tpu.utils.comparators import get_key_type

    kt = get_key_type("uda.tpu.RawBytes")
    store = RunStore([tmp], tag="stagebench") if spool else None
    om = OverlappedMerger(kt, 16, engine="host", run_store=store,
                          stagers=stagers)
    t0 = time.monotonic()
    for i, b in enumerate(batches):
        om.feed(i, b)
    om._drain()  # raises any staging error
    wall = time.monotonic() - t0
    if store is not None:
        assert store.total_records == sum(b.num_records for b in batches)
        store.cleanup()
    return wall


def bench_fetch(segs: int, seg_bytes: int, tmp: str) -> float:
    """Wall seconds to fetch+crack all segments through the engine."""
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.utils.comparators import get_key_type
    from uda_tpu.utils.config import Config

    from scripts.regression.run_regression import _make_terasort_mofs

    root = os.path.join(tmp, "mofs")
    _make_terasort_mofs(root, "stagebench", segs, seg_bytes // 100)
    cfg = Config({"mapred.rdma.wqe.per.conn": 8})
    engine = DataEngine(DirIndexResolver(root), cfg)
    try:
        mm = MergeManager(LocalFetchClient(engine),
                          get_key_type("uda.tpu.RawBytes"), cfg)
        t0 = time.monotonic()
        segments = mm.fetch_all(
            "stagebench",
            [f"attempt_stagebench_m_{m:06d}_0" for m in range(segs)], 0)
        wall = time.monotonic() - t0
        assert all(s.ready for s in segments)
    finally:
        engine.stop()
    return wall


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--segs", type=int, default=64)
    ap.add_argument("--seg-mb", type=int, default=64)
    ap.add_argument("--stagers", type=int, default=4)
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    _force_cpu_if_no_tpu()

    seg_bytes = args.seg_mb << 20
    total_mb = args.segs * args.seg_mb
    tmp = tempfile.mkdtemp(prefix="uda_stagebench_")
    try:
        return _run(args, seg_bytes, total_mb, tmp)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)  # ~4 GB of MOFs at defaults


def _run(args, seg_bytes: int, total_mb: int, tmp: str) -> int:
    fetch_s = bench_fetch(args.segs, seg_bytes, tmp)
    result = {"segs": args.segs, "seg_mb": args.seg_mb,
              "total_mb": total_mb,
              "fetch_s": round(fetch_s, 2),
              "fetch_MBps": round(total_mb / fetch_s, 1)}

    for sorted_input in (True, False):
        batches = make_segments(args.segs, seg_bytes, sorted_input)
        tag = "sorted" if sorted_input else "shuffled"
        for spool in (False, True):
            for nst in (1, args.stagers):
                wall = bench_stage(batches, nst, spool, tmp)
                key = f"stage_{tag}{'_spool' if spool else ''}_x{nst}"
                result[key + "_s"] = round(wall, 2)
                result[key + "_MBps"] = round(total_mb / wall, 1)
        del batches

    # context: the spool path cannot beat the scratch disk's write
    # bandwidth, whatever the CPU does — measure the ceiling
    import numpy as np

    blk = np.zeros(64 << 20, np.uint8)
    p = os.path.join(tmp, "ddprobe")
    t0 = time.monotonic()
    with open(p, "wb") as f:
        for _ in range(4):
            f.write(memoryview(blk))
        f.flush()
        os.fsync(f.fileno())
    result["disk_write_MBps"] = round(256 / (time.monotonic() - t0), 1)
    os.unlink(p)
    result["nproc"] = os.cpu_count()

    # verdict per mode against its own ceiling: the DEFAULT online mode
    # stages in memory and must match the fetch line rate; streaming
    # mode additionally writes runs and is bounded by min(fetch, disk)
    best_mem = max(result[f"stage_sorted_x{n}_MBps"]
                   for n in (1, args.stagers))
    best_spool = max(result[f"stage_sorted_spool_x{n}_MBps"]
                     for n in (1, args.stagers))
    result["staging_keeps_up"] = best_mem >= result["fetch_MBps"] * 0.95
    result["spool_keeps_up_with_disk"] = (
        best_spool >= min(result["fetch_MBps"],
                          result["disk_write_MBps"]) * 0.5)
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    return 0 if result["staging_keeps_up"] else 2


if __name__ == "__main__":
    sys.exit(main())
