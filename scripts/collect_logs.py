#!/usr/bin/env python
"""Log/diagnostics collector: bundle everything a failure analysis needs.

The uda_tpu analogue of the reference's utils/ log collectors
(reference utils/master/daemon-log-collector.sh and the slave variants
gather daemon + job logs from every node of the cluster into one
archive). Here the sources are local: uda log files (the
``mapred.uda.log.to.unique.file`` channel), bench/regression artifacts,
probe failure logs, metrics dumps, and the environment snapshot.

Usage: python scripts/collect_logs.py [--out DIR] [--extra PATH ...]
Prints the bundle directory; never fails the caller (collection is
best-effort by design — it runs AFTER something already went wrong).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import platform
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _snapshot_env(out_dir: str) -> None:
    info = {
        "time_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": platform.platform(),
        "python": sys.version,
        "argv_env": {k: v for k, v in os.environ.items()
                     if k.startswith(("JAX_", "XLA_", "UDA_TPU_"))},
    }
    try:
        info["git_head"] = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=REPO, capture_output=True,
            text=True, timeout=30).stdout.strip()
    except Exception as e:  # noqa: BLE001 - best-effort collection: the
        # failure itself is worth archiving with the snapshot
        info["git_head_error"] = f"{type(e).__name__}: {e}"
    with open(os.path.join(out_dir, "environment.json"), "w") as f:
        json.dump(info, f, indent=2)


def collect(out_dir: str, extra: list[str]) -> str:
    os.makedirs(out_dir, exist_ok=True)
    _snapshot_env(out_dir)
    patterns = [
        os.path.join(REPO, ".bench_probe_*.log"),
        os.path.join(REPO, "BENCH_r*.json"),
        os.path.join(REPO, "MULTICHIP_r*.json"),
        os.path.join(REPO, "ci_artifacts", "**", "*"),
        # the private-file logging channel (udaNetMerger.log naming of
        # the reference, IOUtility.cc:406-466)
        os.path.join(REPO, "*.uda.log"),
        "/tmp/uda_tpu*.log",
    ] + list(extra)
    copied = []
    for pat in patterns:
        for path in glob.glob(pat, recursive=True):
            if os.path.isfile(path):
                # preserve repo-relative structure: same-named files
                # from different subdirs (regression results, nested
                # ci logs) must not overwrite each other
                if os.path.commonpath([REPO, os.path.abspath(path)]) \
                        == REPO:
                    rel = os.path.relpath(os.path.abspath(path), REPO)
                else:
                    rel = os.path.abspath(path).lstrip(os.sep)
                dst = os.path.join(out_dir, rel)
                try:
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    shutil.copy2(path, dst)
                    copied.append(rel)
                except OSError:
                    pass
    with open(os.path.join(out_dir, "MANIFEST"), "w") as f:
        f.write("\n".join(sorted(copied)) + "\n")
    return out_dir


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        REPO, f"diag_{time.strftime('%Y%m%d_%H%M%S')}"))
    ap.add_argument("--extra", nargs="*", default=[])
    args = ap.parse_args()
    print(collect(args.out, args.extra))


if __name__ == "__main__":
    main()
