"""Post-fly-off tuning sweep for the carrychunk champion engine.

The r5 hardware fly-off crowned carrychunk (narrow lax.sort perm +
chunked operand-carry apply) at 3.1 GB/s. Its apply step moves
``nchunks + VALUE_WORDS`` words per record through sort networks, so
larger ``chunk_cols`` strictly reduces network traffic — bounded by
XLA's superlinear variadic-sort compile time (the "carry" pathology).
This sweep times chunk_cols candidates, each compile+measure in its own
budgeted subprocess (a pathological compile costs one budget, not the
window), strictly sequentially (the pool serves ONE device claim).

Also re-probes the two engines whose Mosaic compile failures were fixed
post-fly-off (keys8f select-on-i1, lanes2 narrowing gather) — compile
evidence plus a timing if they lower.

Usage: python scripts/sweep_carrychunk.py [--log-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, HERE)
from stagelib import LIVENESS, run_stage  # noqa: E402

# one candidate: compile bench_step at the official shape, then two
# timed dispatches with fresh seeds (the relay serves identical-input
# repeats from a cache; block_until_ready does not wait on this
# backend, so timing syncs via scalar readback)
CANDIDATE = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
from uda_tpu.utils import compile_cache
compile_cache.apply_platform_env()
compile_cache.enable()
import jax, numpy as np
from uda_tpu.models import terasort

n = 1 << {log2}
k = {rounds}
kw = dict(path={path!r}, tile={tile}, chunk_cols={cc})
gb = n * terasort.RECORD_BYTES * k / 1e9

def once(seed):
    t0 = time.perf_counter()
    viol, ck_in, ck_out = terasort.bench_step(jax.random.key(seed), n, k,
                                              **kw)
    assert int(viol) == 0, "order violations"
    assert np.uint32(ck_in) == np.uint32(ck_out), "checksum mismatch"
    return time.perf_counter() - t0

t0 = time.perf_counter()
once(999)
print(f"compile+first: {{time.perf_counter()-t0:.1f}}s", flush=True)
best = min(once(998), once(997))
print(f"RESULT {path!r} tile={tile} cc={cc}: "
      f"{{gb/best:.3f}} GB/s ({{best:.3f}}s)", flush=True)
"""


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--log-dir",
                    default=os.path.join(REPO, ".sweep_carrychunk"))
    ap.add_argument("--log2", type=int, default=23)
    ap.add_argument("--rounds", type=int, default=4)
    args = ap.parse_args()
    os.makedirs(args.log_dir, exist_ok=True)
    py = sys.executable

    def cand(path, cc=6, tile=4096):
        return CANDIDATE.format(repo=REPO, log2=args.log2,
                                rounds=args.rounds, path=path, tile=tile,
                                cc=cc)

    stages = [
        # chunk_cols ladder over the 23 value words: cc=6 -> 4 sorts
        # (27 operand-words/record), cc=8 -> 3 (26), cc=12 -> 2 (25),
        # cc=23 -> the single-sort extreme, 1 sort of 24 operands
        # (compile risk is exactly what the per-stage budget is for)
        ("cc6", [py, "-c", cand("carrychunk", 6)], 1200),
        ("cc8", [py, "-c", cand("carrychunk", 8)], 1200),
        ("cc12", [py, "-c", cand("carrychunk", 12)], 1500),
        ("cc23", [py, "-c", cand("carrychunk", 23)], 1800),
        # fixed-kernel re-probes (evidence the Mosaic fixes lower)
        ("keys8f_8192", [py, "-c", cand("keys8f", tile=8192)], 1200),
        ("lanes2_4096", [py, "-c", cand("lanes2", tile=4096)], 1500),
    ]

    def alive(tag):
        ok, _ = run_stage(tag, [py, "-c", LIVENESS], 300, args.log_dir)
        return ok

    if not alive("liveness"):
        print("pool wedged; aborting", flush=True)
        return 1
    done = 0
    for name, argv, budget in stages:
        ok, timed_out = run_stage(name, argv, budget, args.log_dir)
        done += 1
        if timed_out and not alive(f"liveness_after_{name}"):
            print(f"pool wedged after {name}; aborting", flush=True)
            return 1
    print(json.dumps({"stages_run": done, "log_dir": args.log_dir}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
