#!/usr/bin/env python
"""Elastic disaggregated-store bench: the ISSUE 18 acceptance numbers.

Two workloads, each with a correctness gate and a perf figure:

- **spill** — a shuffle writing ~10x the host retention budget through
  ``MOFWriter(store=...)`` with the spill ladder armed. Gates: the
  job COMPLETES with the merged output byte-identical to an unspilled
  reference run, and local retention stays bounded — the post-write
  floor is the watermark, the mid-write peak is allowed one partition
  of slack (the write that crosses the line spills synchronously
  before returning, so the ladder can never owe more than the
  partition in hand). Throughput (``spill_MBps``) and process maxrss
  ride along as trend data.

- **join** — a degraded primary supplier (fails the first F attempts
  per hot map, then serves; deterministic, no dice) against a healthy
  replica holding the same partitions. Baseline: the reduce grinds
  through the primary's failures alone, paying F backoffs per hot map.
  Joined: the replica registers mid-job via
  ``MergeManager.notify_join`` — in-flight Segments widen, the first
  retry re-ranks onto the joiner, and the stall collapses. Gates: both
  variants byte-identical to the clean reference, and (full mode) the
  join run beats the baseline by >= JOIN_SPEEDUP_GATE.

Usage: python scripts/bench_elastic.py [--quick] [--overbudget 10]
       [--out BENCH_ELASTIC.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

JOIN_SPEEDUP_GATE = 1.2  # full mode only: quick walls are host noise


def _force_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")


def _maxrss_mb() -> float:
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def _write_shuffle(root, job, num_maps, recs_per_map, val_bytes,
                   store=None, track=None):
    import numpy as np

    from uda_tpu.mofserver.writer import MOFWriter

    rng = np.random.default_rng(1812)
    writer = MOFWriter(root, job, store=store)
    for m in range(num_maps):
        recs = sorted((rng.bytes(10), rng.bytes(val_bytes))
                      for _ in range(recs_per_map))
        writer.write(f"attempt_{job}_m_{m:06d}_0", [recs])
        if track is not None:
            track(store)
    return writer.map_ids


def _merge(root, job, mids, blob_root=None, client_wrap=None):
    """One single-host merge; returns (bytes, wall_s)."""
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver, StoreManager
    from uda_tpu.utils.comparators import get_key_type
    from uda_tpu.utils.config import Config

    resolver = DirIndexResolver(root)
    engine = DataEngine(resolver)
    mgr = None
    if blob_root is not None:
        mgr = StoreManager(resolver, blob_root)
        engine.attach_store(mgr)
    client = LocalFetchClient(engine)
    if client_wrap is not None:
        client = client_wrap(client)
    mm = MergeManager(client, get_key_type("uda.tpu.RawBytes"), Config())
    blocks = []
    t0 = time.monotonic()
    try:
        mm.run(job, mids, 0, lambda b: blocks.append(bytes(b)))
    finally:
        if mgr is not None:
            mgr.close()
        engine.stop()
    return b"".join(blocks), time.monotonic() - t0


def _bench_spill(tmp, num_maps, recs_per_map, val_bytes, overbudget):
    from uda_tpu.mofserver import DirIndexResolver, StoreManager
    from uda_tpu.utils.metrics import metrics

    job = "elspill"
    # reference: same records, NO store, merged once for the oracle
    ref_root = os.path.join(tmp, "ref")
    mids = _write_shuffle(ref_root, job, num_maps, recs_per_map,
                          val_bytes)
    ref, _ = _merge(ref_root, job, mids)
    total = sum(
        os.path.getsize(os.path.join(dirpath, f))
        for dirpath, _, files in os.walk(ref_root) for f in files
        if f == "file.out")
    watermark = max(1, int(total / overbudget))
    metrics.reset()
    local = os.path.join(tmp, "spill_local")
    blob = os.path.join(tmp, "spill_blob")
    resolver = DirIndexResolver(local)
    mgr = StoreManager(resolver, blob, watermark_bytes=watermark)
    peak = {"v": 0}

    def track(store):
        peak["v"] = max(peak["v"], store.retained_bytes())

    t0 = time.monotonic()
    _write_shuffle(local, job, num_maps, recs_per_map, val_bytes,
                   store=mgr, track=track)
    retained = mgr.retained_bytes()
    migrations = len(mgr.migrations())
    spilled = metrics.get("store.spilled.bytes") or 0.0
    mgr.close()
    out, merge_wall = _merge(local, job, mids, blob_root=blob)
    wall = time.monotonic() - t0
    # the mid-write peak may exceed the floor by at most the partition
    # being written (it spills synchronously before write() returns)
    slack = 2 * total / num_maps
    return {
        "total_mb": round(total / 1048576, 3),
        "watermark_mb": round(watermark / 1048576, 3),
        "overbudget_x": overbudget,
        "spill_migrations": migrations,
        "spilled_mb": round(spilled / 1048576, 3),
        "peak_retained_mb": round(peak["v"] / 1048576, 3),
        "final_retained_mb": round(retained / 1048576, 3),
        "retained_bounded": bool(retained <= watermark
                                 and peak["v"] <= watermark + slack),
        "spill_identical": bool(out == ref and len(ref) > 0),
        "spill_wall_s": round(wall, 3),
        "spill_merge_s": round(merge_wall, 3),
        "spill_MBps": round(total / 1048576 / wall, 1),
        "maxrss_mb": round(_maxrss_mb(), 1),
    }


class _DegradedClient:
    """Fails the first ``fail_first`` attempts per hot map with a
    typed StorageError, then serves — a deterministic brown-out."""

    def __init__(self, inner, hot, fail_first):
        self.inner = inner
        self.hot = set(hot)
        self.fail_first = fail_first
        self._attempts = {}
        self._lock = threading.Lock()

    def start_fetch(self, req, cb):
        from uda_tpu.utils.errors import StorageError

        if req.map_id in self.hot:
            with self._lock:
                n = self._attempts.get(req.map_id, 0)
                self._attempts[req.map_id] = n + 1
            if n < self.fail_first:
                cb(StorageError(
                    f"degraded supplier: {req.map_id} attempt {n}"))
                return
        self.inner.start_fetch(req, cb)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def _bench_join(tmp, num_maps, recs_per_map, val_bytes, quick):
    from uda_tpu.merger import (HostRoutingClient, LocalFetchClient,
                                MergeManager)
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.utils.comparators import get_key_type
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.metrics import metrics

    job = "eljoin"
    root = os.path.join(tmp, "join_root")
    mids = _write_shuffle(root, job, num_maps, recs_per_map, val_bytes)
    ref, _ = _merge(root, job, mids)
    hot = mids[:: max(1, num_maps // 4)]  # every 4th map browns out
    fail_first = 4 if quick else 6
    backoff_ms = 60.0 if quick else 120.0
    cfg = Config({"uda.tpu.fetch.retries": fail_first + 6,
                  "mapred.rdma.fetch.retry.backoff.ms": backoff_ms,
                  "mapred.rdma.fetch.retry.backoff.max.ms":
                      backoff_ms * 2})
    kt = get_key_type("uda.tpu.RawBytes")

    def run(join_at_s):
        metrics.reset()
        engines = {"A": DataEngine(DirIndexResolver(root)),
                   "B": DataEngine(DirIndexResolver(root))}

        def connect(host):
            inner = LocalFetchClient(engines[host])
            if host == "A":
                return _DegradedClient(inner, hot, fail_first)
            return inner

        router = HostRoutingClient(connect=connect)
        mm = MergeManager(router, kt, cfg)
        joiner = None
        if join_at_s is not None:
            joiner = threading.Timer(join_at_s,
                                     lambda: mm.notify_join("B"))
            joiner.daemon = True
            joiner.start()
        blocks = []
        t0 = time.monotonic()
        try:
            mm.run(job, [("A", m) for m in mids], 0,
                   lambda b: blocks.append(bytes(b)))
            wall = time.monotonic() - t0
        finally:
            if joiner is not None:
                joiner.cancel()
            mm.stop()
            for e in engines.values():
                e.stop()
        joins = metrics.get("elastic.joins") or 0.0
        return b"".join(blocks), wall, joins

    out_nojoin, wall_nojoin, _ = run(None)
    out_join, wall_join, joins = run(0.1)
    speedup = wall_nojoin / wall_join if wall_join > 0 else 0.0
    return {
        "join_hot_maps": len(hot),
        "join_fail_first": fail_first,
        "join_backoff_ms": backoff_ms,
        "join_identical": bool(out_nojoin == ref == out_join
                               and len(ref) > 0),
        "join_registered": bool(joins > 0),
        "wall_nojoin_s": round(wall_nojoin, 3),
        "wall_join_s": round(wall_join, 3),
        "join_speedup": round(speedup, 3),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--maps", type=int, default=16)
    ap.add_argument("--recs", type=int, default=400)
    ap.add_argument("--val-bytes", type=int, default=1024)
    ap.add_argument("--overbudget", type=float, default=10.0,
                    help="shuffle bytes / retention watermark")
    ap.add_argument("--quick", action="store_true",
                    help="small shape; identity/bounded gates only — "
                    "walls and speedups are trend data")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    _force_cpu()
    num_maps = 8 if args.quick else args.maps
    recs = 60 if args.quick else args.recs
    val_bytes = 256 if args.quick else args.val_bytes
    tmp = tempfile.mkdtemp(prefix="uda_elastic_")
    try:
        result = {"bench": "elastic", "quick": bool(args.quick),
                  "maps": num_maps, "recs_per_map": recs,
                  "val_bytes": val_bytes,
                  "nproc": os.cpu_count()}
        result.update(_bench_spill(tmp, num_maps, recs, val_bytes,
                                   args.overbudget))
        result.update(_bench_join(tmp, num_maps, recs, val_bytes,
                                  args.quick))
        result["join_speedup_ok"] = bool(
            args.quick or result["join_speedup"] >= JOIN_SPEEDUP_GATE)
        print(json.dumps(result))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(result, f, indent=1)
                f.write("\n")
        if not (result["spill_identical"] and result["join_identical"]):
            print("FAIL: elastic bench identity gate", file=sys.stderr)
            return 3
        if not result["retained_bounded"]:
            print("FAIL: spill ladder did not bound local retention",
                  file=sys.stderr)
            return 3
        if not result["join_registered"]:
            print("FAIL: mid-job join never registered", file=sys.stderr)
            return 3
        if not result["join_speedup_ok"]:
            print(f"FAIL: join speedup {result['join_speedup']} < "
                  f"{JOIN_SPEEDUP_GATE}", file=sys.stderr)
            return 2
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
