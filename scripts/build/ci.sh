#!/usr/bin/env bash
# CI driver: build + test + regression + artifact bundle in one gate.
#
# The uda_tpu analogue of the reference's nightly build+smoke system
# (reference scripts/build/: per-Hadoop-version builds, smoke runs,
# db/latest_hadoops bookkeeping) collapsed to what this framework
# needs: native libs -> unit/engine tests -> the workload-ladder
# regression -> one artifacts directory a nightly can archive.
#
# Usage: scripts/build/ci.sh [artifacts_dir]
# Exit code != 0 on any gate failure (the cases/uda.cases CI contract).

set -euo pipefail
cd "$(dirname "$0")/../.."

ART="${1:-ci_artifacts}"
mkdir -p "$ART"
echo "== uda_tpu CI $(date -u +%Y-%m-%dT%H:%M:%SZ) ==" | tee "$ART/ci.log"

echo "-- native build" | tee -a "$ART/ci.log"
make -C uda_tpu/native 2>&1 | tee -a "$ART/ci.log"
make -C uda_tpu/native libuda_tpu_bridge.so 2>&1 | tee -a "$ART/ci.log"
# Java gate. This image has NO Java compiler and cannot get one:
# javac/ecj exist nowhere on the filesystem, bazel's embedded Zulu 21
# JRE (~/.cache/bazel/.../embedded_tools/jdk) is a 13-module jlink
# image WITHOUT jdk.compiler, and the container has zero network
# egress (DNS fails), so vendoring a JDK is impossible here (probed
# 2026-07-30). The real compile gate below arms itself automatically
# on any host with a JDK; until then check_java.py gives the sources
# the strongest compiler-less gate (string-aware structural pass).
if command -v javac >/dev/null 2>&1; then
  echo "-- java build" | tee -a "$ART/ci.log"
  make -C java 2>&1 | tee -a "$ART/ci.log"
else
  echo "-- java build skipped (no JDK in image); structural check" \
    | tee -a "$ART/ci.log"
  python scripts/build/check_java.py 2>&1 | tee -a "$ART/ci.log"
fi

# Static analysis gate: the project invariants (metrics registry,
# config-key declaration, failpoint sites, shutdown-before-close,
# structured-cause branching, no silent swallows, no blocking under a
# lock) AND the udaflow dataflow tier (UDA101 resource balance on
# every CFG path, UDA102 transitive blocking, UDA103 static lock
# order) are machine-enforced BEFORE any test runs — a violation is a
# build failure, like the reference's scripts/build check_* gates.
# The machine-readable findings land in the artifacts (udalint.json)
# so downstream gates consume them structurally, never by grep.
echo "-- udalint static analysis (incl. UDA009 span names + udaflow UDA101-UDA103)" \
  | tee -a "$ART/ci.log"
# human-readable gate FIRST (findings must land in ci.log/console);
# the machine-readable artifact only runs on a clean tree, where the
# second pass hits the content-hash cache (--cache: the JSON pass
# re-parses nothing on an unchanged tree)
python scripts/udalint.py --cache uda_tpu scripts 2>&1 | tee -a "$ART/ci.log" | tail -1
python scripts/udalint.py --cache --json uda_tpu scripts > "$ART/udalint.json"

echo "-- unit + engine tests" | tee -a "$ART/ci.log"
python -m pytest tests/ -q 2>&1 | tee "$ART/pytest.log" | tail -2

# Network data plane: a real server + 2 concurrent reduce clients over
# 127.0.0.1, byte-compared against the in-process path (uda_tpu/net/),
# with span tracing on — the smoke's span JSONL feeds the trace-merge
# gate below, and the smoke itself now round-trips one MSG_STATS poll.
echo "-- net loopback smoke" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu UDA_TPU_STATS=1 \
  python scripts/net_smoke.py --spans "$ART/net_smoke_spans.jsonl" \
  2>&1 | tee -a "$ART/ci.log" | tail -1

# Trace-merge gate: the smoke's span file must stitch into one valid
# Perfetto-loadable Chrome trace (empty or unparsable span files fail;
# the cross-process link assertion rides tier-1's two-process-shaped
# e2e in tests/test_observability.py — the smoke is one process).
echo "-- trace merge (net smoke spans)" | tee -a "$ART/ci.log"
python scripts/trace_merge.py "$ART/net_smoke_spans.jsonl" \
  --out "$ART/net_smoke_trace.json" 2>&1 | tee -a "$ART/ci.log" | tail -1

# Net data-plane bench, quick mode: single-stream + p99 latency + the
# 256-connection fan-in on the event-loop core. Gates on correctness
# (zero fan-in errors/stalls); throughput is reported, not gated, so a
# noisy shared host cannot flake CI (full runs ride BENCH_NET_*.json).
echo "-- net data-plane bench (quick)" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python scripts/net_bench.py --quick --out "$ART/bench_net.json" \
  2>&1 | tee -a "$ART/ci.log" | tail -4

# Batched host-I/O serve A/B, quick mode: the batched+coalesced read
# plane (uda.tpu.read.batch=on) must be BYTE-IDENTICAL to the
# single-pread oracle (=off) on the hot-burst shape — identity is the
# gate (exit 3 on divergence); throughput/speedup are recorded as
# perfwatch trend data (full runs ride BENCH_IO_r*.json and gate the
# >= 1.3x acceptance there).
echo "-- batched host-I/O serve A/B (quick)" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python scripts/io_bench.py --quick --out "$ART/bench_io.json" \
  2>&1 | tee -a "$ART/ci.log" | tail -3

# Multi-tenant fairness bench, quick mode: T concurrent jobs through
# one daemon — the byte-identity gate (every job's concurrent fetch ==
# its solo run; exit 3 on divergence) plus the WDRR plumbing end to
# end; fairness/weighted ratios are recorded as perfwatch trend data
# (full runs ride BENCH_TENANT_r*.json and gate the >= 0.7 fairness +
# ~2:1 weighting bands there).
echo "-- multi-tenant fairness bench (quick)" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python scripts/tenant_bench.py --quick \
  --out "$ART/bench_tenant.json" 2>&1 | tee -a "$ART/ci.log" | tail -4

# Elastic disaggregated-store bench, quick mode: the spill ladder
# (10x-over-budget shuffle completes byte-identical with local
# retention bounded at the watermark) plus the mid-job supplier join
# (a degraded primary's stall collapses when the replica registers) —
# identity/bounded/registered are the gates (exit 3 on divergence);
# walls and the join speedup are perfwatch trend data (full runs ride
# BENCH_ELASTIC_r*.json and gate the >= 1.2x join speedup there).
echo "-- elastic store spill + mid-job join bench (quick)" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python scripts/bench_elastic.py --quick \
  --out "$ART/bench_elastic.json" 2>&1 | tee -a "$ART/ci.log" | tail -2

# Push-shuffle overlap bench, quick mode: supplier-initiated MSG_PUSH
# vs the fetch-wave pull baseline over the real loopback plane — the
# byte-identity gate (sha256 of the merged stream vs the pull oracle;
# exit 3 on divergence) plus push-plane engagement (chunks sent AND
# staged bytes adopted into the Segment ledger) and zero terminal
# FallbackSignals; walls/speedup are perfwatch trend data (full runs
# ride BENCH_PUSH_r*.json and gate the >= 1.1x overlap win there).
echo "-- push-shuffle overlap bench (quick)" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python scripts/bench_push.py --quick \
  --out "$ART/bench_push.json" 2>&1 | tee -a "$ART/ci.log" | tail -2

# Fleet observability gate: one tenanted, observability-armed daemon,
# 8 equal-weight tenant drivers, scripts/udafleet.py --once --json
# polled live against it — the CAP_OBS sections must round-trip and
# every tenant's fleet share of scheduled bytes must land within 2% of
# its weight-proportional entitlement (the WDRR fairness audit the SLI
# book exists to answer).
echo "-- fleet observability smoke (udafleet --once --json)" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python scripts/fleet_smoke.py 2>&1 | tee -a "$ART/ci.log" | tail -1

# Tuning-cache round trip: a quick io.read fly-off probe must persist
# a winner, and a SECOND probe run must serve from the cache without
# re-measuring (tune_probe prints "0 probe(s)" — the self-service
# routing contract; the full lifecycle matrix rides
# tests/test_tuncache.py in tier-1).
echo "-- tuning-cache probe round trip (quick)" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python scripts/tune_probe.py --cache "$ART/tune_cache.json" --quick \
  --domain io.read 2>&1 | tee -a "$ART/ci.log" | tail -2
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python scripts/tune_probe.py --cache "$ART/tune_cache.json" --quick \
  --domain io.read 2>&1 | tee -a "$ART/ci.log" | grep -q "0 probe(s) run" \
  || { echo "FAIL: second tune_probe run re-probed a fresh cache" \
       | tee -a "$ART/ci.log"; exit 1; }

# Hierarchical + CODED exchange gate, quick mode (2x4 virtual mesh):
# the two-stage pod exchange AND the coded multicast stage B must be
# byte-identical to the flat exchange and the host oracles, and the
# accounting invariants must hold — hierarchical per-round DCN
# messages <= the pod-pair bound and <= the flat device-pair count,
# DCN bytes no higher than flat, coded + saved == uncoded payload,
# uniform coded charge <= 0.67x hierarchical, zero coded overhead on
# the uncodable shapes (full 8/16/64 runs ride
# MULTICHIP_SCALE_r*.json and feed perfwatch).
echo "-- hierarchical + coded exchange bench (quick)" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS \
  python scripts/exchange_bench.py --quick \
  --out "$ART/exchange_bench.json" 2>&1 | tee -a "$ART/ci.log" | tail -5

# Staging-pipeline gate, quick mode: the pipelined stage pool must be
# BYTE-IDENTICAL to the serial staging twin across sorted/shuffled
# input, spool mode and a compressed end-to-end run (exit 3 on any
# divergence), and the time-accounting point must partition the task
# wall (buckets + idle == wall within 5%). Runs under UDA_TPU_STATS=1
# (the span layer critpath needs) + UDA_TPU_PROFILE (the sampling
# profiler rides the same run — its overhead is inside the reported
# numbers, which is the honest configuration perfwatch trends).
# Throughput is reported, not gated, in quick mode — the 64x64 MB
# speedup gate rides the full run's BENCH_PIPELINE_r*.json.
echo "-- staging pipeline A/B + time accounting (quick)" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  UDA_TPU_STATS=1 UDA_TPU_PROFILE=47 \
  python scripts/bench_pipeline.py --quick \
  --out "$ART/bench_pipeline.json" 2>&1 | tee -a "$ART/ci.log" | tail -2

# perfwatch gate: the fresh quick point (throughput trends +
# correctness booleans + the time-accounting block) against the
# committed PERF_TRAJECTORY.json. The band is generous — shared CI
# hosts gate direction-of-change, not absolute MB/s; quick-mode
# throughputs are recorded as trend data and the hard gates are the
# correctness/identity metrics (per-entry tol 0). Exit 1 = a shipped
# perf regression, which is a build failure.
echo "-- perfwatch perf-regression gate" | tee -a "$ART/ci.log"
python scripts/perfwatch.py --check "$ART/bench_pipeline.json" \
  --tolerance 0.6 2>&1 | tee -a "$ART/ci.log" | tail -3
python scripts/perfwatch.py --check "$ART/bench_io.json" \
  --tolerance 0.6 2>&1 | tee -a "$ART/ci.log" | tail -3
python scripts/perfwatch.py --check "$ART/bench_tenant.json" \
  --tolerance 0.6 2>&1 | tee -a "$ART/ci.log" | tail -3
python scripts/perfwatch.py --check "$ART/exchange_bench.json" \
  --tolerance 0.6 2>&1 | tee -a "$ART/ci.log" | tail -3
python scripts/perfwatch.py --check "$ART/bench_elastic.json" \
  --tolerance 0.6 2>&1 | tee -a "$ART/ci.log" | tail -3
python scripts/perfwatch.py --check "$ART/bench_push.json" \
  --tolerance 0.6 2>&1 | tee -a "$ART/ci.log" | tail -3

# CPU-only gates run with the accelerator-pool env stripped: the pool's
# sitecustomize otherwise dials the pool from every spawned interpreter
# and can hang at startup while the pool is wedged (pytest strips it
# itself via tests/conftest.py's re-exec).
echo "-- workload-ladder regression" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS python scripts/regression/run_regression.py \
  --size small --out "$ART/regression" 2>&1 | tee -a "$ART/ci.log" | tail -3

echo "-- multi-chip dryrun" | tee -a "$ART/ci.log"
env -u PALLAS_AXON_POOL_IPS \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)" \
  2>&1 | tee -a "$ART/ci.log" | tail -1

echo "== CI PASS ==" | tee -a "$ART/ci.log"
