#!/usr/bin/env python
"""Structural validation of the Java tree without a JDK.

This image ships NO Java compiler: there is no javac/ecj anywhere on
the filesystem, bazel's embedded Zulu JRE is a 13-module jlink image
without jdk.compiler, and the container has zero network egress, so a
JDK cannot be vendored (probed 2026-07-30; see ci.sh, which runs the
real `make -C java` the moment a javac appears). Until then this
checker gives the Java sources the strongest gate available without a
compiler — a string/comment-aware structural pass that catches the
mechanical damage CI most needs to reject:

- unbalanced braces/parens/brackets (string- and comment-aware lexing);
- unterminated string/char literals and block comments;
- package declaration not matching the file's directory path;
- public type name not matching the file name;
- imports of uda packages that resolve to no file in the tree.

It is NOT a compiler and proves nothing about types; it exists so a
truncated file, a bad merge, or a renamed class fails CI instead of
lying dormant in a source-only tree (VERDICT r4 missing #2).
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
JAVA_ROOT = os.path.join(REPO, "java")

OPEN = {"{": "}", "(": ")", "[": "]"}
CLOSE = {v: k for k, v in OPEN.items()}


def strip_literals(src: str, path: str, errors: list[str]) -> str:
    """Replace comments and string/char literals with spaces, preserving
    newlines (so reported line numbers survive)."""
    out = []
    i, n = 0, len(src)
    line = 1
    mode = None  # None | "line" | "block" | '"' | "'" | '"""'
    start_line = 1
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if c == "\n":
            line += 1
        if mode is None:
            if c == "/" and nxt == "/":
                mode = "line"
                out.append("  ")
                i += 2
                continue
            if c == "/" and nxt == "*":
                mode, start_line = "block", line
                out.append("  ")
                i += 2
                continue
            if src.startswith('"""', i):
                mode, start_line = '"""', line
                out.append("   ")
                i += 3
                continue
            if c in ('"', "'"):
                mode, start_line = c, line
                out.append(" ")
                i += 1
                continue
            out.append(c)
            i += 1
            continue
        # inside a literal/comment
        if mode == "line":
            if c == "\n":
                mode = None
                out.append("\n")
            else:
                out.append(" ")
            i += 1
            continue
        if mode == "block":
            if c == "*" and nxt == "/":
                mode = None
                out.append("  ")
                i += 2
            else:
                out.append("\n" if c == "\n" else " ")
                i += 1
            continue
        if mode == '"""':
            if src.startswith('"""', i):
                mode = None
                out.append("   ")
                i += 3
            else:
                out.append("\n" if c == "\n" else " ")
                i += 1
            continue
        # single-line string/char literal
        if c == "\\":
            out.append("  ")
            i += 2
            continue
        if c == mode:
            mode = None
            out.append(" ")
            i += 1
            continue
        if c == "\n":
            errors.append(f"{path}:{start_line}: unterminated {mode} literal")
            mode = None
            out.append("\n")
            i += 1
            continue
        out.append(" ")
        i += 1
    if mode in ("block", '"""'):
        errors.append(f"{path}:{start_line}: unterminated "
                      f"{'block comment' if mode == 'block' else mode}")
    return "".join(out)


def check_file(path: str, rel: str, known_classes: set[str],
               known_packages: set[str], errors: list[str]) -> None:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    stripped = strip_literals(src, rel, errors)

    # bracket balance
    stack: list[tuple[str, int]] = []
    line = 1
    for ch in stripped:
        if ch == "\n":
            line += 1
        elif ch in OPEN:
            stack.append((ch, line))
        elif ch in CLOSE:
            if not stack or stack[-1][0] != CLOSE[ch]:
                errors.append(f"{rel}:{line}: unmatched '{ch}'")
                return
            stack.pop()
    for ch, ln in stack:
        errors.append(f"{rel}:{ln}: unclosed '{ch}'")

    # package <-> path (component-aligned: the directory's tail
    # components must equal the package components exactly)
    m = re.search(r"^\s*package\s+([\w.]+)\s*;", stripped, re.M)
    if m:
        pkg_parts = m.group(1).split(".")
        dir_parts = os.path.dirname(rel).split(os.sep)
        if dir_parts[-len(pkg_parts):] != pkg_parts:
            errors.append(f"{rel}: package {m.group(1)} does not match "
                          f"directory {os.path.dirname(rel)}")
    # public type <-> file name
    base = os.path.splitext(os.path.basename(rel))[0]
    pub = re.search(
        r"^\s*public\s+(?:final\s+|abstract\s+)*"
        r"(?:class|interface|enum|record)\s+(\w+)", stripped, re.M)
    if pub and pub.group(1) != base:
        errors.append(f"{rel}: public type {pub.group(1)} in file {base}.java")

    # uda imports resolve in-tree (wildcard imports check the package
    # prefix instead of a class name)
    for im in re.finditer(r"^\s*import\s+(?:static\s+)?([\w.]+(?:\.\*)?)"
                          r"\s*;", stripped, re.M):
        name = im.group(1)
        if ".uda." not in name and not name.startswith("com.mellanox"):
            continue
        if name.endswith(".*"):
            pkg_dir = name[:-2].replace(".", os.sep)
            if not any(d == pkg_dir or d.endswith(os.sep + pkg_dir)
                       for d in known_packages):
                errors.append(f"{rel}: wildcard import {name} matches no "
                              "package directory in the tree")
        elif name.split(".")[-1] not in known_classes:
            errors.append(f"{rel}: import {name} resolves to no file "
                          "in the tree")


def check_callback_table(java_root: str, errors: list[str]) -> None:
    """Callback-name resolution across the three bridge layers
    (VERDICT.md ask #7): the up-call table must agree between

    - ``bridge_shim.cc``'s ``uda_callbacks_t`` struct (the C ABI: one
      ``ctx`` plus N ordered function-pointer fields) and its
      ``fw_methods`` Python-name table (what the engine calls),
    - ``UdaBridge.java``'s ``buildCallbacks`` (the stubs it binds via
      ``findStatic`` and the 8-byte slots it writes them into), and
    - ``bridge/bridge.py``'s ``UdaCallable`` protocol.

    A renamed, re-ordered, added or dropped up-call in ANY of the three
    fails the gate instead of dereferencing the wrong slot at runtime.
    Java receiver naming rule: slot i's bound method must be ``cb`` +
    a CamelCase prefix of the C field name (cbFetchOver ->
    fetch_over_message), which catches renames while allowing the
    established abbreviations."""
    shim = os.path.join(REPO, "uda_tpu", "native", "bridge_shim.cc")
    jbridge = os.path.join(java_root, "com", "mellanox", "hadoop",
                           "mapred", "UdaBridge.java")
    pybridge = os.path.join(REPO, "uda_tpu", "bridge", "bridge.py")
    if not (os.path.exists(shim) and os.path.exists(jbridge)
            and os.path.exists(pybridge)):
        return  # damaged-tree tests run on a copied java/ only
    shim_src = open(shim, encoding="utf-8").read()
    jsrc = open(jbridge, encoding="utf-8").read()
    pysrc = open(pybridge, encoding="utf-8").read()

    # 1. ordered function-pointer fields of uda_callbacks_t
    m = re.search(r"typedef\s+struct\s+uda_callbacks\s*\{(.*?)\}",
                  shim_src, re.S)
    if not m:
        errors.append("bridge_shim.cc: uda_callbacks_t struct not found")
        return
    fields = re.findall(r"\(\s*\*\s*(\w+)\s*\)", m.group(1))
    if not fields:
        errors.append("bridge_shim.cc: uda_callbacks_t has no function "
                      "pointers")
        return

    # 2. fw_methods table names match the struct fields exactly, in order
    fw = re.search(r"PyMethodDef\s+fw_methods\[\]\s*=\s*\{(.*?)\};",
                   shim_src, re.S)
    fw_names = re.findall(r'\{\s*"(\w+)"', fw.group(1)) if fw else []
    if fw_names != fields:
        errors.append(f"bridge_shim.cc: fw_methods {fw_names} != "
                      f"uda_callbacks_t fields {fields}")

    # 3. every shim method name is a UdaCallable protocol method
    for name in fields:
        if not re.search(rf"def\s+{name}\s*\(", pysrc):
            errors.append(f"bridge_shim.cc: up-call {name!r} has no "
                          f"UdaCallable method in bridge/bridge.py")

    # 4. the Java slot table: local stub var -> bound static method ...
    stub_of = {}
    for sm in re.finditer(
            r"MemorySegment\s+(\w+)\s*=\s*LINKER\.upcallStub\(\s*"
            r"l\.findStatic\(UdaBridge\.class,\s*\"(\w+)\"", jsrc):
        stub_of[sm.group(1)] = sm.group(2)
    # ... and each cbs.set slot (offset -> var); ctx sits at offset 0
    slots = {}
    for sm in re.finditer(r"cbs\.set\(ADDRESS,\s*(\d+)L?,\s*(\w+)\)", jsrc):
        slots[int(sm.group(1))] = sm.group(2)
    want_offsets = [8 * (i + 1) for i in range(len(fields))]
    if sorted(k for k in slots if k != 0) != want_offsets:
        errors.append(
            f"UdaBridge.java: callback slots {sorted(slots)} do not "
            f"cover ctx + {len(fields)} pointers (want 0 and "
            f"{want_offsets})")
        return
    for i, field in enumerate(fields):
        var = slots[8 * (i + 1)]
        method = stub_of.get(var)
        if method is None:
            errors.append(f"UdaBridge.java: slot {8 * (i + 1)} var "
                          f"{var!r} is not an upcallStub/findStatic "
                          f"binding")
            continue
        if not re.search(rf"static\s+\w+(?:\.\w+)*\s+{method}\s*\(", jsrc):
            errors.append(f"UdaBridge.java: findStatic names {method!r} "
                          f"but no such static method exists")
        camel = "cb" + "".join(w.capitalize() for w in field.split("_"))
        if not camel.startswith(method) or len(method) <= 2:
            errors.append(
                f"UdaBridge.java: slot {8 * (i + 1)} binds {method!r} "
                f"but the shim field there is {field!r} (expected a "
                f"prefix of {camel!r}) — renamed or re-ordered up-call")


def main(java_root: str = "") -> int:
    java_root = java_root or (sys.argv[1] if len(sys.argv) > 1
                              else JAVA_ROOT)
    files = []
    for root, _dirs, names in os.walk(java_root):
        for nm in names:
            if nm.endswith(".java"):
                files.append(os.path.join(root, nm))
    if not files:
        print("no java sources found", file=sys.stderr)
        return 2
    known = {os.path.splitext(os.path.basename(f))[0] for f in files}
    known_dirs = {os.path.relpath(os.path.dirname(f), java_root)
                  for f in files}
    errors: list[str] = []
    for f in sorted(files):
        check_file(f, os.path.relpath(f, REPO), known, known_dirs, errors)
    check_callback_table(java_root, errors)
    for e in errors:
        print(e, file=sys.stderr)
    print(f"checked {len(files)} java files: "
          f"{'FAIL' if errors else 'OK'} ({len(errors)} errors)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
