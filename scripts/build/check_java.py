#!/usr/bin/env python
"""Structural validation of the Java tree without a JDK.

This image ships NO Java compiler: there is no javac/ecj anywhere on
the filesystem, bazel's embedded Zulu JRE is a 13-module jlink image
without jdk.compiler, and the container has zero network egress, so a
JDK cannot be vendored (probed 2026-07-30; see ci.sh, which runs the
real `make -C java` the moment a javac appears). Until then this
checker gives the Java sources the strongest gate available without a
compiler — a string/comment-aware structural pass that catches the
mechanical damage CI most needs to reject:

- unbalanced braces/parens/brackets (string- and comment-aware lexing);
- unterminated string/char literals and block comments;
- package declaration not matching the file's directory path;
- public type name not matching the file name;
- imports of uda packages that resolve to no file in the tree.

It is NOT a compiler and proves nothing about types; it exists so a
truncated file, a bad merge, or a renamed class fails CI instead of
lying dormant in a source-only tree (VERDICT r4 missing #2).
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
JAVA_ROOT = os.path.join(REPO, "java")

OPEN = {"{": "}", "(": ")", "[": "]"}
CLOSE = {v: k for k, v in OPEN.items()}


def strip_literals(src: str, path: str, errors: list[str]) -> str:
    """Replace comments and string/char literals with spaces, preserving
    newlines (so reported line numbers survive)."""
    out = []
    i, n = 0, len(src)
    line = 1
    mode = None  # None | "line" | "block" | '"' | "'" | '"""'
    start_line = 1
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if c == "\n":
            line += 1
        if mode is None:
            if c == "/" and nxt == "/":
                mode = "line"
                out.append("  ")
                i += 2
                continue
            if c == "/" and nxt == "*":
                mode, start_line = "block", line
                out.append("  ")
                i += 2
                continue
            if src.startswith('"""', i):
                mode, start_line = '"""', line
                out.append("   ")
                i += 3
                continue
            if c in ('"', "'"):
                mode, start_line = c, line
                out.append(" ")
                i += 1
                continue
            out.append(c)
            i += 1
            continue
        # inside a literal/comment
        if mode == "line":
            if c == "\n":
                mode = None
                out.append("\n")
            else:
                out.append(" ")
            i += 1
            continue
        if mode == "block":
            if c == "*" and nxt == "/":
                mode = None
                out.append("  ")
                i += 2
            else:
                out.append("\n" if c == "\n" else " ")
                i += 1
            continue
        if mode == '"""':
            if src.startswith('"""', i):
                mode = None
                out.append("   ")
                i += 3
            else:
                out.append("\n" if c == "\n" else " ")
                i += 1
            continue
        # single-line string/char literal
        if c == "\\":
            out.append("  ")
            i += 2
            continue
        if c == mode:
            mode = None
            out.append(" ")
            i += 1
            continue
        if c == "\n":
            errors.append(f"{path}:{start_line}: unterminated {mode} literal")
            mode = None
            out.append("\n")
            i += 1
            continue
        out.append(" ")
        i += 1
    if mode in ("block", '"""'):
        errors.append(f"{path}:{start_line}: unterminated "
                      f"{'block comment' if mode == 'block' else mode}")
    return "".join(out)


def check_file(path: str, rel: str, known_classes: set[str],
               known_packages: set[str], errors: list[str]) -> None:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    stripped = strip_literals(src, rel, errors)

    # bracket balance
    stack: list[tuple[str, int]] = []
    line = 1
    for ch in stripped:
        if ch == "\n":
            line += 1
        elif ch in OPEN:
            stack.append((ch, line))
        elif ch in CLOSE:
            if not stack or stack[-1][0] != CLOSE[ch]:
                errors.append(f"{rel}:{line}: unmatched '{ch}'")
                return
            stack.pop()
    for ch, ln in stack:
        errors.append(f"{rel}:{ln}: unclosed '{ch}'")

    # package <-> path (component-aligned: the directory's tail
    # components must equal the package components exactly)
    m = re.search(r"^\s*package\s+([\w.]+)\s*;", stripped, re.M)
    if m:
        pkg_parts = m.group(1).split(".")
        dir_parts = os.path.dirname(rel).split(os.sep)
        if dir_parts[-len(pkg_parts):] != pkg_parts:
            errors.append(f"{rel}: package {m.group(1)} does not match "
                          f"directory {os.path.dirname(rel)}")
    # public type <-> file name
    base = os.path.splitext(os.path.basename(rel))[0]
    pub = re.search(
        r"^\s*public\s+(?:final\s+|abstract\s+)*"
        r"(?:class|interface|enum|record)\s+(\w+)", stripped, re.M)
    if pub and pub.group(1) != base:
        errors.append(f"{rel}: public type {pub.group(1)} in file {base}.java")

    # uda imports resolve in-tree (wildcard imports check the package
    # prefix instead of a class name)
    for im in re.finditer(r"^\s*import\s+(?:static\s+)?([\w.]+(?:\.\*)?)"
                          r"\s*;", stripped, re.M):
        name = im.group(1)
        if ".uda." not in name and not name.startswith("com.mellanox"):
            continue
        if name.endswith(".*"):
            pkg_dir = name[:-2].replace(".", os.sep)
            if not any(d == pkg_dir or d.endswith(os.sep + pkg_dir)
                       for d in known_packages):
                errors.append(f"{rel}: wildcard import {name} matches no "
                              "package directory in the tree")
        elif name.split(".")[-1] not in known_classes:
            errors.append(f"{rel}: import {name} resolves to no file "
                          "in the tree")


def main(java_root: str = "") -> int:
    java_root = java_root or (sys.argv[1] if len(sys.argv) > 1
                              else JAVA_ROOT)
    files = []
    for root, _dirs, names in os.walk(java_root):
        for nm in names:
            if nm.endswith(".java"):
                files.append(os.path.join(root, nm))
    if not files:
        print("no java sources found", file=sys.stderr)
        return 2
    known = {os.path.splitext(os.path.basename(f))[0] for f in files}
    known_dirs = {os.path.relpath(os.path.dirname(f), java_root)
                  for f in files}
    errors: list[str] = []
    for f in sorted(files):
        check_file(f, os.path.relpath(f, REPO), known, known_dirs, errors)
    for e in errors:
        print(e, file=sys.stderr)
    print(f"checked {len(files)} java files: "
          f"{'FAIL' if errors else 'OK'} ({len(errors)} errors)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
