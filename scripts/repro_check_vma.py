#!/usr/bin/env python
"""Minimal repro: Pallas INTERPRET mode vs shard_map's check_vma.

History: through round 4 the distributed sort disabled check_vma for the
lanes engines entirely. Round 5 fixed the one genuine mis-typing in this
repo (the merge-pass fori_loop carry entered replicated and exited
varying; ops/pallas_sort.py now pcasts the init to the data's vma), after
which every lanes engine traces clean with check_vma=True on the REAL
(interpret=False) path — see parallel/distributed.py.

What remains — and what this script reproduces — is an upstream
limitation of the Pallas interpreter only: interpret-mode pallas_call
expands into eval_jaxpr whose grid machinery slices operands with
REPLICATED block indices. Under shard_map with varying inputs that
produces

    ValueError: Primitive dynamic_slice requires varying manual axes to
    match, but got [frozenset({'x'}), frozenset(), frozenset()]

i.e. the emulator's own dynamic_slice mixes a varying operand with
replicated indices. The compiled (Mosaic) path traces pallas_call as one
primitive and type-checks fine. Hence the bypass in
parallel/distributed.py is now scoped to `lanes-engine AND interpret`.

Run (no TPU needed):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/repro_check_vma.py
Expected output: REAL PATH OK / INTERPRET PATH raises the error above.
"""

import os
import sys
from functools import partial

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import shard_map  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from uda_tpu.parallel import distributed as D  # noqa: E402
from uda_tpu.parallel.mesh import make_mesh  # noqa: E402


def main() -> int:
    ndev = len(jax.devices())
    mesh = make_mesh(ndev)
    axis = list(mesh.axis_names)[0]
    n = ndev * 4096  # > 1 tile per shard: the merge-pass loop engages

    def build(interpret: bool):
        @partial(shard_map, mesh=mesh, in_specs=(P(axis),),
                 out_specs=P(axis), check_vma=True)
        def go(w):
            row = jnp.arange(w.shape[0], dtype=jnp.int32)
            return D._sort_valid_rows(w, row >= 0, 2, "lanes",
                                      interpret=interpret)
        return go

    spec = jax.ShapeDtypeStruct((n, 4), jnp.uint32)
    jax.eval_shape(build(False), spec)
    print("REAL PATH (interpret=False): check_vma=True traces OK")

    words = jnp.asarray(np.random.default_rng(0).integers(
        0, 2**32, (n, 4), dtype=np.uint32))
    try:
        build(True)(words)
    except ValueError as e:
        print("INTERPRET PATH: check_vma=True fails inside the Pallas "
              "interpreter (upstream):")
        print("  " + str(e).splitlines()[0])
        return 0
    print("INTERPRET PATH: no error — upstream fixed; remove the "
          "interpret bypass in parallel/distributed.py")
    return 1


if __name__ == "__main__":
    sys.exit(main())
