#!/usr/bin/env python
"""Elastic chaos scenario: seeded blob-kill mid-job + drain-and-join.

The run_chaos.sh elastic rung's dedicated driver (the analogue of the
completion rung's seeded supplier kill): one reduce job over a
disaggregated two-tier store while ALL of ISSUE 18's machinery fires
at once —

- half the partitions are pre-spilled to the blob tier WITH local
  twins; a seeded ambient ``store.get`` schedule then kills a fraction
  of blob reads for the whole job, so every kill must fail over to the
  surviving local tier (``store.failover`` must advance);
- mid-job a second supplier JOINS (``MergeManager.notify_join`` —
  in-flight segments widen, retries re-rank onto it);
- mid-job the primary supplier DRAINS: its remaining retained
  partitions migrate to the blob tier cutover-style (no twin), so the
  tail of the job reads them through the degraded blob backend and
  converges on Segment retries alone.

Contract, enforced by exit code: the merged output is BYTE-IDENTICAL
to a chaos-free reference, store.failover > 0, the drain moved
partitions (store.drained.partitions > 0), the join registered, and
fallback.signals == 0 — the job completed, it never fell back. Runs
under whatever UDA_TPU_LOCKDEP / UDA_TPU_RESLEDGER the rung arms.

Usage: python scripts/elastic_chaos.py --seed N [--out FILE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

NUM_MAPS = 8
RECS_PER_MAP = 500
# per-READ kill probability for the blob tier. Calibrated against the
# twin-LESS post-drain partitions, whose Segment retries restart from
# zero: an attempt survives only if every one of its ~26 rounds reads
# clean, so p must satisfy (1-p)^rounds >> 1/retries — 0.08 gives
# ~0.11 per attempt, converging well inside the 40-retry budget, while
# the twinned partitions still draw dozens of inline failovers per run
KILL_PROB = 0.08


def _force_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")


def _run(seed: int, tmp: str) -> dict:
    import numpy as np

    from uda_tpu.merger import (HostRoutingClient, LocalFetchClient,
                                MergeManager)
    from uda_tpu.mofserver import DataEngine, DirIndexResolver, StoreManager
    from uda_tpu.mofserver.writer import MOFWriter
    from uda_tpu.utils.comparators import get_key_type
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.errors import FallbackSignal
    from uda_tpu.utils.failpoints import failpoints
    from uda_tpu.utils.metrics import metrics

    job = "elchaos"
    root = os.path.join(tmp, "supplier_a")
    blob = os.path.join(tmp, "blob")
    rng = np.random.default_rng(seed)
    writer = MOFWriter(root, job)
    for m in range(NUM_MAPS):
        parts = [sorted((rng.bytes(10), rng.bytes(200))
                        for _ in range(RECS_PER_MAP))
                 for _ in range(2)]
        writer.write(f"attempt_{job}_m_{m:06d}_0", parts)
    mids = writer.map_ids
    kt = get_key_type("uda.tpu.RawBytes")

    # small fetch chunks: every partition spans many rounds, so the
    # join/drain timers land MID-FETCH, not after the phase ended
    cfg = Config({"uda.tpu.fetch.retries": 40,
                  "mapred.rdma.buf.size": 4,
                  "mapred.rdma.fetch.retry.backoff.ms": 10.0,
                  "mapred.rdma.fetch.retry.backoff.max.ms": 40.0})

    def merge(reduce_id, engines, join_at=None, drain_at=None,
              drain_mgr=None):
        router = HostRoutingClient(
            connect=lambda host: LocalFetchClient(engines[host]))
        mm = MergeManager(router, kt, cfg)
        timers = []
        if join_at is not None:
            timers.append(threading.Timer(
                join_at, lambda: mm.notify_join("B")))
        if drain_at is not None:
            def drain():
                # the primary announces departure: routing marks it,
                # its retained MOFs migrate cutover-style to blob
                mm.notify_drain("A")
                drain_mgr.drain(job)
            timers.append(threading.Timer(drain_at, drain))
        for t in timers:
            t.daemon = True
            t.start()
        blocks = []
        try:
            mm.run(job, [("A", m) for m in mids], reduce_id,
                   lambda b: blocks.append(bytes(b)))
            return b"".join(blocks), None
        except FallbackSignal as e:
            return b"".join(blocks), e
        finally:
            for t in timers:
                t.cancel()
            mm.stop()

    # chaos-free reference (no store plumbing at all)
    refs = {}
    ref_engine = DataEngine(DirIndexResolver(root), cfg)
    try:
        for r in range(2):
            out, err = merge(r, {"A": ref_engine})
            assert err is None and out
            refs[r] = out
    finally:
        ref_engine.stop()
    # two suppliers over the SAME local root + SHARED blob tier; each
    # engine shares its manager's resolver so a mid-job cutover
    # (index unlink + invalidate) re-routes its next read
    mgrs, engines = {}, {}
    for h in ("A", "B"):
        resolver = DirIndexResolver(root)
        mgrs[h] = StoreManager(resolver, blob)
        engines[h] = DataEngine(resolver, cfg)
        engines[h].attach_store(mgrs[h])
    # pre-spill half the partitions WITH twins (the failover targets);
    # the rest stay on A's retained book — the drain's cargo
    for mid in mids[: NUM_MAPS // 2]:
        mgrs["A"].migrate(job, mid, reason="spill", shadow=True)
    for mid in mids[NUM_MAPS // 2:]:
        path = os.path.join(root, job, mid, "file.out")
        mgrs["A"].account_write(job, mid, os.path.getsize(path))
    mgrs["B"].resolver.invalidate(job)
    metrics.reset()
    spec = (f"store.get=error:prob:{KILL_PROB}"
            f":seed:{seed}:match:blob")
    outs = {}
    errs = {}
    try:
        with failpoints.scoped(spec):
            for r in range(2):
                # reduce 0 sees the join + drain mid-flight; reduce 1
                # runs entirely in the post-drain world (blob-only
                # partitions through the degraded backend, converging
                # on Segment retries alone)
                outs[r], errs[r] = merge(
                    r, engines,
                    join_at=0.05 if r == 0 else None,
                    drain_at=0.12 if r == 0 else None,
                    drain_mgr=mgrs["A"])
                engines["B"].resolver.invalidate(job)
        result = {
            "seed": seed,
            "schedule": spec,
            "identical": bool(all(outs[r] == refs[r] and not errs[r]
                                  for r in range(2))),
            "fallback_signals": int(metrics.get("fallback.signals")
                                    or 0)
            + sum(1 for e in errs.values() if e),
            "store_failover": metrics.get("store.failover") or 0,
            "store_errors": metrics.get("store.errors") or 0,
            "drained_partitions": metrics.get(
                "store.drained.partitions") or 0,
            "elastic_joins": metrics.get("elastic.joins") or 0,
            "segment_retries": metrics.get("fetch.retries") or 0,
        }
    finally:
        for h in ("A", "B"):
            mgrs[h].close()
            engines[h].stop()
    return result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, required=True)
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    _force_cpu()
    tmp = tempfile.mkdtemp(prefix="uda_elchaos_")
    try:
        result = _run(args.seed, tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    ok = (result["identical"]
          and result["fallback_signals"] == 0
          and result["store_failover"] > 0
          and result["drained_partitions"] > 0
          and result["elastic_joins"] > 0)
    if not ok:
        print(f"FAIL: elastic chaos contract broken: {result}",
              file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
