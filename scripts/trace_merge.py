#!/usr/bin/env python
"""Stitch per-process span JSONL files into ONE Chrome/Perfetto trace.

Each uda_tpu process exports its recorded spans with
``metrics.export_spans_jsonl(path)`` — one JSON object per line
carrying the span record plus ``pid`` and ``ts_unix`` (the span start
converted through the process's wall-clock anchor, so two processes'
spans land on one comparable timeline). This tool merges any number of
such files into a single Perfetto-loadable trace:

- events are keyed by **trace id**: a reduce-side ``net.fetch`` span
  and the supplier-side ``net.serve`` span it caused (wire-carried
  trace context, uda_tpu/net/wire.py) share one trace id and link by
  parent span id even though they were recorded in different
  processes;
- each source process becomes a Perfetto *process* lane (its recorded
  pid), with ``process_name`` metadata naming the source file;
- ``args`` carry trace/span/parent ids and the span attributes, so
  selecting any event shows its cross-process lineage.

Usage::

    python scripts/trace_merge.py spans_a.jsonl spans_b.jsonl \
        --out trace.json [--trace <id>] [--require-cross-process]

Exit codes: 0 ok; 2 usage/IO; 3 no spans (or --require-cross-process
found no wire-linked span) — the ci.sh gate runs it over the net
loopback smoke's span file and fails on an empty or unstitchable
trace.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def load_spans(paths):
    """-> (spans, profiles, per-file counts). Malformed lines fail
    loudly — a torn span file would silently drop the exact spans a
    post-mortem needs. ``kind: "profile"`` records (the sampling
    profiler's per-span summaries, appended by export_spans_jsonl) are
    split out for their own lane."""
    spans = []
    profiles = []
    counts = {}
    for path in paths:
        n = 0
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    raise SystemExit(
                        f"trace_merge: {path}:{lineno}: bad span "
                        f"record: {e}")
                rec.setdefault("pid", 0)
                rec["_src"] = os.path.basename(path)
                if rec.get("kind") == "profile":
                    profiles.append(rec)
                else:
                    spans.append(rec)
                    n += 1
        counts[path] = n
    return spans, profiles, counts


# the synthetic tid profile-lane events render on (one lane per
# process, clear of real thread ids)
PROFILE_TID = 1 << 20


def merge(spans, trace_filter=None, profiles=()):
    """-> (chrome trace dict, stats). Timestamps use ``ts_unix`` when
    present (cross-process comparable); a file exported by an older
    process without the anchor degrades to its raw perf_counter
    timeline (still valid within that process's lane)."""
    if trace_filter is not None:
        spans = [s for s in spans if s.get("trace") == trace_filter]
    events = []
    procs = {}
    ids = {(s["pid"], s["id"]) for s in spans}
    all_ids = {s["id"] for s in spans}
    cross = 0
    for s in spans:
        procs.setdefault(s["pid"], s.get("_src", ""))
        args = dict(s.get("attrs") or {})
        for key, arg in (("trace", "trace_id"), ("id", "span_id"),
                         ("parent", "parent_id")):
            if s.get(key) is not None:
                args[arg] = s[key]
        parent = s.get("parent")
        if parent is not None and (s["pid"], parent) not in ids \
                and parent in all_ids:
            # the parent span exists but in ANOTHER process: this is a
            # wire-stitched link (a net.serve under a remote net.fetch)
            cross += 1
            args["cross_process_parent"] = True
        ts = s.get("ts_unix", s.get("ts", 0.0))
        events.append({"name": s["name"], "ph": "X", "pid": s["pid"],
                       "tid": s.get("tid", 0), "ts": ts * 1e6,
                       "dur": s.get("dur", 0.0) * 1e6, "args": args})
    # profile lane: one X event per sampled span summary, spanning its
    # observed sample window, on a synthetic per-process profiler tid —
    # the where-the-cpu-went view lines up NEXT TO the span lanes
    prof_pids = set()
    for p in profiles:
        t0, t1 = p.get("t0_unix", 0.0), p.get("t1_unix", 0.0)
        if t1 <= t0:
            continue
        prof_pids.add(p["pid"])
        procs.setdefault(p["pid"], p.get("_src", ""))
        events.append({"name": f"profile:{p.get('span', '?')}",
                       "ph": "X", "pid": p["pid"], "tid": PROFILE_TID,
                       "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6,
                       "args": {"self_samples": p.get("self"),
                                "total_samples": p.get("total"),
                                "hz": p.get("hz"),
                                "stacks": p.get("stacks", [])}})
    for pid in sorted(prof_pids):
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": PROFILE_TID,
                       "args": {"name": "sampling profiler"}})
    for pid, src in sorted(procs.items()):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": f"uda_tpu pid {pid} "
                                                  f"({src})"}})
    stats = {"spans": len(spans), "processes": len(procs),
             "traces": len({s.get("trace") for s in spans}),
             "cross_process_links": cross,
             "profile_lanes": len(prof_pids)}
    return {"traceEvents": events}, stats


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="+",
                    help="per-process span JSONL files "
                         "(metrics.export_spans_jsonl)")
    ap.add_argument("--out", required=True,
                    help="merged Chrome trace JSON destination")
    ap.add_argument("--trace", type=int, default=None,
                    help="keep only this trace id")
    ap.add_argument("--require-cross-process", action="store_true",
                    help="fail (exit 3) unless at least one span links "
                         "to a parent recorded in another process — "
                         "the wire trace-context acceptance gate")
    args = ap.parse_args()
    try:
        spans, profiles, counts = load_spans(args.files)
    except OSError as e:
        print(f"trace_merge: {e}", file=sys.stderr)
        return 2
    if not spans:
        print(f"trace_merge: no spans in {len(args.files)} file(s) "
              f"(was the exporting process run with UDA_TPU_STATS=1?)",
              file=sys.stderr)
        return 3
    trace, stats = merge(spans, args.trace, profiles=profiles)
    if args.require_cross_process and not stats["cross_process_links"]:
        print("trace_merge: no cross-process parent link found — wire "
              "trace context did not stitch", file=sys.stderr)
        return 3
    with open(args.out, "w") as f:
        json.dump(trace, f)
    per_file = ", ".join(f"{os.path.basename(p)}:{n}"
                         for p, n in counts.items())
    print(f"trace_merge: {stats['spans']} spans from "
          f"{stats['processes']} process(es) ({per_file}) -> "
          f"{args.out}; {stats['traces']} trace id(s), "
          f"{stats['cross_process_links']} cross-process link(s), "
          f"{stats['profile_lanes']} profile lane(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
