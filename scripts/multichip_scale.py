"""Multichip scaling evidence: run the full dryrun at 8/16/32/64 virtual
devices (each in a FRESH interpreter — the device count locks at
backend init) and write the aggregated exchange-round/byte accounting
plus the v5p-64 ICI roofline extrapolation to MULTICHIP_SCALE_r{N}.json.

Usage: python scripts/multichip_scale.py [--out FILE] [--sizes 8,16,32,64]
       [--per-size-timeout S]   # 64 devices compiles for a while on 1 core
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

CHILD = r"""
import json, sys
sys.path.insert(0, {repo!r})
import __graft_entry__ as g
acct = g.dryrun_multichip({n})
print("ACCT " + json.dumps(acct))
"""

# v5p public specs for the roofline (cloud.google.com/tpu/docs/v5p):
# 4,800 Gbps inter-chip interconnect per chip = 600 GBYTES/s aggregate
# across links; the all-to-all egress-bound lower bound per chip is
# bytes_out / ICI_BW.
V5P_ICI_GBYTES_PER_S_PER_CHIP = 600.0
TERASORT_1TB_BYTES = 1e12
V5P64_CHIPS = 64


def roofline() -> dict:
    """Analytic lower bound for BASELINE config 5 (TeraSort-1TB on
    v5p-64): per-chip egress = (1 TB / 64) x (63/64) riding ICI."""
    per_chip_out = TERASORT_1TB_BYTES / V5P64_CHIPS * (
        (V5P64_CHIPS - 1) / V5P64_CHIPS)
    t_exchange = per_chip_out / (V5P_ICI_GBYTES_PER_S_PER_CHIP * 1e9)
    return {
        "target": "TeraSort-1TB on v5p-64 (BASELINE config 5)",
        "ici_gbytes_per_s_per_chip": V5P_ICI_GBYTES_PER_S_PER_CHIP,
        "ici_gbps_spec": 4800,
        "per_chip_egress_bytes": per_chip_out,
        "exchange_lower_bound_s": t_exchange,
        "note": "all-to-all egress bound only; local sort + HBM "
                "traffic add on top — see PARITY.md roofline section",
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        REPO, "MULTICHIP_SCALE_r05.json"))
    ap.add_argument("--sizes", default="8,16,32,64")
    ap.add_argument("--per-size-timeout", type=float, default=3600)
    args = ap.parse_args()
    sizes = [int(s) for s in args.sizes.split(",")]

    runs = []
    ok = True
    for n in sizes:
        t0 = time.perf_counter()
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS=f"--xla_force_host_platform_device_count={n}")
        # pool-free children: the accelerator-pool sitecustomize dials
        # the pool from every interpreter and can hang at startup while
        # the pool is wedged; these runs are pure CPU by construction
        env.pop("PALLAS_AXON_POOL_IPS", None)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", CHILD.format(repo=REPO, n=n)],
                capture_output=True, text=True, timeout=args.per_size_timeout, env=env,
                cwd=REPO)
            rc, stdout, stderr = proc.returncode, proc.stdout, proc.stderr
        except subprocess.TimeoutExpired as e:
            # one hung size must not discard the completed runs
            rc = -9
            stdout = (e.stdout or b"").decode("utf-8", "replace") \
                if isinstance(e.stdout, bytes) else (e.stdout or "")
            stderr = f"TIMEOUT after {e.timeout:.0f}s"
        dt = time.perf_counter() - t0
        acct = None
        for line in stdout.splitlines():
            if line.startswith("ACCT "):
                acct = json.loads(line[5:])
        runs.append({"devices": n, "ok": rc == 0 and acct is not None,
                     "wall_s": round(dt, 1), "accounting": acct,
                     "tail": stdout.strip().splitlines()[-1:]
                     if rc == 0 else
                     (stderr or stdout).strip().splitlines()[-8:]})
        ok = ok and runs[-1]["ok"]
        print(f"[{n} devices] {'ok' if runs[-1]['ok'] else 'FAIL'} "
              f"in {dt:.0f}s")

    report = {"runs": runs, "roofline_v5p64": roofline(), "ok": ok}
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"wrote {args.out} ok={ok}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
