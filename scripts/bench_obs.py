#!/usr/bin/env python
"""Observability-plane overhead A/B: control tower on vs off.

ISSUE 17's acceptance gate. Arming the live telemetry plane
(utils/timeseries.py -> anomaly detectors + per-tenant SLI book) buys
recent-history rollups, online anomaly detection and SLO accounting at
the cost of one timer thread snapshotting the metrics hub every
``uda.tpu.ts.interval.s`` and running the detector pass per rollup.
This bench prices that on the BENCH_PIPELINE_r09 64x64 MB pipelined
spool shape (feed -> stage pool -> run spool -> streaming finish):

- **identity gate** (always): the armed run's emitted byte count must
  equal the disarmed run's — the plane observes, it must never touch
  the data path;
- **liveness gate** (always): the armed variant's ring must actually
  have sampled (a plane that priced at 0% because it never ran is not
  a result);
- **overhead gate** (full mode): the plane's measured time share —
  total wall spent inside ``TimeSeries.sample()`` (snapshot + delta +
  the detector/SLI listener pass, all of which run in the sampler
  thread) divided by the armed run's wall — gate: <= 1%.

The overhead gate is a direct measurement, not an A/B wall diff, by
necessity: on the shared hosts this runs on, run-to-run wall spread of
the IDENTICAL disarmed workload is 5-10% (CPU-frequency and co-tenant
drift; measured here and recorded as ``wall_spread_pct``), so a wall
A/B cannot resolve a 1% effect — it prices the host's mood, not the
plane. The instrumented share is exact to ~0.01% and captures
everything the plane does per tick; the A/B walls are still run
(identity needs both variants anyway) and reported as trend data.

Both variants run with the stats plane (histograms) ON so the numbers
isolate the tower itself, not the hub it reads.

Usage: python scripts/bench_obs.py [--segs 64] [--seg-mb 64]
       [--interval 1.0] [--reps 3] [--quick] [--out BENCH_OBS.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

OVERHEAD_GATE_PCT = 1.0


def _force_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")


def _spool_once(batches, tmp: str, armed: bool, interval: float) -> dict:
    """One pipelined spool run (the BENCH_PIPELINE_r09 shape) with the
    observability plane armed or disarmed. Wall covers feed through
    emitted bytes — everything the timer thread could perturb."""
    # drain the PREDECESSOR run's dirty pages before the timer starts:
    # each run spools GBs through the page cache, and without a sync
    # whichever variant runs second pays the first one's writeback
    # inside its own timed window — on this host that bias alone
    # measured ~19% wall, dwarfing the <= 1% gate under test
    os.sync()
    from uda_tpu.merger.emitter import FramedEmitter
    from uda_tpu.merger.overlap import OverlappedMerger
    from uda_tpu.merger.streaming import RunStore
    from uda_tpu.utils.comparators import get_key_type
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.metrics import metrics
    from uda_tpu.utils.timeseries import (arm_observability_plane,
                                          disarm_observability_plane,
                                          timeseries)

    kt = get_key_type("uda.tpu.RawBytes")
    metrics.reset()
    metrics.enable_stats()  # both variants: the A/B prices the tower,
    # not the histogram hub it reads
    samples = 0
    plane = {"s": 0.0}
    if armed:
        assert arm_observability_plane(Config({
            "uda.tpu.stats.enable": True,
            "uda.tpu.ts.interval.s": interval}))
        # instrument the sampler: every tick's full cost (hub snapshot,
        # delta fold, ring append AND the listener pass — detectors +
        # SLI book run inside sample()) accumulates into plane["s"]
        inner = timeseries.sample

        def timed_sample():
            t0 = time.monotonic()
            try:
                return inner()
            finally:
                plane["s"] += time.monotonic() - t0

        timeseries.sample = timed_sample  # instance attr, dropped below
    store = RunStore([tmp], tag=f"obsbench_{'on' if armed else 'off'}")
    om = OverlappedMerger(kt, 16, engine="host", run_store=store,
                          pipeline=True)
    total = sum(b.num_records for b in batches)
    sink = {"n": 0}
    t0 = time.monotonic()
    try:
        for i, b in enumerate(batches):
            om.feed(i, b)
        om.finish_streaming(
            FramedEmitter(1 << 16),
            lambda blk: sink.__setitem__("n", sink["n"] + len(blk)),
            expected_records=total)
        wall = time.monotonic() - t0
    finally:
        if armed:
            samples = timeseries.summary()["samples"]
            timeseries.__dict__.pop("sample", None)
            disarm_observability_plane()
        store.cleanup()
        metrics.reset()
    return {"wall_s": wall, "out_bytes": sink["n"],
            "ts_samples": int(samples), "plane_s": plane["s"]}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--segs", type=int, default=64)
    ap.add_argument("--seg-mb", type=int, default=64)
    ap.add_argument("--interval", type=float, default=1.0,
                    help="rollup interval for the armed variant "
                    "(default = the uda.tpu.ts.interval.s default)")
    ap.add_argument("--reps", type=int, default=3,
                    help="runs per variant; best wall is scored — disk "
                    "noise is one-sided (interference only ever slows "
                    "a run), so min estimates the clean wall (damps "
                    "shared-host noise under the tight 1%% gate)")
    ap.add_argument("--quick", action="store_true",
                    help="small shape, one rep; identity + liveness "
                    "gate only (overhead reported, not gated)")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    _force_cpu()
    tmp = tempfile.mkdtemp(prefix="uda_obsbench_")
    try:
        return _run(args, tmp)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


def _run(args, tmp: str) -> int:
    from scripts.bench_staging import make_segments

    segs = 6 if args.quick else args.segs
    seg_mb = 4 if args.quick else args.seg_mb
    reps = 1 if args.quick else max(1, args.reps)
    # quick mode still needs >= 2 rollup intervals inside the run for
    # the liveness gate; the armed interval scales down with the shape
    interval = min(args.interval, 0.1) if args.quick else args.interval
    total_mb = segs * seg_mb
    result: dict = {"bench": "obs_overhead", "segs": segs,
                    "seg_mb": seg_mb, "total_mb": total_mb,
                    "interval_s": interval, "reps": reps,
                    "nproc": os.cpu_count(), "quick": bool(args.quick)}
    batches = make_segments(segs, seg_mb << 20, True)
    runs = {False: [], True: []}
    # interleaved reps with ALTERNATING order: drift (thermal, page
    # cache) lands on both variants, and neither variant owns the
    # first-slot advantage — with a fixed off->on order plus best-of
    # scoring, "off" always gets the cleanest slot and the measured
    # overhead is the host's positional bias, not the plane's cost
    for rep in range(reps):
        order = (False, True) if rep % 2 == 0 else (True, False)
        for armed in order:
            runs[armed].append(_spool_once(batches, tmp, armed,
                                           interval))
    off = min(runs[False], key=lambda r: r["wall_s"])
    on = min(runs[True], key=lambda r: r["wall_s"])
    identical = all(r["out_bytes"] == off["out_bytes"] > 0
                    for v in runs.values() for r in v)
    sampled = all(r["ts_samples"] >= 2 for r in runs[True])
    result["obs_off_s"] = round(off["wall_s"], 3)
    result["obs_on_s"] = round(on["wall_s"], 3)
    result["obs_off_MBps"] = round(total_mb / off["wall_s"], 1)
    result["obs_on_MBps"] = round(total_mb / on["wall_s"], 1)
    result["ts_samples"] = on["ts_samples"]
    result["identical"] = identical
    result["plane_sampled"] = sampled
    # trend data, NOT the gate: the wall diff of best-of reps, plus
    # the off variant's own rep-to-rep spread — the noise floor that
    # makes the wall diff unreadable at the 1% scale
    result["wall_overhead_pct"] = round(
        100.0 * (on["wall_s"] - off["wall_s"]) / off["wall_s"], 2)
    off_walls = [r["wall_s"] for r in runs[False]]
    result["wall_spread_pct"] = round(
        100.0 * (max(off_walls) - min(off_walls)) / min(off_walls), 2)
    # THE overhead gate: the plane's measured time share, worst armed
    # rep (sampler + detector + SLI cost over that rep's wall)
    result["overhead_pct"] = round(max(
        100.0 * r["plane_s"] / r["wall_s"] for r in runs[True]), 4)
    # gate only in full mode: a noisy shared host must not flake CI
    result["overhead_ok"] = result["overhead_pct"] <= OVERHEAD_GATE_PCT
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    if not (identical and sampled):
        print("FAIL: observability A/B identity/liveness gate",
              file=sys.stderr)
        return 3
    if args.quick:
        return 0
    return 0 if result["overhead_ok"] else 2


if __name__ == "__main__":
    sys.exit(main())
