#!/usr/bin/env python
"""udafleet: fleet-wide aggregation over the CAP_OBS stats plane.

Polls MANY shuffle daemons (``host[:port]`` each) with the windowed
MSG_STATS request (uda_tpu/net/wire.py ``_STATS_OPT`` tail) and merges
the per-daemon observability sections into ONE fleet view:

- **throughput** — fleet-total fetch/serve byte rates from each
  daemon's time-series window (sum of per-interval byte deltas over
  the wall-clock the window spans);
- **tenants** — each tenant's scheduled bytes and window share summed
  ACROSS daemons (a tenant squeezed on one daemon but overfed on
  another nets out here — the per-daemon SLI book cannot see that),
  worst SLO attainment/burn anywhere in the fleet, and the daemons on
  which it is currently starving;
- **anomalies** — every active anomaly in the fleet, labeled with the
  daemon that raised it;
- **daemons** — per-endpoint status: ``ok`` / ``down`` (unreachable:
  TransportError) / ``unsupported`` (pre-MSG_STATS peer:
  ProtocolError) / ``plain`` (answers MSG_STATS but predates CAP_OBS
  — the sections are absent, the daemon still counts as up).

Usage::

    python scripts/udafleet.py host1 host2:9012 --window 60 --once --json
    python scripts/udafleet.py $(cat fleet.txt) --interval 5

``--once --json`` prints one merged fleet document and exits — the
scriptable surface ci.sh gates on. The console never crashes over one
sick daemon (UDA005: down-vs-unsupported branches on exception TYPE).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from uda_tpu.net.client import fetch_remote_stats  # noqa: E402
from uda_tpu.utils.config import Config  # noqa: E402
from uda_tpu.utils.errors import (ProtocolError, TransportError,  # noqa: E402
                                  UdaError)


def parse_host(spec: str, default_port: int):
    host, _, port = spec.partition(":")
    return host or "127.0.0.1", int(port) if port else default_port


def poll(targets, timeout: float, window_s: int):
    """{spec: snapshot dict | "down" | "unsupported"} — one windowed
    poll per daemon, typed-degradation contract as udatop."""
    snaps = {}
    for spec, (host, port) in targets.items():
        try:
            snaps[spec] = fetch_remote_stats(host, port, timeout=timeout,
                                             window_s=window_s)
        except TransportError:
            snaps[spec] = "down"
        except (ProtocolError, UdaError):
            # a typed refusal (old peer) — up, but not speaking
            # MSG_STATS; vs "down" above on the TYPE (UDA005)
            snaps[spec] = "unsupported"
    return snaps


def _window_byte_rate(ts_block: dict, counter: str) -> float:
    """Sum of a counter's per-interval deltas across the daemon's
    returned window, over the wall-clock the window spans — the
    daemon's trailing-window byte rate (0.0 when the window is empty
    or the counter never moved)."""
    rolls = ts_block.get("rollups") or []
    total = 0.0
    span = 0.0
    for roll in rolls:
        span += roll.get("dt", 0.0)
        total += (roll.get("counters") or {}).get(counter, 0.0)
    return total / span if span > 0 else 0.0


def merge(snaps: dict) -> dict:
    """The fleet document: per-daemon sections folded into one view."""
    fleet = {
        "ts": round(time.time(), 3),
        "daemons": {},
        "throughput": {"fetch_mb_s": 0.0, "serve_mb_s": 0.0},
        "tenants": {},
        "anomalies": [],
    }
    sched_total = 0.0
    for spec, snap in sorted(snaps.items()):
        if isinstance(snap, str):
            fleet["daemons"][spec] = snap
            continue
        has_obs = isinstance(snap.get("timeseries"), dict)
        fleet["daemons"][spec] = "ok" if has_obs else "plain"
        if not has_obs:
            continue
        ts_block = snap["timeseries"]
        fleet["throughput"]["fetch_mb_s"] += round(
            _window_byte_rate(ts_block, "fetch.bytes") / 1e6, 3)
        fleet["throughput"]["serve_mb_s"] += round(
            _window_byte_rate(ts_block, "supplier.bytes") / 1e6, 3)
        sli = snap.get("sli")
        if isinstance(sli, dict):
            for t, blk in (sli.get("tenants") or {}).items():
                agg = fleet["tenants"].setdefault(t, {
                    "sched_bytes": 0, "daemons": 0,
                    "worst_attainment": None, "worst_burn": None,
                    "worst_burn_sli": None, "starving_on": []})
                agg["daemons"] += 1
                agg["sched_bytes"] += int(blk.get("sched_bytes") or 0)
                sched_total += blk.get("sched_bytes") or 0
                if blk.get("starve_streak_s"):
                    agg["starving_on"].append(spec)
                for sli_name, s in (blk.get("slo") or {}).items():
                    att, burn = s.get("attainment"), s.get("burn")
                    if att is not None and (
                            agg["worst_attainment"] is None
                            or att < agg["worst_attainment"]):
                        agg["worst_attainment"] = att
                    if burn is not None and (
                            agg["worst_burn"] is None
                            or burn > agg["worst_burn"]):
                        agg["worst_burn"] = burn
                        agg["worst_burn_sli"] = sli_name
        anomalies = snap.get("anomalies")
        if isinstance(anomalies, dict):
            for a in anomalies.get("active") or []:
                fleet["anomalies"].append(dict(a, daemon=spec))
    # fleet-wide share: each tenant's scheduled bytes over every
    # tenant's, ACROSS daemons — the number no single daemon can
    # compute locally
    for agg in fleet["tenants"].values():
        agg["fleet_share"] = (round(agg["sched_bytes"] / sched_total, 4)
                              if sched_total else None)
    fleet["throughput"]["fetch_mb_s"] = round(
        fleet["throughput"]["fetch_mb_s"], 3)
    fleet["throughput"]["serve_mb_s"] = round(
        fleet["throughput"]["serve_mb_s"], 3)
    return fleet


def render(fleet: dict) -> None:
    up = sum(1 for s in fleet["daemons"].values() if s in ("ok", "plain"))
    print(time.strftime("%H:%M:%S"), "udafleet —",
          f"{up}/{len(fleet['daemons'])} daemons up,",
          f"fetch {fleet['throughput']['fetch_mb_s']:g} MB/s,",
          f"serve {fleet['throughput']['serve_mb_s']:g} MB/s")
    for spec, status in fleet["daemons"].items():
        if status != "ok":
            print(f"  {spec:<22} {status}")
    if fleet["tenants"]:
        print(f"  {'tenant':<20} {'share':>7} {'sched MB':>9} "
              f"{'worst att':>9} {'burn':>6}  starving on")
        for t, agg in sorted(fleet["tenants"].items()):
            share = (f"{agg['fleet_share'] * 100:6.1f}%"
                     if agg["fleet_share"] is not None else "      -")
            att = (f"{agg['worst_attainment']:9.4f}"
                   if agg["worst_attainment"] is not None else "        -")
            burn = (f"{agg['worst_burn']:6g}"
                    if agg["worst_burn"] is not None else "     -")
            starving = ",".join(agg["starving_on"]) or "-"
            print(f"  {t:<20} {share} "
                  f"{agg['sched_bytes'] / 1e6:9.1f} {att} {burn}  "
                  f"{starving}")
    for a in fleet["anomalies"]:
        print(f"  ! {a.get('kind')}({a.get('key')}) on {a.get('daemon')}")
    sys.stdout.flush()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("hosts", nargs="+",
                    help="daemon endpoints, host[:port]")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--window", type=int, default=60, metavar="S",
                    help="trailing time-series window to request")
    ap.add_argument("--once", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="print the merged fleet document as JSON")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args()
    default_port = int(Config().get("uda.tpu.net.port"))
    targets = {spec: parse_host(spec, default_port)
               for spec in args.hosts}
    while True:
        fleet = merge(poll(targets, args.timeout, args.window))
        if args.json:
            print(json.dumps(fleet, default=repr))
        else:
            render(fleet)
        if args.once:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except KeyboardInterrupt:
        sys.exit(0)
