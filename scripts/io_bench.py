#!/usr/bin/env python
"""Serve-path read A/B: batched+coalesced vs single-pread.

The host-I/O half of ROADMAP item 5: a ShuffleServer on 127.0.0.1
serving a synthetic MOF with the zero-copy plane OFF (the byte serve
path — where every chunk costs a pool handoff (~100 us on this host,
PR 6's measurement) plus a pread (~20 us)), measured two ways:

- ``uda.tpu.read.batch=off`` — the single-pread oracle: one pool
  handoff + one pread per chunk, exactly the pre-batching path;
- ``uda.tpu.read.batch=on`` — the batched plane: the event-loop server
  accumulates each recv's decoded burst and unpark sweep into ONE
  ``DataEngine.submit_batch`` (per-fd grouping, gap-threshold range
  coalescing, ``os.preadv`` vectored reads — O(files) syscalls for a
  burst against one hot MOF, not O(chunks)).

The workload is the hot-index burst shape from PR 6's parked-request
test: N pipelined small-chunk fetches of one hot MOF fired at once
against the credit window, so decoded requests arrive (and unpark) in
bursts. **Byte identity is gated on every compared configuration**
(every chunk of both configs is compared against the file bytes; any
mismatch exits 3) — throughput is recorded and banded by perfwatch,
not hard-gated, since it is host-dependent.

Emits BENCH_IO_r13.json; ``--quick`` (the ci.sh gate) shrinks sizes
and gates identity only.

Usage: scripts/io_bench.py [--quick] [--out PATH]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from uda_tpu.mofserver import DataEngine, ShuffleRequest  # noqa: E402
from uda_tpu.mofserver.index import IndexRecord  # noqa: E402
from uda_tpu.net import ShuffleServer  # noqa: E402
from uda_tpu.net.client import RemoteFetchClient  # noqa: E402
from uda_tpu.utils.config import Config  # noqa: E402
from uda_tpu.utils.metrics import metrics  # noqa: E402

JOB = "jobIoBench"
MAP = "attempt_jobIoBench_m_000000_0"


class _SyntheticResolver:
    """Every (job, map, reduce) resolves to one hot pre-written MOF —
    the bench measures the read plane, not index parsing (the
    hot-index shape: resolve is always a cache-class hit)."""

    def __init__(self, path: str, nbytes: int):
        self._rec = IndexRecord(start_offset=0, raw_length=nbytes,
                                part_length=nbytes, path=path)

    def resolve(self, job_id: str, map_id: str, reduce_id: int):
        return self._rec


def _make_data_file(tmp: str, nbytes: int) -> str:
    path = os.path.join(tmp, "iobench.mof")
    block = os.urandom(1 << 20)
    with open(path, "wb") as f:
        left = nbytes
        while left > 0:
            f.write(block[:min(left, len(block))])
            left -= len(block)
    return path


def _offsets(total: int, chunk: int, n: int) -> list:
    """The hot-burst shape: a mostly-sequential chunk walk of the hot
    MOF with light seeded jitter (windows of 4 shuffled) — the real
    serve arrival order: a Segment walks its partition sequentially,
    but pipelining and credit unparking interleave neighbours. This is
    what per-fd grouping + gap coalescing exist for. Deterministic —
    every configuration fetches the SAME ranges, so identity and
    throughput compare like for like."""
    import random

    offs = [(i * chunk) % max(total - chunk, 1) for i in range(n)]
    rng = random.Random(1913)
    for base in range(0, n, 4):
        window = offs[base:base + 4]
        rng.shuffle(window)
        offs[base:base + 4] = window
    return offs


def run_burst(path: str, total: int, chunk: int, n: int,
              batch: str, timeout_s: float = 600.0) -> dict:
    """Fire n pipelined fetches at once (the parked-request burst);
    returns throughput + the per-offset digests for the identity
    gate."""
    metrics.reset()
    engine = DataEngine(
        _SyntheticResolver(path, total),
        Config({"uda.tpu.read.batch": batch}))
    server = ShuffleServer(engine,
                           Config({"uda.tpu.net.zerocopy": False}),
                           host="127.0.0.1", port=0).start()
    client = RemoteFetchClient("127.0.0.1", server.port, Config())
    offs = _offsets(total, chunk, n)
    results: list = [None] * n
    done = threading.Event()
    lock = threading.Lock()
    count = [0]

    def make_cb(i):
        def cb(res):
            results[i] = res
            with lock:
                count[0] += 1
                if count[0] == n:
                    done.set()
        return cb

    t0 = time.perf_counter()
    for i, off in enumerate(offs):
        client.start_fetch(ShuffleRequest(JOB, MAP, 0, off, chunk),
                           make_cb(i))
    ok = done.wait(timeout=timeout_s)
    secs = time.perf_counter() - t0
    snap = metrics.snapshot()
    client.stop()
    server.stop()
    engine.stop()
    if not ok:
        raise RuntimeError(
            f"burst stalled: {count[0]}/{n} completed (batch={batch})")
    errors = [r for r in results if isinstance(r, Exception)]
    if errors:
        raise RuntimeError(f"burst saw {len(errors)} errors, first: "
                           f"{errors[0]} (batch={batch})")
    digests = {}
    nbytes = 0
    for off, res in zip(offs, results):
        nbytes += len(res.data)
        # last-writer-wins per offset: every config fetches identical
        # ranges, so the digest map compares exactly
        digests[off] = hashlib.sha256(bytes(res.data)).hexdigest()
    return {
        "config": f"batch_{batch}",
        "chunks": n, "chunk_kb": chunk // 1024,
        "bytes": nbytes, "seconds": round(secs, 4),
        "mb_per_s": round(nbytes / (1 << 20) / max(secs, 1e-9), 1),
        "io_batch_submits": int(snap.get("io.batch.submits", 0)),
        "io_batch_requests": int(snap.get("io.batch.requests", 0)),
        "io_batch_reads": int(snap.get("io.batch.reads", 0)),
        "io_coalesce_runs": int(snap.get("io.coalesce.runs", 0)),
        "io_coalesce_gap_bytes": int(snap.get("io.coalesce.gap.bytes",
                                              0)),
        "_digests": digests,
    }


def oracle_digests(path: str, total: int, chunk: int, n: int) -> dict:
    out = {}
    with open(path, "rb") as f:
        for off in _offsets(total, chunk, n):
            f.seek(off)
            out[off] = hashlib.sha256(
                f.read(min(chunk, total - off))).hexdigest()
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="small sizes; identity-gate only (ci.sh)")
    ap.add_argument("--out",
                    default=os.path.join(REPO, "BENCH_IO_r13.json"))
    ap.add_argument("--reps", type=int, default=3,
                    help="burst repetitions per config; best is "
                         "reported (noisy-host discipline)")
    args = ap.parse_args()

    if args.quick:
        total, chunk, n = 16 << 20, 64 << 10, 192
        args.reps = min(args.reps, 2)
    else:
        total, chunk, n = 64 << 20, 64 << 10, 768

    tmp = tempfile.mkdtemp(prefix="uda_io_bench_")
    path = _make_data_file(tmp, total)
    oracle = oracle_digests(path, total, chunk, n)

    out: dict = {"bench": "io_serve", "round": "r13",
                 "quick": args.quick, "chunk_kb": chunk // 1024,
                 "chunks": n, "burst": {}}
    rc = 0
    identity_all = True
    best: dict = {}
    for batch in ("off", "on"):
        runs = [run_burst(path, total, chunk, n, batch)
                for _ in range(max(1, args.reps))]
        r = max(runs, key=lambda x: x["mb_per_s"])
        r["reps_mb_per_s"] = [x["mb_per_s"] for x in runs]
        # identity gated on EVERY run of EVERY configuration, not just
        # the best-of rep — a fast-but-wrong run must never hide
        for x in runs:
            identical = x.pop("_digests") == oracle
            r.setdefault("identity_runs", []).append(identical)
            identity_all = identity_all and identical
        r.pop("_digests", None)
        r["identical"] = all(r["identity_runs"])
        best[batch] = r
        out["burst"][f"batch_{batch}"] = r
        print(f"batch={batch}: {r['mb_per_s']} MB/s best of "
              f"{r['reps_mb_per_s']} ({n} x {chunk >> 10} KB chunks; "
              f"batch submits {r['io_batch_submits']}, coalesced runs "
              f"{r['io_coalesce_runs']}, reads "
              f"{r['io_batch_reads']}, identical={r['identical']})")

    speedup = round(best["on"]["mb_per_s"]
                    / max(best["off"]["mb_per_s"], 1e-9), 3)
    out["identity_all"] = identity_all
    out["speedup_batched"] = speedup
    print(f"batched/single-pread speedup: {speedup}x "
          f"(identity_all={identity_all})")
    if not identity_all:
        print("FAIL: byte identity broke between configurations",
              file=sys.stderr)
        rc = 3
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}")
    try:
        os.remove(path)
        os.rmdir(tmp)
    except OSError:
        pass
    return rc


if __name__ == "__main__":
    sys.exit(main())
