#!/usr/bin/env bash
# Flow driver for the regression harness — the retry/restart wrapper role
# of the reference's performBM*.sh (reference
# scripts/regression_for_limited_permissions_cluster/executeTerasort.sh:
# 22-80: run, check, retry on transient failure, collect results).
#
# Usage: regression.sh [--size small|medium|large] [--retries N] [args...]
# Extra args pass through to run_regression.py.

set -u
HERE="$(cd "$(dirname "$0")" && pwd)"
RETRIES=1
ARGS=()
while [[ $# -gt 0 ]]; do
  case "$1" in
    --retries) RETRIES="$2"; shift 2 ;;
    *) ARGS+=("$1"); shift ;;
  esac
done

PYTHON="${PYTHON:-python3}"
attempt=0
while :; do
  attempt=$((attempt + 1))
  echo "== regression attempt ${attempt} =="
  "${PYTHON}" "${HERE}/run_regression.py" "${ARGS[@]+"${ARGS[@]}"}"
  rc=$?
  if [[ ${rc} -eq 0 ]]; then
    echo "== regression PASSED (attempt ${attempt}) =="
    exit 0
  fi
  if [[ ${rc} -eq 2 ]]; then
    echo "== usage error (not retryable) ==" >&2
    exit 2
  fi
  if [[ ${attempt} -gt ${RETRIES} ]]; then
    echo "== regression FAILED after ${attempt} attempts ==" >&2
    exit 1
  fi
  echo "== retrying... ==" >&2
done
