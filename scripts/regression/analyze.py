"""Regression report analyzer: results.json -> comparison tables.

The reference rendered its cluster regression into time tables
(reference scripts/regression/analizeTerasort.sh:1-60 awk over job
logs, mr-dstatExcel.sh for resource charts). The equivalent here reads
one or more run_regression.py reports and renders a markdown table —
one run: per-workload wall/cpu/rss; several runs: side-by-side
wall-clock with the speedup of the LAST run vs the FIRST (e.g. CPU vs
ambient-chip, or before vs after a change).

Usage: python scripts/regression/analyze.py results.json [more.json...]
       [--out report.md]
"""

from __future__ import annotations

import argparse
import json


def _label(report: dict) -> str:
    return f"{report.get('platform', '?')}/{report.get('size', '?')}"


def _rows(report: dict) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for r in report.get("results", []):
        # keep the best (min wall) rep per workload, like the bench's
        # best-of-dispatches rule — but a PASS rep always beats a FAIL
        # rep (a fast crash must not hide a valid timing; the exit-code
        # gate scans every rep separately). Any two non-PASS statuses
        # rank equal (FAIL vs TIMEOUT) and fall through to wall time.
        cur = out.get(r["workload"])
        better = (cur is None
                  or (r["status"] == "PASS") > (cur["status"] == "PASS")
                  or ((r["status"] == "PASS") == (cur["status"] == "PASS")
                      and r["wall_s"] < cur["wall_s"]))
        if better:
            out[r["workload"]] = r
    return out


def render(reports: list[dict]) -> tuple[str, bool]:
    """Returns (markdown text, all_reps_passed)."""
    labels = [_label(r) for r in reports]
    tables = [_rows(r) for r in reports]
    names: list[str] = []
    for t in tables:
        names.extend(n for n in t if n not in names)

    lines = []
    if len(reports) == 1:
        t = tables[0]
        lines.append(f"# Regression report — {labels[0]}")
        lines.append("")
        lines.append("| workload | status | wall s | cpu s | rss MB |")
        lines.append("|---|---|---:|---:|---:|")
        for n in names:
            r = t[n]
            lines.append(
                f"| {n} | {r['status']} | {r['wall_s']:.2f} | "
                f"{r['cpu_user_s'] + r['cpu_sys_s']:.2f} | "
                f"{r['max_rss_mb']:.0f} |")
    else:
        lines.append("# Regression comparison — " + " vs ".join(labels))
        lines.append("")
        hdr = "| workload | " + " | ".join(f"{lb} wall s" for lb in labels)
        lines.append(hdr + f" | {labels[-1]} vs {labels[0]} |")
        lines.append("|---|" + "---:|" * (len(labels) + 1))
        for n in names:
            cells = []
            for t in tables:
                r = t.get(n)
                cells.append("—" if r is None
                             else (f"{r['wall_s']:.2f}"
                                   if r["status"] == "PASS"
                                   else r["status"]))
            a, b = tables[0].get(n), tables[-1].get(n)
            if (a and b and a["status"] == b["status"] == "PASS"
                    and b["wall_s"] > 0):
                ratio = f"{a['wall_s'] / b['wall_s']:.2f}x"
            else:
                ratio = "—"
            lines.append(f"| {n} | " + " | ".join(cells) + f" | {ratio} |")
    # failure scan covers EVERY rep of every report (a failing rep must
    # not be masked by a faster passing rep of the same workload)
    fails = [(lb, r["workload"], r.get("rep", 0))
             for lb, rep in zip(labels, reports)
             for r in rep.get("results", []) if r["status"] != "PASS"]
    lines.append("")
    lines.append("All PASS." if not fails else
                 "FAILURES: " + ", ".join(f"{n} rep{i} ({lb})"
                                          for lb, n, i in fails))
    return "\n".join(lines) + "\n", not fails


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("reports", nargs="+")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    reports = []
    for p in args.reports:
        with open(p) as f:
            reports.append(json.load(f))
    text, ok = render(reports)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    print(text, end="")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
