#!/usr/bin/env python
"""Regression harness: the workload ladder as a repeatable gate.

The rebuild of the reference's cluster regression system (reference
scripts/regression/: executeTerasort.sh + terasortAnallizer.sh run the
job, check sort validity, and emit timing tables; mr-dstatExcel.sh folds
dstat resource CSVs into the report; performBM*.sh drives the flow with
retries). Here the same roles are played in one place:

- every workload of the BASELINE ladder runs end-to-end through the
  engine (MOF writer -> DataEngine -> MergeManager -> reduce) with its
  validity gate enforced — correctness is "job success + output
  validity" exactly like the reference's regression defined it;
- wall-clock per workload plus a /proc-based resource sample (user/sys
  CPU seconds, max RSS) replace the dstat CSVs;
- results land as one JSON file and a markdown table; a nonzero exit
  means the gate failed (CI semantics the reference's cases/uda.cases
  wrapper provided).

Usage:
  python scripts/regression/run_regression.py [--size small|medium|large]
      [--workloads wordcount,terasort,...] [--reps N] [--out DIR]
      [--platform cpu|ambient]

Defaults run everything at small size on CPU (laptop/CI friendly);
--platform ambient keeps whatever backend the environment provides (the
single real TPU chip under the driver).
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import subprocess
import sys
import tempfile
import time


def _add_repo_to_path() -> None:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if repo not in sys.path:
        sys.path.insert(0, repo)


_add_repo_to_path()

SIZES = {
    # per-workload scale knobs: (small, medium, large[, xlarge])
    "wordcount_bytes": (1 << 16, 1 << 20, 1 << 24),
    "terasort_records": (1 << 12, 1 << 16, 1 << 20),
    "secsort_groups": (10, 60, 300),
    "invidx_docs": (20, 120, 600),
    "grep_bytes": (1 << 16, 1 << 20, 1 << 24),
    "dist_records_per_dev": (256, 2048, 16384),
    "sort_records": (1 << 10, 1 << 13, 1 << 16),
    "pi_points_per_map": (500, 5000, 50000),
    "dfsio_bytes_per_file": (1 << 18, 1 << 22, 1 << 26),
    # engine-direct shuffle lanes (100-byte TeraSort records through
    # fetch -> merge -> framed emit, no Python map phase): total records
    # across all maps. xlarge = the >=1 GB rung of the reference's
    # cluster regression (reference scripts/regression/
    # executeTerasort.sh:22-80 scale intent); xxlarge = the full
    # BASELINE config-2 scale (TeraSort 10 GB)
    "shuffle_records": (1 << 14, 1 << 17, 1 << 20, 10_500_000,
                        105_000_000),
}

# workloads that exist to be run at the xlarge rung (the engine-scale
# gate); everything else tops out at large
XLARGE_WORKLOADS = ("terasort_shuffle_hybrid", "terasort_shuffle_streaming")


def _size(name: str, size: str) -> int:
    idx = {"small": 0, "medium": 1, "large": 2, "xlarge": 3,
           "xxlarge": 4}[size]
    knobs = SIZES[name]
    return knobs[min(idx, len(knobs) - 1)]


class Sampler:
    """getrusage-based stand-in for the reference's dstat collection."""

    def __enter__(self):
        self.r0 = resource.getrusage(resource.RUSAGE_SELF)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        r1 = resource.getrusage(resource.RUSAGE_SELF)
        self.wall = time.perf_counter() - self.t0
        self.user = r1.ru_utime - self.r0.ru_utime
        self.sys = r1.ru_stime - self.r0.ru_stime
        self.max_rss_mb = r1.ru_maxrss / 1024.0

    def row(self) -> dict:
        return {"wall_s": round(self.wall, 3), "cpu_user_s": round(self.user, 3),
                "cpu_sys_s": round(self.sys, 3),
                "max_rss_mb": round(self.max_rss_mb, 1)}


# -- workloads (each: run + validity gate; raises on failure) ---------------

def wl_wordcount(size: str, work_dir: str) -> dict:
    import numpy as np

    from uda_tpu.models.wordcount import run_wordcount

    n = _size("wordcount_bytes", size)
    rng = np.random.default_rng(1)
    vocab = [b"w%04d" % i for i in range(500)]
    words, total = [], 0
    while total < n:
        w = vocab[int(rng.integers(0, len(vocab)))]
        words.append(w)
        total += len(w) + 1
    text = b" ".join(words)
    counts = run_wordcount(text, num_maps=4, num_reducers=3,
                           work_dir=work_dir)
    # validity: exact recount
    want: dict[bytes, int] = {}
    for w in words:
        want[w] = want.get(w, 0) + 1
    assert counts == want, "wordcount mismatch"
    return {"input_bytes": len(text), "distinct_words": len(want)}


def wl_terasort(size: str, work_dir: str) -> dict:
    import jax
    import numpy as np

    from uda_tpu.models import terasort

    n = _size("terasort_records", size)
    words = terasort.teragen(jax.random.key(42), n)
    out = terasort.single_chip_sort(words)
    terasort.validate_sorted(out, words)  # the terasortAnallizer gate
    return {"records": n, "bytes": n * terasort.RECORD_BYTES}


def wl_distributed_terasort(size: str, work_dir: str) -> dict:
    import jax
    import numpy as np

    from uda_tpu.models import terasort
    from uda_tpu.parallel.mesh import make_mesh

    ndev = len(jax.devices())
    per = _size("dist_records_per_dev", size)
    n = ndev * per
    mesh = make_mesh(ndev)
    words = np.asarray(jax.device_get(terasort.teragen(jax.random.key(7), n)))
    res = terasort.distributed_terasort(words, mesh)
    res.check()
    out = np.asarray(res.words).reshape(ndev, -1, terasort.RECORD_WORDS)
    nvalid = np.asarray(res.valid_counts).reshape(-1)
    rows = np.concatenate([out[d, :nvalid[d]] for d in range(ndev)])
    assert rows.shape[0] == n
    terasort.validate_sorted(rows, words)
    return {"devices": ndev, "records": n}


def wl_secondary_sort(size: str, work_dir: str) -> dict:
    from uda_tpu.models.secondary_sort import run_secondary_sort

    g = _size("secsort_groups", size)
    run_secondary_sort(num_groups=g, per_group=40, work_dir=work_dir)
    return {"groups": g}


def wl_inverted_index(size: str, work_dir: str) -> dict:
    from uda_tpu.models.inverted_index import run_inverted_index

    d = _size("invidx_docs", size)
    idx = run_inverted_index(num_docs=d, words_per_doc=80, work_dir=work_dir)
    return {"docs": d, "terms": len(idx)}


def wl_grep(size: str, work_dir: str) -> dict:
    import numpy as np

    from uda_tpu.models.grep import run_grep

    n = _size("grep_bytes", size)
    rng = np.random.default_rng(3)
    lines = []
    total = 0
    while total < n:
        tok = b"needle%d" % int(rng.integers(0, 20)) \
            if rng.random() < 0.3 else b"hay%06d" % int(rng.integers(0, 9999))
        lines.append(tok)
        total += len(tok) + 1
    text = b"\n".join(lines)
    result = run_grep(text, rb"needle\d+", work_dir=work_dir)
    counts = [c for _, c in result]
    assert counts == sorted(counts, reverse=True), "grep sort order broken"
    assert sum(counts) == sum(1 for t in lines if t.startswith(b"needle"))
    return {"input_bytes": len(text), "matches": sum(counts)}


def wl_compressed_shuffle(size: str, work_dir: str) -> dict:
    # the compression-path regression: same wordcount, zlib-block MOFs
    import numpy as np

    from uda_tpu.models.wordcount import run_wordcount
    from uda_tpu.utils.config import Config

    n = max(1 << 14, _size("wordcount_bytes", size) // 4)
    rng = np.random.default_rng(5)
    text = b" ".join(b"z%03d" % int(rng.integers(0, 99)) for _ in range(n // 5))
    cfg = Config({"mapred.compress.map.output": True,
                  "mapred.map.output.compression.codec": "zlib"})
    counts = run_wordcount(text, num_maps=3, num_reducers=2, config=cfg,
                           work_dir=work_dir)
    want: dict[bytes, int] = {}
    for w in text.split(b" "):
        want[w] = want.get(w, 0) + 1
    assert counts == want, "compressed wordcount mismatch"
    return {"input_bytes": len(text)}


def wl_sort(size: str, work_dir: str) -> dict:
    # the Hadoop Sort example: identity map/reduce, pure shuffle+merge
    import numpy as np

    from uda_tpu.models.sort_job import run_sort
    from uda_tpu.utils.comparators import memcmp

    n = _size("sort_records", size)
    rng = np.random.default_rng(11)
    records = [(rng.bytes(int(rng.integers(1, 24))),
                rng.bytes(int(rng.integers(0, 64)))) for _ in range(n)]
    out = run_sort(records, num_maps=4, num_reducers=3, work_dir=work_dir)
    got = []
    for r, recs in sorted(out.items()):
        keys = [k for k, _ in recs]
        assert all(memcmp(a, b) <= 0 for a, b in zip(keys, keys[1:])), \
            f"reducer {r} output not sorted"
        got.extend(recs)
    assert sorted(got) == sorted(records), "sort record multiset changed"
    return {"records": n}


def wl_mesh_shuffle(size: str, work_dir: str) -> dict:
    # the MapReduce driver with the device mesh as the wire (the
    # cluster deployment shape): output must match a direct count
    import collections
    import re

    import jax
    import numpy as np

    from uda_tpu.models.wordcount import run_wordcount
    from uda_tpu.parallel.mesh import make_mesh

    ndev = min(4, len(jax.devices()))
    n = max(1 << 14, _size("wordcount_bytes", size) // 4)
    rng = np.random.default_rng(13)
    text = b" ".join(b"m%03d" % int(rng.integers(0, 200))
                     for _ in range(n // 5))
    got = run_wordcount(text, num_maps=3, num_reducers=3,
                        work_dir=work_dir, mesh=make_mesh(ndev))
    want = collections.Counter(
        m.group(0).lower()
        for m in re.finditer(rb"[A-Za-z0-9]+", text))
    assert got == dict(want), "mesh shuffle wordcount mismatch"
    return {"input_bytes": len(text), "distinct_words": len(want)}


def _make_terasort_mofs(root: str, job: str, num_maps: int,
                        records_per_map: int, seed: int = 17,
                        first_map: int = 0) -> None:
    """Vectorized TeraSort MOF generator: per-map sorted 10B-key/90B-value
    records, native-framed straight to disk (no per-record Python) —
    the xlarge rungs measure the ENGINE, not a Python map phase.
    ``first_map`` writes a suffix of the map set (each map's records
    derive from ``seed + m``, so a split generation is byte-identical
    to a whole one — the push_streaming workload commits maps in two
    waves)."""
    import numpy as np

    from uda_tpu import native
    from uda_tpu.mofserver.index import write_index_file
    from uda_tpu.utils.ifile import RecordBatch

    for m in range(first_map, first_map + num_maps):
        rng = np.random.default_rng(seed + m)
        n = records_per_map
        keys = rng.integers(0, 256, (n, 10), dtype=np.uint8)
        keys = keys[np.lexsort(tuple(keys[:, c] for c in range(9, -1, -1)))]
        vals = rng.integers(0, 256, (n, 90), dtype=np.uint8)
        buf = np.concatenate([keys.reshape(-1), vals.reshape(-1)])
        batch = RecordBatch(
            buf,
            np.arange(n, dtype=np.int64) * 10, np.full(n, 10, np.int64),
            n * 10 + np.arange(n, dtype=np.int64) * 90,
            np.full(n, 90, np.int64))
        d = os.path.join(root, job, f"attempt_{job}_m_{m:06d}_0")
        os.makedirs(d, exist_ok=True)
        mof = os.path.join(d, "file.out")
        with open(mof, "wb") as f:
            for piece in native.iter_framed_chunks(batch, write_eof=True):
                f.write(piece)
        size = os.path.getsize(mof)
        write_index_file(mof + ".index", [(0, size, size)])


def _verify_sorted_stream(path: str, expected_records: int) -> None:
    """Vectorized sortedness + count gate over a framed 100B-record
    output stream (the terasortAnallizer role) with bounded memory."""
    import numpy as np

    from uda_tpu.utils.ifile import crack_partial

    prev_tail = None
    total = 0
    carry = b""
    with open(path, "rb") as f:
        while True:
            chunk = f.read(64 << 20)
            if not chunk:
                break
            data = carry + chunk
            batch, consumed, saw_eof = crack_partial(data)
            carry = data[consumed:]
            n = batch.num_records
            if n == 0:
                continue
            total += n
            assert np.all(batch.key_len == 10), "key width drifted"
            keys = batch.data[
                batch.key_off[:, None] + np.arange(10)[None, :]]
            # pad to 16B, view as 2 big-endian u64 for vector compare
            padded = np.zeros((n, 16), np.uint8)
            padded[:, :10] = keys
            w = padded.reshape(-1).tobytes()
            u = np.frombuffer(w, dtype=">u8").reshape(n, 2)
            a, b = u[:-1], u[1:]
            ok = (a[:, 0] < b[:, 0]) | ((a[:, 0] == b[:, 0])
                                        & (a[:, 1] <= b[:, 1]))
            assert bool(np.all(ok)), "output stream not sorted"
            if prev_tail is not None:
                pa, pb = prev_tail, u[0]
                assert (pa[0] < pb[0]) or (pa[0] == pb[0]
                                           and pa[1] <= pb[1]), \
                    "output not sorted across chunk boundary"
            prev_tail = u[-1]
    assert carry in (b"", b"\xff\xff"), "trailing garbage after records"
    assert total == expected_records, \
        f"record count {total} != {expected_records}"


def _terasort_shuffle(size: str, work_dir: str, mode: str) -> dict:
    """1-reducer shuffle of TeraSort MOFs through the real engine path
    (DataEngine -> fetch window -> merge -> framed emit), hybrid or
    streaming-online, with the sortedness gate on the emitted stream."""
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.utils import comparators
    from uda_tpu.utils.config import Config

    total = _size("shuffle_records", size)
    num_maps = max(4, min(64, total // 160_000 or 4))
    per_map = (total + num_maps - 1) // num_maps
    job = f"shuf{mode}"
    _make_terasort_mofs(work_dir, job, num_maps, per_map)
    approach = {"hybrid": 2, "streaming": 1, "auto": 0}[mode]
    cfg = Config({
        "mapred.netmerger.merge.approach": approach,
        "uda.tpu.online.streaming": mode == "streaming",
        "uda.tpu.spill.dirs": os.path.join(work_dir, "spill"),
        "mapred.rdma.wqe.per.conn": 8,
    })
    engine = DataEngine(DirIndexResolver(work_dir), cfg)
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    out_path = os.path.join(work_dir, "reduce.out")
    try:
        mm = MergeManager(LocalFetchClient(engine), kt, cfg)
        with open(out_path, "wb") as out:
            emitted = mm.run(
                job, [f"attempt_{job}_m_{m:06d}_0" for m in range(num_maps)],
                0, lambda mv: out.write(mv))
    finally:
        engine.stop()
    _verify_sorted_stream(out_path, num_maps * per_map)
    shuffled = num_maps * per_map * 100
    return {"mode": mode, "maps": num_maps, "records": num_maps * per_map,
            "shuffle_bytes": shuffled, "emitted_bytes": emitted}


def wl_terasort_shuffle_hybrid(size: str, work_dir: str) -> dict:
    return _terasort_shuffle(size, work_dir, "hybrid")


def wl_terasort_shuffle_streaming(size: str, work_dir: str) -> dict:
    return _terasort_shuffle(size, work_dir, "streaming")


def wl_terasort_shuffle_auto(size: str, work_dir: str) -> dict:
    # approach=0: the size-estimate policy picks the mode (hybrid at
    # regression sizes; the xlarge/xxlarge rungs cross the threshold)
    return _terasort_shuffle(size, work_dir, "auto")


def wl_coded_shuffle(size: str, work_dir: str) -> dict:
    # the CODED-job regression (ROADMAP item 3 follow-up): the full
    # sort workload with rs:2:3 map-output stripes fanned across three
    # supplier roots under failure-domain placement — the sortedness +
    # record-multiset gates of the plain sort PLUS a clean stripe
    # scrub over the written layout (every parity section re-derives,
    # every peer shard matches its placement)
    import numpy as np

    from uda_tpu.coding.scrub import scrub_roots
    from uda_tpu.models.sort_job import run_sort
    from uda_tpu.utils.comparators import memcmp
    from uda_tpu.utils.config import Config

    n = _size("sort_records", size)
    rng = np.random.default_rng(23)
    records = [(rng.bytes(int(rng.integers(1, 24))),
                rng.bytes(int(rng.integers(0, 64)))) for _ in range(n)]
    roots = [work_dir] + [work_dir + f"_peer{i}" for i in (1, 2)]
    domains = ",".join(f"{r}=rack{i % 2}" for i, r in enumerate(roots))
    cfg = Config({"uda.tpu.coding.scheme": "rs:2:3",
                  "uda.tpu.coding.domains": domains})
    out = run_sort(records, num_maps=4, num_reducers=3, config=cfg,
                   work_dir=work_dir, supplier_roots=roots)
    got = []
    for r, recs in sorted(out.items()):
        keys = [k for k, _ in recs]
        assert all(memcmp(a, b) <= 0 for a, b in zip(keys, keys[1:])), \
            f"coded reducer {r} output not sorted"
        got.extend(recs)
    assert sorted(got) == sorted(records), \
        "coded sort record multiset changed"
    rep = scrub_roots(roots, domains={r: f"rack{i % 2}"
                                      for i, r in enumerate(roots)})
    assert rep["maps"] > 0 and rep["stripes"] > 0, rep
    assert rep["parity_mismatches"] == 0 and rep["shard_faults"] == 0, \
        rep
    return {"records": n, "coded_maps": rep["maps"],
            "stripes_scrubbed": rep["stripes"]}


def wl_resume_shuffle(size: str, work_dir: str) -> dict:
    # the crash/resume regression (ISSUE 16): a checkpoint-armed
    # streaming shuffle is killed at a DETERMINISTIC point (a terminal
    # injected fetch fault on one map, zero retries), then restarted.
    # Gates: the resumed output passes the sortedness + record-count
    # stream gate, AND the second attempt RESUMED rather than silently
    # restarting from scratch (ckpt.resumed counted, at least one
    # checkpointed run file adopted instead of refetched).
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.utils import comparators
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.errors import FallbackSignal
    from uda_tpu.utils.failpoints import failpoints
    from uda_tpu.utils.metrics import metrics

    total = _size("shuffle_records", size)
    num_maps = max(4, min(64, total // 160_000 or 4))
    per_map = (total + num_maps - 1) // num_maps
    job = "shufresume"
    _make_terasort_mofs(work_dir, job, num_maps, per_map)
    mids = [f"attempt_{job}_m_{m:06d}_0" for m in range(num_maps)]
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    ckdir = os.path.join(work_dir, "ckpt")
    out_path = os.path.join(work_dir, "reduce.out")

    def attempt(fault: str, retries: int):
        cfg = Config({"uda.tpu.online.streaming": True,
                      "uda.tpu.ckpt.dir": ckdir,
                      "uda.tpu.ckpt.interval.s": 0.0,
                      "uda.tpu.fetch.retries": retries,
                      "mapred.rdma.wqe.per.conn": 8})
        engine = DataEngine(DirIndexResolver(work_dir), cfg)
        try:
            mm = MergeManager(LocalFetchClient(engine), kt, cfg)
            with open(out_path, "wb") as out:
                if fault:
                    with failpoints.scoped(fault):
                        mm.run(job, mids, 0, lambda mv: out.write(mv))
                else:
                    mm.run(job, mids, 0, lambda mv: out.write(mv))
        finally:
            engine.stop()

    # attempt 1: dies on the seeded kill point, leaving the checkpoint
    fault = f"segment.fetch=error:match:m_{num_maps - 1:06d}"
    try:
        attempt(fault, retries=0)
        raise AssertionError("seeded kill point did not fire")
    except FallbackSignal:
        pass
    snap0 = metrics.snapshot()
    attempt("", retries=3)  # attempt 2: must RESUME
    snap1 = metrics.snapshot()
    resumed = snap1.get("ckpt.resumed", 0) - snap0.get("ckpt.resumed", 0)
    adopted = (snap1.get("ckpt.runs.adopted", 0)
               - snap0.get("ckpt.runs.adopted", 0))
    assert resumed >= 1, "second attempt restarted from scratch"
    assert adopted >= 1, "no checkpointed run file was adopted"
    _verify_sorted_stream(out_path, num_maps * per_map)
    return {"maps": num_maps, "records": num_maps * per_map,
            "runs_adopted": int(adopted)}


def _record_multiset_hash(rows) -> int:
    """Order-independent hash of (n, 100) u8 record rows: each record's
    position-weighted u64 digest, summed mod 2^64 — equal multisets of
    records hash equal regardless of merge order."""
    import numpy as np

    weights = ((np.arange(100, dtype=np.uint64) + 1)
               * np.uint64(0x9E3779B97F4A7C15))
    return int(np.sum(rows.astype(np.uint64) @ weights,
                      dtype=np.uint64))


def _expected_multiset_hash(num_maps: int, per_map: int,
                            seed: int = 17) -> int:
    """Re-derive the multiset hash of everything _make_terasort_mofs
    wrote (same seeds, same generation order)."""
    import numpy as np

    h = np.uint64(0)
    for m in range(num_maps):
        rng = np.random.default_rng(seed + m)
        keys = rng.integers(0, 256, (per_map, 10), dtype=np.uint8)
        keys = keys[np.lexsort(tuple(keys[:, c]
                                     for c in range(9, -1, -1)))]
        vals = rng.integers(0, 256, (per_map, 90), dtype=np.uint8)
        h += np.uint64(_record_multiset_hash(
            np.concatenate([keys, vals], axis=1)))
    return int(h)


def _output_multiset_hash(path: str) -> int:
    """The emitted stream's record-multiset hash (streamed, bounded
    memory like the sortedness gate)."""
    import numpy as np

    from uda_tpu.utils.ifile import crack_partial

    h = np.uint64(0)
    carry = b""
    with open(path, "rb") as f:
        while True:
            chunk = f.read(64 << 20)
            if not chunk:
                break
            data = carry + chunk
            batch, consumed, _ = crack_partial(data)
            carry = data[consumed:]
            n = batch.num_records
            if n == 0:
                continue
            rows = np.empty((n, 100), np.uint8)
            rows[:, :10] = batch.data[
                batch.key_off[:, None] + np.arange(10)[None, :]]
            rows[:, 10:] = batch.data[
                batch.val_off[:, None] + np.arange(90)[None, :]]
            h += np.uint64(_record_multiset_hash(rows))
    return int(h)


def wl_push_streaming(size: str, work_dir: str) -> dict:
    # the push-shuffle regression (ISSUE 19): NEW map outputs commit —
    # and stream over as MSG_PUSH — WHILE THE REDUCER IS ALREADY
    # DRAINING. Half the maps exist before the reduce starts (their
    # pushes ride the catch-up path); the other half commit from a
    # background thread racing the fetch wave (their pushes ride the
    # notify_commit fan-out and are adopted at segment start). Gates:
    # the sortedness + record-count stream gate, the record-MULTISET
    # hash against the generator (no record lost or duplicated across
    # the push/pull seam), and at least one chunk actually pushed.
    import threading as _threading

    from uda_tpu.merger import HostRoutingClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.net import ShuffleServer
    from uda_tpu.utils import comparators
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.metrics import metrics

    total = _size("shuffle_records", size)
    num_maps = max(4, min(64, total // 160_000 or 4))
    per_map = (total + num_maps - 1) // num_maps
    job = "shufpush"
    cfg = Config({
        "uda.tpu.push.enable": True,
        "uda.tpu.spill.dirs": os.path.join(work_dir, "spill"),
        "mapred.rdma.wqe.per.conn": 8,
        "uda.tpu.fetch.retries": 8,
        # 64 KB push chunks: every map spans several chunks even at
        # the small rung, so take()'s last-chunk trim still leaves a
        # prefix to adopt (the path under test)
        "mapred.rdma.buf.size": 64,
    })
    mids = [f"attempt_{job}_m_{m:06d}_0" for m in range(num_maps)]
    half = max(1, num_maps // 2)
    _make_terasort_mofs(work_dir, job, half, per_map)
    engine = DataEngine(DirIndexResolver(work_dir), cfg)
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0).start()
    addr = f"127.0.0.1:{server.port}"
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    out_path = os.path.join(work_dir, "reduce.out")
    router = HostRoutingClient(config=cfg)
    mm = MergeManager(router, kt, cfg)
    errs: list = []

    def _late_maps():
        try:
            for m in range(half, num_maps):
                _make_terasort_mofs(work_dir, job, 1, per_map,
                                    first_map=m)
                server.notify_commit(job, mids[m])
        except Exception as e:  # noqa: BLE001 - reported via the gate
            errs.append(e)

    try:
        staging = mm.arm_push(job, 0, hosts={addr})
        assert staging is not None, "push plane did not arm"
        for m in range(half):
            server.notify_commit(job, mids[m])
        # let the catch-up pushes land a first prefix before the
        # reducer starts (deterministic adoption); the LATE half still
        # races the fetch wave for real
        deadline = time.monotonic() + 30
        while staging.staged_bytes() == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert staging.staged_bytes() > 0, "catch-up pushes never landed"
        late = _threading.Thread(target=_late_maps, daemon=True)
        late.start()
        with open(out_path, "wb") as out:
            mm.run(job, [(addr, mid) for mid in mids], 0,
                   lambda mv: out.write(mv))
        late.join(60)
        assert not errs, f"late map writer failed: {errs[0]}"
    finally:
        router.stop()
        server.stop()
        engine.stop()
    _verify_sorted_stream(out_path, num_maps * per_map)
    got = _output_multiset_hash(out_path)
    want = _expected_multiset_hash(num_maps, per_map)
    assert got == want, \
        f"record multiset drifted across the push/pull seam " \
        f"({got:#x} != {want:#x})"
    snap = metrics.snapshot()
    assert snap.get("push.chunks", 0) > 0, "no pushes flowed"
    return {"maps": num_maps, "records": num_maps * per_map,
            "push_chunks": int(snap.get("push.chunks", 0)),
            "push_adopted_bytes": int(snap.get("push.adopted.bytes", 0)),
            "push_refused": int(snap.get("push.refused", 0))}


def wl_pi(size: str, work_dir: str) -> dict:
    from uda_tpu.models.pi import run_pi

    pts = _size("pi_points_per_map", size)
    res = run_pi(num_maps=4, points_per_map=pts, work_dir=work_dir)
    assert abs(res["estimate"] - 3.14159) < 0.3, res
    return res


def wl_dfsio(size: str, work_dir: str) -> dict:
    from uda_tpu.models.dfsio import run_dfsio

    per = _size("dfsio_bytes_per_file", size)
    return run_dfsio(num_files=4, bytes_per_file=per, work_dir=work_dir)


WORKLOADS = {
    "wordcount": wl_wordcount,
    "terasort": wl_terasort,
    "distributed_terasort": wl_distributed_terasort,
    "sort": wl_sort,
    "secondary_sort": wl_secondary_sort,
    "inverted_index": wl_inverted_index,
    "grep": wl_grep,
    "compressed_shuffle": wl_compressed_shuffle,
    "coded_shuffle": wl_coded_shuffle,
    "mesh_shuffle": wl_mesh_shuffle,
    "pi": wl_pi,
    "dfsio": wl_dfsio,
    "terasort_shuffle_hybrid": wl_terasort_shuffle_hybrid,
    "terasort_shuffle_streaming": wl_terasort_shuffle_streaming,
    "terasort_shuffle_auto": wl_terasort_shuffle_auto,
    "resume_shuffle": wl_resume_shuffle,
    "push_streaming": wl_push_streaming,
}


def _setup_platform(platform: str) -> None:
    if platform == "cpu":
        # must precede any jax device use; the ambient environment may
        # force an accelerator backend (see tests/conftest.py). Append
        # rather than setdefault: an already-exported XLA_FLAGS must not
        # silently drop the virtual-device flag (it would degrade the
        # distributed workload to one device while still passing).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    from uda_tpu.utils import compile_cache

    compile_cache.enable()


def _run_single(name: str, size: str, platform: str, out_dir: str,
                rep: int) -> int:
    """Child-process mode: run ONE workload and print its result row as
    JSON. Isolation makes ru_maxrss a true per-workload peak (it is a
    process-lifetime high-water mark) and keeps a crashing workload from
    taking the harness down."""
    _setup_platform(platform)
    work_dir = tempfile.mkdtemp(prefix=f"uda_reg_{name}_", dir=out_dir)
    status, detail, err = "PASS", {}, ""
    with Sampler() as s:
        try:
            detail = WORKLOADS[name](size, work_dir)
        except Exception as e:  # noqa: BLE001 - the gate boundary
            status, err = "FAIL", f"{type(e).__name__}: {e}"
    row = {"workload": name, "rep": rep, "size": size, "status": status,
           **s.row(), "detail": detail, "error": err}
    print("RESULT " + json.dumps(row))
    return 0 if status == "PASS" else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", choices=("small", "medium", "large", "xlarge",
                                       "xxlarge"),
                    default="small")
    ap.add_argument("--workloads", default="",
                    help="comma list; default = all (xlarge: the engine "
                         "shuffle lanes only)")
    ap.add_argument("--reps", type=int, default=1)
    ap.add_argument("--out", default="")
    ap.add_argument("--platform", choices=("cpu", "ambient"), default="cpu")
    ap.add_argument("--single", default="", help=argparse.SUPPRESS)
    ap.add_argument("--rep", type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.single:
        return _run_single(args.single, args.size, args.platform,
                           args.out or tempfile.gettempdir(), args.rep)

    if args.workloads:
        names = [w.strip() for w in args.workloads.split(",") if w.strip()]
    elif args.size in ("xlarge", "xxlarge"):
        names = list(XLARGE_WORKLOADS)
    else:
        names = list(WORKLOADS)
    unknown = [w for w in names if w not in WORKLOADS]
    if unknown:
        print(f"unknown workloads: {unknown}", file=sys.stderr)
        return 2

    out_dir = args.out or os.path.join(
        tempfile.gettempdir(), f"uda_regression_{int(time.time())}")
    os.makedirs(out_dir, exist_ok=True)

    rows = []
    failed = []
    for name in names:
        for rep in range(args.reps):
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--single", name, "--size", args.size,
                   "--platform", args.platform, "--out", out_dir,
                   "--rep", str(rep)]
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  check=False)
            row = None
            for line in proc.stdout.splitlines():
                if line.startswith("RESULT "):
                    row = json.loads(line[len("RESULT "):])
            if row is None:  # crashed before reporting
                row = {"workload": name, "rep": rep, "size": args.size,
                       "status": "FAIL", "wall_s": 0.0, "cpu_user_s": 0.0,
                       "cpu_sys_s": 0.0, "max_rss_mb": 0.0, "detail": {},
                       "error": f"worker died rc={proc.returncode}: "
                                f"{proc.stderr[-300:]}"}
            rows.append(row)
            if row["status"] == "FAIL":
                failed.append(name)
            print(f"{row['status']:4s} {name:22s} rep{rep} "
                  f"{row['wall_s']:8.2f}s  "
                  f"cpu {row['cpu_user_s'] + row['cpu_sys_s']:7.2f}s  "
                  f"rss {row['max_rss_mb']:7.1f}MB  {row['error']}")

    report = {"size": args.size, "platform": args.platform,
              "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
              "results": rows, "failed": sorted(set(failed))}
    with open(os.path.join(out_dir, "results.json"), "w") as f:
        json.dump(report, f, indent=2)
    with open(os.path.join(out_dir, "results.md"), "w") as f:
        f.write(f"# uda_tpu regression — {args.size} ({report['timestamp']})\n\n")
        f.write("| workload | rep | status | wall s | cpu s | rss MB |\n")
        f.write("|---|---|---|---|---|---|\n")
        for r in rows:
            f.write(f"| {r['workload']} | {r['rep']} | {r['status']} | "
                    f"{r['wall_s']} | {r['cpu_user_s'] + r['cpu_sys_s']:.2f} "
                    f"| {r['max_rss_mb']} |\n")
    print(f"\nreport: {out_dir}/results.json")
    if failed:
        print(f"FAILED: {sorted(set(failed))}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
