"""Pool-recovery watcher: re-run the staged hardware work when the
wedged TPU pool answers again.

The pool has wedged repeatedly mid-round (a killed-mid-compile client
is the documented trigger; see scripts/tpu_return.py discipline notes).
This watcher polls a cheap liveness probe on a long interval and, on
recovery, runs the remaining hardware agenda in priority order:

1. scripts/sweep_carrychunk.py  — chunk-width ladder + the keys8f /
   lanes2 Mosaic-fix re-probes (each stage is its own budgeted
   subprocess; the sweep aborts itself if the pool re-wedges)
2. the ambient small-tier regression retry for inverted_index (the one
   FAIL in BENCH_HW_r05.json's ambient table, environmental)

Every attempt is logged under --log-dir. The watcher exits after the
agenda completes once, or after --max-hours of wall clock.

Usage: python scripts/pool_watch.py [--interval 600] [--max-hours 8]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, HERE)
from stagelib import LIVENESS, run_stage  # noqa: E402


def run(name, argv, budget_s, log_dir):
    ok, _ = run_stage(name, argv, budget_s, log_dir)
    return ok


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600)
    ap.add_argument("--max-hours", type=float, default=8)
    ap.add_argument("--log-dir", default=os.path.join(REPO, ".pool_watch"))
    args = ap.parse_args()
    os.makedirs(args.log_dir, exist_ok=True)
    py = sys.executable
    deadline = time.time() + args.max_hours * 3600

    attempt = 0
    sweep_done = False
    regress_done = False
    while time.time() < deadline:
        attempt += 1
        if run(f"liveness{attempt}", [py, "-c", LIVENESS], 300,
               args.log_dir):
            print(f"[watch] pool ALIVE (attempt {attempt})", flush=True)
            if not sweep_done:
                # Budget must EXCEED the sweep's own worst case (its
                # stage budgets + liveness probes self-terminate within
                # ~3h): the sweep's candidate stages run in their own
                # sessions, so killing the sweep's process group from
                # here could NOT reach an in-flight candidate — an
                # orphaned client holding the pool's single device
                # claim is the documented wedge trigger. Let the sweep
                # always finish itself.
                sweep_done = run(
                    f"sweep{attempt}",
                    [py, os.path.join(HERE, "sweep_carrychunk.py"),
                     "--log-dir",
                     os.path.join(REPO, ".sweep_carrychunk")],
                    4 * 3600, args.log_dir)
            if sweep_done and not regress_done:
                regress_done = run(
                    f"regress{attempt}",
                    [py, os.path.join(HERE, "regression",
                                      "run_regression.py"),
                     "--platform", "ambient", "--size", "small",
                     "--workloads", "inverted_index",
                     "--out", os.path.join(args.log_dir, "ambient_retry")],
                    2400, args.log_dir)
            if sweep_done and regress_done:
                print("[watch] agenda complete", flush=True)
                return 0
        time.sleep(args.interval)
    print("[watch] deadline reached", flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
