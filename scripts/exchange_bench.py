#!/usr/bin/env python
"""A/B/C bench + correctness gate: flat vs hierarchical vs CODED
exchange on CPU virtual multi-pod meshes.

For each mesh size (default ``dcn:2,ici:4`` / ``dcn:4,ici:4`` /
``dcn:8,ici:8`` — 8/16/64 virtual devices, each in a FRESH interpreter:
the device count locks at backend init) the child runs uniform, skewed
and pod-local workloads through ``shuffle_exchange`` THREE times on the
SAME 2-axis mesh — ``mode="flat"`` (one global all_to_all per round,
every cross-pod device pair its own DCN lane), ``mode="hierarchical"``
(pod-local all_to_all + ONE coalesced DCN tile per pod pair) and
``mode="coded"`` (the pair tile carries GF(2^8)-coded chunks every
member decodes locally — the Coded TeraSort multicast phase) — and
checks, per round:

- **byte-identity**: the hierarchical AND coded deliveries equal the
  flat delivery array-for-array, and all equal a pure-numpy host
  oracle of the window protocol; the per-destination record multiset
  equals the RecordBatch host oracle (``exchange_record_batches``);
- **accounting invariants**: hierarchical per-round DCN messages <=
  pods*(pods-1) (the pod-pair bound) and <= the flat per-round count;
  total hierarchical DCN bytes <= flat DCN bytes; the coded ledger sum
  ``coded + saved == uncoded payload``; on the uniform workload the
  coded DCN payload charge <= 0.67x hierarchical (the ~k-fold
  multicast cut, k = pod size); on the UNCODABLE workloads (skew,
  pod-local) zero coded overhead bytes — the plan routes every window
  to the plain tile. Byte figures are the planner's RECORD-payload
  ledger; the coded series charge the redundant-map multicast model —
  see the scope notes in uda_tpu/parallel/exchange.py + planner.py.

Wall clock is measured on the post-compile run (every mode executes
once to compile, then the timed pass). Output (default
``MULTICHIP_SCALE_r15.json``) carries per-size flat/hier/coded
accounting + timing; exit != 0 on any identity/invariant failure —
the ci.sh ``--quick`` gate (size 8 only).

Usage: scripts/exchange_bench.py [--quick] [--out PATH]
       [--sizes dcn:2,ici:4;dcn:4,ici:4;dcn:8,ici:8]
       [--per-size-timeout S]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

DEFAULT_SIZES = "dcn:2,ici:4;dcn:4,ici:4;dcn:8,ici:8"


def _parse_spec(spec: str):
    names, sizes = [], []
    for part in spec.split(","):
        name, _, size = part.partition(":")
        names.append(name.strip())
        sizes.append(int(size))
    if len(names) != 2:
        raise ValueError(f"mesh spec {spec!r} must be 'dcn:P,ici:C'")
    return tuple(names), tuple(sizes)


# ---------------------------------------------------------------------------
# child (runs in a fresh interpreter with the device count forced)

def _host_oracle_round(words, dest, capacity, r, p):
    """Pure-numpy model of the window protocol: the expected
    (recv_words, recv_counts) of round ``r`` on every device."""
    import numpy as np

    n, w = words.shape
    shard = n // p
    recv = np.zeros((p, p * capacity, w), words.dtype)
    counts = np.zeros((p, p), np.int64)
    for s in range(p):
        pos = {}
        for row in range(s * shard, (s + 1) * shard):
            t = int(dest[row])
            q = pos.get(t, 0)
            pos[t] = q + 1
            slot = q - r * capacity
            if 0 <= slot < capacity:
                recv[t, s * capacity + slot] = words[row]
                counts[t, s] += 1
    return recv, counts


def run_child(spec: str, rows_per_device: int, quick: bool) -> dict:
    names, sizes = _parse_spec(spec)
    ndev = sizes[0] * sizes[1]
    os.environ.setdefault(
        "XLA_FLAGS", f"--xla_force_host_platform_device_count={ndev}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh

    from uda_tpu.parallel import plan_rounds, shuffle_exchange
    from uda_tpu.parallel.exchange import exchange_record_batches
    from uda_tpu.utils.ifile import RecordBatch, crack, write_records
    from uda_tpu.utils.metrics import metrics

    p_pods, c_chips = sizes
    mesh = Mesh(np.asarray(jax.devices()[:ndev]).reshape(sizes), names)
    axis = names
    rng = np.random.default_rng(7)
    n = ndev * rows_per_device
    wcols = 4
    rec_bytes = wcols * 4

    def workloads():
        uni = rng.integers(0, 2**32, size=(n, wcols), dtype=np.uint32)
        yield "uniform", uni, (uni[:, 1] % ndev).astype(np.int32), \
            max(2, rows_per_device // ndev + 2)
        skew = rng.integers(0, 2**32, size=(n, wcols), dtype=np.uint32)
        sdest = (skew[:, 1] % ndev).astype(np.int32)
        sdest[: (3 * n) // 4] = 0          # 75% of records hit device 0
        yield "skewed", skew, sdest, max(2, rows_per_device // 8)
        hot = rng.integers(0, 2**32, size=(n, wcols), dtype=np.uint32)
        # every record to ONE chip: every pod pair has a single
        # destination block — nothing to encode across, the plan must
        # decline every window (zero coded bytes)
        yield "skew_single_dest", hot, np.zeros(n, np.int32), \
            max(2, rows_per_device // 8)
        pod = rng.integers(0, 2**32, size=(n, wcols), dtype=np.uint32)
        pdest = np.zeros(n, np.int32)      # pod-local: no DCN traffic
        shard = n // ndev
        for s in range(ndev):
            base = (s // c_chips) * c_chips
            pdest[s * shard:(s + 1) * shard] = \
                base + pod[s * shard:(s + 1) * shard, 1] % c_chips
        yield "pod_local", pod, pdest, max(2, rows_per_device // ndev + 2)

    def run_mode(words, dest, capacity, mode):
        metrics.reset()
        t0 = time.perf_counter()
        results, layout = shuffle_exchange(words, dest, mesh, axis,
                                           capacity, mode=mode)
        compile_s = time.perf_counter() - t0
        host = [(np.asarray(rw), np.asarray(rc).reshape(-1))
                for rw, rc in results]
        snap = dict(metrics.counters)
        # timed pass: same layout/plan, post-compile
        t0 = time.perf_counter()
        results2, _ = shuffle_exchange(words, dest, mesh, axis,
                                       capacity, mode=mode)
        for rw, rc in results2:
            np.asarray(rw)                 # block until delivered
        wall = time.perf_counter() - t0
        plan = plan_rounds(layout.counts, capacity, layout.topology,
                           rec_bytes, layout.hierarchical,
                           coded=layout.coded)
        per_round_msgs = [w.dcn_messages for w in plan.windows]
        return {
            "rounds": len(host),
            "skipped": int(snap.get("exchange.rounds.skipped", 0)),
            "wall_s": round(wall, 4),
            "first_run_s": round(compile_s, 4),
            "ici_bytes": int(snap.get("exchange.ici.bytes", 0)),
            "dcn_bytes": int(snap.get("exchange.dcn.bytes", 0)),
            "dcn_messages": int(snap.get("exchange.dcn.messages", 0)),
            "dcn_messages_per_round_max":
                max(per_round_msgs, default=0),
            "dcn_coded_bytes":
                int(snap.get("exchange.dcn.coded.bytes", 0)),
            "dcn_saved_bytes":
                int(snap.get("exchange.dcn.saved.bytes", 0)),
            "decode_fallbacks":
                int(snap.get("exchange.decode.fallbacks", 0)),
            "coded_windows": sum(1 for w in plan.windows if w.coded),
        }, host

    def batch_of(rows):
        return crack(write_records([(r.tobytes(), b"") for r in rows]))

    cases = []
    ok = True
    for label, words, dest, capacity in workloads():
        flat_acct, flat_rounds = run_mode(words, dest, capacity, "flat")
        hier_acct, hier_rounds = run_mode(words, dest, capacity,
                                          "hierarchical")
        coded_acct, coded_rounds = run_mode(words, dest, capacity,
                                            "coded")
        checks = {"byte_identical": True, "oracle_identical": True,
                  "recordbatch_identical": True,
                  "coded_byte_identical": True}
        if len(flat_rounds) != len(hier_rounds):
            checks["byte_identical"] = False
        if len(flat_rounds) != len(coded_rounds):
            checks["coded_byte_identical"] = False
        for r, ((fw, fc), (cw, cc)) in enumerate(zip(flat_rounds,
                                                     coded_rounds)):
            if not (np.array_equal(fw, cw) and np.array_equal(fc, cc)):
                checks["coded_byte_identical"] = False
        for r, ((fw, fc), (hw, hc)) in enumerate(zip(flat_rounds,
                                                     hier_rounds)):
            if not (np.array_equal(fw, hw) and np.array_equal(fc, hc)):
                checks["byte_identical"] = False
            ow, oc = _host_oracle_round(words, dest, capacity, r, ndev)
            got_w = hw.reshape(ndev, ndev * capacity, wcols)
            got_c = hc.reshape(ndev, ndev)
            if not (np.array_equal(got_w, ow)
                    and np.array_equal(got_c, oc)):
                checks["oracle_identical"] = False
        # RecordBatch host oracle: per-destination record multiset
        shard = n // ndev
        by_dest = [[batch_of(words[s * shard:(s + 1) * shard]
                             [dest[s * shard:(s + 1) * shard] == t])
                    for t in range(ndev)] for s in range(ndev)]
        oracle = exchange_record_batches(by_dest)
        for t in range(ndev):
            want = sorted(k for k, _ in oracle[t].iter_records())
            got = []
            for (hw, hc) in hier_rounds:
                gw = hw.reshape(ndev, ndev, capacity, wcols)
                gc = hc.reshape(ndev, ndev)
                for s in range(ndev):
                    got.extend(gw[t, s, i].tobytes()
                               for i in range(gc[t, s]))
            if sorted(got) != want:
                checks["recordbatch_identical"] = False
        pair_bound = p_pods * (p_pods - 1)
        checks["dcn_messages_le_pod_pair_bound"] = \
            hier_acct["dcn_messages_per_round_max"] <= pair_bound
        checks["dcn_messages_le_flat"] = \
            hier_acct["dcn_messages"] <= flat_acct["dcn_messages"]
        checks["dcn_bytes_le_flat"] = \
            hier_acct["dcn_bytes"] <= flat_acct["dcn_bytes"]
        # the coded ledger-sum invariant: every window books either
        # its full payload (plain) or coded + saved == payload
        checks["coded_ledger_sum"] = (
            coded_acct["dcn_bytes"] + coded_acct["dcn_saved_bytes"]
            == hier_acct["dcn_bytes"])
        if label == "uniform" and hier_acct["dcn_bytes"]:
            # THE acceptance figure: the multicast charge cuts the
            # uniform cross-pod DCN payload to <= 0.67x hierarchical
            checks["coded_dcn_le_067x_hier"] = (
                coded_acct["dcn_bytes"]
                <= 0.67 * hier_acct["dcn_bytes"])
        elif label == "skewed":
            # partial skew: the break-even guard may still code the
            # balanced early windows (a genuine saving) but must NEVER
            # regress the ledger past the plain tile
            checks["skew_never_regresses"] = (
                coded_acct["dcn_bytes"] <= hier_acct["dcn_bytes"])
        else:
            # fully-uncodable shapes (single-destination skew,
            # pod-local): the plan must route every window to the
            # plain tile — zero coded overhead, byte-for-byte the
            # hierarchical ledger
            checks["uncodable_zero_coded_overhead"] = (
                coded_acct["dcn_coded_bytes"] == 0
                and coded_acct["dcn_bytes"] == hier_acct["dcn_bytes"])
        ok = ok and all(checks.values())
        cases.append({"workload": label, "capacity": int(capacity),
                      "flat": flat_acct, "hierarchical": hier_acct,
                      "coded": coded_acct,
                      "pod_pair_bound": pair_bound,
                      "device_pair_bound": ndev * (ndev - 1),
                      "checks": checks})
    return {"mesh": spec, "devices": ndev, "pods": p_pods,
            "pod_size": c_chips, "rows": n, "record_bytes": rec_bytes,
            "cases": cases, "ok": ok}


# ---------------------------------------------------------------------------
# parent

def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="size 8 only, small rows (the ci.sh gate)")
    ap.add_argument("--out", default=os.path.join(
        REPO, "MULTICHIP_SCALE_r15.json"))
    ap.add_argument("--sizes", default=None,
                    help=f"';'-separated mesh specs "
                         f"(default {DEFAULT_SIZES})")
    ap.add_argument("--rows-per-device", type=int, default=None)
    ap.add_argument("--per-size-timeout", type=float, default=1800)
    ap.add_argument("--child", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child:
        rows = args.rows_per_device or (32 if args.quick else 128)
        report = run_child(args.child, rows, args.quick)
        print("ACCT " + json.dumps(report))
        return 0 if report["ok"] else 1

    sizes = (args.sizes or
             ("dcn:2,ici:4" if args.quick else DEFAULT_SIZES)).split(";")
    rows = args.rows_per_device or (32 if args.quick else 128)
    runs = []
    ok = True
    for spec in sizes:
        _, dims = _parse_spec(spec)
        ndev = dims[0] * dims[1]
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS=f"--xla_force_host_platform_device_count="
                             f"{ndev}")
        # pool-free children: the accelerator-pool sitecustomize dials
        # the pool from every interpreter and can hang while it is
        # wedged; these runs are pure CPU by construction
        env.pop("PALLAS_AXON_POOL_IPS", None)
        t0 = time.perf_counter()
        cmd = [sys.executable, os.path.abspath(__file__),
               "--child", spec, "--rows-per-device", str(rows)]
        if args.quick:
            cmd.append("--quick")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=args.per_size_timeout, env=env,
                                  cwd=REPO)
            rc, stdout, stderr = proc.returncode, proc.stdout, proc.stderr
        except subprocess.TimeoutExpired as e:
            rc = -9
            stdout = (e.stdout or b"").decode("utf-8", "replace") \
                if isinstance(e.stdout, bytes) else (e.stdout or "")
            stderr = f"TIMEOUT after {e.timeout:.0f}s"
        dt = time.perf_counter() - t0
        acct = None
        for line in stdout.splitlines():
            if line.startswith("ACCT "):
                acct = json.loads(line[5:])
        good = rc == 0 and acct is not None and acct.get("ok", False)
        runs.append({"mesh": spec, "devices": ndev, "ok": good,
                     "wall_s": round(dt, 1), "report": acct,
                     "tail": [] if good else
                     (stderr or stdout).strip().splitlines()[-8:]})
        ok = ok and good
        print(f"[{spec}] {'ok' if good else 'FAIL'} in {dt:.0f}s")
        if acct:
            for case in acct["cases"]:
                f, h = case["flat"], case["hierarchical"]
                c = case.get("coded", {})
                print(f"  {case['workload']:>9}: DCN msgs/round "
                      f"{f['dcn_messages_per_round_max']} -> "
                      f"{h['dcn_messages_per_round_max']} "
                      f"(pod-pair bound {case['pod_pair_bound']}), "
                      f"DCN bytes {f['dcn_bytes']} -> {h['dcn_bytes']} "
                      f"-> coded {c.get('dcn_bytes', 0)} "
                      f"(saved {c.get('dcn_saved_bytes', 0)}), "
                      f"wall {f['wall_s']}s -> {h['wall_s']}s -> "
                      f"{c.get('wall_s', 0)}s, checks "
                      f"{'PASS' if all(case['checks'].values()) else case['checks']}")

    report = {"bench": "exchange_modes", "round": "r15",
              "quick": args.quick, "rows_per_device": rows,
              "runs": runs, "ok": ok}
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    print(f"wrote {args.out} ok={ok}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
