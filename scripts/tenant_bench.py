#!/usr/bin/env python
"""Multi-tenant fairness bench: many jobs, one shuffle daemon.

The ROADMAP item-1 acceptance bench: one ShuffleServer on 127.0.0.1
runs as a multi-job daemon (``uda.tpu.tenant.enable``) serving T
tenants' jobs concurrently under a deliberately small shared credit
pool (``uda.tpu.tenant.wqe.total``), so the weighted-fair
CreditScheduler — not the clients' arrival order — decides who drains.
Three phases, all on the same daemon:

1. **identity** — every tenant fetches its whole job SOLO, then all
   tenants fetch concurrently; each job's concurrent digest must equal
   its solo digest (byte identity under contention is the hard gate,
   exit 3 — a fair-but-wrong scheduler is worthless);
2. **equal weights** — T pipelined drivers hammer the daemon for a
   fixed window; per-tenant goodput is the bytes completed inside the
   window. Reported ``fairness_ratio`` = min/max goodput; the full run
   gates it >= 0.7 (the acceptance bar — WDRR over equal weights must
   not let arrival luck starve anyone);
3. **2:1 weight** — tenant 0 re-registers at weight 2; its goodput
   over the mean of the weight-1 tenants must land ~2x (gated to the
   [1.4, 3.0] band in full mode; recorded in quick mode — CI hosts
   gate direction, not absolutes).

``--quick`` (the ci.sh gate) shrinks sizes/windows and gates identity
only. Emits BENCH_TENANT_r14.json with the session telemetry block
(tenant.sched.* / tenant.admission.* counters ride it).

Usage: scripts/tenant_bench.py [--quick] [--out PATH]
        [--tenants N] [--conns-per-tenant N]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tests.helpers import make_mof_tree, map_ids  # noqa: E402
from uda_tpu.mofserver import (DataEngine, DirIndexResolver,  # noqa: E402
                               FetchResult, ShuffleRequest)
from uda_tpu.net import ShuffleServer  # noqa: E402
from uda_tpu.net.client import RemoteFetchClient  # noqa: E402
from uda_tpu.utils.config import Config  # noqa: E402
from uda_tpu.utils.stats import telemetry_block  # noqa: E402


def tenant_name(i: int) -> str:
    return f"tenant{i:02d}"


def job_name(i: int) -> str:
    return f"jobTen{i:02d}"


def client_cfg(i: int, weight: int = 1) -> Config:
    return Config({"uda.tpu.tenant.id": tenant_name(i),
                   "uda.tpu.tenant.weight": weight,
                   "uda.tpu.net.sockbuf.kb": 64})


def fetch_sync(client, req, timeout=30.0):
    box, done = [], threading.Event()

    def on_complete(res):
        box.append(res)
        done.set()

    client.start_fetch(req, on_complete)
    if not done.wait(timeout):
        raise RuntimeError("fetch never completed")
    return box[0]


def digest_job(client, job: str, num_maps: int, chunk: int) -> str:
    """Fetch the whole job (reducer 0), chunked, and digest the byte
    stream in (map, offset) order."""
    h = hashlib.sha256()
    for mid in map_ids(job, num_maps):
        offset = 0
        while True:
            res = fetch_sync(client,
                             ShuffleRequest(job, mid, 0, offset, chunk))
            if not isinstance(res, FetchResult):
                raise RuntimeError(f"fetch of {job}/{mid} failed: {res!r}")
            h.update(bytes(res.data))
            offset += len(res.data)
            if res.is_last:
                break
    return h.hexdigest()


def run_driver(args) -> int:
    """One tenant's load-generator SUBPROCESS (--driver): fairness
    only exists when arrival can outpace service, and in one
    interpreter the client and server share a GIL — the drivers must
    be separate processes so the daemon's loop is the contended
    resource and the WDRR queues actually form."""
    client = RemoteFetchClient(
        "127.0.0.1", args.port,
        Config({"uda.tpu.tenant.id": args.tenant,
                "uda.tpu.tenant.weight": args.weight}))
    client.bind_job(args.job)
    maps = map_ids(args.job, args.maps)
    state = {"bytes": 0, "errors": 0}
    stop = threading.Event()
    lock = threading.Lock()
    window = [float("inf"), float("-inf")]  # [t0, t1)

    def issue() -> None:
        client.start_fetch(
            ShuffleRequest(args.job, maps[state["bytes"] % len(maps)],
                           0, 0, args.chunk), on_done)

    def on_done(res) -> None:
        now = time.monotonic()
        with lock:
            if isinstance(res, FetchResult):
                if window[0] <= now < window[1]:
                    state["bytes"] += len(res.data)
            else:
                state["errors"] += 1
        if not stop.is_set():
            issue()

    for _ in range(args.depth):
        issue()
    time.sleep(args.warmup)
    with lock:
        window[0] = time.monotonic()
        window[1] = window[0] + args.window
    time.sleep(args.window + 0.05)
    stop.set()
    time.sleep(0.1)
    client.stop()
    print(json.dumps({"tenant": args.tenant,
                      "bytes": state["bytes"],
                      "errors": state["errors"],
                      "window_s": args.window}))
    return 0


def measure_window(port: int, tenants: int, num_maps: int, chunk: int,
                   depth: int, warmup_s: float, window_s: float,
                   weights=None) -> dict:
    """Spawn one driver PROCESS per tenant; collect each driver's own
    measured window (the warmup absorbs start skew)."""
    import subprocess

    weights = weights or {}
    procs = []
    for i in range(tenants):
        cmd = [sys.executable, os.path.abspath(__file__), "--driver",
               "--port", str(port), "--tenant", tenant_name(i),
               "--job", job_name(i), "--maps", str(num_maps),
               "--chunk", str(chunk), "--depth", str(depth),
               "--weight", str(weights.get(i, 1)),
               "--warmup", str(warmup_s), "--window", str(window_s)]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      stderr=subprocess.DEVNULL,
                                      text=True, env=env))
    goodput, errors = {}, {}
    deadline = warmup_s + window_s + 60
    for i, p in enumerate(procs):
        out, _ = p.communicate(timeout=deadline)
        line = out.strip().splitlines()[-1] if out.strip() else "{}"
        rec = json.loads(line)
        goodput[rec.get("tenant", tenant_name(i))] = round(
            rec.get("bytes", 0) / window_s / (1 << 20), 3)
        errors[rec.get("tenant", tenant_name(i))] = rec.get("errors", 0)
    return {"goodput_mb_s": goodput, "errors": errors,
            "window_s": window_s, "driver_processes": tenants}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="small sizes/windows; identity-gate only "
                         "(ci.sh)")
    ap.add_argument("--out",
                    default=os.path.join(REPO, "BENCH_TENANT_r14.json"))
    ap.add_argument("--tenants", type=int, default=0,
                    help="concurrent jobs (0 = 8 full / 4 quick; "
                         "scale to what this host sustains)")
    ap.add_argument("--depth", type=int, default=16,
                    help="pipelined fetches per tenant driver")
    # the per-tenant load-generator subprocess (internal)
    ap.add_argument("--driver", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--port", type=int, help=argparse.SUPPRESS)
    ap.add_argument("--tenant", help=argparse.SUPPRESS)
    ap.add_argument("--job", help=argparse.SUPPRESS)
    ap.add_argument("--maps", type=int, help=argparse.SUPPRESS)
    ap.add_argument("--chunk", type=int, help=argparse.SUPPRESS)
    ap.add_argument("--weight", type=int, default=1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--warmup", type=float, help=argparse.SUPPRESS)
    ap.add_argument("--window", type=float, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.driver:
        return run_driver(args)

    tenants = args.tenants or (4 if args.quick else 8)
    # serve-dominated shape: whole-partition fetches of ~0.5 MB (full)
    # so the daemon's loop (sendfile + frame writes) is the contended
    # resource — with tiny chunks the round-trip dominates and the
    # scheduler has nothing to arbitrate
    if args.quick:
        num_maps, records, val_bytes, chunk = 1, 100, 500, 4 << 20
        warmup_s, window_s = 0.5, 1.2
    else:
        num_maps, records, val_bytes, chunk = 2, 500, 1000, 4 << 20
        warmup_s, window_s = 1.5, 4.0

    tmp = tempfile.mkdtemp(prefix="uda_tenant_bench_")
    for i in range(tenants):
        make_mof_tree(tmp, job_name(i), num_maps=num_maps,
                      num_reducers=1, records_per_map=records,
                      val_bytes=val_bytes, seed=100 + i)
    engine = DataEngine(DirIndexResolver(tmp), Config())
    # a deliberately SMALL shared pool + byte-path serves (zerocopy
    # off) + small socket buffers: a credit must be HELD for the
    # request's real service time (engine pool read + multi-round
    # frame write) — the inline zero-copy fast path settles a credit
    # synchronously on the loop thread, so the pool would never fill
    # and the scheduler would have nothing to arbitrate. Aggregate
    # demand (tenants x depth) far exceeds the pool, so the WDRR owns
    # the ordering.
    server = ShuffleServer(
        engine, Config({"uda.tpu.tenant.enable": True,
                        "uda.tpu.net.zerocopy": False,
                        "uda.tpu.net.sockbuf.kb": 64,
                        "uda.tpu.tenant.wqe.total":
                            max(2, tenants // 2)}),
        host="127.0.0.1", port=0).start()
    out: dict = {"bench": "tenant_fairness", "round": "r14",
                 "quick": args.quick, "tenants": tenants,
                 "jobs": tenants, "maps_per_job": num_maps,
                 "chunk_kb": chunk >> 10, "driver_depth": args.depth,
                 "credit_pool": server._sched.total}
    rc = 0
    try:
        # phase 1: byte identity — solo digests, then concurrent
        solo = {}
        for i in range(tenants):
            c = RemoteFetchClient("127.0.0.1", server.port,
                                  client_cfg(i))
            try:
                c.bind_job(job_name(i))
                solo[i] = digest_job(c, job_name(i), num_maps, chunk)
            finally:
                c.stop()
        conc: dict = {}
        errs: list = []

        def one(i: int) -> None:
            c = RemoteFetchClient("127.0.0.1", server.port,
                                  client_cfg(i))
            try:
                c.bind_job(job_name(i))
                conc[i] = digest_job(c, job_name(i), num_maps, chunk)
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append((i, repr(e)))
            finally:
                c.stop()

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(tenants)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        identical = not errs and conc == solo
        out["identity"] = {"concurrent_equals_solo": identical,
                           "errors": errs}
        print(f"identity: {tenants} concurrent jobs == solo runs: "
              f"{identical}")
        if not identical:
            print("FAIL: concurrent fetch diverged from solo bytes",
                  file=sys.stderr)
            rc = 3

        # phase 2: equal-weight fairness window
        eq = measure_window(server.port, tenants, num_maps, chunk,
                            args.depth, warmup_s, window_s)
        vals = list(eq["goodput_mb_s"].values())
        eq["fairness_ratio"] = round(min(vals) / max(max(vals), 1e-9), 3)
        out["equal_weight"] = eq
        print(f"equal weights: goodput {eq['goodput_mb_s']} MB/s -> "
              f"fairness ratio {eq['fairness_ratio']}")

        # phase 3: 2:1 weight — tenant 0 earns a double share
        wt = measure_window(server.port, tenants, num_maps, chunk,
                            args.depth, warmup_s, window_s,
                            weights={0: 2})
        g = wt["goodput_mb_s"]
        others = [v for k, v in g.items() if k != tenant_name(0)]
        wt["weights"] = {tenant_name(0): 2}
        wt["weighted_ratio"] = round(
            g[tenant_name(0)] / max(sum(others) / max(len(others), 1),
                                    1e-9), 3)
        out["weighted"] = wt
        print(f"2:1 weights: goodput {g} MB/s -> weighted ratio "
              f"{wt['weighted_ratio']} (want ~2)")

        if not args.quick:
            if eq["fairness_ratio"] < 0.7:
                print(f"FAIL: fairness ratio {eq['fairness_ratio']} "
                      f"< 0.7 under equal weights", file=sys.stderr)
                rc = rc or 4
            if not 1.4 <= wt["weighted_ratio"] <= 3.0:
                print(f"FAIL: weighted ratio {wt['weighted_ratio']} "
                      f"outside [1.4, 3.0]", file=sys.stderr)
                rc = rc or 4
    finally:
        server.stop()
        engine.stop()
    out["telemetry"] = telemetry_block()
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
