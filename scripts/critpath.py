#!/usr/bin/env python
"""critpath: offline time-accounting over exported span JSONL files.

The standalone face of ``uda_tpu.utils.critpath``: point it at one or
more ``metrics.export_spans_jsonl`` files (the same inputs
``scripts/trace_merge.py`` stitches) and it prints where the wall-clock
went — the per-bucket critical/busy partition, overlap factors and the
longest dependency chain — without needing the process that recorded
them.

Usage::

    python scripts/critpath.py spans.jsonl [more.jsonl ...]
        [--root reduce_task] [--json]

Exit codes: 0 ok; 2 usage/IO; 3 no analyzable spans. ``--json`` dumps
the raw ``time_accounting`` block (the exact shape the StatsReporter
final record and MSG_STATS carry); default output is a human table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from uda_tpu.utils.critpath import analyze  # noqa: E402


def load(paths):
    spans = []
    missing_anchor = set()
    for path in paths:
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    raise SystemExit(f"critpath: {path}:{lineno}: bad "
                                     f"span record: {e}")
                # cross-process comparability: raw "ts" is seconds
                # since that PROCESS's arbitrary perf_counter epoch —
                # stitching two files on it yields a garbage window.
                # The exporter added "ts_unix" (wall-clock through the
                # process anchor) exactly for this; prefer it. Within
                # one file the rewrite is a uniform shift (harmless).
                if "ts_unix" in rec:
                    rec["ts"] = rec["ts_unix"]
                elif rec.get("kind") is None:
                    missing_anchor.add(path)
                spans.append(rec)
    if missing_anchor and len(paths) > 1:
        print("critpath: WARNING: "
              + ", ".join(sorted(os.path.basename(p)
                                 for p in missing_anchor))
              + " lack the ts_unix anchor — multi-file timelines from "
                "different processes will not align", file=sys.stderr)
    return spans


def render(block) -> str:
    lines = [f"critpath: root={block['root'] or '(none)'} "
             f"wall={block['wall_s']:.3f}s over {block['spans']} spans",
             f"  {'bucket':<16} {'critical':>10} {'share':>7} "
             f"{'busy':>10} {'overlap':>8}"]
    for b, rec in block["buckets"].items():
        if not rec["busy_s"] and not rec["critical_s"]:
            continue
        lines.append(f"  {b:<16} {rec['critical_s']:>9.3f}s "
                     f"{rec['share'] * 100:>6.1f}% "
                     f"{rec['busy_s']:>9.3f}s {rec['overlap']:>8.2f}")
    lines.append(f"  {'idle':<16} {block['idle_s']:>9.3f}s "
                 f"{block['idle_s'] / block['wall_s'] * 100 if block['wall_s'] else 0:>6.1f}%")
    lines.append("  reference trio (critical seconds): "
                 + ", ".join(f"{k}={v:.3f}s"
                             for k, v in block["trio"].items()))
    lines.append("  critical path: "
                 + " -> ".join(f"{s['name']}({s['dur_s']:.3f}s)"
                               for s in block["critical_path"]))
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="+",
                    help="span JSONL files (metrics.export_spans_jsonl)")
    ap.add_argument("--root", default="reduce_task",
                    help="root span name framing the window "
                         "(default %(default)s; absent = whole file)")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw time_accounting block")
    args = ap.parse_args()
    try:
        spans = load(args.files)
    except OSError as e:
        print(f"critpath: {e}", file=sys.stderr)
        return 2
    block = analyze(spans, root_name=args.root)
    if block is None:
        print(f"critpath: no analyzable spans in {len(args.files)} "
              f"file(s) (exported with UDA_TPU_STATS=1?)",
              file=sys.stderr)
        return 3
    print(json.dumps(block) if args.json else render(block))
    return 0


if __name__ == "__main__":
    sys.exit(main())
