#!/usr/bin/env python
"""Push-shuffle overlap bench: the ISSUE 19 acceptance numbers.

One shape, two end-to-end runs over the REAL loopback data plane
(ShuffleServer + HostRoutingClient), byte-compared:

- **pull** — the fetch-wave baseline: a timed map phase writes every
  MOF (vectorized TeraSort records, native-framed), then the reduce
  starts cold and fetches everything. End-to-end wall is
  ``map_wall + reduce_wall`` with zero overlap by construction.

- **push** — the same map phase against a CAP_PUSH server with the
  reduce's ``PushStaging`` armed BEFORE the first commit: every
  ``notify_commit`` streams the new map's partition to the reduce side
  while the map phase is still producing, so by the time the fetch
  wave starts most bytes are already staged and adopted into the
  Segment ledger. The reduce tail shrinks by the overlapped volume.

Two regime knobs make the overlap observable on a loopback host, both
applied to BOTH variants symmetrically:

- ``--map-pace-ms`` sleeps after each map commit — the map-compute
  time a real map task spends between spills, which is exactly the
  window the push plane streams into (pull reducers idle through it).
- ``--serve-delay-ms`` arms the ``data_engine.pread`` delay failpoint
  for the whole bench — the storage/network-bound supplier regime the
  fetch-wave barrier actually hurts in. RAM-speed loopback serving
  makes the fetch wave nearly free and the overlap unmeasurable; the
  delay restores the deployment-shaped read cost for pull fetches and
  pushed reads alike (push pays it during the map phase, which is the
  point).

Gates: byte-identity (sha256 of the merged stream, pull is the
oracle — exit 3 on divergence) and zero terminal FallbackSignals in
both runs. Full mode additionally gates the overlap win: end-to-end
push wall must beat pull by >= OVERLAP_GATE x (the 64x64 MB
acceptance); quick mode records walls/speedup as perfwatch trend data
only — shared CI hosts gate direction-of-change, not absolute seconds.

Usage: python scripts/bench_push.py [--quick] [--maps 64] [--map-mb 64]
       [--out BENCH_PUSH.json]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

OVERLAP_GATE = 1.1  # full mode: push end-to-end must beat pull by 10%
RECORD = 100        # 10B key + 90B value, the TeraSort record


def _force_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")


def _write_maps(root, job, num_maps, recs_per_map, on_commit=None,
                pace_s=0.0):
    """The map phase: vectorized sorted-record MOFs, native-framed
    straight to disk (no per-record Python — the bench measures the
    shuffle plane, not a Python map loop). ``on_commit`` fires after
    each map's index lands, the MOFWriter commit-point contract."""
    import numpy as np

    from uda_tpu import native
    from uda_tpu.mofserver.index import write_index_file
    from uda_tpu.utils.ifile import RecordBatch

    for m in range(num_maps):
        rng = np.random.default_rng(4242 + m)
        n = recs_per_map
        keys = rng.integers(0, 256, (n, 10), dtype=np.uint8)
        keys = keys[np.lexsort(tuple(keys[:, c]
                                     for c in range(9, -1, -1)))]
        vals = rng.integers(0, 256, (n, 90), dtype=np.uint8)
        buf = np.concatenate([keys.reshape(-1), vals.reshape(-1)])
        batch = RecordBatch(
            buf,
            np.arange(n, dtype=np.int64) * 10, np.full(n, 10, np.int64),
            n * 10 + np.arange(n, dtype=np.int64) * 90,
            np.full(n, 90, np.int64))
        mid = f"attempt_{job}_m_{m:06d}_0"
        d = os.path.join(root, job, mid)
        os.makedirs(d, exist_ok=True)
        mof = os.path.join(d, "file.out")
        with open(mof, "wb") as f:
            for piece in native.iter_framed_chunks(batch, write_eof=True):
                f.write(piece)
        size = os.path.getsize(mof)
        write_index_file(mof + ".index", [(0, size, size)])
        if on_commit is not None:
            on_commit(job, mid)
        if pace_s > 0:
            time.sleep(pace_s)


def _run_variant(tmp, job, num_maps, recs_per_map, push, quick,
                 pace_s=0.0):
    """One end-to-end run; returns (sha256, stats dict)."""
    from uda_tpu.merger import HostRoutingClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.net import ShuffleServer
    from uda_tpu.utils.comparators import get_key_type
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.metrics import metrics

    metrics.reset()
    root = os.path.join(tmp, "push_root" if push else "pull_root")
    total_mb = num_maps * recs_per_map * RECORD / 1048576
    cfg = Config({
        "uda.tpu.push.enable": push,
        # stage the whole shuffle: a modest eager window in host RAM,
        # the rest through the spill tier — the overlap win must not
        # depend on holding the full map output resident
        "uda.tpu.push.eager.mb": 256.0,
        "uda.tpu.push.staged.mb": max(64.0, total_mb * 1.25),
        "uda.tpu.spill.dirs": os.path.join(tmp, "spill"),
        "mapred.rdma.wqe.per.conn": 8,
        # take() withholds the staged LAST chunk (pull re-fetches the
        # tail as the byte-identity oracle), so a map must span
        # several chunks for adoption to have a prefix to keep — on
        # the quick shape's 0.5 MB maps that needs a sub-MB chunk
        "mapred.rdma.buf.size": 128 if quick else 1024,
    })
    engine = DataEngine(DirIndexResolver(root), cfg)
    server = ShuffleServer(engine, cfg, host="127.0.0.1", port=0).start()
    router = HostRoutingClient(config=cfg)
    mm = MergeManager(router, get_key_type("uda.tpu.RawBytes"), cfg)
    addr = f"127.0.0.1:{server.port}"
    mids = [f"attempt_{job}_m_{m:06d}_0" for m in range(num_maps)]
    sha = hashlib.sha256()
    out_bytes = [0]

    def sink(mv):
        sha.update(mv)
        out_bytes[0] += len(mv)

    try:
        t0 = time.monotonic()
        staging = None
        if push:
            staging = mm.arm_push(job, 0, hosts={addr})
        _write_maps(root, job, num_maps, recs_per_map,
                    on_commit=server.notify_commit if push else None,
                    pace_s=pace_s)
        map_wall = time.monotonic() - t0
        if push and staging is not None:
            # deterministic engagement on tiny quick shapes: the first
            # chunk must have landed before the fetch wave claims the
            # maps (a no-op on full shapes — the long map phase stages
            # most of the shuffle long before this point)
            deadline = time.monotonic() + 10.0
            while (time.monotonic() < deadline
                   and staging.staged_bytes() <= 0):
                time.sleep(0.005)
        t1 = time.monotonic()
        mm.run(job, [(addr, m) for m in mids], 0, sink)
        reduce_wall = time.monotonic() - t1
        total_wall = time.monotonic() - t0
    finally:
        router.stop()
        server.stop()
        engine.stop()
    stats = {
        "map_wall_s": round(map_wall, 3),
        "reduce_wall_s": round(reduce_wall, 3),
        "total_wall_s": round(total_wall, 3),
        "MBps": round(total_mb / total_wall, 1) if total_wall else 0.0,
        "out_mb": round(out_bytes[0] / 1048576, 3),
        "fallbacks": int(metrics.get("fallback.signals") or 0),
    }
    if push:
        stats.update({
            "push_chunks": int(metrics.get("push.chunks") or 0),
            "push_adopted": int(metrics.get("push.adopted") or 0),
            "push_adopted_mb": round(
                (metrics.get("push.adopted.bytes") or 0.0) / 1048576, 3),
            "push_refused": int(sum(
                v for k, v in metrics.snapshot().items()
                if k.startswith("push.refused"))),
            "push_errors": int(metrics.get("push.errors") or 0),
        })
    return sha.hexdigest(), stats


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--maps", type=int, default=64)
    ap.add_argument("--map-mb", type=float, default=64.0)
    ap.add_argument("--map-pace-ms", type=float, default=None,
                    help="map-compute sleep after each commit "
                    "(default: 250 full, 0 quick)")
    ap.add_argument("--serve-delay-ms", type=float, default=None,
                    help="per-pread delay on the supplier engine, the "
                    "storage-bound regime (default: 10 full, 0 quick)")
    ap.add_argument("--quick", action="store_true",
                    help="small shape; identity + engagement gates "
                    "only — walls and the speedup are trend data")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    _force_cpu()
    from uda_tpu.utils.failpoints import failpoints

    num_maps = 8 if args.quick else args.maps
    map_mb = 0.5 if args.quick else args.map_mb
    pace_ms = args.map_pace_ms if args.map_pace_ms is not None \
        else (0.0 if args.quick else 250.0)
    delay_ms = args.serve_delay_ms if args.serve_delay_ms is not None \
        else (0.0 if args.quick else 10.0)
    recs_per_map = max(64, int(map_mb * 1048576 / RECORD))
    tmp = tempfile.mkdtemp(prefix="uda_push_")
    spec = (f"data_engine.pread=delay:{int(delay_ms)}:prob:1"
            if delay_ms > 0 else "")
    try:
        job = "pushbench"
        # an empty spec arms nothing — scoped("") is the documented
        # no-op, so the quick/undelayed path shares this block
        with failpoints.scoped(spec):
            pull_sha, pull = _run_variant(tmp, job, num_maps,
                                          recs_per_map, push=False,
                                          quick=args.quick,
                                          pace_s=pace_ms / 1000.0)
            push_sha, push = _run_variant(tmp, job, num_maps,
                                          recs_per_map, push=True,
                                          quick=args.quick,
                                          pace_s=pace_ms / 1000.0)
        speedup = (pull["total_wall_s"] / push["total_wall_s"]
                   if push["total_wall_s"] else 0.0)
        result = {
            "bench": "push_overlap", "quick": bool(args.quick),
            "maps": num_maps, "map_mb": map_mb,
            "map_pace_ms": pace_ms, "serve_delay_ms": delay_ms,
            "total_mb": round(num_maps * recs_per_map * RECORD
                              / 1048576, 1),
            "nproc": os.cpu_count(),
            "pull": pull, "push": push,
            "identity_push_eq_pull": bool(pull_sha == push_sha
                                          and pull["out_mb"] > 0),
            "push_engaged": bool(push.get("push_chunks", 0) > 0
                                 and push.get("push_adopted_mb", 0) > 0),
            "zero_fallbacks": bool(pull["fallbacks"] == 0
                                   and push["fallbacks"] == 0),
            "speedup_e2e": round(speedup, 3),
            "overlap_margin_s": round(pull["total_wall_s"]
                                      - push["total_wall_s"], 3),
            "reduce_tail_shrinks": bool(push["reduce_wall_s"]
                                        < pull["reduce_wall_s"]),
        }
        result["overlap_ok"] = bool(args.quick
                                    or speedup >= OVERLAP_GATE)
        print(json.dumps(result))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(result, f, indent=1)
                f.write("\n")
        if not result["identity_push_eq_pull"]:
            print("FAIL: push output diverged from the pull oracle",
                  file=sys.stderr)
            return 3
        if not result["push_engaged"]:
            print("FAIL: push plane never engaged (no chunks adopted)",
                  file=sys.stderr)
            return 3
        if not result["zero_fallbacks"]:
            print("FAIL: terminal FallbackSignal during a bench run",
                  file=sys.stderr)
            return 3
        if not result["overlap_ok"]:
            print(f"FAIL: push e2e speedup {result['speedup_e2e']} < "
                  f"{OVERLAP_GATE}", file=sys.stderr)
            return 2
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
