#!/usr/bin/env python
"""udatop: a live per-supplier console over the MSG_STATS plane.

Polls a list of shuffle endpoints (``host[:port]``) with the wire's
uncredited MSG_STATS snapshot request (uda_tpu/net/wire.py) and
renders one line per supplier: connections, in-flight serves, serve
throughput (delta of ``net.bytes.out{role=server}`` between polls),
read-latency p95, penalties, ResourceLedger obligations/leaks. This is
the scrape surface ROADMAP item 1's per-tenant fairness gates will
consume — today it is the operator's top(1).

Usage::

    python scripts/udatop.py host1 host2:9012 --interval 2
    python scripts/udatop.py 127.0.0.1:9012 --once --json

``--once`` prints a single sample and exits (scriptable; ``--json``
dumps the raw snapshots instead of the table). A peer that refuses
MSG_STATS (old version: typed ERR or disconnect) renders as
``unsupported``; an unreachable one as ``down`` — the console never
crashes over one sick supplier.

``--window N`` asks each CAP_OBS peer for its observability sections
(time-series rollups for the trailing N seconds, per-tenant SLIs,
active anomalies): tenanted peers grow per-tenant sub-rows (scheduled
share vs entitlement, worst SLO burn rate, starvation streak) and an
``anomalies:`` line. A pre-observability peer simply renders the
plain table — never ``unsupported``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from uda_tpu.net.client import fetch_remote_stats  # noqa: E402
from uda_tpu.utils.config import Config  # noqa: E402
from uda_tpu.utils.errors import UdaError  # noqa: E402

_HEADER = (f"{'supplier':<22} {'gen':>5} {'conns':>5} {'onair':>5} "
           f"{'MB/s':>8} {'read p95':>9} {'penal':>5} {'oblig':>5} "
           f"{'leaks':>5} {'where':<16}")


def where_time_goes(prov: dict) -> str:
    """The dominant time-accounting bucket from the peer's
    ``time_accounting`` stats provider (uda_tpu.utils.critpath rides
    MSG_STATS), e.g. ``merge 62%`` — '-' when the peer records no
    spans or predates the provider."""
    ta = prov.get("time_accounting") if isinstance(prov, dict) else None
    if not isinstance(ta, dict):
        return "-"
    buckets = ta.get("buckets")
    if not isinstance(buckets, dict) or not buckets:
        return "-"
    best = max(buckets.items(),
               key=lambda kv: kv[1].get("critical_s", 0.0))
    if best[1].get("critical_s", 0.0) <= 0:
        return "-"
    return f"{best[0]} {best[1].get('share', 0.0) * 100:.0f}%"


def parse_host(spec: str, default_port: int):
    host, _, port = spec.partition(":")
    return host or "127.0.0.1", int(port) if port else default_port


def worst_burn(tslo: dict) -> tuple:
    """(burn, sli name) of the tenant's hottest SLO, ('-', '-') when
    nothing is judged yet."""
    best = None
    for sli, block in (tslo or {}).items():
        burn = block.get("burn") if isinstance(block, dict) else None
        if burn is None:
            continue
        if best is None or burn > best[0]:
            best = (burn, sli)
    return best if best else ("-", "-")


def tenant_rows(snap: dict) -> list:
    """Per-tenant sub-rows from a CAP_OBS peer's ``sli`` block (empty
    for untenanted or pre-observability peers)."""
    sli = snap.get("sli")
    if not isinstance(sli, dict) or not sli.get("tenants"):
        return []
    lines = []
    for t, blk in sli["tenants"].items():
        share = blk.get("window_share")
        entitled = blk.get("entitled")
        burn, burn_sli = worst_burn(blk.get("slo"))
        share_txt = (f"{share * 100:5.1f}%" if share is not None
                     else "    -")
        tail = (f" of {entitled * 100:5.1f}% entitled"
                if entitled else "")
        burn_txt = (f"  burn {burn:g} ({burn_sli})"
                    if burn != "-" else "  burn -")
        starve = blk.get("starve_streak_s") or 0
        lines.append(f"  └ {t:<19} share {share_txt}{tail}"
                     f"{burn_txt}"
                     + (f"  STARVED {starve:g}s" if starve else ""))
    anomalies = snap.get("anomalies")
    if isinstance(anomalies, dict) and anomalies.get("active"):
        kinds = ", ".join(f"{a['kind']}({a['key']})"
                          for a in anomalies["active"])
        lines.append(f"  ! anomalies: {kinds}")
    return lines


def row(spec: str, snap, prev, dt: float) -> str:
    if isinstance(snap, str):  # "down" / "unsupported"
        return f"{spec:<22} {snap}"
    c = snap.get("counters", {})
    g = snap.get("gauges", {})
    p = snap.get("percentiles", {})
    led = snap.get("resledger", {})
    prov = snap.get("providers", {})
    srv = prov.get("net.server", {}) if isinstance(prov, dict) else {}
    out_now = c.get("net.bytes.out{role=server}", 0.0)
    out_prev = (prev.get("counters", {})
                .get("net.bytes.out{role=server}", 0.0)
                if isinstance(prev, dict) else None)
    mb_s = ((out_now - out_prev) / dt / 1e6
            if out_prev is not None and dt > 0 else 0.0)
    p95 = p.get("supplier.read.latency_ms", {}).get("p95", 0.0)
    return (f"{spec:<22} {srv.get('generation', '?'):>5} "
            f"{int(g.get('net.server.connections', 0)):>5} "
            f"{int(g.get('net.server.inflight', 0)):>5} "
            f"{mb_s:>8.2f} {p95:>8.1f}ms "
            f"{int(c.get('fetch.penalties', 0)):>5} "
            f"{led.get('outstanding', 0):>5} "
            f"{led.get('leak_reports', 0):>5} "
            f"{where_time_goes(prov):<16}")


def poll(targets, timeout: float, window_s=None):
    snaps = {}
    for spec, (host, port) in targets.items():
        try:
            snaps[spec] = fetch_remote_stats(host, port, timeout=timeout,
                                             window_s=window_s)
        except UdaError as e:
            # a typed refusal (ProtocolError from an old peer) vs a
            # dead endpoint — branch on the exception TYPE, never its
            # message (UDA005)
            from uda_tpu.utils.errors import TransportError
            snaps[spec] = ("down" if isinstance(e, TransportError)
                           else "unsupported")
    return snaps


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("hosts", nargs="+", help="supplier endpoints, "
                                             "host[:port]")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="one sample, no screen clearing")
    ap.add_argument("--json", action="store_true",
                    help="dump raw snapshots as JSON (implies no table)")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--window", type=int, default=None, metavar="S",
                    help="request CAP_OBS observability sections for "
                         "the trailing S seconds (per-tenant SLI "
                         "sub-rows + anomalies; old peers degrade to "
                         "the plain table)")
    args = ap.parse_args()
    default_port = int(Config().get("uda.tpu.net.port"))
    targets = {spec: parse_host(spec, default_port)
               for spec in args.hosts}
    prev: dict = {}
    prev_t = time.monotonic()
    while True:
        snaps = poll(targets, args.timeout, window_s=args.window)
        now = time.monotonic()
        dt = max(now - prev_t, 1e-9)
        if args.json:
            print(json.dumps({spec: s if isinstance(s, dict) else
                              {"status": s} for spec, s in snaps.items()},
                             default=repr))
        else:
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(time.strftime("%H:%M:%S"), "udatop —",
                  len(targets), "supplier(s), every",
                  f"{args.interval:g}s")
            print(_HEADER)
            for spec in args.hosts:
                print(row(spec, snaps[spec], prev.get(spec), dt))
                if isinstance(snaps[spec], dict):
                    for line in tenant_rows(snaps[spec]):
                        print(line)
            sys.stdout.flush()
        if args.once:
            return 0
        prev, prev_t = snaps, now
        time.sleep(args.interval)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except KeyboardInterrupt:
        sys.exit(0)
