#!/usr/bin/env bash
# Chaos tier: run the fault-marked tests under a randomized-but-seeded
# failpoint schedule (uda_tpu.utils.failpoints.chaos_spec). The seed is
# printed first — reproduce any failure exactly with:
#
#   CHAOS_SEED=<seed> scripts/run_chaos.sh
#
# The schedule is recoverable by construction (transport errors, delays,
# truncations — no undetectable corruption), so a failure here means the
# retry/backoff/penalty/carry machinery regressed, not that the dice
# came up wrong. Extra pytest args pass through ("$@").
#
# Telemetry: the run accumulates the session's fault/recovery counters
# (tests/conftest.py) and writes CHAOS_TELEMETRY.json — the same
# comparable "telemetry" block bench.py embeds — wrapped with the seed
# and schedule so chaos rounds diff against each other.
set -euo pipefail
cd "$(dirname "$0")/.."

SEED="${CHAOS_SEED:-$RANDOM}"
SPEC="$(python -c "from uda_tpu.utils.failpoints import chaos_spec; print(chaos_spec(${SEED}))")"
OUT="${CHAOS_TELEMETRY_JSON:-CHAOS_TELEMETRY.json}"
COUNTERS="$(mktemp)"
trap 'rm -f "${COUNTERS}"' EXIT
echo "chaos seed:          ${SEED}"
echo "failpoint schedule:  ${SPEC}"

rc=0
env JAX_PLATFORMS=cpu UDA_FAILPOINTS="${SPEC}" UDA_TPU_STATS=1 \
    UDA_TPU_CHAOS_TELEMETRY="${COUNTERS}" \
    python -m pytest tests/ -m faults -q -p no:cacheprovider \
    --continue-on-collection-errors "$@" || rc=$?

python - "${SEED}" "${SPEC}" "${COUNTERS}" "${OUT}" "${rc}" <<'EOF'
import json, sys
seed, spec, counters_path, out, rc = sys.argv[1:6]
try:
    with open(counters_path) as f:
        telemetry = json.load(f)
except Exception:
    telemetry = {"counters": {}}
with open(out, "w") as f:
    json.dump({"chaos_seed": int(seed), "schedule": spec,
               "pytest_exit": int(rc), "telemetry": telemetry},
              f, indent=1, sort_keys=True)
    f.write("\n")
print(f"chaos telemetry:     {out}")
EOF
exit "${rc}"
